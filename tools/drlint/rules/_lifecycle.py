"""Shared machinery for the lifecycle family (thread-lifecycle,
resource-lifecycle).

Both passes answer the same shape of question: "does every acquisition
site in this class reach its release on a stop path?" — where a *stop
path* is any method reachable, transitively through same-class calls,
from one of the teardown entry points the runtime actually uses
(`close`/`stop`/`shutdown`/`drain`/`join`/`terminate`/`abort`,
`__exit__`, `__del__`, and their `close_producer`-style variants).

This module owns:

- the stop-entry name test and the transitive stop-reachable method
  set (resolved over the inheritance-merged class model, the same
  `rules/_locks.py` machinery lock-order resolves calls with);
- per-method alias maps: locals copied from `self.X` (`t = self._thread`,
  `threads = list(self._threads)`) and for-loop variables iterating a
  container attribute — so `for t in threads: t.join()` proves the join
  of `self._threads`;
- the shared merged-class memo on `Program._cache`, so the lifecycle
  passes piggyback on ONE class-model build per lint invocation (the
  de-flake contract: program passes never re-derive global facts).
"""

from __future__ import annotations

import ast

from tools.drlint.core import Program
from tools.drlint.rules._locks import (
    ClassModel,
    _self_attr,
    merged_class,
    program_classes,
)

# Substrings that make a method a teardown ENTRY point. Matching is by
# substring so the repo's close_producer/close_consumer/close_metrics/
# stop_all variants all count without a per-name registry.
_STOP_STEMS = ("close", "stop", "shutdown", "drain", "join", "terminate",
               "abort", "unlink")
_STOP_EXACT = ("__exit__", "__del__")


def is_stop_entry(name: str) -> bool:
    return name in _STOP_EXACT or any(s in name for s in _STOP_STEMS)


def merged(program: Program, name: str) -> ClassModel | None:
    """Inheritance-merged class model, memoized per Program so the two
    lifecycle passes (and reconcile) share one merge per class."""
    memo = program._cache.setdefault("lifecycle_merged", {})
    if name not in memo:
        cls = program_classes(program).get(name)
        memo[name] = None if cls is None else merged_class(program, cls)
    return memo[name]


def stop_reachable(program: Program, cls: ClassModel) -> set[str]:
    """Method names of `cls` (merged view) reachable from a stop entry
    via `self.m()` calls — the set in which a `.join()`/`.close()`
    proves teardown actually runs."""
    memo = program._cache.setdefault("lifecycle_reachable", {})
    if cls.name in memo:
        return memo[cls.name]
    # self.m() call edges within the (merged) class.
    calls: dict[str, set[str]] = {}
    for name, fn in cls.methods.items():
        out: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id in ("self", "cls") and \
                    node.func.attr in cls.methods:
                out.add(node.func.attr)
        calls[name] = out
    reach = {m for m in cls.methods if is_stop_entry(m)}
    frontier = list(reach)
    while frontier:
        cur = frontier.pop()
        for nxt in calls.get(cur, ()):
            if nxt not in reach:
                reach.add(nxt)
                frontier.append(nxt)
    memo[cls.name] = reach
    return reach


def _copy_source_attr(value: ast.AST) -> str | None:
    """Attr name when `value` is `self.X` or a shallow copy of it
    (`list(self.X)`, `tuple(self.X)`, `sorted(self.X)`, `self.X[:]`,
    `list(self.X.values())`) — the idiom every stop path here uses to
    snapshot a thread list under its lock before joining outside it."""
    attr = _self_attr(value)
    if attr is not None:
        return attr
    if isinstance(value, ast.Call) and isinstance(value.func, ast.Name) and \
            value.func.id in ("list", "tuple", "sorted", "set") and \
            len(value.args) == 1:
        inner = value.args[0]
        attr = _self_attr(inner)
        if attr is not None:
            return attr
        # list(self.X.values()) / list(self.X.items())
        if isinstance(inner, ast.Call) and \
                isinstance(inner.func, ast.Attribute) and \
                inner.func.attr in ("values", "items", "keys"):
            return _self_attr(inner.func.value)
    if isinstance(value, ast.Subscript):  # self.X[:]
        return _self_attr(value.value)
    return None


def method_aliases(fn: ast.FunctionDef) -> dict[str, str]:
    """local name -> self attribute it aliases, within one method:
    direct copies (`t = self._thread`, `ts = list(self._threads)`) and
    for-loop variables over an attribute or an aliased copy
    (`for t in threads:` after `threads = list(self._threads)`)."""
    out: dict[str, str] = {}
    # Two passes: ast.walk is breadth-first, so a top-level `for t in
    # threads:` is visited BEFORE the `threads = list(self._threads)`
    # nested in a `with` block above it — collect all copies first.
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            src = _copy_source_attr(node.value)
            if src is not None:
                out[node.targets[0].id] = src
    for node in ast.walk(fn):
        if isinstance(node, (ast.For, ast.AsyncFor)) and \
                isinstance(node.target, ast.Name):
            src = _copy_source_attr(node.iter)
            if src is None and isinstance(node.iter, ast.Name):
                src = out.get(node.iter.id)
            if src is not None:
                out[node.target.id] = src
    return out


def attr_calls(fn: ast.FunctionDef, method: str,
               aliases: dict[str, str] | None = None) -> set[str]:
    """Self attributes on which `.method()` is called anywhere in `fn`,
    aliases resolved: `self.X.join()` -> {'X'}; with aliases,
    `t.join()` after `t = self._thread` (or a loop over the container)
    also -> the attr."""
    if aliases is None:
        aliases = method_aliases(fn)
    out: set[str] = set()
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call) and
                isinstance(node.func, ast.Attribute) and
                node.func.attr == method):
            continue
        recv = node.func.value
        attr = _self_attr(recv)
        if attr is not None:
            out.add(attr)
        elif isinstance(recv, ast.Name) and recv.id in aliases:
            out.add(aliases[recv.id])
    return out
