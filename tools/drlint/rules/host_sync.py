"""host-sync: no hidden device syncs inside runtime step loops.

The throughput argument of the whole stack (free-running learner,
pipelined dispatch, K-step publish cadence — docs/performance.md) dies
the moment a step loop blocks on a device value: one stray `.item()`
turns overlapped dispatch back into lockstep. TorchBeast-style eager
stacks accumulate exactly these. Scope (by construction, not
convention): `runtime/*_runner.py` and `runtime/anakin*.py`, inside the
named hot-loop functions only.

Hot functions:
- actor loops  — ``run_unroll``, ``run_steps``
- learner loops — ``step``, ``train``, ``ingest``, ``ingest_many``,
  ``ingest_batch``, ``train_chunk``, ``collect_chunk``

Flagged in BOTH: `.item()`, `jax.device_get`, `.block_until_ready()` —
unambiguous blocking syncs.

Flagged in LEARNER loops only: `np.asarray(...)` and `float(...)` /
`int(...)` on non-constants. The actor's act→env boundary is a host
boundary by design (actions must reach a host env), so asarray there is
the idiom, not a bug; on the learner thread every one of these stalls
the dispatch pipeline and must be either removed or explicitly
justified with an inline suppression.
"""

from __future__ import annotations

import ast
import posixpath

from tools.drlint.core import Finding, ModuleInfo

RULE = "host-sync"

ACTOR_HOT = {"run_unroll", "run_steps"}
LEARNER_HOT = {"step", "train", "ingest", "ingest_many", "ingest_batch",
               "train_chunk", "collect_chunk"}


def in_scope(path: str) -> bool:
    base = posixpath.basename(path)
    return "runtime/" in path and (base.endswith("_runner.py")
                                   or base.startswith("anakin"))


def _check_node(mod: ModuleInfo, node: ast.AST, learner: bool) -> Finding | None:
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    if isinstance(func, ast.Attribute):
        if func.attr == "item" and not node.args:
            return mod.finding(RULE, node,
                               ".item() blocks on the device inside a hot loop")
        if func.attr == "block_until_ready":
            return mod.finding(RULE, node,
                               "block_until_ready() inside a hot loop "
                               "serializes the dispatch pipeline")
    chain = mod.resolve_chain(func)
    if chain in ("jax.device_get", "jax.block_until_ready"):
        return mod.finding(RULE, node,
                           f"`{chain}` blocks on the device inside a hot loop")
    if not learner:
        return None
    if chain == "numpy.asarray":
        return mod.finding(RULE, node,
                           "np.asarray() on the learner thread is a D2H "
                           "sync; move it off the step path or justify it")
    if isinstance(func, ast.Name) and func.id in ("float", "int") and node.args:
        arg = node.args[0]
        if not isinstance(arg, ast.Constant):
            return mod.finding(RULE, node,
                               f"{func.id}() on a runtime value forces a "
                               f"device sync when the value is a device "
                               f"array; hoist it off the learn loop or "
                               f"justify it")
    return None


def check(mod: ModuleInfo) -> list[Finding]:
    if not in_scope(mod.path):
        return []
    findings: list[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name in LEARNER_HOT:
            learner = True
        elif node.name in ACTOR_HOT:
            learner = False
        else:
            continue
        for stmt in node.body:
            for sub in ast.walk(stmt):
                f = _check_node(mod, sub, learner)
                if f is not None:
                    findings.append(f)
    return findings
