"""Rule registries.

`RULES`: id -> check(ModuleInfo) -> list[Finding] — per-module passes.
`PROGRAM_RULES`: id -> check(Program) -> list[Finding] — whole-program
passes (lock graphs, protocol contracts, registry round-trips) that
need every linted module at once; `lint_paths`/`lint_sources` build the
Program and run them after the per-module passes.

Rule ids are the kebab-case names used in suppression comments
(`# drlint: disable=<id>`) and baseline entries. Adding a rule = adding
a module here + a catalog section in docs/static_analysis.md + a
positive/negative fixture pair in tests/test_drlint.py.
"""

from tools.drlint.rules.blocking_under_lock import check as _blocking_under_lock
from tools.drlint.rules.dtype_pitfall import check as _dtype_pitfall
from tools.drlint.rules.guardedby_completeness import check as _guardedby_completeness
from tools.drlint.rules.host_sync import check as _host_sync
from tools.drlint.rules.jit_purity import check as _jit_purity
from tools.drlint.rules.knob_registry import check as _knob_registry
from tools.drlint.rules.lock_discipline import check as _lock_discipline
from tools.drlint.rules.lock_order import check as _lock_order
from tools.drlint.rules.nondeterminism import check as _nondeterminism
from tools.drlint.rules.protocol_contract import check as _protocol_contract
from tools.drlint.rules.resource_lifecycle import check as _resource_lifecycle
from tools.drlint.rules.silent_except import check as _silent_except
from tools.drlint.rules.thread_lifecycle import check as _thread_lifecycle

RULES = {
    "jit-purity": _jit_purity,
    "host-sync": _host_sync,
    "lock-discipline": _lock_discipline,
    "guardedby-completeness": _guardedby_completeness,
    "nondeterminism": _nondeterminism,
    "dtype-pitfall": _dtype_pitfall,
    "silent-except": _silent_except,
}

PROGRAM_RULES = {
    "blocking-under-lock": _blocking_under_lock,
    "lock-order": _lock_order,
    "protocol-contract": _protocol_contract,
    "knob-registry": _knob_registry,
    "thread-lifecycle": _thread_lifecycle,
    "resource-lifecycle": _resource_lifecycle,
}

ALL_RULES = {**RULES, **PROGRAM_RULES}
