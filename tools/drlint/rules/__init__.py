"""Rule registry: id -> check(ModuleInfo) -> list[Finding].

Rule ids are the kebab-case names used in suppression comments
(`# drlint: disable=<id>`) and baseline entries. Adding a rule = adding
a module here + a catalog section in docs/static_analysis.md + a
positive/negative fixture pair in tests/test_drlint.py.
"""

from tools.drlint.rules.dtype_pitfall import check as _dtype_pitfall
from tools.drlint.rules.host_sync import check as _host_sync
from tools.drlint.rules.jit_purity import check as _jit_purity
from tools.drlint.rules.lock_discipline import check as _lock_discipline
from tools.drlint.rules.nondeterminism import check as _nondeterminism

RULES = {
    "jit-purity": _jit_purity,
    "host-sync": _host_sync,
    "lock-discipline": _lock_discipline,
    "nondeterminism": _nondeterminism,
    "dtype-pitfall": _dtype_pitfall,
}
