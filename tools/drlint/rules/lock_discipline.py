"""lock-discipline: `_GUARDED_BY`-annotated attributes need their lock.

The GuardedBy race check (the TF graph runtime used to police shared
state for free; the threaded Python runtime has nothing but convention).
A class opts in by declaring, at class level:

    _GUARDED_BY = {
        "stats": "_stats_lock",              # one lock
        "_items": ("_lock", "_not_empty"),   # any of several aliases
    }

Every `self.<attr>` touch (read OR write — torn reads of dicts/tuples
under mutation are the races transport.py actually had) of a declared
attribute must then be lexically inside `with self.<lock>:` for one of
the declared lock names. Conditions constructed over a lock are listed
as aliases, as fifo.TrajectoryQueue does.

Escapes, by convention (docs/static_analysis.md):
- `__init__`/`__del__` are exempt (construction happens-before any
  other thread; destruction happens-after).
- methods whose name ends in `_locked` are exempt — the suffix is the
  repo's caller-holds-the-lock contract.
- nested functions/lambdas inherit the lexically held set (a
  `wait_for(lambda: ...)` inside a `with` is covered; a closure that
  escapes the lock's scope is on the author — suppress inline and say
  why).

The check is lexical and per-class: accesses through other names
(`server.stats` from a module function) are out of scope, exactly like
Java's @GuardedBy.
"""

from __future__ import annotations

import ast

from tools.drlint.core import Finding, ModuleInfo

RULE = "lock-discipline"

_EXEMPT = {"__init__", "__del__"}


def _literal_guards(value: ast.AST) -> dict[str, frozenset[str]] | None:
    if not isinstance(value, ast.Dict):
        return None
    out: dict[str, frozenset[str]] = {}
    for k, v in zip(value.keys, value.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            return None
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            locks = frozenset({v.value})
        elif isinstance(v, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in v.elts):
            locks = frozenset(e.value for e in v.elts)
        else:
            return None
        out[k.value] = locks
    return out


def _class_guards(cls: ast.ClassDef) -> dict[str, frozenset[str]] | None:
    for stmt in cls.body:
        target = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            target = stmt.targets[0].id
        elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            target = stmt.target.id
        if target == "_GUARDED_BY":
            return _literal_guards(stmt.value)
    return None


def _self_attr(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _walk(mod: ModuleInfo, node: ast.AST, held: frozenset[str],
          guards: dict[str, frozenset[str]], out: list[Finding]) -> None:
    if isinstance(node, (ast.With, ast.AsyncWith)):
        acquired = set()
        for item in node.items:
            _walk(mod, item.context_expr, held, guards, out)
            name = _self_attr(item.context_expr)
            if name:
                acquired.add(name)
            if item.optional_vars is not None:
                _walk(mod, item.optional_vars, held, guards, out)
        inner = held | frozenset(acquired)
        for stmt in node.body:
            _walk(mod, stmt, inner, guards, out)
        return
    attr = _self_attr(node)
    if attr is not None and attr in guards and not (held & guards[attr]):
        locks = "/".join(sorted(guards[attr]))
        out.append(mod.finding(
            RULE, node,
            f"self.{attr} touched without holding self.{locks} "
            f"(declared in _GUARDED_BY)"))
    for child in ast.iter_child_nodes(node):
        _walk(mod, child, held, guards, out)


def check(mod: ModuleInfo) -> list[Finding]:
    findings: list[Finding] = []
    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        guards = _class_guards(cls)
        if not guards:
            continue
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if method.name in _EXEMPT or method.name.endswith("_locked"):
                continue
            for stmt in method.body:
                _walk(mod, stmt, frozenset(), guards, findings)
    return findings
