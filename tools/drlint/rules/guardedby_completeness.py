"""guardedby-completeness: lock-owning classes declare ALL shared state.

The lock-discipline pass enforces the `_GUARDED_BY` entries a class
HAS; nothing enforced that the map is COMPLETE. A class that owns a
lock is self-declaring "my instances are touched by multiple threads"
— and every mutable attribute it initializes is then shared state that
either needs a lock (add it to `_GUARDED_BY`) or a conscious decision
that it doesn't (declare it in a `_NOT_GUARDED` waiver map with a
justification). This pass closes the annotate-or-waive loop so a new
field added to a threaded class can never silently skip the
concurrency contract; the runtime sanitizer then verifies the
`_GUARDED_BY` side is real (docs/static_analysis.md "Runtime
sanitizer").

Trigger: any class whose OWN body assigns a `threading.Lock/RLock/
Condition/Semaphore` to `self.<x>` (lock construction is the static
proxy for "touched by multiple threads"; classes that merely receive
shared objects are out of scope, like lock-discipline's
other-name accesses).

Flagged: an instance attribute assigned in `__init__` that is

- rebound in any other method (torn read/lost update risk), or
- initialized to a mutable container (list/dict/set displays or
  comprehensions, or a call to list/dict/set/deque/defaultdict/
  OrderedDict/Counter/bytearray),

and appears in neither `_GUARDED_BY` nor `_NOT_GUARDED`. Lock
attributes themselves, Conditions, and immutable run-once config
(ints, strings, tuples, param objects) are exempt.

`_NOT_GUARDED` is a class-level dict `{"attr": "justification", ...}`
(a tuple of `(attr, justification)` pairs also parses). Justifications
under 10 chars, and entries for attrs that no longer exist or are now
in `_GUARDED_BY`, are findings — the waiver map can only shrink.
"""

from __future__ import annotations

import ast

from tools.drlint.core import Finding, ModuleInfo
from tools.drlint.rules._locks import _called_chain_tail, LOCK_CTORS

RULE = "guardedby-completeness"

_MUTABLE_CALLS = {"list", "dict", "set", "deque", "defaultdict",
                  "OrderedDict", "Counter", "bytearray"}


def _self_attr_targets(node: ast.AST) -> list[str]:
    """Attr names a statement assigns on self (tuple unpacking too)."""
    out: list[str] = []
    targets: list[ast.AST] = []
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        targets = [node.target]
    for tgt in targets:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            targets.extend(tgt.elts)
        elif isinstance(tgt, ast.Attribute) and \
                isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
            out.append(tgt.attr)
    return out


def _is_mutable_init(value: ast.AST | None) -> bool:
    if value is None:
        return False
    if isinstance(value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call):
        name = None
        if isinstance(value.func, ast.Name):
            name = value.func.id
        elif isinstance(value.func, ast.Attribute):
            name = value.func.attr
        return name in _MUTABLE_CALLS
    return False


def _literal_str_map(value: ast.AST) -> dict[str, str] | None:
    """Parse `_NOT_GUARDED`: a {"attr": "why"} dict or a tuple/list of
    ("attr", "why") pairs. None if the shape is unrecognizable."""
    if isinstance(value, ast.Dict):
        out = {}
        for k, v in zip(value.keys, value.values):
            if not (isinstance(k, ast.Constant) and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)):
                return None
            out[k.value] = v.value
        return out
    if isinstance(value, (ast.Tuple, ast.List)):
        out = {}
        for elt in value.elts:
            if not (isinstance(elt, (ast.Tuple, ast.List))
                    and len(elt.elts) == 2
                    and all(isinstance(e, ast.Constant)
                            and isinstance(e.value, str)
                            for e in elt.elts)):
                return None
            out[elt.elts[0].value] = elt.elts[1].value
        return out
    return None


def _class_level_assign(cls: ast.ClassDef, name: str) -> ast.AST | None:
    for stmt in cls.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                stmt.targets[0].id == name:
            return stmt
        if isinstance(stmt, ast.AnnAssign) and \
                isinstance(stmt.target, ast.Name) and stmt.target.id == name:
            return stmt
    return None


def _guarded_keys(cls: ast.ClassDef) -> set[str]:
    stmt = _class_level_assign(cls, "_GUARDED_BY")
    value = getattr(stmt, "value", None)
    if not isinstance(value, ast.Dict):
        return set()
    return {k.value for k in value.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)}


def _check_class(mod: ModuleInfo, cls: ast.ClassDef,
                 out: list[Finding]) -> None:
    # Trigger + exempt set: everything lock-shaped this class's own
    # body constructs or aliases.
    lock_attrs: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _called_chain_tail(mod, node.value) in LOCK_CTORS:
                for attr in _self_attr_targets(node):
                    lock_attrs.add(attr)
    if not lock_attrs:
        return

    guarded = _guarded_keys(cls)
    ng_stmt = _class_level_assign(cls, "_NOT_GUARDED")
    waived: dict[str, str] = {}
    if ng_stmt is not None:
        parsed = _literal_str_map(ng_stmt.value)
        if parsed is None:
            out.append(mod.finding(
                RULE, ng_stmt,
                "_NOT_GUARDED must be a literal {'attr': 'justification'} "
                "dict (or tuple of (attr, justification) pairs)"))
        else:
            waived = parsed

    methods = {m.name: m for m in cls.body
               if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}
    init = methods.get("__init__")

    init_attrs: dict[str, ast.AST] = {}
    if init is not None:
        for node in ast.walk(init):
            for attr in _self_attr_targets(node):
                init_attrs.setdefault(attr, node)

    rebound: set[str] = set()
    for name, meth in methods.items():
        if name == "__init__":
            continue
        for node in ast.walk(meth):
            rebound.update(_self_attr_targets(node))

    for attr, node in sorted(init_attrs.items()):
        if attr in lock_attrs or attr in guarded or attr in waived:
            continue
        value = getattr(node, "value", None)
        if attr not in rebound and not _is_mutable_init(value):
            continue  # immutable run-once config
        why = ("rebound outside __init__" if attr in rebound
               else "initialized to a mutable container")
        out.append(mod.finding(
            RULE, node,
            f"self.{attr} in lock-owning class {cls.name} ({why}) is in "
            f"neither _GUARDED_BY nor _NOT_GUARDED — declare its lock or "
            f"waive it with a justification"))

    # Waiver hygiene, mirroring the baseline contract.
    if ng_stmt is not None:
        for attr, why in sorted(waived.items()):
            if attr in guarded:
                out.append(mod.finding(
                    RULE, ng_stmt,
                    f"_NOT_GUARDED entry {attr!r} is also in _GUARDED_BY — "
                    f"pick one"))
            elif attr not in init_attrs and attr not in rebound:
                out.append(mod.finding(
                    RULE, ng_stmt,
                    f"_NOT_GUARDED entry {attr!r} matches no instance "
                    f"attribute of {cls.name} — remove it"))
            if len(why.strip()) < 10:
                out.append(mod.finding(
                    RULE, ng_stmt,
                    f"_NOT_GUARDED entry {attr!r} needs a real "
                    f"justification, not {why!r}"))


def check(mod: ModuleInfo) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            _check_class(mod, node, findings)
    return findings
