"""dtype-pitfall: no dtype-less numpy constructors on device-bound paths.

numpy defaults to float64. On TPU that either x64-truncates with a
warning or — worse, with jax_enable_x64 — silently doubles every
downstream buffer and knocks matmuls off the bf16 MXU fast path. The
rule covers the code whose arrays feed devices:

- everything under `agents/`, `ops/`, `models/`, `parallel/`;
- any traced function anywhere (rules/_traced.py), since an np array
  materialized inside a trace becomes a baked-in constant.

Flags `np.zeros/ones/empty/full` without an explicit dtype, and any
`np.float64` reference in scope (an explicit float64 on a device path
is the same pitfall spelled confidently). Host-side bookkeeping (the
replay tree's float64 priorities, env simulators) lives outside the
scoped directories on purpose.

`jnp.*` constructors are NOT flagged: their default is float32, which
is exactly the intended device default.
"""

from __future__ import annotations

import ast

from tools.drlint.core import Finding, ModuleInfo
from tools.drlint.rules._traced import traced_roots

RULE = "dtype-pitfall"

_DEVICE_DIRS = ("/agents/", "/ops/", "/models/", "/parallel/")
# dtype position among positional args: zeros/ones/empty take (shape,
# dtype); full takes (shape, fill_value, dtype).
_CTORS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2}


def _has_dtype(node: ast.Call, pos: int) -> bool:
    return len(node.args) > pos or any(k.arg == "dtype" for k in node.keywords)


def _check_node(mod: ModuleInfo, node: ast.AST) -> Finding | None:
    if isinstance(node, ast.Call):
        chain = mod.resolve_chain(node.func)
        if chain and chain.startswith("numpy."):
            name = chain.rsplit(".", 1)[-1]
            if name in _CTORS and not _has_dtype(node, _CTORS[name]):
                return mod.finding(
                    RULE, node,
                    f"dtype-less `np.{name}` defaults to float64 on a "
                    f"device-bound path — pass an explicit dtype")
    elif isinstance(node, ast.Attribute):
        if mod.resolve_chain(node) == "numpy.float64" and \
                not isinstance(mod.parents.get(node), ast.Attribute):
            return mod.finding(
                RULE, node,
                "np.float64 on a device-bound path breaks bf16/f32 "
                "compute — use the model dtype or float32")
    return None


def check(mod: ModuleInfo) -> list[Finding]:
    findings: list[Finding] = []
    seen: set[tuple[int, int]] = set()

    def emit(node: ast.AST) -> None:
        f = _check_node(mod, node)
        if f is not None:
            pos = (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))
            if pos not in seen:
                seen.add(pos)
                findings.append(f)

    if any(d in f"/{mod.path}" for d in _DEVICE_DIRS):
        for node in ast.walk(mod.tree):
            emit(node)
    else:
        roots, _ = traced_roots(mod)
        for root in roots:
            body = root.body if isinstance(root.body, list) else [root.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    emit(node)
    return findings
