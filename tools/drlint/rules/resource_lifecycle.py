"""resource-lifecycle: every acquired OS resource must have a provable
release owner — and the RIGHT owner.

Acquisition sites recognized (module-alias resolved):

- **SharedMemory** — `SharedMemory(create=True, ...)` and the repo's
  `create_or_reclaim_shm(...)` helper are CREATE sites;
  `SharedMemory(name=...)` / `attach_shm(...)` are ATTACH sites. The
  PR 9 creator-pid contract applies: the creator must reach both
  `close()` and `unlink()`; an attacher must reach `close()` and must
  NOT reach `unlink()` — an attach-side unlink destroys a segment the
  creator still owns, and is reported wherever it appears. The
  launcher's pid-keyed reaper is a crash backstop, not a release path:
  it never substitutes for the in-process close/unlink pair.
- **sockets** — `socket.socket(...)` / `socket.create_connection(...)`
  must reach `close()` (or `shutdown`/`detach`).
- **files** — builtin `open(...)`, `Path.open(...)`, `os.fdopen(...)`,
  `tempfile.NamedTemporaryFile/TemporaryFile` must reach `close()`.

Ownership and proof mirror thread-lifecycle (rules/_lifecycle.py):
class-owned attributes (`self.X = acquire()`, directly or through a
local) need the release reachable from a stop entry
(`close`/`stop`/`shutdown`/`__exit__`/...) over the merged class
model — either called on the attribute, or the attribute passed to a
callee whose name says it releases (`*close*`/`*unlink*`/`*destroy*`),
or a class-level `atexit.register` hook. Function-locals are fine when
used as context managers (`with open(...) as f:`), released in the
same function, or escaping (returned/yielded/passed on — the new
owner's scope is judged there). Flow-insensitivity is the deliberate
trade: a release anywhere in the owning scope counts, and the runtime
leak census (rt/census.py) catches the paths that dodge it in
practice.
"""

from __future__ import annotations

import ast

from tools.drlint.core import Finding, ModuleInfo, Program
from tools.drlint.rules._lifecycle import (
    attr_calls,
    merged,
    method_aliases,
    stop_reachable,
)
from tools.drlint.rules._locks import _self_attr, module_model

RULE = "resource-lifecycle"

# kind -> (verbs that count as release, verbs forbidden for this kind)
_RELEASE = {
    "shm-create": {"close", "unlink"},   # BOTH required (checked apart)
    "shm-attach": {"close", "detach"},
    "socket": {"close", "shutdown", "detach"},
    "file": {"close"},
}
_CALLEE_RELEASE_STEMS = ("close", "unlink", "destroy", "shutdown",
                         "release", "cleanup")

_FILE_CHAINS = {"os.fdopen", "tempfile.NamedTemporaryFile",
                "tempfile.TemporaryFile", "io.open", "gzip.open"}
_SOCKET_CHAINS = {"socket.socket", "socket.create_connection"}
_SHM_TAIL = "SharedMemory"


def _shm_create_kw(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "create" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


def _acquisition_kind(mod: ModuleInfo, node: ast.AST) -> str | None:
    if not isinstance(node, ast.Call):
        return None
    chain = mod.resolve_chain(node.func)
    if chain in _SOCKET_CHAINS:
        return "socket"
    if chain in _FILE_CHAINS:
        return "file"
    if chain is not None and chain.rsplit(".", 1)[-1] == _SHM_TAIL:
        return "shm-create" if _shm_create_kw(node) else "shm-attach"
    name = node.func.id if isinstance(node.func, ast.Name) else \
        node.func.attr if isinstance(node.func, ast.Attribute) else None
    if name == "open":
        # builtin open() or Path.open() — both hand back a closeable.
        return "file"
    if name == "attach_shm":
        return "shm-attach"
    if name in ("create_or_reclaim_shm", "create_shm"):
        return "shm-create"
    if name == _SHM_TAIL:
        return "shm-create" if _shm_create_kw(node) else "shm-attach"
    return None


def _under_with(mod: ModuleInfo, node: ast.AST) -> bool:
    cur = mod.parents.get(node)
    while cur is not None and not isinstance(cur, ast.stmt):
        if isinstance(cur, ast.withitem):
            return True
        cur = mod.parents.get(cur)
    return False


def _enclosing_stmt(mod: ModuleInfo, node: ast.AST) -> ast.stmt | None:
    cur: ast.AST | None = node
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = mod.parents.get(cur)
    return cur  # type: ignore[return-value]


def _local_self_stores(fn: ast.AST, name: str) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Name) and node.value.id == name:
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr is not None:
                    out.add(attr)
    return out


def _local_released(fn: ast.AST, name: str, verbs: set[str]) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in verbs and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == name:
            return True
    return False


def _local_escapes(fn: ast.AST, name: str) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, (ast.Return, ast.Yield)):
            v = node.value
            if isinstance(v, ast.Name) and v.id == name:
                return True
            # return (shm, created) — tuple escapes too
            if isinstance(v, (ast.Tuple, ast.List)) and any(
                    isinstance(e, ast.Name) and e.id == name
                    for e in v.elts):
                return True
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == name:
                continue  # f.read() — a use, not an escape
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id == name:
                    return True
        # stored into a container/dict: self._segs[k] = shm, d[k] = shm
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Name) and node.value.id == name:
            for tgt in node.targets:
                if isinstance(tgt, (ast.Subscript, ast.Attribute)):
                    return True
    return False


def _class_atexit(cls) -> bool:
    for fn in cls.methods.values():
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "register" and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "atexit":
                return True
    return False


def _callee_released_attrs(fn: ast.AST, stems=_CALLEE_RELEASE_STEMS
                           ) -> set[str]:
    """Self attrs passed as an argument to a callee whose name claims a
    release (`_destroy_segment(self._shm)`, `shutil_close(self._f)`)."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        fname = node.func.id if isinstance(node.func, ast.Name) else \
            node.func.attr if isinstance(node.func, ast.Attribute) else ""
        if not any(s in fname for s in stems):
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            attr = _self_attr(arg)
            if attr is not None:
                out.add(attr)
    return out


def build_resource_model(program: Program) -> dict[str, dict]:
    """Per owning class: attr -> acquisition kind, plus the release
    verbs provably reachable from stop entries. Cached on
    Program._cache; shared with --reconcile's lifecycle diff."""
    cached = program._cache.get("resource_model")
    if cached is not None:
        return cached  # type: ignore[return-value]
    model: dict[str, dict] = {}
    for mod in program.modules:
        for cname, cls in module_model(mod).classes.items():
            attrs: dict[str, tuple] = {}  # attr -> (kind, call node)
            local_sites: list[tuple] = []  # (method fn, call, kind, name)
            for meth in cls.node.body:
                if not isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                for node in ast.walk(meth):
                    kind = _acquisition_kind(mod, node)
                    if kind is None or _under_with(mod, node):
                        continue
                    stmt = _enclosing_stmt(mod, node)
                    if isinstance(stmt, ast.Assign) and \
                            len(stmt.targets) == 1:
                        tgt = stmt.targets[0]
                        attr = _self_attr(tgt)
                        if attr is not None:
                            attrs.setdefault(attr, (kind, node, meth))
                            continue
                        if isinstance(tgt, ast.Name):
                            stores = _local_self_stores(meth, tgt.id)
                            if stores:
                                attrs.setdefault(sorted(stores)[0],
                                                 (kind, node, meth))
                            else:
                                local_sites.append((meth, node, kind,
                                                    tgt.id))
                            continue
                        # self._segs[k] = SharedMemory(...) — container
                        # ownership; the census owns the empirical check.
                        continue
                    local_sites.append((meth, node, kind, None))
            if not attrs and not local_sites:
                continue
            m = merged(program, cname)
            if m is None or m.node is not cls.node:
                m = cls
            reach = stop_reachable(program, m)
            released: dict[str, set[str]] = {}
            unlinked_anywhere: dict[str, ast.AST] = {}
            for mname, fn in m.methods.items():
                aliases = method_aliases(fn)
                for a in attr_calls(fn, "unlink", aliases):
                    unlinked_anywhere.setdefault(
                        a, next((n for n in ast.walk(fn)
                                 if isinstance(n, ast.Call)
                                 and isinstance(n.func, ast.Attribute)
                                 and n.func.attr == "unlink"), fn))
                if mname not in reach:
                    continue
                for verb in ("close", "unlink", "detach", "shutdown",
                             "terminate"):
                    for a in attr_calls(fn, verb, aliases):
                        released.setdefault(a, set()).add(verb)
                for a in _callee_released_attrs(fn):
                    released.setdefault(a, set()).update(
                        ("close", "unlink"))
            model[cname] = {
                "mod": mod, "cls": m, "attrs": attrs,
                "local_sites": local_sites, "released": released,
                "unlinked": unlinked_anywhere,
                "atexit": _class_atexit(m),
            }
    program._cache["resource_model"] = model
    return model


def _check_local(mod: ModuleInfo, fn, findings: list,
                 sites: list | None = None) -> None:
    """Function-local acquisitions: with-managed, released in-function,
    or escaping — anything else is a leak-by-construction."""
    if sites is None:
        sites = []
        for node in ast.walk(fn):
            kind = _acquisition_kind(mod, node)
            if kind is None or _under_with(mod, node):
                continue
            stmt = _enclosing_stmt(mod, node)
            name = None
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                tgt = stmt.targets[0]
                if _self_attr(tgt) is not None:
                    continue
                if isinstance(tgt, ast.Name):
                    if _local_self_stores(fn, tgt.id):
                        continue
                    name = tgt.id
                else:
                    continue  # container store: census territory
            elif not isinstance(stmt, (ast.Expr, ast.Return)):
                continue
            sites.append((fn, node, kind, name))
    for owner_fn, node, kind, name in sites:
        if name is None:
            # Anonymous: `return open(p)` escapes; a bare-Expr
            # acquisition can never be released.
            stmt = _enclosing_stmt(mod, node)
            if isinstance(stmt, ast.Return) or \
                    isinstance(mod.parents.get(node), (ast.Return,
                                                       ast.Yield)):
                continue
            if isinstance(stmt, ast.Expr) and stmt.value is node:
                findings.append(mod.finding(
                    RULE, node,
                    f"{kind} acquired and immediately dropped — nothing "
                    f"holds a reference to release it"))
            continue
        verbs = _RELEASE[kind]
        if kind == "shm-attach" and _local_released(owner_fn, name,
                                                    {"unlink"}):
            findings.append(mod.finding(
                RULE, node,
                f"attached SharedMemory '{name}' is unlinked in this "
                f"scope — only the creator may unlink (creator-pid "
                f"contract); attachers close()"))
        if _local_released(owner_fn, name, verbs):
            if kind == "shm-create" and not _local_released(
                    owner_fn, name, {"unlink"}) and not \
                    _local_escapes(owner_fn, name):
                findings.append(mod.finding(
                    RULE, node,
                    f"created SharedMemory '{name}' is closed but never "
                    f"unlinked here and never escapes — the segment "
                    f"outlives the process"))
            continue
        if _local_escapes(owner_fn, name):
            continue
        findings.append(mod.finding(
            RULE, node,
            f"{kind} '{name}' is never released in this function and "
            f"never escapes it — close it (with-statement, explicit "
            f"close, or hand it to an owner with a stop path)"))


def check(program: Program) -> list[Finding]:
    findings: list[Finding] = []
    model = build_resource_model(program)
    for cname, info in sorted(model.items()):
        mod = info["mod"]
        released, unlinked = info["released"], info["unlinked"]
        for attr, (kind, node, meth) in sorted(info["attrs"].items()):
            got = released.get(attr, set())
            if kind == "shm-attach" and attr in unlinked:
                findings.append(mod.finding(
                    RULE, unlinked[attr],
                    f"{cname} attaches SharedMemory '{attr}' but calls "
                    f"unlink() on it — only the creator may unlink "
                    f"(creator-pid contract); attachers close()"))
            if info["atexit"]:
                continue
            if not got & _RELEASE[kind]:
                findings.append(mod.finding(
                    RULE, node,
                    f"{kind} '{attr}' of {cname} has no reachable "
                    f"release ({'/'.join(sorted(_RELEASE[kind]))}) on "
                    f"any close()/stop()/__exit__ path"))
            elif kind == "shm-create" and "unlink" not in got:
                findings.append(mod.finding(
                    RULE, node,
                    f"created SharedMemory '{attr}' of {cname} is "
                    f"closed but never unlinked on any stop path — the "
                    f"creator owns the unlink (the pid-keyed reaper is "
                    f"a crash backstop, not a release path)"))
        if info["local_sites"]:
            by_fn: dict[int, list] = {}
            for site in info["local_sites"]:
                by_fn.setdefault(id(site[0]), []).append(site)
            for sites in by_fn.values():
                _check_local(mod, sites[0][0], findings, sites)
    for mod in program.modules:
        for fn in module_model(mod).functions.values():
            _check_local(mod, fn, findings)
    findings.sort(key=lambda f: (f.path, f.line, f.message))
    return findings
