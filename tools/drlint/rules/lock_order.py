"""lock-order: whole-program lock-acquisition graph, cycles flagged.

Deadlock needs four coupon-collector ingredients and three are ambient
in this runtime (mutual exclusion, hold-and-wait, no preemption) — the
only one a linter can police is CIRCULAR WAIT. This pass builds one
global directed graph over every lock in the program:

- **nodes**: `(ClassName, lock_attr)` for instance locks (discovered
  from `threading.*` constructor assignments, `_GUARDED_BY` values and
  bare `with self.X:` targets — see rules/_locks.py; Conditions
  constructed over a lock alias to it) and `(module, name)` for
  module-level locks;
- **edges**: lock A -> lock B whenever B is acquired while A is held —
  lexically nested `with` blocks, a blocking `.acquire()` under a held
  lock, or a CALL made under A to a function that (transitively)
  acquires B. Calls are resolved within a class (`self.m()`), through
  typed attributes (`self._ladder = RetryLadder(...)` makes
  `self._ladder.try_acquire()` resolve to `RetryLadder.try_acquire`,
  across modules), and to same-module functions for module locks.

Any cycle in that graph — including the 2-cycle of two locks taken in
both orders from different call paths — is a potential deadlock and is
reported once per strongly-connected component, with the acquisition
sites that close it. A lock nested under itself is NOT reported here
(re-entrancy is a per-class concern the runtime's RLock-free style
already avoids lexically).

The analysis is name-coarse on purpose (same contract as _traced.py):
two classes sharing a name merge, untyped attribute calls contribute
nothing. That trades recall for a zero-noise gate — an edge only
exists when the pass can PROVE both acquisitions.
"""

from __future__ import annotations

import ast

from tools.drlint.core import Finding, ModuleInfo, Program
from tools.drlint.rules._locks import (
    ClassModel,
    HeldWalker,
    _self_attr,
    is_blocking_acquire,
    merged_class,
    module_model,
    program_classes,
)

RULE = "lock-order"

LockNode = tuple[str, str]  # (owner: class or module path, lock name)


def _fmt(node: LockNode) -> str:
    return f"{node[0]}.{node[1]}"


class _Analysis:
    def __init__(self, program: Program):
        self.program = program
        self.classes = program_classes(program)
        self.merged: dict[str, ClassModel] = {}
        self._locks_memo: dict[tuple[str, str], frozenset[LockNode]] = {}
        # (src, dst) -> (mod, ast node, human site description)
        self.edges: dict[tuple[LockNode, LockNode], tuple] = {}

    def model(self, name: str) -> ClassModel | None:
        cls = self.classes.get(name)
        if cls is None:
            return None
        if name not in self.merged:
            self.merged[name] = merged_class(self.program, cls)
        return self.merged[name]

    # -- transitive acquired-lock sets -----------------------------------

    def method_locks(self, cls_name: str, meth: str,
                     _stack: frozenset = frozenset()) -> frozenset[LockNode]:
        """Every lock `ClassName.meth` may acquire, transitively through
        same-class and typed-attribute calls.

        Only TOP-LEVEL results are memoized: a set computed inside a
        non-empty recursion stack may be truncated by the cycle guard
        (a mutually-recursive callee's back-edge contributes nothing),
        and caching that under-approximation would make cycle detection
        depend on which edge site happened to ask first. The top-level
        result is a sound fixpoint for its own root — anything
        reachable through a truncated back-edge is also reachable from
        the root directly."""
        key = (cls_name, meth)
        if key in self._locks_memo:
            return self._locks_memo[key]
        if key in _stack:
            return frozenset()
        cls = self.model(cls_name)
        if cls is None or meth not in cls.methods:
            return frozenset()
        out: set[LockNode] = set()
        for node in ast.walk(cls.methods[meth]):
            out |= self._locks_of_node(cls.mod, cls, node, _stack | {key})
        result = frozenset(out)
        if not _stack:
            self._locks_memo[key] = result
        return result

    def function_locks(self, mod: ModuleInfo, fn_name: str,
                       _stack: frozenset = frozenset()) -> frozenset[LockNode]:
        """Every lock a MODULE-LEVEL function may acquire: module locks
        plus transitive same-module function calls (memoization policy
        mirrors method_locks)."""
        key = (mod.path, fn_name)
        if key in self._locks_memo:
            return self._locks_memo[key]
        if key in _stack:
            return frozenset()
        fn = module_model(mod).functions.get(fn_name)
        if fn is None:
            return frozenset()
        out: set[LockNode] = set()
        for node in ast.walk(fn):
            out |= self._locks_of_node(mod, None, node, _stack | {key})
        result = frozenset(out)
        if not _stack:
            self._locks_memo[key] = result
        return result

    def _acquired_node(self, mod: ModuleInfo, cls: ClassModel | None,
                       expr: ast.AST) -> LockNode | None:
        """Lock node a with-target / acquire-receiver names: an
        instance lock of `cls`, or a module-level lock of `mod`."""
        if cls is not None:
            attr = _self_attr(expr)
            if attr is not None and attr in cls.lock_attrs:
                return (cls.name, cls.canon(attr))
        if isinstance(expr, ast.Name) and \
                expr.id in module_model(mod).module_locks:
            return (mod.path, expr.id)
        return None

    def _callee_locks(self, mod: ModuleInfo, cls: ClassModel | None,
                      call: ast.Call, stack: frozenset) -> frozenset[LockNode]:
        """Transitive lock set of a resolvable callee: a same-class /
        typed-attribute method, or a same-module function by bare name."""
        if cls is not None:
            callee = self._resolve_call(cls, call)
            if callee is not None:
                return self.method_locks(*callee, _stack=stack)
        if isinstance(call.func, ast.Name) and \
                call.func.id in module_model(mod).functions:
            return self.function_locks(mod, call.func.id, _stack=stack)
        return frozenset()

    def _locks_of_node(self, mod: ModuleInfo, cls: ClassModel | None,
                       node: ast.AST, stack: frozenset) -> set[LockNode]:
        out: set[LockNode] = set()
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                lock = self._acquired_node(mod, cls, item.context_expr)
                if lock is not None:
                    out.add(lock)
        elif isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "acquire" and is_blocking_acquire(node):
                lock = self._acquired_node(mod, cls, node.func.value)
                if lock is not None:
                    out.add(lock)
            out |= self._callee_locks(mod, cls, node, stack)
        return out

    def _resolve_call(self, cls: ClassModel,
                      call: ast.Call) -> tuple[str, str] | None:
        """-> (class_name, method) for `self.m()` and typed `self.x.m()`
        calls; None for anything the program can't pin down."""
        fn = call.func
        if not isinstance(fn, ast.Attribute):
            return None
        if isinstance(fn.value, ast.Name) and fn.value.id in ("self", "cls"):
            if fn.attr in cls.methods:
                return (cls.name, fn.attr)
            return None
        attr = _self_attr(fn.value)
        if attr is not None:
            target = cls.typed_attrs.get(attr)
            if target is not None and target in self.classes:
                return (target, fn.attr)
        return None

    # -- edge collection --------------------------------------------------

    def _add_edges(self, mod: ModuleInfo, site: ast.AST,
                   held: tuple[LockNode, ...], acquired) -> None:
        for dst in (acquired if isinstance(acquired, (set, frozenset))
                    else (acquired,)):
            for src in held:
                if src != dst and (src, dst) not in self.edges:
                    # No line numbers in the site string: it feeds the
                    # finding MESSAGE, and Finding.fingerprint() hashes
                    # the message — the id must survive line shifts.
                    # The finding's own `line` field carries the number.
                    where = (f"{mod.path} in "
                             f"{mod.context_of(site) or '<module>'}")
                    self.edges[(src, dst)] = (mod, site, where)

    def walk_class(self, cls: ClassModel) -> None:
        walker = _EdgeWalker(self, cls.mod, cls)
        for meth in (m for m in cls.node.body
                     if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))):
            walker.walk_body(meth.body, ())

    def walk_module_functions(self, mod: ModuleInfo) -> None:
        """Module-level functions acquire module locks (native.py's
        _lib_lock, codec.py's _flag_lock) — their nestings are edges of
        the same global graph."""
        walker = _EdgeWalker(self, mod, None)
        for fn in module_model(mod).functions.values():
            walker.walk_body(fn.body, ())


class _EdgeWalker(HeldWalker):
    """Edge collection over the shared held-lock walk (_locks.HeldWalker
    owns with-scoping, explicit acquire/release tracking in EVERY
    statement list, and the nested-def/lambda rules)."""

    def __init__(self, analysis: _Analysis, mod: ModuleInfo,
                 cls: ClassModel | None):
        self.analysis = analysis
        self.mod = mod
        self.cls = cls

    def lock_of(self, expr: ast.AST) -> LockNode | None:
        return self.analysis._acquired_node(self.mod, self.cls, expr)

    def handle_with_acquired(self, item_expr: ast.AST, lock: LockNode,
                             held_before: tuple) -> None:
        self.analysis._add_edges(self.mod, item_expr, held_before, lock)

    def handle_node(self, node: ast.AST, held: tuple) -> None:
        if not (isinstance(node, ast.Call) and held):
            return
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "acquire" and is_blocking_acquire(node):
            lock = self.lock_of(node.func.value)
            if lock is not None:
                self.analysis._add_edges(self.mod, node, held, lock)
        locks = self.analysis._callee_locks(self.mod, self.cls, node,
                                            frozenset())
        if locks:
            self.analysis._add_edges(self.mod, node, held, locks)


def _sccs(nodes, edges) -> list[list[LockNode]]:
    """Tarjan strongly-connected components (iterative)."""
    adj: dict[LockNode, list[LockNode]] = {n: [] for n in nodes}
    for (src, dst) in edges:
        adj.setdefault(src, []).append(dst)
        adj.setdefault(dst, [])
    index: dict[LockNode, int] = {}
    low: dict[LockNode, int] = {}
    on_stack: set[LockNode] = set()
    stack: list[LockNode] = []
    out: list[list[LockNode]] = []
    counter = [0]

    for root in list(adj):
        if root in index:
            continue
        work = [(root, iter(adj[root]))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(adj[w])))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    out.append(comp)
    return out


def build_analysis(program: Program) -> _Analysis:
    """The populated whole-program analysis (nodes + edges). Shared by
    `check` and the runtime reconciler (`tools/drlint/rt/reconcile.py`),
    which diffs OBSERVED acquisition edges against `analysis.edges` —
    one edge prover for both halves of the contract."""
    analysis = _Analysis(program)
    for mod in program.modules:
        analysis.walk_module_functions(mod)
        for cls in module_model(mod).classes.values():
            # Use the inheritance-merged view for attr/typed resolution
            # while walking the class's OWN method bodies.
            merged = analysis.model(cls.name) or cls
            analysis.walk_class(merged if merged.node is cls.node else cls)
    return analysis


def check(program: Program) -> list[Finding]:
    analysis = build_analysis(program)
    edges = analysis.edges
    nodes = {n for e in edges for n in e}
    findings: list[Finding] = []
    for comp in _sccs(nodes, edges):
        comp_set = set(comp)
        cyc_edges = [(e, edges[e]) for e in edges
                     if e[0] in comp_set and e[1] in comp_set]
        cyc_edges.sort(key=lambda item: item[1][2])
        order = " ; ".join(f"{_fmt(src)} -> {_fmt(dst)} at {where}"
                           for (src, dst), (_m, _n, where) in cyc_edges)
        mod, site, _where = cyc_edges[0][1]
        findings.append(mod.finding(
            RULE, site,
            f"lock-order cycle between {', '.join(sorted(map(_fmt, comp)))} "
            f"(potential deadlock): {order}"))
    return findings
