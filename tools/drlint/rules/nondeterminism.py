"""nondeterminism: no module-level RNG in library code.

Reproducibility across the distributed topology requires every random
stream to be owned and seeded: JAX keys threaded explicitly, numpy via
per-object `np.random.RandomState(seed)` / `default_rng(seed)`. The
module-level `np.random.*` / stdlib `random.*` functions share ONE
process-global state — any thread (a transport handler, the prefetch
worker, a metrics pump) that touches it perturbs every other consumer's
stream, so runs stop replaying the moment thread timing shifts.

Flags:
- calls through the global numpy RNG (`np.random.uniform(...)`) — the
  seeded constructors (`RandomState`, `default_rng`, `Generator`, ...)
  are the fix, not a violation;
- the global RNG object used as a *value* (`rng = rng or np.random`) —
  it aliases the same shared state through a polite name;
- stdlib `random.*` calls (except constructing `random.Random(seed)` /
  `random.SystemRandom()` instances).
"""

from __future__ import annotations

import ast

from tools.drlint.core import Finding, ModuleInfo

RULE = "nondeterminism"

_SEEDED = {"RandomState", "Generator", "default_rng", "SeedSequence",
           "PCG64", "Philox", "MT19937", "BitGenerator"}
_STDLIB_OK = {"Random", "SystemRandom"}


def check(mod: ModuleInfo) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            chain = mod.resolve_chain(node.func)
            if chain is None:
                continue
            if chain.startswith("numpy.random.") and \
                    chain.rsplit(".", 1)[-1] not in _SEEDED:
                findings.append(mod.finding(
                    RULE, node,
                    f"`{chain}` draws from the process-global numpy RNG — "
                    f"use a seeded np.random.RandomState/default_rng owned "
                    f"by the caller"))
            elif chain.startswith("random.") and \
                    chain.rsplit(".", 1)[-1] not in _STDLIB_OK:
                # resolve_chain roots only at real imports, so this
                # catches `import random as r; r.uniform()` and skips
                # local variables that happen to be named `random`.
                findings.append(mod.finding(
                    RULE, node,
                    f"stdlib `{chain}` uses the process-global RNG — seed "
                    f"a random.Random(seed) instance instead"))
        elif isinstance(node, ast.Attribute):
            # The bare `np.random` object as a value (`rng or np.random`):
            # parent-Attribute cases (np.random.X) are handled above.
            if mod.resolve_chain(node) == "numpy.random" and \
                    not isinstance(mod.parents.get(node), ast.Attribute):
                findings.append(mod.finding(
                    RULE, node,
                    "the global `np.random` module used as an RNG object — "
                    "pass a seeded np.random.RandomState/default_rng"))
    return findings
