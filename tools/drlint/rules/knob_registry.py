"""knob-registry: every DRL_* gate registered, documented, and alive.

The repo steers ~60 behavior gates through `DRL_*` environment
variables; PRs keep adding them, and an unregistered gate is invisible
to the docs, the launcher, and the next session. The contract, with
`tools/drlint/knobs.py` as the single source of truth:

- any `DRL_*` string literal in linted source must name a registered
  knob (reads, `os.environ` exports to children, and monkeypatches all
  couple to the knob's contract equally) — typos in gate names fail
  lint instead of silently disabling a fast path;
- the `docs/performance.md` knob table must be byte-identical to the
  registry-generated block (`python -m tools.drlint.knobs --write`
  regenerates) — docs drift is a lint failure, reported once per run
  anchored at the registry module;
- a registered knob whose owner module is part of the linted program
  but is never referenced there is STALE — the registry must shrink
  with the code it describes.

The docs-drift leg is skipped when docs/performance.md does not exist
next to the linted tree (fixture programs in tmp dirs).
"""

from __future__ import annotations

import os

from tools.drlint.core import Finding, Program

RULE = "knob-registry"


def check(program: Program) -> list[Finding]:
    # Lazy: importing the registry at rules-package import time would
    # pre-load tools.drlint.knobs into sys.modules and make the
    # documented `python -m tools.drlint.knobs` CLI warn about (and
    # re-execute) its own module.
    from tools.drlint import knobs

    findings: list[Finding] = []
    referenced: dict[str, bool] = {}
    owner_mods: set[str] = set()
    for mod in program.modules:
        if mod.path in knobs.SCAN_EXCLUDE:
            # The registry's own entries (and the linter test suite's
            # fake fixture names) are not knob references — counting
            # them would make every registered knob look "referenced"
            # whenever knobs.py is in the lint set, hiding stale
            # entries. Same exclusion set as knobs.scan_tree.
            continue
        owner_mods.add(mod.path)
        # One scanner definition for the whole linter (knobs.knob_nodes)
        # so this pass and the `knobs --check` round-trip can never
        # disagree about what counts as a knob reference.
        for name, node in knobs.knob_nodes(mod.tree):
            referenced[name] = True
            if name not in knobs.KNOBS:
                findings.append(mod.finding(
                    RULE, node,
                    f"unregistered knob {name}: add it to "
                    f"tools/drlint/knobs.py (type/default/owner/doc) and "
                    f"regenerate the docs table, or fix the typo"))
    # Stale entries: the owner module is in this program but nothing in
    # the program references the knob any more. Owners outside the
    # linted set (scripts/tests gates) are judged by the knobs CLI
    # round-trip, not here.
    for name, knob in knobs.KNOBS.items():
        if knob.owner in owner_mods and name not in referenced:
            owner = program.by_path[knob.owner]
            findings.append(owner.finding(
                RULE, owner.tree,
                f"stale registry entry {name}: owner {knob.owner} is "
                f"linted but nothing references the knob — remove it "
                f"from tools/drlint/knobs.py and the docs table"))
    # Docs drift: one finding per run, only when the real docs file
    # exists (the gate tree; fixture programs in tmp dirs skip it).
    if os.path.exists(knobs.DOCS_PATH) and any(
            m.path.startswith("distributed_reinforcement_learning_tpu/")
            for m in program.modules):
        try:
            with open(knobs.DOCS_PATH, encoding="utf-8") as f:
                drift = knobs.docs_drift(f.read())
        except OSError as e:
            drift = f"cannot read docs/performance.md: {e}"
        if drift:
            findings.append(Finding(
                rule=RULE, path="docs/performance.md", line=1,
                message=drift, context=""))
    return findings
