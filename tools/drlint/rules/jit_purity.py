"""jit-purity: no host side effects inside traced functions.

The on-device story (PAPER.md; Podracer's hot-loop ban on host
round-trips) only holds if jitted/scanned code is pure: a `time.time()`
or `print` inside a traced function runs once at trace time — silently
wrong — and module-level RNG inside a trace bakes one draw into the
compiled graph forever. Flags, inside any traced function (see
rules/_traced.py for how "traced" is decided):

- host clock reads (`time.time/perf_counter/monotonic/...`)
- builtin `print` (use `jax.debug.print`, which is trace-legal)
- stdlib `random.*` and `numpy.random.*` calls (thread JAX PRNG keys)
- `global` statements (trace-time global mutation)

Host calls wrapped in `jax.debug.*` / `io_callback` / `pure_callback`
are exempt: that machinery exists precisely to host-execute them.
"""

from __future__ import annotations

import ast

from tools.drlint.core import Finding, ModuleInfo
from tools.drlint.rules._traced import is_callback_wrapped, traced_roots

RULE = "jit-purity"

_CLOCKS = {
    "time.time", "time.time_ns", "time.perf_counter", "time.perf_counter_ns",
    "time.monotonic", "time.monotonic_ns", "time.process_time", "time.sleep",
}
# numpy.random constructors that *return seeded generators* are fine to
# call even at trace time setup; everything else is a hidden host draw.
_SEEDED_CTORS = {"RandomState", "Generator", "default_rng", "SeedSequence",
                 "PCG64", "Philox", "MT19937"}


def _check_call(mod: ModuleInfo, node: ast.Call) -> Finding | None:
    if isinstance(node.func, ast.Name) and node.func.id == "print":
        return mod.finding(RULE, node,
                           "print() inside traced code runs at trace time "
                           "only — use jax.debug.print")
    # resolve_chain only resolves through real imports (aliases
    # included), so `import time as _t; _t.time()` is caught and a
    # local variable named `time` is not.
    chain = mod.resolve_chain(node.func)
    if chain is None:
        return None
    if chain in _CLOCKS:
        return mod.finding(RULE, node,
                           f"host clock `{chain}` inside traced code is "
                           f"evaluated once at trace time")
    if chain.startswith("numpy.random.") and \
            chain.rsplit(".", 1)[-1] not in _SEEDED_CTORS:
        return mod.finding(RULE, node,
                           f"`{chain}` inside traced code bakes one host "
                           f"draw into the compiled graph — thread a JAX "
                           f"PRNG key instead")
    if chain.startswith("random."):
        return mod.finding(RULE, node,
                           f"stdlib `{chain}` inside traced code — thread "
                           f"a JAX PRNG key instead")
    return None


def check(mod: ModuleInfo) -> list[Finding]:
    roots, _ = traced_roots(mod)
    findings: list[Finding] = []
    seen: set[tuple[int, int]] = set()  # roots may nest (decorated + called)
    for root in roots:
        body = root.body if isinstance(root.body, list) else [root.body]
        for stmt in body:
            for node in ast.walk(stmt):
                pos = (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))
                if pos in seen:
                    continue
                f = None
                if isinstance(node, ast.Call):
                    f = _check_call(mod, node)
                elif isinstance(node, ast.Global):
                    f = mod.finding(RULE, node,
                                    "`global` inside traced code mutates "
                                    "host state at trace time")
                if f is not None and not is_callback_wrapped(mod, node):
                    seen.add(pos)
                    findings.append(f)
    return findings
