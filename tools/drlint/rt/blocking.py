"""Blocking-call hooks: the dynamic half of blocking-under-lock.

The static pass flags blocking calls it can lexically place under a
lock; this module catches the ones it can't — any socket/subprocess/
shared-memory/long-sleep operation executed while the CURRENT THREAD
holds a sanitized lock, regardless of how many call hops separate the
``with`` from the syscall. The hook set mirrors the static
classifier's vocabulary (rules/blocking_under_lock.py
``_classify_call``) exactly, so a suppression that silences one
silences the other:

- ``socket.create_connection`` and the socket method set
  connect/accept/recv/recv_into/recvfrom/sendall/sendmsg (wrapped on
  the Python ``socket.socket`` class, shadowing the inherited C
  implementations);
- ``subprocess.Popen`` construction (``run``/``call``/``check_output``
  all route through it) and ``os.system``;
- ``time.sleep`` at or above the static SLEEP_THRESHOLD_S;
- ``multiprocessing.shared_memory.SharedMemory`` attach and
  ``.unlink()``.

Every wrapper is a no-op fast path when the thread holds nothing.
"""

from __future__ import annotations

import functools
import os
import socket
import subprocess
import time

from multiprocessing import shared_memory

import threading as _threading

from tools.drlint.rt import sanitizer as _san_mod

_SOCKET_METHODS = ("connect", "accept", "recv", "recv_into", "recvfrom",
                   "sendall", "sendmsg")

_installed = False

# Re-entrancy guard: socket.create_connection internally calls
# sock.connect() — one blocking call must yield ONE finding, reported
# at the outermost wrapped entry point.
_tl = _threading.local()


def _wrap(orig, what: str):
    @functools.wraps(orig)
    def wrapper(*args, **kwargs):
        if getattr(_tl, "depth", 0):
            return orig(*args, **kwargs)
        san = _san_mod.get()
        if san is not None and san.held():
            san.on_blocking_call(what)
        _tl.depth = 1
        try:
            return orig(*args, **kwargs)
        finally:
            _tl.depth = 0
    wrapper.__wrapped_by_drlint_rt__ = True
    return wrapper


def _wrap_sleep(orig):
    @functools.wraps(orig)
    def wrapper(secs):
        san = _san_mod.get()
        if not getattr(_tl, "depth", 0) and san is not None and \
                san.held() and secs >= _san_mod.SLEEP_THRESHOLD_S:
            san.on_blocking_call(f"time.sleep({secs:g})")
        return orig(secs)
    wrapper.__wrapped_by_drlint_rt__ = True
    return wrapper


def install_blocking_hooks() -> None:
    global _installed
    if _installed:
        return
    _installed = True

    socket.create_connection = _wrap(socket.create_connection,
                                     "socket.create_connection")
    for meth in _SOCKET_METHODS:
        orig = getattr(socket.socket, meth)
        setattr(socket.socket, meth, _wrap(orig, f"socket .{meth}()"))

    subprocess.Popen.__init__ = _wrap(subprocess.Popen.__init__,
                                      "subprocess.Popen(...)")
    os.system = _wrap(os.system, "os.system")
    time.sleep = _wrap_sleep(time.sleep)

    shared_memory.SharedMemory.__init__ = _wrap(
        shared_memory.SharedMemory.__init__,
        "shared-memory attach (SharedMemory(...))")
    shared_memory.SharedMemory.unlink = _wrap(
        shared_memory.SharedMemory.unlink, "shared-memory .unlink()")
