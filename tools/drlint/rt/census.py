"""Leak census: the runtime twin of the lifecycle passes.

Factory hooks register every thread, SharedMemory segment, and socket
whose creation runs through PACKAGE code (innermost repo frame inside
``distributed_reinforcement_learning_tpu/`` — or a
``DRL_SANITIZE_SCOPE`` dir, the planted-fixture opt-in; resources
created directly by tests or stdlib internals are out of scope, same
rule as the guardedby checker). At process exit — and on demand, per
test, via :func:`report` from the sanitize harness — the census walks
its registries and emits findings through the ordinary
``Sanitizer.finding`` path (same JSONL artifact, same SARIF-lite
fingerprints, same suppression comments — aliased to the static
``thread-lifecycle``/``resource-lifecycle`` ids):

- ``rt-thread-leak`` — a tracked thread still alive past its owner's
  teardown window (at interpreter exit, CPython has already joined
  non-daemon threads, so anything alive here is a daemon that outlived
  every close());
- ``rt-shm-leak`` — a segment this process CREATED and never unlinked
  (the creator-pid contract: the launcher's reaper is a crash
  backstop, not a release path);
- ``rt-shm-attach-unlink`` — fired LIVE when an attach-side handle
  calls ``unlink()`` (the contract violation the static pass proves
  lexically, observed empirically);
- ``rt-socket-leak`` — a tracked socket still open (``fileno() != -1``)
  at exit.

Each registry also aggregates into ``kind: "lifecycle"`` summary
records (resource / owner class / creation site / started vs ended
counts) — the observed spawn/join and create/unlink pairs
``--reconcile`` diffs against the static thread/resource models.

Gate: ``DRL_SANITIZE_CENSUS=0`` disables the hooks (census is on by
default whenever ``DRL_SANITIZE=1``).
"""

from __future__ import annotations

import _thread
import atexit
import functools
import os
import socket
import sys
import threading
import weakref

from multiprocessing import shared_memory

from tools.drlint.core import _REPO_ROOT, repo_rel
from tools.drlint.rt import sanitizer as _san_mod
from tools.drlint.rt.sanitizer import (
    _defining_class,
    _in_repo,
    _is_rt_frame,
    _scope_dirs,
)

_PKG_ROOT = os.path.join(_REPO_ROOT, "distributed_reinforcement_learning_tpu")

_installed = False
_state = _thread.allocate_lock()  # raw: never instrumented

# Registries. Weakrefs only — the census must never extend a leaked
# object's lifetime (that would turn a report into a cause).
_threads: list[dict] = []   # {ref, site, frames, owner, name, daemon, joined}
_sockets: list[dict] = []   # {ref, site, frames, owner}
_segments: dict[str, dict] = {}  # seg name -> {creator info, counts}

_tl = threading.local()  # re-entrancy guard for the __init__ wrappers


def enabled() -> bool:
    return os.environ.get("DRL_SANITIZE_CENSUS", "1") != "0"


_EXECUTOR_FRAG = os.sep + os.path.join("concurrent", "futures") + os.sep


def _creation_site():
    """(in_scope, 'repo-rel:line', owner class, frames) for the
    innermost non-rt/non-threading caller frame. In scope = package
    code or a DRL_SANITIZE_SCOPE dir — the census tracks resources the
    RUNTIME acquires, not ones tests poke into being directly. The
    owner class is resolved by walking OUTWARD to the first in-scope
    frame with a defining class, so an acquisition routed through a
    module-level helper (``create_or_reclaim_shm``) still attributes to
    the class whose method called it — the name the static models use."""
    f = sys._getframe(2)
    frames: list[tuple[str, int, str]] = []
    site = None
    site_scoped = False
    owner = None
    while f is not None and len(frames) < 25:
        path = f.f_code.co_filename
        if _EXECUTOR_FRAG in path:
            # Executor-spawned worker: the pool owns its threads
            # (shutdown() joins them) — out of census scope.
            return False, "?", None, frames
        if not _is_rt_frame(path) and not path.endswith("threading.py") \
                and not path.endswith("weakref.py"):
            frames.append((path, f.f_lineno, f.f_code.co_name))
            if _in_repo(path):
                scoped = path.startswith(_PKG_ROOT + os.sep) or \
                    any(path.startswith(d + os.sep) for d in _scope_dirs())
                if site is None:
                    site = f"{repo_rel(path)}:{f.f_lineno}"
                    site_scoped = scoped
                if owner is None and scoped:
                    owner = _defining_class(f)
        f = f.f_back
    if site is None or not site_scoped:
        return False, "?", None, frames
    return True, site, owner, frames


# -- thread hooks -----------------------------------------------------------

def _wrap_thread_init(orig):
    @functools.wraps(orig)
    def wrapper(self, *args, **kwargs):
        orig(self, *args, **kwargs)
        if getattr(_tl, "depth", 0):
            return
        _tl.depth = 1
        try:
            in_scope, site, owner, frames = _creation_site()
            if not in_scope:
                return
            meta = {"ref": weakref.ref(self), "site": site,
                    "frames": frames, "owner": owner,
                    "name": getattr(self, "name", "?"),
                    "daemon": bool(getattr(self, "daemon", False)),
                    "joined": False}
            self._drlint_census = meta
            with _state:
                _threads.append(meta)
        finally:
            _tl.depth = 0
    wrapper.__wrapped_by_drlint_rt__ = True
    return wrapper


def _wrap_thread_join(orig):
    @functools.wraps(orig)
    def wrapper(self, timeout=None):
        orig(self, timeout)
        meta = getattr(self, "_drlint_census", None)
        if meta is not None and not self.is_alive():
            meta["joined"] = True
    wrapper.__wrapped_by_drlint_rt__ = True
    return wrapper


# -- shared-memory hooks ----------------------------------------------------

def _wrap_shm_init(orig):
    @functools.wraps(orig)
    def wrapper(self, *args, **kwargs):
        orig(self, *args, **kwargs)
        if getattr(_tl, "depth", 0):
            return
        _tl.depth = 1
        try:
            create = bool(kwargs.get("create", False)) or \
                (len(args) >= 2 and bool(args[1]))
            in_scope, site, owner, frames = _creation_site()
            if not in_scope:
                return
            name = getattr(self, "name", None) or "?"
            self._drlint_census = {"name": name, "create": create,
                                   "owner": owner}
            with _state:
                seg = _segments.setdefault(name, {
                    "created": False, "site": site, "frames": frames,
                    "owner": owner, "attaches": 0, "unlinked": False,
                    "closes": 0})
                if create:
                    # Creation wins the attribution: the leak (a segment
                    # left in /dev/shm) belongs to the creator.
                    seg.update(created=True, site=site, frames=frames,
                               owner=owner)
                else:
                    seg["attaches"] += 1
        finally:
            _tl.depth = 0
    wrapper.__wrapped_by_drlint_rt__ = True
    return wrapper


def _wrap_shm_close(orig):
    @functools.wraps(orig)
    def wrapper(self):
        meta = getattr(self, "_drlint_census", None)
        if meta is not None:
            with _state:
                seg = _segments.get(meta["name"])
                if seg is not None:
                    seg["closes"] += 1
        return orig(self)
    wrapper.__wrapped_by_drlint_rt__ = True
    return wrapper


def _wrap_shm_unlink(orig):
    @functools.wraps(orig)
    def wrapper(self):
        meta = getattr(self, "_drlint_census", None)
        if meta is not None:
            with _state:
                seg = _segments.get(meta["name"])
                if seg is not None:
                    seg["unlinked"] = True
            if not meta["create"]:
                san = _san_mod.get()
                if san is not None:
                    san.finding(
                        "rt-shm-attach-unlink",
                        f"attach-side unlink of shm segment "
                        f"'{meta['name']}' — only the creator may unlink "
                        f"(creator-pid contract)",
                        _san_mod._stack_frames())
        return orig(self)
    wrapper.__wrapped_by_drlint_rt__ = True
    return wrapper


# -- socket hooks -----------------------------------------------------------

def _wrap_socket_init(orig):
    @functools.wraps(orig)
    def wrapper(self, *args, **kwargs):
        orig(self, *args, **kwargs)
        if getattr(_tl, "depth", 0):
            return
        _tl.depth = 1
        try:
            in_scope, site, owner, frames = _creation_site()
            if not in_scope:
                return
            with _state:
                _sockets.append({"ref": weakref.ref(self), "site": site,
                                 "frames": frames, "owner": owner})
        finally:
            _tl.depth = 0
    wrapper.__wrapped_by_drlint_rt__ = True
    return wrapper


# -- the census report ------------------------------------------------------

def _owner_label(owner: str | None) -> str:
    return owner if owner else "<module>"


def report(final: bool = True) -> int:
    """Walk the registries, emit leak findings + lifecycle summaries.
    Returns the number of leaks found. Called at interpreter exit
    (after CPython joined non-daemon threads — anything alive is a
    daemon that outlived its owner's close) and per-test by the
    sanitize harness with final=False (no lifecycle records, keeps
    counting)."""
    san = _san_mod.get()
    if san is None:
        return 0
    leaks = 0
    me = threading.current_thread()
    with _state:
        threads = list(_threads)
        sockets = list(_sockets)
        segments = {k: dict(v) for k, v in _segments.items()}
    for meta in threads:
        t = meta["ref"]()
        if t is None or t is me or not t.is_alive():
            continue
        leaks += 1
        san.finding(
            "rt-thread-leak",
            f"thread '{meta['name']}' (owner "
            f"{_owner_label(meta['owner'])}, started at {meta['site']}) "
            f"still alive past owner close"
            + (" at process exit" if final else ""),
            meta["frames"])
    for name, seg in segments.items():
        if seg["created"] and not seg["unlinked"]:
            leaks += 1
            san.finding(
                "rt-shm-leak",
                f"shm segment '{name}' created by "
                f"{_owner_label(seg['owner'])} at {seg['site']} was "
                f"never unlinked by its creator",
                seg["frames"])
    for meta in sockets:
        s = meta["ref"]()
        open_now = False
        try:
            open_now = s is not None and s.fileno() != -1
        except OSError:
            open_now = False
        if not open_now:
            continue
        leaks += 1
        san.finding(
            "rt-socket-leak",
            f"socket opened by {_owner_label(meta['owner'])} at "
            f"{meta['site']} never closed",
            meta["frames"])
    if final:
        _emit_lifecycle(san, threads, sockets, segments)
    return leaks


def _emit_lifecycle(san, threads, sockets, segments) -> None:
    """Aggregate per (resource, owner, site): observed start/end pairs
    for --reconcile's lifecycle diff."""
    agg: dict[tuple[str, str, str], dict] = {}
    for meta in threads:
        key = ("thread", _owner_label(meta["owner"]), meta["site"])
        a = agg.setdefault(key, {"n": 0, "ended": 0, "joined": 0})
        a["n"] += 1
        t = meta["ref"]()
        if t is None or not t.is_alive():
            a["ended"] += 1
        if meta["joined"]:
            a["joined"] += 1
    for meta in sockets:
        key = ("socket", _owner_label(meta["owner"]), meta["site"])
        a = agg.setdefault(key, {"n": 0, "ended": 0})
        a["n"] += 1
        s = meta["ref"]()
        try:
            if s is None or s.fileno() == -1:
                a["ended"] += 1
        except OSError:
            a["ended"] += 1
    for name, seg in segments.items():
        key = ("shm", _owner_label(seg["owner"]), seg["site"])
        a = agg.setdefault(key, {"n": 0, "ended": 0, "attaches": 0})
        a["n"] += 1
        if seg["unlinked"] or not seg["created"]:
            a["ended"] += 1
        a["attaches"] += seg["attaches"]
    for (res, owner, site), a in sorted(agg.items()):
        san._emit({"kind": "lifecycle", "res": res, "owner": owner,
                   "site": site, **a})


def install_census_hooks() -> None:
    global _installed
    if _installed or not enabled():
        return
    _installed = True
    threading.Thread.__init__ = _wrap_thread_init(threading.Thread.__init__)
    threading.Thread.join = _wrap_thread_join(threading.Thread.join)
    shared_memory.SharedMemory.__init__ = _wrap_shm_init(
        shared_memory.SharedMemory.__init__)
    shared_memory.SharedMemory.close = _wrap_shm_close(
        shared_memory.SharedMemory.close)
    shared_memory.SharedMemory.unlink = _wrap_shm_unlink(
        shared_memory.SharedMemory.unlink)
    socket.socket.__init__ = _wrap_socket_init(socket.socket.__init__)
    # Registered AFTER the Sanitizer's own atexit flushes (activate()
    # precedes hook installs in rt.install): LIFO ordering runs the
    # census first, so its finding_count/lifecycle records still land
    # in the artifact before the final flush.
    atexit.register(report)
