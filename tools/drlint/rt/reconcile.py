"""Static↔dynamic reconciliation: diff a sanitizer artifact against the
static lock model (`python -m tools.drlint --reconcile <artifact>`).

The static model makes claims; the sanitized suites produce evidence;
this module closes the loop in both directions:

- **stale-annotation** — a committed ``_GUARDED_BY`` entry that no
  sanitized run ever exercised (no ``access`` record of that
  (class, attr) with the lock held): either dead annotation, dead
  code, or a suite gap. Waivable in ``tools/drlint/rt/waivers.py``
  with a justification.
- **model-gap** — an acquisition edge the runtime OBSERVED between two
  statically-known locks that the static lock-order graph cannot
  prove: the whole-program pass's resolution has a blind spot there
  (untyped attribute call, dynamic dispatch), which is exactly where
  an inversion could hide from lint. Waivable with justification.
- **rt finding replay** — every distinct runtime finding recorded in
  the artifact is surfaced again (deduped by fingerprint, with a
  count), so `--reconcile` is a one-stop gate for a sanitized run.
- **lifecycle diff** — the census's observed spawn/join and
  create/unlink pairs (``lifecycle`` records) against the static
  thread/resource models: an observed owner the static pass has no
  site for is a ``lifecycle-model-gap`` (resolution blind spot —
  exactly where an unjoined thread could hide from lint); a static
  owner no sanitized run ever observed is ``stale-lifecycle`` (dead
  code or a suite gap), waivable in ``LIFECYCLE_WAIVERS``. Skipped
  for pre-census artifacts (no ``lifecycle`` records).
- **waiver hygiene** — a waiver whose subject was actually observed
  (or that names an unknown entry), or whose justification is shorter
  than 10 chars, is itself a finding: the list can only shrink.

Node naming must agree between the two sides for any of this to work.
The runtime names a lock by the class that DEFINES the ``__init__``
constructing it; static edges are named by the class whose method body
was walked (which may be a subclass using an inherited lock). Both
sides are therefore normalized through ``_definer`` — the deepest
class in the inheritance chain whose OWN body assigns the attribute a
``threading`` constructor — before comparison.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field

from tools.drlint.core import Finding, ModuleInfo, Program, iter_py_files, repo_rel
from tools.drlint.rules._locks import LOCK_CTORS, _called_chain_tail, program_classes
from tools.drlint.rules.lock_discipline import _class_guards
from tools.drlint.rules.lock_order import build_analysis

_PKG = "distributed_reinforcement_learning_tpu"

STALE_RULE = "stale-annotation"
GAP_RULE = "model-gap"
WAIVER_RULE = "waiver-hygiene"
LIFE_GAP_RULE = "lifecycle-model-gap"
LIFE_STALE_RULE = "stale-lifecycle"

Node = tuple[str, str]


@dataclass
class Artifact:
    findings: list[dict] = field(default_factory=list)
    # fingerprint -> total occurrences (the sanitizer writes each
    # finding once plus a finding_count record for hot-path repeats).
    finding_counts: dict[str, int] = field(default_factory=dict)
    edges: list[dict] = field(default_factory=list)
    accesses: set[tuple[str, str]] = field(default_factory=set)
    holds: dict[str, dict] = field(default_factory=dict)
    lifecycle: list[dict] = field(default_factory=list)
    pids: set[int] = field(default_factory=set)

    @classmethod
    def load(cls, path: str) -> "Artifact":
        return cls.load_many([path])

    @classmethod
    def load_many(cls, paths: list[str]) -> "Artifact":
        """Stream any number of artifact files into ONE merged view —
        the single definition of the JSONL reading contract (torn final
        lines of SIGKILLed processes are skipped), shared with
        obs_report's Sanitizer section."""
        art = cls()
        for path in paths:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        r = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn final line of a SIGKILLed process
                    if isinstance(r, dict):
                        art.consume(r)
        return art

    def consume(self, r: dict) -> None:
        kind = r.get("kind")
        if "pid" in r:
            self.pids.add(r["pid"])
        if kind == "finding":
            self.findings.append(r)
            fp = r.get("fingerprint", "?")
            self.finding_counts[fp] = self.finding_counts.get(fp, 0) + 1
        elif kind == "finding_count":
            fp = r.get("fingerprint", "?")
            # Repeats beyond the first within ONE process: add n-1 on
            # top of the finding record already counted.
            self.finding_counts[fp] = self.finding_counts.get(fp, 0) + \
                max(int(r.get("count", 1)) - 1, 0)
        elif kind == "edge":
            self.edges.append(r)
        elif kind == "access":
            self.accesses.add((r.get("cls", ""), r.get("attr", "")))
        elif kind == "lifecycle":
            self.lifecycle.append(r)
        elif kind == "hold":
            h = self.holds.setdefault(
                r.get("site", "?"),
                {"count": 0, "total_ms": 0.0, "max_ms": 0.0})
            h["count"] += r.get("count", 0)
            h["total_ms"] += r.get("total_ms", 0.0)
            h["max_ms"] = max(h["max_ms"], r.get("max_ms", 0.0))


def build_program(paths: list[str] | None = None) -> Program:
    if paths is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        paths = [os.path.join(root, _PKG)]
    mods = []
    for fp in iter_py_files(paths):
        try:
            with open(fp, encoding="utf-8") as f:
                mods.append(ModuleInfo(f.read(), repo_rel(fp)))
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue
    return Program(mods)


def static_guards(program: Program) -> dict[tuple[str, str], tuple[ModuleInfo, ast.ClassDef]]:
    """(ClassName, attr) -> (module, class node) for every _GUARDED_BY
    entry in the program — the claims the artifact must substantiate."""
    out: dict[tuple[str, str], tuple[ModuleInfo, ast.ClassDef]] = {}
    for mod in program.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            guards = _class_guards(node)
            if not guards:
                continue
            for attr in guards:
                out.setdefault((node.name, attr), (mod, node))
    return out


def _ctor_assigns(mod: ModuleInfo, cls_node: ast.ClassDef) -> set[str]:
    """Attrs this class's OWN body assigns a threading ctor."""
    out: set[str] = set()
    for node in ast.walk(cls_node):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _called_chain_tail(mod, node.value) in LOCK_CTORS:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        out.add(tgt.attr)
    return out


class _Normalizer:
    """Maps any (owner, name) node to its canonical defining-class form
    so runtime and static edge names compare equal."""

    def __init__(self, program: Program):
        self.program = program
        self.classes = program_classes(program)
        self._ctor_memo: dict[str, set[str]] = {}
        self.module_paths = {m.path for m in program.modules}

    def _own_ctors(self, cls_name: str) -> set[str]:
        if cls_name not in self._ctor_memo:
            cls = self.classes.get(cls_name)
            self._ctor_memo[cls_name] = (
                _ctor_assigns(cls.mod, cls.node) if cls is not None else set())
        return self._ctor_memo[cls_name]

    def definer(self, cls_name: str, attr: str,
                _seen: frozenset = frozenset()) -> str:
        """Deepest ancestor whose own body constructs `attr`; falls back
        to `cls_name` when nothing in the chain provably does."""
        if cls_name in _seen:
            return cls_name
        if attr in self._own_ctors(cls_name):
            return cls_name
        cls = self.classes.get(cls_name)
        if cls is not None:
            for base in cls.bases:
                if base in self.classes and base != cls_name:
                    hit = self.definer(base, attr, _seen | {cls_name})
                    if attr in self._own_ctors(hit):
                        return hit
        return cls_name

    def canon(self, node: Node) -> Node:
        owner, name = node
        if owner in self.classes:
            cls = self.classes[owner]
            name = cls.alias.get(name, name)
            return (self.definer(owner, name), name)
        return (owner, name)

    def known(self, node: Node) -> bool:
        """Is this node's owner part of the static program? Fixture and
        test locks (owner = a tmp path or an unlinted class) are out of
        reconciliation scope."""
        owner, _name = node
        return owner in self.classes or owner in self.module_paths


def reconcile(artifact: Artifact, program: Program,
              guarded_waivers: dict | None = None,
              edge_waivers: dict | None = None,
              lifecycle_waivers: dict | None = None) -> list[Finding]:
    """The full diff -> drlint Findings (renderable/JSON-able like any
    static pass's)."""
    if guarded_waivers is None or edge_waivers is None \
            or lifecycle_waivers is None:
        from tools.drlint.rt import waivers as _w
        guarded_waivers = _w.GUARDED_WAIVERS if guarded_waivers is None \
            else guarded_waivers
        edge_waivers = _w.EDGE_WAIVERS if edge_waivers is None \
            else edge_waivers
        lifecycle_waivers = _w.LIFECYCLE_WAIVERS if lifecycle_waivers \
            is None else lifecycle_waivers
    # Always copy: entries are consumed (pop) below, and a caller-owned
    # dict — including the module-level waiver maps — must survive a
    # second reconcile() in the same process.
    guarded_waivers = dict(guarded_waivers)
    edge_waivers = dict(edge_waivers)
    lifecycle_waivers = dict(lifecycle_waivers)
    findings: list[Finding] = []
    norm = _Normalizer(program)

    # 0. Waiver justifications validated up front (before entries are
    #    consumed below) — the lint-baseline contract, same bar.
    for subj, why in [*guarded_waivers.items(), *edge_waivers.items(),
                      *lifecycle_waivers.items()]:
        if not isinstance(why, str) or len(why.strip()) < 10:
            findings.append(Finding(
                rule=WAIVER_RULE, path="tools/drlint/rt/waivers.py", line=1,
                message=f"waiver {subj} needs a real justification, "
                        f"not {why!r}", context=""))

    # 1. Runtime findings, deduped by fingerprint.
    by_fp: dict[str, dict] = {}
    for r in artifact.findings:
        by_fp.setdefault(r.get("fingerprint", "?"), r)
    for fp, r in sorted(by_fp.items()):
        n = max(artifact.finding_counts.get(fp, 1), 1)
        times = f" ({n}x)" if n > 1 else ""
        findings.append(Finding(
            rule=r.get("rule", "rt"), path=r.get("file", "?"),
            line=int(r.get("line", 0)),
            message=f"{r.get('message', '')}{times}",
            context=r.get("context", "")))

    # 2. Stale _GUARDED_BY annotations: claimed but never observed.
    claims = static_guards(program)
    observed = set(artifact.accesses)
    for (cls_name, attr), (mod, cls_node) in sorted(claims.items()):
        if (cls_name, attr) in observed:
            continue
        waiver = guarded_waivers.pop((cls_name, attr), None)
        if waiver is not None:
            continue
        findings.append(mod.finding(
            STALE_RULE, cls_node,
            f"_GUARDED_BY entry {cls_name}.{attr} was never exercised by "
            f"the sanitized run (no access with its lock held): dead "
            f"annotation, dead code, or a suite gap — fix or waive in "
            f"tools/drlint/rt/waivers.py"))

    # 3. Model gaps: observed edges the static graph cannot prove.
    analysis = build_analysis(program)
    static_edge_set = {(norm.canon(src), norm.canon(dst))
                       for (src, dst) in analysis.edges}
    seen_gaps: set[tuple[Node, Node]] = set()
    observed_edges: set[tuple[Node, Node]] = set()
    for e in artifact.edges:
        src, dst = e.get("src"), e.get("dst")
        if not src or not dst:
            continue  # unresolved runtime name: nothing to compare
        key = (norm.canon((src[0], src[1])), norm.canon((dst[0], dst[1])))
        if not (norm.known(key[0]) and norm.known(key[1])):
            continue  # fixture/test locks are out of scope
        observed_edges.add(key)
        if key in static_edge_set or key in seen_gaps:
            continue
        if edge_waivers.pop(key, None) is not None:
            seen_gaps.add(key)
            continue
        seen_gaps.add(key)
        mod = program.by_path.get(key[0][0])
        path = mod.path if mod is not None else \
            (norm.classes[key[0][0]].mod.path
             if key[0][0] in norm.classes else "?")
        line = (norm.classes[key[0][0]].node.lineno
                if key[0][0] in norm.classes else 1)
        findings.append(Finding(
            rule=GAP_RULE, path=path, line=line,
            message=(
                f"observed acquisition edge "
                f"{key[0][0]}.{key[0][1]} -> {key[1][0]}.{key[1][1]} "
                f"(at {e.get('src_site', '?')} -> {e.get('dst_site', '?')}) "
                f"is absent from the static lock-order graph: the static "
                f"model has a resolution gap here — add typing the pass "
                f"can follow, restructure, or waive in "
                f"tools/drlint/rt/waivers.py"),
            context=""))

    # 4. Waiver hygiene: what's left in the dicts was never needed; an
    #    entry consumed above but whose subject WAS observed is stale too.
    for (cls_name, attr), why in sorted(guarded_waivers.items()):
        status = ("was exercised by this run"
                  if (cls_name, attr) in observed else
                  "names no committed _GUARDED_BY entry"
                  if (cls_name, attr) not in claims else None)
        if status is None:
            continue  # valid but unexercised claim path can't happen: popped
        findings.append(Finding(
            rule=WAIVER_RULE, path="tools/drlint/rt/waivers.py", line=1,
            message=f"guarded waiver ({cls_name}, {attr}) {status} — "
                    f"remove it", context=""))
    for key, why in sorted(edge_waivers.items()):
        if key in observed_edges and key in static_edge_set:
            findings.append(Finding(
                rule=WAIVER_RULE, path="tools/drlint/rt/waivers.py", line=1,
                message=f"edge waiver {key} is provable statically — "
                        f"remove it", context=""))
        elif not (norm.known(norm.canon(tuple(key[0])))
                  and norm.known(norm.canon(tuple(key[1])))):
            # Same unknown-entry hygiene the guarded waivers get: a
            # renamed class must not leave its edge waiver rotting
            # while the edge resurfaces as a model gap under the new
            # name.
            findings.append(Finding(
                rule=WAIVER_RULE, path="tools/drlint/rt/waivers.py", line=1,
                message=f"edge waiver {key} names no statically-known "
                        f"lock owner — remove or update it", context=""))

    # 5. Lifecycle: observed spawn/create owners vs the static
    #    thread/resource models. Gated on the artifact actually carrying
    #    census records — pre-census artifacts (or DRL_SANITIZE_CENSUS=0
    #    runs) reconcile exactly as before.
    if artifact.lifecycle:
        findings.extend(_lifecycle_diff(artifact, program, norm,
                                        lifecycle_waivers))
    findings.sort(key=lambda f: (f.rule, f.path, f.line, f.message))
    return findings


def static_lifecycle(program: Program) -> dict[tuple[str, str], tuple]:
    """(ClassName, res) -> (module, class node) for every class the
    static lifecycle passes model as owning a thread / shm segment /
    socket — the claims the census's observed records must meet."""
    from tools.drlint.rules.resource_lifecycle import build_resource_model
    from tools.drlint.rules.thread_lifecycle import build_thread_model

    out: dict[tuple[str, str], tuple] = {}
    for cname, info in build_thread_model(program).items():
        out.setdefault((cname, "thread"), (info["mod"], info["cls"].node))
    for cname, info in build_resource_model(program).items():
        kinds = {k for (k, _node, _meth) in info["attrs"].values()}
        kinds.update(k for (_fn, _node, k, _name) in info["local_sites"])
        loc = (info["mod"], info["cls"].node)
        if any(k.startswith("shm") for k in kinds):
            out.setdefault((cname, "shm"), loc)
        if "socket" in kinds:
            out.setdefault((cname, "socket"), loc)
    return out


def _lifecycle_diff(artifact: Artifact, program: Program,
                    norm: _Normalizer, lifecycle_waivers: dict
                    ) -> list[Finding]:
    findings: list[Finding] = []
    static_life = static_lifecycle(program)
    observed: set[tuple[str, str]] = set()
    gap_seen: set[tuple[str, str]] = set()
    for rec in artifact.lifecycle:
        owner = rec.get("owner") or "<module>"
        res = rec.get("res", "?")
        observed.add((owner, res))
        if owner not in norm.classes:
            continue  # module-level or fixture-owned: no class model
        if (owner, res) in static_life or (owner, res) in gap_seen:
            continue
        gap_seen.add((owner, res))
        cls = norm.classes[owner]
        findings.append(cls.mod.finding(
            LIFE_GAP_RULE, cls.node,
            f"runtime observed {owner} acquiring a {res} (at "
            f"{rec.get('site', '?')}) that the static {res} lifecycle "
            f"model has no site for — the lifecycle pass has a "
            f"resolution blind spot here, exactly where an unjoined "
            f"thread or leaked segment could hide from lint"))
    for (owner, res), (mod, node) in sorted(static_life.items()):
        if (owner, res) in observed:
            continue
        if lifecycle_waivers.pop((owner, res), None) is not None:
            continue
        findings.append(mod.finding(
            LIFE_STALE_RULE, node,
            f"static lifecycle model says {owner} owns a {res} but no "
            f"sanitized run ever observed it acquire one: dead code or "
            f"a suite gap — fix or waive in tools/drlint/rt/waivers.py"))
    # Leftover-waiver hygiene, same bar as the guarded/edge lists.
    for (owner, res), _why in sorted(lifecycle_waivers.items()):
        status = ("was observed by this run"
                  if (owner, res) in observed else
                  "names no static lifecycle entry"
                  if (owner, res) not in static_life else None)
        if status is None:
            continue
        findings.append(Finding(
            rule=WAIVER_RULE, path="tools/drlint/rt/waivers.py", line=1,
            message=f"lifecycle waiver ({owner}, {res}) {status} — "
                    f"remove it", context=""))
    return findings


def main(artifact_path: str, paths: list[str] | None,
         as_json: bool = False) -> int:
    art = Artifact.load(artifact_path)
    program = build_program(paths if paths else None)
    findings = reconcile(art, program)
    claims = static_guards(program)
    exercised = sum(1 for key in claims if key in art.accesses)
    summary = {
        "findings": len(findings),
        "rt_findings": len({r.get("fingerprint") for r in art.findings}),
        "guarded_total": len(claims),
        "guarded_exercised": exercised,
        "edges_observed": len(art.edges),
        "lifecycle_observed": len(art.lifecycle),
        "processes": len(art.pids),
    }
    if as_json:
        print(json.dumps({
            "schema": "drlint-reconcile-v1",
            "findings": [f.to_json() for f in findings],
            "summary": summary,
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        import sys
        print(f"drlint --reconcile: {len(findings)} finding(s); "
              f"{exercised}/{len(claims)} _GUARDED_BY entries exercised "
              f"across {len(art.pids)} sanitized process(es)",
              file=sys.stderr)
        print(json.dumps({"drlint-reconcile": summary}))
    return 1 if findings else 0
