"""Runtime GuardedBy enforcement: `_GUARDED_BY` attrs become data
descriptors that verify the declared lock is held by the accessing
thread.

The static lock-discipline pass checks the LEXICAL form (a touch
inside `with self._lock:`); this module checks the TRUTH — on every
read and write of an annotated attribute, is one of the declared locks
actually owned by the current thread right now? That closes both gaps
the static pass documents: accesses through other names (a module
function touching `server.stats`) and accesses whose lock the AST
could not resolve.

Mechanics: for every class carrying a class-level ``_GUARDED_BY`` dict,
each annotated attribute is replaced by a :class:`GuardedAttr` data
descriptor. The value itself still lives in the instance ``__dict__``
under the same name (data descriptors take precedence for both get and
set, so the descriptor stays in control while ``vars(obj)`` keeps
working for pickling/copy/repr). The check resolves the declared lock
names against the instance — Condition-over-lock aliasing falls out
naturally, because a declared Condition's ``_lock`` IS the shared
sanitized mutex. The ``*_locked`` caller-holds convention needs no
special case on the happy path (the caller really does hold the lock);
the violation path exempts ``__init__``/``__del__`` frames, ``*_locked``
methods reached without the lock, and accesses whose nearest repo
frame is outside the package (tests poking internals are out of scope,
exactly like the static pass).

Classes are wrapped at import time by a ``sys.meta_path`` hook
installed under the gate, so no runtime module changes hands-on; a
retrofit pass covers anything imported before install.
"""

from __future__ import annotations

import importlib.abc
import importlib.machinery
import inspect
import sys

from tools.drlint.rt import sanitizer as _san_mod

_PKG = "distributed_reinforcement_learning_tpu"

_MISSING = object()


class GuardedAttr:
    """Data descriptor enforcing + observing one _GUARDED_BY entry.

    ``claims`` lists EVERY class in the instrumented class's MRO whose
    own ``_GUARDED_BY`` declares this attr: a subclass that re-declares
    an inherited entry (ContinuousInferenceServer over InferenceServer)
    shadows the base's descriptor, and an exercised access must credit
    both annotations or reconcile would misreport the base's as stale."""

    __slots__ = ("attr", "locks", "cls_name", "claims", "default")

    def __init__(self, attr: str, locks: tuple[str, ...], cls_name: str,
                 claims: tuple[str, ...] = (), default=_MISSING):
        self.attr = attr
        self.locks = locks
        self.cls_name = cls_name
        self.claims = claims or (cls_name,)
        self.default = default

    def _check(self, obj, write: bool) -> None:
        san = _san_mod.get()
        if san is None:
            return
        d = obj.__dict__
        found_lock = False
        for ln in self.locks:
            lk = d.get(ln)
            if lk is None:
                continue
            inner = getattr(lk, "_lock", None)  # Condition -> its mutex
            if inner is not None:
                lk = inner
            ident = getattr(lk, "owner_ident", _MISSING)
            if ident is _MISSING:
                continue  # un-sanitized lock: cannot prove either way
            found_lock = True
            if ident == _san_mod.threading.get_ident():
                for claim in self.claims:
                    san.on_guarded_ok(claim, self.attr)
                return
        if not found_lock:
            return  # locks not constructed yet (mid-__init__) or foreign
        san.on_guarded_violation(obj, self.cls_name, self.attr,
                                 self.locks, write)

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        val = obj.__dict__.get(self.attr, _MISSING)
        if val is _MISSING:
            if self.default is _MISSING:
                raise AttributeError(
                    f"{type(obj).__name__!r} object has no attribute "
                    f"{self.attr!r}")
            val = self.default
        self._check(obj, write=False)
        return val

    def __set__(self, obj, value):
        self._check(obj, write=True)
        obj.__dict__[self.attr] = value

    def __delete__(self, obj):
        self._check(obj, write=True)
        try:
            del obj.__dict__[self.attr]
        except KeyError:
            raise AttributeError(self.attr) from None


def instrument_class(cls: type) -> bool:
    """Wrap one class's own _GUARDED_BY attrs; True if instrumented."""
    guards = cls.__dict__.get("_GUARDED_BY")
    if not isinstance(guards, dict):
        return False
    if "__slots__" in cls.__dict__:
        print(f"drlint-rt: cannot guard __slots__ class {cls.__name__}",
              file=sys.stderr)
        return False
    for attr, locks in guards.items():
        if not isinstance(attr, str):
            continue
        lock_names = (locks,) if isinstance(locks, str) else tuple(locks)
        default = cls.__dict__.get(attr, _MISSING)
        if isinstance(default, GuardedAttr):
            continue  # already instrumented
        claims = tuple(
            base.__name__ for base in cls.__mro__
            if isinstance(vars(base).get("_GUARDED_BY"), dict)
            and attr in vars(base)["_GUARDED_BY"])
        setattr(cls, attr,
                GuardedAttr(attr, lock_names, cls.__name__, claims, default))
    return True


def instrument_module(module) -> int:
    n = 0
    mod_name = getattr(module, "__name__", "")
    for obj in list(vars(module).values()):
        if inspect.isclass(obj) and obj.__module__ == mod_name:
            if instrument_class(obj):
                n += 1
    return n


class _GuardLoader(importlib.abc.Loader):
    """Delegating loader: exec the real module, then wrap its classes."""

    def __init__(self, orig):
        self._orig = orig

    def create_module(self, spec):
        return self._orig.create_module(spec)

    def exec_module(self, module):
        self._orig.exec_module(module)
        instrument_module(module)

    def __getattr__(self, name):  # get_source/is_package/... for tooling
        return getattr(self._orig, name)


class _GuardFinder(importlib.abc.MetaPathFinder):
    """Routes package submodule imports through _GuardLoader."""

    def find_spec(self, fullname, path, target=None):
        if fullname != _PKG and not fullname.startswith(_PKG + "."):
            return None
        spec = importlib.machinery.PathFinder.find_spec(fullname, path)
        if spec is None or spec.loader is None:
            return None
        if isinstance(spec.loader, _GuardLoader):
            return None
        spec.loader = _GuardLoader(spec.loader)
        return spec


_FINDER: _GuardFinder | None = None


def install_guard_hook() -> None:
    global _FINDER
    if _FINDER is not None:
        return
    _FINDER = _GuardFinder()
    sys.meta_path.insert(0, _FINDER)
    # Retrofit anything already imported (install() runs at package
    # __init__ time, so normally only the package root itself exists —
    # but a lazy install via tests must still cover the tree).
    for name, module in list(sys.modules.items()):
        if module is not None and \
                (name == _PKG or name.startswith(_PKG + ".")):
            instrument_module(module)
