"""drlint-rt — runtime concurrency sanitizer (gated by ``DRL_SANITIZE=1``).

The dynamic counterpart of drlint's static concurrency passes. The
static model (53 ``_GUARDED_BY`` entries, the whole-program lock-order
graph, the blocking-under-lock catalog) is checked at lint time but
never *observed*: a wrong or incomplete annotation passes lint while
hiding a real race. Under the gate, this package instruments the live
process with three checkers and an evidence stream:

1. **Lock-order enforcement** (``rt-lock-order``) — instrumented
   Lock/RLock/Condition factories maintain per-thread held-sets,
   record every observed acquisition edge, and flag an edge that
   closes a cycle in the observed graph (both stack traces in the
   finding) or contradicts a static lock-order model supplied via
   ``DRL_SANITIZE_MODEL``.
2. **GuardedBy enforcement** (``rt-guardedby``) — ``_GUARDED_BY``
   attrs become descriptors that verify the declared lock is actually
   held by the accessing thread (honoring the ``*_locked``
   caller-holds convention and Condition-over-lock aliasing, the same
   escapes as the static pass).
3. **Blocking-under-lock watchdog** (``rt-blocking`` / ``rt-hold``) —
   socket/subprocess/shm/long-sleep calls under a held sanitized lock
   are findings; every lock release feeds a per-site hold-time
   histogram, with holds past ``DRL_SANITIZE_HOLD_MS`` flagged.
4. **Leak census** (``rt-thread-leak`` / ``rt-shm-leak`` /
   ``rt-shm-attach-unlink`` / ``rt-socket-leak``) — factory hooks
   register every thread, SharedMemory segment, and socket acquired
   through package code; the at-exit report flags threads alive past
   their owner's close, segments the creator never unlinked, attach-
   side unlinks, and sockets never closed, and streams observed
   spawn/join + create/unlink pairs as ``lifecycle`` records for
   ``--reconcile``. Disable with ``DRL_SANITIZE_CENSUS=0``.

Findings and first-seen edges/accesses stream to the JSONL artifact
named by ``DRL_SANITIZE_OUT`` (fingerprints reuse drlint's SARIF-lite
scheme); ``python -m tools.drlint --reconcile <artifact>`` then diffs
the OBSERVED behavior against the static model — a never-exercised
``_GUARDED_BY`` entry is a stale annotation, an observed edge missing
from the static graph is a model gap.

Zero overhead when the gate is off: ``install()`` is only ever called
from the package's ``__init__`` under ``DRL_SANITIZE=1``; nothing is
patched otherwise. ``install()`` must run before the package's
submodules execute their lock constructions — the package ``__init__``
seam guarantees that for normal imports.
"""

from __future__ import annotations

_installed = False


def installed() -> bool:
    return _installed


def install(out_path: str | None = None):
    """Activate the sanitizer: patch the threading ctors, register the
    GuardedBy import hook (+ retrofit), install the blocking-call
    hooks. Idempotent; returns the process Sanitizer."""
    global _installed
    from tools.drlint.rt import blocking, census, guards, locks, sanitizer

    san = sanitizer.activate(out_path=out_path)
    if not _installed:
        _installed = True
        locks.install_lock_factories()
        guards.install_guard_hook()
        blocking.install_blocking_hooks()
        # Last: the census wraps on top of blocking.py's shm wrappers,
        # and its atexit report (LIFO) must run before the sanitizer's
        # final count flush.
        census.install_census_hooks()
    return san


def get_sanitizer():
    from tools.drlint.rt import sanitizer
    return sanitizer.get()
