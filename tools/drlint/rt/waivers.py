"""Reconciliation waivers: the committed list of static-model entries
the sanitized suites are NOT expected to exercise, each with a human
justification (the `--reconcile` analog of the 10-entry lint baseline,
and like it, a list that should shrink).

A ``_GUARDED_BY`` entry proves its worth by being OBSERVED — a guarded
attribute that no sanitized suite ever touches with its lock held is
either dead annotation or dead code, and ``python -m tools.drlint
--reconcile`` flags it. Some entries are legitimately unobservable on
this container (error-path-only state, fields only touched under
chaos schedules the bounded suites don't run); they live here, keyed
``(ClassName, attr)``, value = justification (>= 10 chars, enforced by
the reconciler).

``EDGE_WAIVERS`` plays the same role for observed-edge model gaps: an
acquisition edge the runtime lawfully observes but the static
lock-order pass cannot resolve (cross-object calls through untyped
attributes). Key: ``((src_owner, src_name), (dst_owner, dst_name))``.

``LIFECYCLE_WAIVERS`` covers the census diff: a class the static
lifecycle passes model as owning a thread / shm segment / socket that
the sanitized suites legitimately never construct (gated features,
chaos-only paths). Key: ``(ClassName, res)`` with res in
``thread | shm | socket``.
"""

from __future__ import annotations

GUARDED_WAIVERS: dict[tuple[str, str], str] = {
    # Native (C++) backends are availability-dependent: make_replay /
    # NativeTrajectoryQueue fall back to the pure-python paths when the
    # in-tree lib doesn't build, so the nine concurrency suites cannot
    # pin these on every container. test_native/test_data own them.
    ("NativeTrajectoryQueue", "_pool"):
        "native-lib-only path; exercised by test_native when cpp builds",
    ("NativeTrajectoryQueue", "_pool_idx"):
        "native-lib-only path; exercised by test_native when cpp builds",
    ("NativeTrajectoryQueue", "_pool_sig"):
        "native-lib-only path; exercised by test_native when cpp builds",
    ("NativeTrajectoryQueue", "_scratch"):
        "native-lib-only path; exercised by test_native when cpp builds",
    ("NativePrioritizedReplay", "_data"):
        "native-lib-only path; exercised by test_data when cpp builds",
    ("NativePrioritizedReplay", "beta"):
        "native-lib-only path; exercised by test_data when cpp builds",
    ("_CodecCaches", "_dedup"):
        "populated only under DRL_OBS_DEDUP=1 (parked opt-in fast path, "
        "codec_verdict.json honest negative on this container)",
    ("ShardedReplayService", "updates_dropped"):
        "written only when the async priority-writeback ring overflows "
        "(latest-wins drop); the bounded suites never saturate it",
    ("RingDrainer", "_dropped"):
        "corruption-only accounting; healthy-suite rings drop nothing — "
        "the slow-marked chaos drill is the owning exercise",
    # Telemetry is off (DRL_TELEMETRY unset) in the nine concurrency
    # suites — instruments are no-ops before configure(). The maps were
    # added by ISSUE 13's guardedby-completeness pass; test_observability
    # is the owning exercise.
    ("Telemetry", "_counters"):
        "telemetry disabled in the sanitized suites; test_observability "
        "exercises the instrument maps",
    ("Telemetry", "_gauges"):
        "telemetry disabled in the sanitized suites; test_observability "
        "exercises the instrument maps",
    ("Telemetry", "_providers"):
        "telemetry disabled in the sanitized suites; test_observability "
        "exercises the instrument maps",
    ("TraceEmitter", "dropped"):
        "telemetry disabled in the sanitized suites; test_observability "
        "exercises the trace buffer",
    ("TraceEmitter", "_pending"):
        "telemetry disabled in the sanitized suites; test_observability "
        "exercises the trace buffer",
    ("TraceEmitter", "_written"):
        "telemetry disabled in the sanitized suites; test_observability "
        "exercises the trace buffer",
    ("TraceEmitter", "_file"):
        "telemetry disabled in the sanitized suites; test_observability "
        "exercises the trace buffer",
    ("TraceEmitter", "_closed"):
        "telemetry disabled in the sanitized suites; test_observability "
        "exercises the trace buffer",
    ("Telemetry", "_flush_errors"):
        "error-path-only counter (flush loop failure); telemetry is "
        "disabled in the sanitized suites anyway",
    ("Telemetry", "_provider_errors"):
        "error-path-only counter (provider callback failure); telemetry "
        "is disabled in the sanitized suites anyway",
}

EDGE_WAIVERS: dict[tuple[tuple[str, str], tuple[str, str]], str] = {
    # Layered component->leaf acquisitions the static resolver cannot
    # follow (factory-returned backends, ctor-param objects, cross-
    # module function calls). In each, the inner lock is a LEAF that
    # never calls back out of its class, so the edge cannot close a
    # cycle; the runtime cycle checker still watches the real order.
    (("ReplayShard", "_lock"), ("ArrayPrioritizedReplay", "_lock")):
        "shard wraps a make_replay backend (dynamic factory); backend "
        "lock is a leaf — its methods make no outward calls",
    (("ReplayShard", "_lock"), ("NativePrioritizedReplay", "_lock")):
        "same layered shard->backend edge with the native backend",
    (("ReplayShard", "_lock"), ("TieredStore", "_io_lock")):
        "restart() closes the old factory-returned tiered backend under "
        "the shard lock; _io_lock is a leaf (manifest write cursor + "
        "closed flag, no outward calls), so the edge cannot cycle",
    (("ReplayShard", "_lock"),
     ("distributed_reinforcement_learning_tpu/data/native.py", "_lib_lock")):
        "backend probe compiles the cpp lib exactly once under the "
        "module lock; compile makes no outward calls to runtime locks",
    (("ReplayIngestFifo", "_lock"), ("ReplayShard", "_lock")):
        "ingest fifo routes to shards passed in via ctor param (untyped "
        "for the static pass); shard lock is a leaf on this path",
    (("WeightStore", "_lock"), ("_CodecCaches", "_lock")):
        "store encodes under its lock via module-level codec functions; "
        "the codec cache lock is a leaf (pure encode/decode, no "
        "outward calls)",
}

LIFECYCLE_WAIVERS: dict[tuple[str, str], str] = {
    ("Telemetry", "thread"):
        "flush/provider loops only spawn after configure(); telemetry "
        "is disabled in the sanitized suites — test_observability owns",
    ("MetricsPump", "thread"):
        "pump spawns only under DRL_ASYNC_METRICS with a live logger; "
        "the sanitized suites run learners sync — test_observability "
        "owns the pump",
    ("DevicePrefetcher", "thread"):
        "legacy host-batch prefetcher superseded by DeviceSamplePath "
        "in the sanitized device-path suite; test_prefetch owns it",
}
