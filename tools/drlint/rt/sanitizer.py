"""drlint-rt core: shared state, artifact writer, naming, suppressions.

The runtime half of drlint's concurrency model. The static passes
(rules/lock_order.py, rules/lock_discipline.py, rules/
blocking_under_lock.py) PROVE properties of the code they can resolve;
this module OBSERVES the same properties in a live process and streams
what it sees to a JSONL artifact:

- ``finding`` records — violations, named with runtime rule ids that
  mirror the static catalog (``rt-lock-order``, ``rt-guardedby``,
  ``rt-blocking``, ``rt-hold``) and carrying the same SARIF-lite
  fingerprint scheme (sha256(rule|file|context|message)[:16]) as
  ``core.Finding`` so CI diffing treats both alike;
- ``edge`` records — every first-seen lock-acquisition edge (lock B
  acquired while A held), the raw material ``--reconcile`` diffs
  against the static lock-order graph;
- ``access`` records — every first-seen guarded-attribute access made
  WITH its declared lock held, proving the ``_GUARDED_BY`` entry is
  exercised (a committed entry with no access record is a
  stale-annotation finding at reconcile time);
- ``hold`` records — per-acquisition-site hold-time histograms,
  flushed at exit, rendered by obs_report's Sanitizer section.

Inline ``# drlint: disable=<rule>`` suppressions are honored at
runtime with the SAME file/line semantics as the static passes: a
would-be finding whose stack crosses a suppressed line (for the
matching static rule id or the rt- id) is dropped. That keeps the two
halves of the contract symmetric — a deliberately-held design
suppressed statically (the transport client's serialized exchange)
does not re-fire dynamically.

Everything here uses PRE-PATCH threading primitives (the state lock is
a raw ``_thread`` lock captured at import) so the sanitizer can never
trip over its own instrumentation.
"""

from __future__ import annotations

import _thread
import atexit
import hashlib
import json
import os
import re
import sys
import threading
import time
import traceback

from tools.drlint.core import _REPO_ROOT, parse_suppression_tokens, repo_rel

_RT_DIR = os.path.dirname(os.path.abspath(__file__))

SLEEP_THRESHOLD_S = 0.05  # same bar as the static blocking-under-lock

# Runtime rule id -> static rule ids whose suppression comments also
# silence it (the symmetric-contract table above).
SUPPRESSION_ALIASES = {
    "rt-lock-order": ("lock-order",),
    "rt-guardedby": ("lock-discipline",),
    "rt-blocking": ("blocking-under-lock",),
    "rt-hold": ("blocking-under-lock",),
    # Leak-census rules (rt/census.py): the static lifecycle passes'
    # suppressions silence their runtime twins.
    "rt-thread-leak": ("thread-lifecycle",),
    "rt-shm-leak": ("resource-lifecycle",),
    "rt-shm-attach-unlink": ("resource-lifecycle",),
    "rt-socket-leak": ("resource-lifecycle",),
}

# Same grammar as core._SUPPRESS_RE, parsed by the shared token parser
# (justification hygiene included) so the two halves never drift.
_SUPPRESS_RE = re.compile(
    r"#\s*drlint:\s*disable=\s*([a-zA-Z0-9_\-]+(?:\([^()]*\))?"
    r"(?:\s*,\s*[a-zA-Z0-9_\-]+(?:\([^()]*\))?)*)")


def _hold_threshold_ms() -> float:
    raw = os.environ.get("DRL_SANITIZE_HOLD_MS", "")
    try:
        return float(raw) if raw else 1000.0
    except ValueError:
        return 1000.0


_SCOPE: tuple[str, ...] | None = None


def _scope_dirs() -> tuple[str, ...]:
    """Extra in-scope directories (DRL_SANITIZE_SCOPE, colon-separated):
    the planted-bug fixture scripts live in pytest tmp dirs, outside the
    repo, and opt in through this. Read once per process."""
    global _SCOPE
    if _SCOPE is None:
        raw = os.environ.get("DRL_SANITIZE_SCOPE", "")
        _SCOPE = tuple(os.path.abspath(p) for p in raw.split(":") if p)
    return _SCOPE


def _in_repo(path: str) -> bool:
    if path.startswith(_REPO_ROOT + os.sep):
        return True
    return any(path.startswith(d + os.sep) or path == d
               for d in _scope_dirs())


def _is_rt_frame(path: str) -> bool:
    return path.startswith(_RT_DIR + os.sep) or path == __file__


def fingerprint(rule: str, path: str, context: str, message: str) -> str:
    """core.Finding.fingerprint, byte-identical scheme."""
    blob = "|".join((rule, path, context, message))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class _SuppressionCache:
    """Per-file `# drlint: disable=` maps, scanned lazily (the runtime
    cannot afford core.ModuleInfo's full parse per finding)."""

    def __init__(self):
        self._files: dict[str, dict[int, set[str]]] = {}

    def _scan(self, path: str) -> dict[int, set[str]]:
        out: dict[int, set[str]] = {}
        try:
            with open(path, encoding="utf-8") as f:
                lines = f.read().splitlines()
        except OSError:
            return out
        for i, line in enumerate(lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = parse_suppression_tokens(m.group(1))
            target = i + 1 if line.lstrip().startswith("#") else i
            out.setdefault(target, set()).update(rules)
        return out

    def suppressed(self, path: str, line: int, rule: str) -> bool:
        if path not in self._files:
            self._files[path] = self._scan(path)
        rules = self._files[path].get(line, ())
        if not rules:
            return False
        wanted = {rule, "all", *SUPPRESSION_ALIASES.get(rule, ())}
        return bool(wanted & set(rules))


def _stack_frames(skip_rt: bool = True, limit: int = 25) -> list[tuple[str, int, str]]:
    """(abs file, line, function) outermost-last, rt/threading frames
    dropped."""
    out: list[tuple[str, int, str]] = []
    f = sys._getframe(1)
    while f is not None and len(out) < limit:
        path = f.f_code.co_filename
        if not (skip_rt and (_is_rt_frame(path) or path.endswith("threading.py"))):
            out.append((path, f.f_lineno, f.f_code.co_name))
        f = f.f_back
    return out


def _render_stack(frames: list[tuple[str, int, str]]) -> list[str]:
    return [f"{repo_rel(p)}:{ln} in {fn}" for p, ln, fn in frames]


def _defining_class(frame) -> str | None:
    """Name of the class that DEFINES the function executing in `frame`
    (not the instance's concrete type): matches how the static model
    attributes a lock to the class whose __init__ textually creates it,
    so runtime names line up with static (ClassName, attr) nodes even
    for subclass instances."""
    obj = frame.f_locals.get("self")
    if obj is None:
        obj = frame.f_locals.get("cls")
    if obj is None:
        return None
    klass = obj if isinstance(obj, type) else type(obj)
    code = frame.f_code
    for base in getattr(klass, "__mro__", (klass,)):
        fn = vars(base).get(code.co_name)
        fn = getattr(fn, "__func__", fn)  # classmethod/staticmethod
        if getattr(fn, "__code__", None) is code:
            return base.__name__
    return klass.__name__


class Sanitizer:
    """Process-global sanitizer state. One instance per process, built
    by rt.install(); every hook (locks, guards, blocking) funnels here."""

    def __init__(self, out_path: str | None = None):
        self.out_path = out_path if out_path is not None else \
            os.environ.get("DRL_SANITIZE_OUT") or None
        self.hold_ms = _hold_threshold_ms()
        self._state = _thread.allocate_lock()  # raw: never instrumented
        self._tl = threading.local()
        self._suppr = _SuppressionCache()
        # Observed acquisition graph over live lock OBJECTS (identity,
        # not names: two locks of different instances taken in both
        # orders is not a deadlock). Strong refs keep ids stable.
        self._adj: dict[int, set[int]] = {}
        self._edge_meta: dict[tuple[int, int], dict] = {}
        self._locks_by_id: dict[int, object] = {}
        # Static lock-order edges to contradict (optional, loaded from
        # DRL_SANITIZE_MODEL — a JSON {"edges": [[[own,name],[own,name]], ..]}).
        self._static_edges: set[tuple] = set()
        self._load_static_model()
        self._seen_accesses: set[tuple[str, str]] = set()
        self._holds: dict[str, dict] = {}  # site -> histogram
        # First-seen-by-fingerprint dedup: a violation on a hot path
        # (an unguarded attr read in a drain loop) must not turn the
        # artifact into GBs of identical records — repeats only bump a
        # counter, flushed at exit as finding_count records.
        self._finding_counts: dict[str, int] = {}
        self.findings = 0
        self._wrote_meta = False
        atexit.register(self._flush_counts)
        atexit.register(self._flush_holds)

    # -- artifact ---------------------------------------------------------

    def _load_static_model(self) -> None:
        path = os.environ.get("DRL_SANITIZE_MODEL", "")
        if not path:
            return
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            for src, dst in doc.get("edges", []):
                self._static_edges.add((tuple(src), tuple(dst)))
        except (OSError, ValueError):
            pass

    def _emit(self, record: dict) -> None:
        """One JSONL line, O_APPEND single-write so concurrent sanitized
        processes (the two-process suites) interleave whole lines."""
        if self.out_path is None:
            return
        record.setdefault("pid", os.getpid())
        line = json.dumps(record, sort_keys=True) + "\n"
        try:
            if not self._wrote_meta:
                self._wrote_meta = True
                meta = json.dumps({"kind": "meta", "pid": os.getpid(),
                                   "argv": sys.argv[:4],
                                   "hold_ms": self.hold_ms,
                                   "t": time.time()}) + "\n"
                line = meta + line
            with open(self.out_path, "a", encoding="utf-8") as f:
                f.write(line)
        except OSError:
            pass

    def finding(self, rule: str, message: str,
                frames: list[tuple[str, int, str]],
                stack2: list[str] | None = None,
                detail: str | None = None) -> None:
        """Record one violation. `frames` is the capture from the
        violation site; the innermost REPO frame anchors file/line/
        context. Suppression comments on ANY repo frame's active line
        (for this rule or its static alias) drop the finding — the
        PR 11 transport-exchange design must not re-fire at runtime."""
        repo_frames = [fr for fr in frames if _in_repo(fr[0])]
        for path, line, _fn in repo_frames:
            if self._suppr.suppressed(path, line, rule):
                return
        anchor = repo_frames[0] if repo_frames else (frames[0] if frames
                                                     else ("<unknown>", 0, ""))
        path = repo_rel(anchor[0])
        fp = fingerprint(rule, path, anchor[2], message)
        with self._state:
            self.findings += 1
            count = self._finding_counts.get(fp, 0) + 1
            self._finding_counts[fp] = count
        if count > 1:
            return  # first-seen only; repeats flush as finding_count
        record = {
            "kind": "finding", "rule": rule, "file": path,
            "line": anchor[1], "context": anchor[2], "message": message,
            "fingerprint": fp,
            "stack": _render_stack(frames),
            "tid": threading.get_ident(), "t": time.time(),
        }
        if stack2:
            record["stack2"] = stack2
        if detail:
            record["detail"] = detail
        self._emit(record)
        print(f"drlint-rt: [{rule}] {path}:{anchor[1]}: {message}"
              f"{' [' + detail + ']' if detail else ''}",
              file=sys.stderr)

    # -- held-set ---------------------------------------------------------

    def held(self) -> list:
        """This thread's held SanLock stack (innermost last)."""
        try:
            return self._tl.stack
        except AttributeError:
            self._tl.stack = []
            return self._tl.stack

    def on_acquired(self, lock) -> None:
        held = self.held()
        now = time.monotonic()
        site = self._acquire_site()
        lock._hold_t0 = now
        lock._hold_site = site
        lock.owner_ident = threading.get_ident()
        for h in held:
            self._record_edge(h, lock)
        held.append(lock)

    def on_released(self, lock) -> None:
        held = self.held()
        try:
            held.remove(lock)
        except ValueError:
            pass  # released by a thread that never saw the acquire
        lock.owner_ident = None
        t0 = getattr(lock, "_hold_t0", None)
        site = getattr(lock, "_hold_site", None)
        if t0 is None or site is None:
            return
        dt_ms = (time.monotonic() - t0) * 1000.0
        with self._state:
            h = self._holds.setdefault(
                site, {"count": 0, "total_ms": 0.0, "max_ms": 0.0})
            h["count"] += 1
            h["total_ms"] += dt_ms
            h["max_ms"] = max(h["max_ms"], dt_ms)
        if dt_ms >= self.hold_ms:
            frames = _stack_frames()
            # The measured duration goes in `detail`, NOT the message:
            # the fingerprint hashes the message, and a per-occurrence
            # millisecond value would defeat the first-seen dedup (one
            # slow site per loop iteration = one record per iteration).
            # The per-site max/mean live in the hold histogram anyway.
            self.finding(
                "rt-hold",
                f"lock {self.lock_label(lock)} held past the "
                f"{self.hold_ms:.0f} ms threshold at {site}", frames,
                detail=f"{dt_ms:.0f} ms")

    def _acquire_site(self) -> str:
        """repo-relative file:line of the innermost non-rt caller frame
        — the acquisition site the hold histogram keys on."""
        f = sys._getframe(2)
        while f is not None:
            path = f.f_code.co_filename
            if not _is_rt_frame(path) and not path.endswith("threading.py"):
                return f"{repo_rel(path)}:{f.f_lineno}"
            f = f.f_back
        return "<unknown>"

    # -- edges + cycles ---------------------------------------------------

    def _record_edge(self, src, dst) -> None:
        if src is dst:
            return
        key = (id(src), id(dst))
        if key in self._edge_meta:
            return
        frames = _stack_frames()
        stack = _render_stack(frames)
        with self._state:
            if key in self._edge_meta:
                return
            self._locks_by_id[id(src)] = src
            self._locks_by_id[id(dst)] = dst
            self._adj.setdefault(id(src), set()).add(id(dst))
            self._edge_meta[key] = {"stack": stack}
            cycle_path = self._find_path(id(dst), id(src))
        src_name = self.lock_name(src)
        dst_name = self.lock_name(dst)
        self._emit({"kind": "edge",
                    "src": list(src_name) if src_name else None,
                    "dst": list(dst_name) if dst_name else None,
                    "src_site": getattr(src, "site", "?"),
                    "dst_site": getattr(dst, "site", "?"),
                    "stack": stack})
        if cycle_path is not None:
            other = self._edge_meta.get((cycle_path[0], cycle_path[1]),
                                        {}).get("stack", [])
            self.finding(
                "rt-lock-order",
                f"lock-order cycle closed: {self.lock_label(dst)} acquired "
                f"while holding {self.lock_label(src)}, but the reverse "
                f"order was already observed (potential deadlock)",
                frames, stack2=other)
        elif self._static_edges and src_name and dst_name and \
                (dst_name, src_name) in self._static_edges:
            self.finding(
                "rt-lock-order",
                f"observed order {self.lock_label(src)} -> "
                f"{self.lock_label(dst)} contradicts the static lock_order "
                f"graph edge {dst_name} -> {src_name}", frames)

    def _find_path(self, start: int, goal: int) -> list[int] | None:
        """DFS in the observed graph (state lock held by caller)."""
        if start == goal:
            return [start]
        seen = {start}
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in self._adj.get(node, ()):
                if nxt == goal:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -- lock naming ------------------------------------------------------

    def lock_name(self, lock) -> tuple[str, str] | None:
        """Static-model node name for a runtime lock: (ClassName, attr)
        for instance locks — ClassName being the DEFINING class of the
        ctor frame, matching _locks.ClassModel — or (repo-relative
        module path, var) for module-level locks. Resolved lazily by
        scanning the owner's attributes for the lock object (or a
        Condition wrapping it); None until the assignment is findable."""
        cached = getattr(lock, "name", None)
        if cached is not None:
            return cached
        owner_cls = getattr(lock, "owner_cls", None)
        owner_ref = getattr(lock, "owner_ref", None)
        owner = owner_ref() if owner_ref is not None else None
        if owner is not None and owner_cls:
            attr = self._scan_for(owner, lock)
            if attr is not None:
                lock.name = (owner_cls, attr)
                return lock.name
            return None
        mod_name = getattr(lock, "module", None)
        if mod_name:
            mod = sys.modules.get(mod_name)
            if mod is not None:
                attr = self._scan_for(mod, lock)
                if attr is not None:
                    # site is already "repo-rel-path:line" (locks.py) —
                    # strip the line, keep the path verbatim.
                    lock.name = (getattr(lock, "site", "?")
                                 .rsplit(":", 1)[0], attr)
                    return lock.name
        return None

    @staticmethod
    def _scan_for(owner, lock) -> str | None:
        try:
            items = list(vars(owner).items())
        except TypeError:
            return None
        indirect = None
        for k, v in items:
            if v is lock:
                return k
            # A Condition over this lock: prefer the mutex's own attr
            # name (the static canon), fall back to the condition's.
            if getattr(v, "_lock", None) is lock and indirect is None:
                indirect = k
        return indirect

    def lock_label(self, lock) -> str:
        name = self.lock_name(lock)
        if name is not None:
            return f"{name[0]}.{name[1]}"
        return f"<lock @ {getattr(lock, 'site', '?')}>"

    # -- guarded accesses -------------------------------------------------

    def on_guarded_ok(self, cls_name: str, attr: str) -> None:
        key = (cls_name, attr)
        if key in self._seen_accesses:
            return
        with self._state:
            if key in self._seen_accesses:
                return
            self._seen_accesses.add(key)
        self._emit({"kind": "access", "cls": cls_name, "attr": attr})

    def on_guarded_violation(self, obj, cls_name: str, attr: str,
                             locks: tuple[str, ...], write: bool) -> None:
        """Called only on the slow path (no declared lock held). Runtime
        exemptions mirror the static lock-discipline escapes: __init__/
        __del__ of the instance itself, *_locked caller-holds methods,
        and accesses whose nearest repo frame is OUTSIDE the package
        (tests poking internals are out of scope, like Java's
        @GuardedBy)."""
        frames = _stack_frames()
        pkg_root = os.path.join(_REPO_ROOT,
                                "distributed_reinforcement_learning_tpu")
        for path, _line, fn in frames:
            if not _in_repo(path):
                continue
            if fn.endswith("_locked") or fn in ("__init__", "__del__"):
                return
            if not path.startswith(pkg_root + os.sep) and \
                    not any(path.startswith(d + os.sep)
                            for d in _scope_dirs()):
                return  # nearest repo frame is test/tool code: out of scope
            break
        else:
            return
        kind = "write to" if write else "read of"
        self.finding(
            "rt-guardedby",
            f"{kind} {cls_name}.{attr} without holding "
            f"{'/'.join(locks)} (declared in _GUARDED_BY)", frames)

    # -- blocking calls ---------------------------------------------------

    def on_blocking_call(self, what: str) -> None:
        held = self.held()
        if not held:
            return
        frames = _stack_frames()
        labels = ", ".join(self.lock_label(h) for h in held)
        self.finding("rt-blocking",
                     f"{what} while holding {labels}", frames)

    # -- hold histogram flush ---------------------------------------------

    def _flush_counts(self) -> None:
        with self._state:
            repeats = {fp: n for fp, n in self._finding_counts.items()
                       if n > 1}
        for fp, n in repeats.items():
            self._emit({"kind": "finding_count", "fingerprint": fp,
                        "count": n})

    def _flush_holds(self) -> None:
        with self._state:
            holds = {site: dict(h) for site, h in self._holds.items()}
        for site, h in holds.items():
            self._emit({"kind": "hold", "site": site, "count": h["count"],
                        "total_ms": round(h["total_ms"], 3),
                        "max_ms": round(h["max_ms"], 3)})


_INSTANCE: Sanitizer | None = None


def get() -> Sanitizer | None:
    return _INSTANCE


def activate(out_path: str | None = None) -> Sanitizer:
    global _INSTANCE
    if _INSTANCE is None:
        _INSTANCE = Sanitizer(out_path=out_path)
    return _INSTANCE
