"""Instrumented Lock/RLock/Condition factories + the threading patch.

``install_lock_factories()`` replaces ``threading.Lock``, ``threading.
RLock`` and ``threading.Condition`` with factories that return
sanitized primitives **only for locks constructed from repo code** —
the factory inspects the creating frame once and hands foreign callers
(stdlib ``queue``, jax, third-party threads) the real primitive, so
the sanitizer's blast radius is exactly the package + tests + tools
tree the static passes lint. Locks created BEFORE install (imports
that ran pre-gate) stay untouched; the gate installs at package-import
time, before any package module body runs, so every package lock is
covered.

SanLock/SanRLock mirror the real primitives' protocol exactly —
``acquire(blocking, timeout)``, ``release``, ``locked``, context
manager, plus the ``_is_owned``/``_release_save``/``_acquire_restore``
trio ``threading.Condition`` duck-types against — and additionally
carry the metadata sanitizer.py keys on: creation site, defining
class, a weakref to the owning instance (for lazy (Class, attr)
naming), and the current owner thread. SanCondition subclasses the
real Condition so ``isinstance`` and subclass users keep working; it
only swaps the implicit lock for a sanitized one when the creator is
repo code.

Like the real primitives, sanitized locks refuse to pickle.
"""

from __future__ import annotations

import sys
import threading
import weakref

from tools.drlint.core import repo_rel
from tools.drlint.rt import sanitizer as _san_mod

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition


def _creation_info():
    """(is_repo, site 'repo-rel:line', defining class name or None,
    weakref-to-self or None, creating module name) for the frame that
    called a factory."""
    f = sys._getframe(2)
    while f is not None and _san_mod._is_rt_frame(f.f_code.co_filename):
        f = f.f_back
    if f is None:
        return False, "<unknown>", None, None, None
    path = f.f_code.co_filename
    if not _san_mod._in_repo(path):
        return False, "", None, None, None
    site = f"{repo_rel(path)}:{f.f_lineno}"
    cls = _san_mod._defining_class(f)
    ref = None
    obj = f.f_locals.get("self")
    if obj is not None and cls is not None:
        try:
            ref = weakref.ref(obj)
        except TypeError:
            ref = None
    return True, site, cls, ref, f.f_globals.get("__name__")


class _SanBase:
    """Shared metadata + protocol surface of the sanitized primitives."""

    def __init__(self, site: str, owner_cls, owner_ref, module):
        self.site = site
        self.owner_cls = owner_cls
        self.owner_ref = owner_ref
        self.module = module
        self.name = None  # resolved lazily by sanitizer.lock_name
        self.owner_ident = None
        self._hold_t0 = None
        self._hold_site = None

    def __reduce__(self):
        raise TypeError(f"cannot pickle {type(self).__name__} object")

    def __enter__(self):
        self.acquire()
        return True

    def __exit__(self, *exc):
        self.release()
        return False


class SanLock(_SanBase):
    """Sanitized non-reentrant mutex (the `threading.Lock` shape)."""

    def __init__(self, site, owner_cls, owner_ref, module):
        super().__init__(site, owner_cls, owner_ref, module)
        self._lk = _REAL_LOCK()

    def acquire(self, blocking=True, timeout=-1):
        ok = self._lk.acquire(blocking, timeout)
        if ok:
            san = _san_mod.get()
            if san is not None:
                san.on_acquired(self)
        return ok

    def release(self):
        san = _san_mod.get()
        if san is not None:
            san.on_released(self)
        self._lk.release()

    def locked(self):
        return self._lk.locked()

    def _at_fork_reinit(self):
        self._lk._at_fork_reinit()
        self.owner_ident = None

    # Condition duck-typing: with these three, Condition.wait routes its
    # release/reacquire through the sanitizer (so held-sets and hold
    # times stay exact across a wait) and _is_owned is precise instead
    # of the stock try-acquire heuristic.
    def _is_owned(self):
        return self.owner_ident == threading.get_ident()

    def _release_save(self):
        self.release()

    def _acquire_restore(self, _state):
        self.acquire()

    def __repr__(self):
        state = "locked" if self._lk.locked() else "unlocked"
        return f"<SanLock {state} site={self.site}>"


class SanRLock(_SanBase):
    """Sanitized reentrant mutex. Tracks its own owner/count (the real
    RLock does not expose them) and reports only the OUTERMOST
    acquire/release to the sanitizer — re-entry is not an edge."""

    def __init__(self, site, owner_cls, owner_ref, module):
        super().__init__(site, owner_cls, owner_ref, module)
        self._lk = _REAL_RLOCK()
        self._count = 0

    def acquire(self, blocking=True, timeout=-1):
        ok = self._lk.acquire(blocking, timeout)
        if ok:
            self._count += 1
            if self._count == 1:
                san = _san_mod.get()
                if san is not None:
                    san.on_acquired(self)
        return ok

    def release(self):
        if self._count == 1:
            san = _san_mod.get()
            if san is not None:
                san.on_released(self)
        self._count -= 1
        self._lk.release()

    def _is_owned(self):
        return self.owner_ident == threading.get_ident() and self._count > 0

    def _release_save(self):
        count = self._count
        self._count = 0
        san = _san_mod.get()
        if san is not None:
            san.on_released(self)
        state = self._lk._release_save()
        return (state, count)

    def _acquire_restore(self, state):
        inner, count = state
        self._lk._acquire_restore(inner)
        self._count = count
        san = _san_mod.get()
        if san is not None:
            san.on_acquired(self)

    def __repr__(self):
        return f"<SanRLock count={self._count} site={self.site}>"


class SanCondition(_REAL_CONDITION):
    """threading.Condition that sanitizes its implicit lock when the
    creator is repo code. A Condition over an EXPLICIT lock needs no
    help — the passed lock is already sanitized (or deliberately real),
    and the stock Condition duck-types against SanLock's
    _is_owned/_release_save/_acquire_restore."""

    def __init__(self, lock=None):
        if lock is None:
            is_repo, site, owner_cls, owner_ref, module = _creation_info()
            if is_repo:
                lock = SanRLock(site, owner_cls, owner_ref, module)
        super().__init__(lock)


def _lock_factory():
    is_repo, site, owner_cls, owner_ref, module = _creation_info()
    if not is_repo:
        return _REAL_LOCK()
    return SanLock(site, owner_cls, owner_ref, module)


def _rlock_factory():
    is_repo, site, owner_cls, owner_ref, module = _creation_info()
    if not is_repo:
        return _REAL_RLOCK()
    return SanRLock(site, owner_cls, owner_ref, module)


def install_lock_factories() -> None:
    if threading.Lock is _lock_factory:  # idempotent
        return
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = SanCondition
