"""drlint: repo-native static analysis for the TPU RL stack.

Five stdlib-`ast` passes encode the invariants the paper's architecture
depends on but nothing previously enforced (docs/static_analysis.md has
the full catalog and workflow):

- ``jit-purity``       no host side effects inside traced (jit/pmap/
                       shard_map/lax-control-flow) functions
- ``host-sync``        no hidden device syncs inside the learner/actor
                       step loops of ``runtime/``
- ``lock-discipline``  attributes declared in a class's ``_GUARDED_BY``
                       map are only touched under the matching lock
- ``nondeterminism``   no module-level ``random``/``np.random`` RNG in
                       library code (seeded generators are fine)
- ``dtype-pitfall``    no dtype-less numpy constructors / ``np.float64``
                       on device-bound paths (silently breaks bf16)

Run ``python -m tools.drlint <paths>`` (see ``scripts/drlint.sh``), or
use :func:`lint_paths` / :func:`lint_source` from tests. Pure stdlib:
importing this package must never pull in jax/numpy — it has to run in
a bare CI interpreter in well under a second.
"""

from tools.drlint.core import (  # noqa: F401
    Baseline,
    BaselineError,
    Finding,
    lint_paths,
    lint_source,
    write_baseline,
)
from tools.drlint.rules import RULES  # noqa: F401
