"""drlint: repo-native static analysis for the TPU RL stack.

Nine stdlib-`ast` passes encode the invariants the paper's architecture
depends on but nothing previously enforced (docs/static_analysis.md has
the full catalog and workflow). Per-module passes:

- ``jit-purity``          no host side effects inside traced (jit/pmap/
                          shard_map/lax-control-flow) functions
- ``host-sync``           no hidden device syncs inside the learner/
                          actor step loops of ``runtime/``
- ``lock-discipline``     attributes declared in a class's
                          ``_GUARDED_BY`` map are only touched under
                          the matching lock
- ``nondeterminism``      no module-level ``random``/``np.random`` RNG
                          in library code (seeded generators are fine)
- ``dtype-pitfall``       no dtype-less numpy constructors /
                          ``np.float64`` on device-bound paths

Whole-program passes (every linted file forms one Program):

- ``blocking-under-lock`` no socket I/O, subprocess, long/unbounded
                          sleeps, shm attach/unlink, or untimed
                          condition waits while a mutex is held
                          (inheritance-aware across modules)
- ``lock-order``          global lock-acquisition graph; cycles
                          (potential deadlocks) are findings
- ``protocol-contract``   every ``OP_*`` has a server dispatch arm and
                          a client sender; every reachable ``ST_*`` is
                          handled (or typed-raised) by each caller
- ``knob-registry``       every ``DRL_*`` literal names a registered
                          knob (tools/drlint/knobs.py) and the
                          docs/performance.md table matches the
                          registry byte-for-byte

Run ``python -m tools.drlint <paths>`` (see ``scripts/drlint.sh``), or
use :func:`lint_paths` / :func:`lint_source` / :func:`lint_sources`
from tests. Pure stdlib: importing this package must never pull in
jax/numpy — it has to run in a bare CI interpreter in well under a
second.
"""

from tools.drlint.core import (  # noqa: F401
    Baseline,
    BaselineError,
    Finding,
    Program,
    lint_paths,
    lint_source,
    lint_sources,
    write_baseline,
)
from tools.drlint.rules import ALL_RULES, PROGRAM_RULES, RULES  # noqa: F401
