"""drlint engine: module model, suppressions, baseline, runners.

Deliberately stdlib-only (ast/json/re/dataclasses): the linter gates
tier-1 and must cost milliseconds, not a jax import.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field

# Inline suppression: `# drlint: disable=rule-a,rule-b` on the finding's
# line, or on a comment-only line directly above it (useful when the
# offending expression is long). Rule ids use the catalog's kebab-case.
# A rule may carry a parenthesized justification —
# `# drlint: disable=silent-except(shutdown race, queue closed)` — and
# the rules in JUSTIFIED_RULES *require* one (>= 10 chars, the baseline
# bar): a bare `disable=silent-except` does not suppress.
_SUPPRESS_TOKEN = r"[a-zA-Z0-9_\-]+(?:\([^()]*\))?"
_SUPPRESS_RE = re.compile(
    r"#\s*drlint:\s*disable=\s*(%s(?:\s*,\s*%s)*)"
    % (_SUPPRESS_TOKEN, _SUPPRESS_TOKEN))
_SUPPRESS_TOKEN_RE = re.compile(r"([a-zA-Z0-9_\-]+)(?:\(([^()]*)\))?")

# Rules whose suppressions must carry a justification: the suppression
# IS the documentation (the demote-ladder "permanent, with one log"
# contract), so an undocumented one is worthless.
JUSTIFIED_RULES = frozenset({"silent-except"})

MIN_JUSTIFICATION = 10  # chars, the baseline/waiver bar


def parse_suppression_tokens(tail: str) -> set[str]:
    """Rule ids a matched `disable=` tail suppresses, justification
    hygiene applied: a JUSTIFIED_RULES id with no (or a too-short)
    parenthesized justification is dropped — the finding still fires."""
    out: set[str] = set()
    for m in _SUPPRESS_TOKEN_RE.finditer(tail):
        rule, just = m.group(1), m.group(2)
        if rule in JUSTIFIED_RULES and \
                len((just or "").strip()) < MIN_JUSTIFICATION:
            continue
        out.add(rule)
    return out

# Grandfathered-findings cap: the baseline exists to land the linter on
# an imperfect tree, not to become a second tree. Ten entries, each with
# a human justification, is the hard ceiling (ISSUE 2 acceptance).
BASELINE_MAX_ENTRIES = 10

# Finding paths are REPO-relative (this file lives at tools/drlint/),
# never CWD-relative: baseline entries and the path-scoped rules
# (host-sync, dtype-pitfall) must match identically whether the linter
# runs from the repo root, from pytest in a tmp dir, or from an IDE.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def repo_rel(path: str) -> str:
    """Repo-relative forward-slash path; absolute for paths outside the
    repo (fixture files in tmp dirs keep an unambiguous identity)."""
    ap = os.path.abspath(path)
    try:
        rel = os.path.relpath(ap, _REPO_ROOT)
    except ValueError:  # different drive (windows)
        return ap.replace(os.sep, "/")
    if rel == ".." or rel.startswith(".." + os.sep):
        return ap.replace(os.sep, "/")
    return rel.replace(os.sep, "/")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    context: str  # dotted class/function context ('' at module level)

    def key(self) -> tuple[str, str, str]:
        """Baseline identity: line numbers churn with every edit, so a
        grandfathered finding is matched by (rule, path, context)."""
        return (self.rule, self.path, self.context)

    def fingerprint(self) -> str:
        """Stable finding identity for CI diff annotation (the SARIF
        partialFingerprints idea): line numbers churn with every edit,
        so the hash covers (rule, path, context, message) only. Two
        byte-identical findings in one context share a fingerprint —
        that is the SARIF behavior too, and it is what makes the id
        survive an unrelated edit three lines above."""
        blob = "|".join((self.rule, self.path, self.context, self.message))
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def to_json(self) -> dict:
        """The pinned SARIF-lite record (tests/test_drlint.py
        TestJsonSchema): exactly these six keys, `file` repo-relative."""
        return {"rule": self.rule, "file": self.path, "line": self.line,
                "context": self.context, "message": self.message,
                "fingerprint": self.fingerprint()}

    def render(self) -> str:
        where = f" (in {self.context})" if self.context else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{where}"


class BaselineError(RuntimeError):
    """Malformed baseline file (over cap, missing justification, ...)."""


@dataclass
class Baseline:
    entries: list[dict] = field(default_factory=list)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
        entries = raw.get("entries", raw) if isinstance(raw, dict) else raw
        if not isinstance(entries, list):
            raise BaselineError(f"{path}: expected a list of entries")
        if len(entries) > BASELINE_MAX_ENTRIES:
            raise BaselineError(
                f"{path}: {len(entries)} entries exceeds the cap of "
                f"{BASELINE_MAX_ENTRIES} — fix findings instead of "
                f"growing the baseline")
        for i, e in enumerate(entries):
            for k in ("rule", "path", "context", "justification"):
                if not isinstance(e.get(k), str):
                    raise BaselineError(f"{path}: entry {i} missing '{k}'")
            if "match" in e and not isinstance(e["match"], str):
                raise BaselineError(f"{path}: entry {i} 'match' must be a string")
            just = e["justification"].strip()
            if len(just) < 10 or just.startswith("TODO"):
                raise BaselineError(
                    f"{path}: entry {i} ({e['rule']} @ {e['path']}) needs a "
                    f"real justification, not {e['justification']!r}")
        return cls(entries)

    @staticmethod
    def _matches(e: dict, f: Finding) -> bool:
        # The optional `match` substring narrows an entry to specific
        # findings inside its (rule, path, context) cell, so one
        # grandfathered float() doesn't also forgive a future .item()
        # added to the same function.
        return ((e["rule"], e["path"], e["context"]) == f.key()
                and e.get("match", "") in f.message)

    def split(self, findings: list[Finding], ran_rules=None,
              linted_paths=None) -> tuple[list[Finding], list[Finding], list[dict]]:
        """-> (new, grandfathered, stale_entries).

        An unhit entry is STALE only when this run could have produced
        its finding: its rule among `ran_rules` and its path among
        `linted_paths` (None = everything ran/was linted — the
        whole-tree gate). Partial runs (`--rules` subsets, `--changed`)
        must not misreport still-valid entries as stale."""
        new, old = [], []
        hit: set[int] = set()
        for f in findings:
            idx = next((i for i, e in enumerate(self.entries)
                        if self._matches(e, f)), None)
            if idx is None:
                new.append(f)
            else:
                hit.add(idx)
                old.append(f)
        stale = [e for i, e in enumerate(self.entries)
                 if i not in hit
                 and (ran_rules is None or e["rule"] in ran_rules)
                 and (linted_paths is None or e["path"] in linted_paths)]
        return new, old, stale


def write_baseline(findings: list[Finding], path: str,
                   justification: str = "TODO: justify or fix") -> None:
    """Emit a baseline skeleton for `findings` (dedup'd by key). The cap
    still applies on write: a >10-finding tree must be fixed, not frozen."""
    seen: dict = {}
    for f in findings:
        seen.setdefault(f.key(), {
            "rule": f.rule, "path": f.path, "context": f.context,
            "justification": justification,
        })
    entries = list(seen.values())
    if len(entries) > BASELINE_MAX_ENTRIES:
        raise BaselineError(
            f"{len(entries)} distinct findings exceed the baseline cap of "
            f"{BASELINE_MAX_ENTRIES}; fix some before grandfathering")
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"entries": entries}, f, indent=2)
        f.write("\n")


class ModuleInfo:
    """One parsed source file + the derived maps every rule shares."""

    def __init__(self, src: str, path: str):
        self.src = src
        self.path = path.replace(os.sep, "/")
        self.tree = ast.parse(src, filename=path)
        self.lines = src.splitlines()
        # Parent links + dotted context names, one walk for all rules.
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self._context_cache: dict[ast.AST, str] = {}
        self.module_aliases = self._collect_aliases()
        self.suppressions = self._collect_suppressions()
        self._cache: dict[str, object] = {}  # cross-rule scratch (traced fns)

    # -- aliases ---------------------------------------------------------
    def _collect_aliases(self) -> dict[str, str]:
        """Names this module binds to modules of interest:
        `import numpy as np` -> {'np': 'numpy'}; `from jax import lax`
        -> {'lax': 'jax.lax'}; `import random` -> {'random': 'random'}."""
        out: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    out[a.asname or a.name] = f"{node.module}.{a.name}"
        return out

    def resolve_chain(self, node: ast.AST) -> str | None:
        """Dotted module-level name of an attribute chain, aliases
        resolved: `np.random.uniform` -> 'numpy.random.uniform',
        `lax.scan` -> 'jax.lax.scan', `r.uniform` (after `import random
        as r`) -> 'random.uniform'. None for non-static chains AND for
        chains whose root name was never imported — a local variable
        that happens to be called `time` or `random` must not resolve
        to the stdlib module, and an *aliased* stdlib import must not
        escape the rules that key on the canonical module name."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name) or node.id not in self.module_aliases:
            return None
        return ".".join([self.module_aliases[node.id], *reversed(parts)])

    # -- context ---------------------------------------------------------
    def context_of(self, node: ast.AST) -> str:
        """Dotted enclosing class/function names ('Cls.meth')."""
        if node in self._context_cache:
            return self._context_cache[node]
        names: list[str] = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                names.append(cur.name)
            cur = self.parents.get(cur)
        ctx = ".".join(reversed(names))
        self._context_cache[node] = ctx
        return ctx

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(rule=rule, path=self.path,
                       line=getattr(node, "lineno", 0), message=message,
                       context=self.context_of(node))

    # -- suppressions ----------------------------------------------------
    def _collect_suppressions(self) -> dict[int, set[str]]:
        out: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = parse_suppression_tokens(m.group(1))
            # A comment-only line suppresses the NEXT line; a trailing
            # comment suppresses its own line.
            target = i + 1 if line.lstrip().startswith("#") else i
            out.setdefault(target, set()).update(rules)
        return out

    def suppressed(self, f: Finding) -> bool:
        rules = self.suppressions.get(f.line, ())
        return f.rule in rules or "all" in rules


def iter_py_files(paths: list[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith(".") and d != "__pycache__")
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        else:
            raise FileNotFoundError(p)
    return out


class Program:
    """The whole-program view the cross-module passes analyze: every
    parsed module of one lint invocation, plus shared lookups. Built
    once per `lint_paths`/`lint_sources` call — a pass must derive all
    global facts (lock graphs, opcode tables, knob reads) from here,
    never from re-reading the filesystem."""

    def __init__(self, modules: list[ModuleInfo]):
        self.modules = modules
        self.by_path: dict[str, ModuleInfo] = {m.path: m for m in modules}
        self._cache: dict[str, object] = {}  # cross-pass scratch

    def module_for(self, f: Finding) -> ModuleInfo | None:
        return self.by_path.get(f.path)


def _run_module_rules(mod: ModuleInfo, rules: dict) -> list[Finding]:
    findings: list[Finding] = []
    for name, check in rules.items():
        for f in check(mod):
            assert f.rule == name, (f.rule, name)
            if not mod.suppressed(f):
                findings.append(f)
    return findings


def _run_program_rules(program: Program, program_rules: dict) -> list[Finding]:
    findings: list[Finding] = []
    for name, check in program_rules.items():
        for f in check(program):
            assert f.rule == name, (f.rule, name)
            mod = program.module_for(f)
            if mod is None or not mod.suppressed(f):
                findings.append(f)
    return findings


def lint_source(src: str, path: str = "<string>",
                rules: dict | None = None) -> list[Finding]:
    """Lint one source blob with the per-module rules; suppression
    comments applied, no baseline, no cross-module passes (those need a
    Program — use `lint_sources` or `lint_paths`)."""
    from tools.drlint.rules import RULES

    mod = ModuleInfo(src, path)
    findings = _run_module_rules(mod, RULES if rules is None else rules)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def _lint_modules(mods: list[ModuleInfo], rules: dict | None,
                  program_rules: dict | None) -> list[Finding]:
    """The one lint tail both entry points share: per-module rules on
    each module, then the cross-module passes over the whole set as one
    Program, sorted."""
    from tools.drlint.rules import PROGRAM_RULES, RULES

    findings: list[Finding] = []
    for mod in mods:
        findings.extend(_run_module_rules(mod, RULES if rules is None else rules))
    program = Program(mods)
    findings.extend(_run_program_rules(
        program, PROGRAM_RULES if program_rules is None else program_rules))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def lint_sources(sources: dict[str, str], rules: dict | None = None,
                 program_rules: dict | None = None) -> list[Finding]:
    """Lint a {path: source} set as ONE program: per-module rules on
    each file plus the cross-module passes over the whole set. The
    fixture-side mirror of `lint_paths` (tests hand it small multi-file
    programs without touching the filesystem)."""
    return _lint_modules([ModuleInfo(src, path)
                          for path, src in sources.items()],
                         rules, program_rules)


def lint_paths(paths: list[str], rules: dict | None = None,
               program_rules: dict | None = None
               ) -> tuple[list[Finding], list[str]]:
    """Lint files/trees -> (findings, errors). Unparseable files are
    reported as errors, not silently skipped (a syntax error in a linted
    module must fail the gate, not shrink its coverage). All given files
    form ONE program for the cross-module passes."""
    mods: list[ModuleInfo] = []
    errors: list[str] = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
            mods.append(ModuleInfo(src, repo_rel(path)))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append(f"{path}: {type(e).__name__}: {e}")
    return _lint_modules(mods, rules, program_rules), errors
