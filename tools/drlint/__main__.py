"""CLI: `python -m tools.drlint <paths>` (scripts/drlint.sh wraps this).

Exit codes: 0 = clean (after baseline), 1 = non-baselined findings or
stale baseline entries (scoped to what this run covered), 2 = usage /
parse / baseline-format error. The default baseline is
tools/drlint/baseline.json when it exists; `--no-baseline` ignores it,
`--write-baseline` regenerates it from the current findings (still
subject to the 10-entry cap — fix findings, don't freeze them).

`--changed [BASE]` lints only the .py files `git diff --name-only
BASE` (default HEAD) reports, plus untracked ones — the fast local
iteration loop. The cross-module passes then see only that subset, so
a whole-tree contract (a deleted dispatch arm's missing opcode) still
needs the full run the tier-1 gate performs.

Text mode always ends with one compact JSON summary line on stdout
(`{"drlint": {...}}`) — the line scripts/drlint.sh and CI grep;
`--json` emits the full SARIF-lite document instead (schema pinned in
tests/test_drlint.py::TestJsonSchema).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from tools.drlint.core import (
    Baseline,
    BaselineError,
    iter_py_files,
    lint_paths,
    repo_rel,
    write_baseline,
)
from tools.drlint.rules import ALL_RULES, PROGRAM_RULES, RULES

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")

JSON_SCHEMA = "drlint-json-v2"


def changed_py_files(base: str) -> list[str]:
    """Changed-vs-`base` plus untracked .py files, absolute paths,
    resolved against the git toplevel of the CWD. NUL-separated git
    output (`-z`) so names with spaces or non-ASCII bytes survive."""
    top = subprocess.run(["git", "rev-parse", "--show-toplevel"],
                         capture_output=True, text=True, check=True
                         ).stdout.strip()
    names = subprocess.run(
        ["git", "diff", "--name-only", "-z", base, "--"],
        capture_output=True, text=True, check=True, cwd=top
        ).stdout.split("\0")
    names += subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard", "-z"],
        capture_output=True, text=True, check=True, cwd=top
        ).stdout.split("\0")
    out = []
    for n in names:
        if n.endswith(".py"):
            p = os.path.join(top, n)
            if os.path.isfile(p):
                out.append(p)
    return sorted(set(out))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.drlint",
        description="Repo-native static analysis (see docs/static_analysis.md)")
    ap.add_argument("paths", nargs="*", default=[],
                    help="files or directories to lint")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="BASE",
                    help="lint only .py files changed vs BASE (default "
                         "HEAD) plus untracked ones; positional paths "
                         "are ignored")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable SARIF-lite output on stdout")
    ap.add_argument("--reconcile", default=None, metavar="ARTIFACT",
                    help="diff a drlint-rt sanitizer artifact (JSONL) "
                         "against the static lock model of PATHS "
                         "(default: the package); exit 1 on stale "
                         "annotations, model gaps, or recorded runtime "
                         "findings")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rule ids to run")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE} if present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline file")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings to the baseline file and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name in ALL_RULES:
            print(name)
        return 0

    if args.reconcile is not None:
        from tools.drlint.rt import reconcile as _reconcile

        if not os.path.isfile(args.reconcile):
            print(f"drlint: --reconcile: no such artifact: "
                  f"{args.reconcile}", file=sys.stderr)
            return 2
        return _reconcile.main(args.reconcile, args.paths or None,
                               as_json=args.as_json)

    # Rule selection is validated BEFORE any --changed early exit: a
    # typo'd rule id must fail (rc 2) on a no-change run too, not
    # green-light the CI job until the next diff arrives.
    rules, program_rules = RULES, PROGRAM_RULES
    if args.rules:
        wanted = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in wanted if r not in ALL_RULES]
        if unknown:
            ap.error(f"unknown rules: {', '.join(unknown)} "
                     f"(have: {', '.join(ALL_RULES)})")
        rules = {r: RULES[r] for r in wanted if r in RULES}
        program_rules = {r: PROGRAM_RULES[r] for r in wanted
                         if r in PROGRAM_RULES}

    if args.changed is not None:
        try:
            paths = changed_py_files(args.changed)
        except (subprocess.CalledProcessError, OSError) as e:
            print(f"drlint: --changed needs a git checkout: {e}",
                  file=sys.stderr)
            return 2
        if not paths:
            # Fall through with an empty file set: the normal exit path
            # emits the output contract (SARIF-lite document or summary
            # line) from ONE place, all-clean case included.
            print(f"drlint: no .py files changed vs {args.changed}",
                  file=sys.stderr)
    else:
        paths = args.paths
        if not paths:
            ap.error("no paths given (or use --changed)")

    try:
        # Enumerate once: the flat file list feeds both lint_paths and
        # the summary's file count (no second tree walk).
        files = iter_py_files(paths)
        findings, errors = lint_paths(files, rules, program_rules)
    except FileNotFoundError as e:
        print(f"drlint: error: no such path: {e}", file=sys.stderr)
        return 2
    if errors:
        for e in errors:
            print(f"drlint: error: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None)
    if args.no_baseline:
        baseline_path = None

    if args.write_baseline:
        if args.changed is not None or args.rules:
            # A baseline regenerated from a partial run would silently
            # drop every out-of-scope entry; only full runs may write.
            ap.error("--write-baseline needs a full run "
                     "(drop --changed/--rules)")
        target = args.baseline or DEFAULT_BASELINE
        try:
            write_baseline(findings, target)
        except BaselineError as e:
            print(f"drlint: {e}", file=sys.stderr)
            return 2
        print(f"drlint: wrote {len(findings)} finding(s) to {target} — "
              f"fill in the justification fields", file=sys.stderr)
        return 0

    grandfathered: list = []
    stale: list[dict] = []
    if baseline_path is not None:
        try:
            baseline = Baseline.load(baseline_path)
        except (BaselineError, OSError, json.JSONDecodeError) as e:
            print(f"drlint: bad baseline {baseline_path}: {e}", file=sys.stderr)
            return 2
        # Stale detection scoped to what this run actually covered: on
        # a --rules subset or --changed diff, entries for unlinted
        # files / unrun rules are simply out of scope, not stale.
        findings, grandfathered, stale = baseline.split(
            findings,
            ran_rules=set(rules) | set(program_rules),
            linted_paths={repo_rel(f) for f in files})

    summary = {"findings": len(findings), "baselined": len(grandfathered),
               "files": len(files),
               "rules": len(rules) + len(program_rules)}
    if args.as_json:
        print(json.dumps({
            "schema": JSON_SCHEMA,
            "findings": [f.to_json() for f in findings],
            "grandfathered": [f.to_json() for f in grandfathered],
            "stale_baseline_entries": stale,
            "rules": [*rules, *program_rules],
            "summary": summary,
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        for e in stale:
            print(f"drlint: warning: stale baseline entry {e['rule']} @ "
                  f"{e['path']} ({e['context']}) — the finding is gone; "
                  f"remove the entry", file=sys.stderr)
        print(f"drlint: {len(findings)} finding(s)"
              f" ({len(grandfathered)} baselined)", file=sys.stderr)
        print(json.dumps({"drlint": summary}))
    return 1 if findings or stale else 0


if __name__ == "__main__":
    sys.exit(main())
