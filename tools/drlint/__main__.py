"""CLI: `python -m tools.drlint <paths>` (scripts/drlint.sh wraps this).

Exit codes: 0 = clean (after baseline), 1 = non-baselined findings,
2 = usage / parse / baseline-format error. The default baseline is
tools/drlint/baseline.json when it exists; `--no-baseline` ignores it,
`--write-baseline` regenerates it from the current findings (still
subject to the 10-entry cap — fix findings, don't freeze them).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tools.drlint.core import Baseline, BaselineError, lint_paths, write_baseline
from tools.drlint.rules import RULES

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.drlint",
        description="Repo-native static analysis (see docs/static_analysis.md)")
    ap.add_argument("paths", nargs="*", default=[],
                    help="files or directories to lint")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output on stdout")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rule ids to run")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE} if present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline file")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings to the baseline file and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name in RULES:
            print(name)
        return 0
    if not args.paths:
        ap.error("no paths given")

    rules = RULES
    if args.rules:
        wanted = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in wanted if r not in RULES]
        if unknown:
            ap.error(f"unknown rules: {', '.join(unknown)} "
                     f"(have: {', '.join(RULES)})")
        rules = {r: RULES[r] for r in wanted}

    findings, errors = lint_paths(args.paths, rules)
    if errors:
        for e in errors:
            print(f"drlint: error: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None)
    if args.no_baseline:
        baseline_path = None

    if args.write_baseline:
        target = args.baseline or DEFAULT_BASELINE
        try:
            write_baseline(findings, target)
        except BaselineError as e:
            print(f"drlint: {e}", file=sys.stderr)
            return 2
        print(f"drlint: wrote {len(findings)} finding(s) to {target} — "
              f"fill in the justification fields", file=sys.stderr)
        return 0

    grandfathered: list = []
    stale: list[dict] = []
    if baseline_path is not None:
        try:
            baseline = Baseline.load(baseline_path)
        except (BaselineError, OSError, json.JSONDecodeError) as e:
            print(f"drlint: bad baseline {baseline_path}: {e}", file=sys.stderr)
            return 2
        findings, grandfathered, stale = baseline.split(findings)

    if args.as_json:
        print(json.dumps({
            "findings": [f.__dict__ for f in findings],
            "grandfathered": [f.__dict__ for f in grandfathered],
            "stale_baseline_entries": stale,
            "rules": list(rules),
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        for e in stale:
            print(f"drlint: warning: stale baseline entry {e['rule']} @ "
                  f"{e['path']} ({e['context']}) — the finding is gone; "
                  f"remove the entry", file=sys.stderr)
        summary = (f"drlint: {len(findings)} finding(s)"
                   f" ({len(grandfathered)} baselined)")
        print(summary, file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
