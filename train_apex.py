#!/usr/bin/env python
"""Ape-X DQN launcher (counterpart of the reference's `train_apex.py`).

    python train_apex.py --section apex --updates 1000
"""

from __future__ import annotations

import argparse


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", default="config.json")
    p.add_argument("--section", default="apex")
    p.add_argument("--mode", default="local",
                   choices=["local", "learner", "actor", "anakin", "inference"])
    p.add_argument("--anakin_envs", type=int, default=None,
                   help="anakin mode: parallel on-device envs")
    p.add_argument("--anakin_capacity", type=int, default=None,
                   help="anakin mode: device transition-ring capacity "
                        "(default min(replay_capacity, 32768))")
    p.add_argument("--task", type=int, default=-1)
    p.add_argument("--updates", type=int, default=1000)
    p.add_argument("--run_dir", default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--checkpoint_dir", default=None,
                   help="learner mode: save/resume TrainState checkpoints here")
    p.add_argument("--checkpoint_interval", type=int, default=500)
    p.add_argument("--actor_grace", type=float, default=120.0,
                   help="actor mode: seconds to ride out a learner outage before exiting")
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. 'cpu'); actors default to cpu "
                        "so they never grab the TPU chip")
    p.add_argument("--serve_inference", action="store_true",
                   help="learner mode: serve SEED-style centralized inference")
    p.add_argument("--remote_act", action="store_true",
                   help="actor mode: offload act() to the learner's inference service")
    args = p.parse_args()

    # Actors AND inference replicas default to cpu: neither may grab
    # the TPU chip the learner process holds (single-owner libtpu) —
    # pass --platform explicitly when a replica has its own accelerator.
    platform = args.platform or (
        "cpu" if args.mode in ("actor", "inference") else None)
    if platform:
        import jax
        jax.config.update("jax_platforms", platform)

    if args.mode == "anakin":
        # On-device transition replay (runtime/anakin_apex.py).
        from distributed_reinforcement_learning_tpu.runtime.launch import train_anakin_apex

        print(train_anakin_apex(args.config, args.section, args.updates,
                                seed=args.seed, num_envs=args.anakin_envs,
                                capacity=args.anakin_capacity,
                                checkpoint_dir=args.checkpoint_dir,
                                run_dir=args.run_dir))
        return
    if args.mode == "local":
        from distributed_reinforcement_learning_tpu.runtime.launch import train_local

        result = train_local(args.config, args.section, args.updates,
                             run_dir=args.run_dir, seed=args.seed,
                             checkpoint_dir=args.checkpoint_dir,
                             checkpoint_interval=args.checkpoint_interval)
        print({k: v for k, v in result.items() if k != "episode_returns"})
    else:
        from distributed_reinforcement_learning_tpu.runtime.transport import run_role

        run_role("apex", args.config, args.section, args.mode, args.task,
                 num_updates=args.updates, run_dir=args.run_dir, seed=args.seed,
                 checkpoint_dir=args.checkpoint_dir,
                 checkpoint_interval=args.checkpoint_interval,
                 actor_grace=args.actor_grace,
                 serve_inference=args.serve_inference,
                 remote_act=args.remote_act)


if __name__ == "__main__":
    main()
