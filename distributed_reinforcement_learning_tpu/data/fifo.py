"""Bounded trajectory FIFO with blocking backpressure.

Host-side replacement for the reference's learner-placed `tf.FIFOQueue`
(`distributed_queue/buffer_queue.py:28-36,153-160,368-378`): a
thread-safe bounded queue of numpy pytrees. Producers (actor threads or
the transport server) block when full — the same backpressure the TF
queue kernel gave the reference. The learner drains whole batches in one
call and gets stacked arrays ready for one host->device transfer,
replacing the reference's 32 sequential dequeue round-trips per batch
(`buffer_queue.py:416-435`, the anti-pattern called out in SURVEY §7).

A C++ ring-buffer backend (cpp/) slots in behind the same interface for
the multi-process data plane.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any

import numpy as np

from distributed_reinforcement_learning_tpu.observability import TELEMETRY as _OBS


def stack_pytrees(items: list[Any]) -> Any:
    """Stack a list of identically-structured numpy pytrees along axis 0."""
    import jax

    return jax.tree.map(lambda *xs: np.stack(xs), *items)


def blob_ingest(queue: Any) -> tuple[Any, Any]:
    """-> (prepare, put) for feeding CODEC BLOBS into a trajectory queue.

    The single definition of blob-ingest semantics, shared by the TCP
    transport server and the shm-ring drainer so the two transports
    cannot drift. Three queue shapes, most specific first:

    - replay-shard facades (`ingest_blob`,
      runtime/replay_shard.ReplayIngestFifo) take the RAW wire blob
      untouched — the owning shard decodes it ONCE on the transport
      thread (a dedup-packed blob decodes straight to the plain pytree,
      skipping the unpack->re-encode round trip blob-native queues pay);
    - blob-native queues (`put_bytes`, the C++ backend) take the raw
      bytes routed through `codec.unpack_blob` so a dedup-packed wire
      blob (DRL_OBS_DEDUP) is reconstructed to the plain layout BEFORE
      the queue (the native batch-gather assumes it; a plain blob passes
      through as the same object, no copy);
    - pytree queues take a decoded COPY — the blob's buffer may be
      reused or unmapped by the caller the moment `prepare` returns, and
      decode reconstructs packed leaves bit-identically as part of that
      copy.

    Either way, replay, prioritization, and training see byte-for-byte
    the trajectories a dedup-off run would see.
    `put(item, timeout=...)` follows the queue's blocking-put contract
    (False on timeout, RuntimeError once closed).
    """
    from distributed_reinforcement_learning_tpu.data import codec

    if hasattr(queue, "ingest_blob"):
        return (lambda blob: blob), queue.ingest_blob
    if hasattr(queue, "put_bytes"):
        # strip_stamp first: a priority-stamped wire blob (ISSUE 18,
        # data/admission.py) carries an extension frame the native
        # batch-gather must never see; the monolithic consumer behind a
        # blob-native queue re-scores at ingest anyway, so the stamp is
        # dead weight here. decode() below is stamp-transparent itself.
        return (lambda blob: codec.unpack_blob(codec.strip_stamp(blob))), \
            queue.put_bytes
    return (lambda blob: codec.decode(blob, copy=True)), queue.put


def put_batch_size() -> int:
    """The actor's PUT batch size: how many unrolls ride one batched
    exchange (`DRL_PUT_BATCH`). 0 (the default) keeps today's behavior —
    the whole extract() round in one OP_PUT_TRAJ_N exchange (and, for
    the Ape-X actor's per-step puts, one unroll per put). Sizing
    guidance vs actor count: docs/performance.md ("PUT batch sizing")."""
    try:
        return max(0, int(os.environ.get("DRL_PUT_BATCH", "0") or 0))
    except ValueError:
        return 0


def put_round(queue: Any, items: list[Any]) -> None:
    """Ship one actor round (the N trajectories of an `extract()`) to a
    queue, batched when the queue supports it.

    Over the socket data plane, `put_many` is ONE round trip for the
    whole round (OP_PUT_TRAJ_N) instead of N request/replies — the
    actor-side fix for the reference's per-item-RPC anti-pattern
    (`buffer_queue.py:416-435`). In-process queues just loop.
    `DRL_PUT_BATCH=k` chunks the round into k-unroll exchanges (smaller
    server-side enqueue bursts under many actors, at more round trips).
    """
    put_many = getattr(queue, "put_many", None)
    if put_many is None:
        for item in items:
            queue.put(item)
        return
    chunk = put_batch_size()
    if chunk <= 0 or chunk >= len(items):
        put_many(items)
    else:
        for i in range(0, len(items), chunk):
            put_many(items[i:i + chunk])


class TrajectoryQueue:
    """Bounded MPMC queue of trajectory pytrees.

    put() blocks when full (backpressure on actors, like the reference's
    blocking enqueue); get_batch(n) blocks until n items are available and
    returns them stacked along a new leading batch axis.
    """

    # Concurrency map (tools/drlint lock-discipline): `_not_full` and
    # `_not_empty` are Conditions over the SAME `_lock`, so any of the
    # three names is the same mutex; producers, consumers, and the
    # transport server's enqueue slices all go through it.
    _GUARDED_BY = {
        "_items": ("_lock", "_not_full", "_not_empty"),
        "_closed": ("_lock", "_not_full", "_not_empty"),
    }

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._items: deque = deque()
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._not_empty = threading.Condition(self._lock)
        self._closed = False

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def size(self) -> int:
        """Queue depth, the learner's readiness poll (`buffer_queue.py:437-439`)."""
        return len(self)

    def close(self) -> None:
        """Wake all blocked producers/consumers; subsequent puts raise."""
        with self._lock:
            self._closed = True
            self._not_full.notify_all()
            self._not_empty.notify_all()

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def put(self, item: Any, timeout: float | None = None) -> bool:
        with self._not_full:
            if not self._not_full.wait_for(
                lambda: len(self._items) < self.capacity or self._closed, timeout
            ):
                return False
            if self._closed:
                raise RuntimeError("queue closed")
            self._items.append(item)
            depth = len(self._items)
            self._not_empty.notify()
        # Telemetry outside the queue lock (the telemetry lock is a leaf).
        if _OBS.enabled:
            _OBS.count("fifo/puts")
            _OBS.gauge("fifo/fill", depth / self.capacity)
        return True

    def put_many(self, items: list[Any], timeout: float | None = None) -> int:
        """Enqueue a list of items; returns how many were accepted.

        Blocks per item under backpressure like put(). Stops at the first
        timeout — the remainder is NOT enqueued (callers may retry it).
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        accepted = 0
        for item in items:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            if not self.put(item, timeout=remaining):
                break
            accepted += 1
        return accepted

    def get(self, timeout: float | None = None) -> Any | None:
        with self._not_empty:
            if not self._not_empty.wait_for(lambda: self._items or self._closed, timeout):
                return None
            if not self._items:  # closed and drained
                return None
            item = self._items.popleft()
            self._not_full.notify()
        if _OBS.enabled:
            _OBS.count("fifo/gets")
        return item

    def get_batch(self, batch_size: int, timeout: float | None = None) -> Any | None:
        """Dequeue `batch_size` items and stack them into `[B, ...]` arrays.

        `timeout` is a total deadline across the whole batch. On timeout the
        already-dequeued items are pushed back to the FRONT of the queue in
        order (no data loss, no reordering) and None is returned.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        items = []
        for _ in range(batch_size):
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            item = self.get(remaining)
            if item is None:
                if items:
                    with self._lock:
                        self._items.extendleft(reversed(items))
                        self._not_empty.notify_all()
                return None
            items.append(item)
        return stack_pytrees(items)
