"""Single-source bf16 RNE codec (numpy-only, jax-free).

One kernel, two planes: the weight plane's quantized broadcast shards
(runtime/weight_shards.py) and the learner collective's quantized
gradient exchange (parallel/collective.py) must round IDENTICALLY —
a gradient merged through one rounding and weights published through
another would make the two planes disagree about the same float. The
kernel lives here so both import the same bytes-for-bytes behavior
(tests/test_collective_partition.py pins byte-identity against the
weight-shard aliases).

Kept numpy + stdlib only: parallel/collective.py's bench/test children
rely on a jax-free import footprint.
"""

from __future__ import annotations

import sys

import numpy as np


def f32_to_bf16_u16(a: np.ndarray) -> np.ndarray:
    """Round-to-nearest-even f32 -> bf16, carried as uint16 (numpy has
    no bf16 dtype; the codec moves raw buffers either way). All-uint32
    arithmetic — a uint64 promotion here measured ~14x slower at real
    publish sizes. The +0x7FFF(+1) add can only wrap for negative-NaN
    bit patterns (u >= 0xFFFF8001), and every NaN is overwritten by the
    fixup below (mantissa forced non-zero so a NaN cannot round into
    Inf), so the wraparound is unobservable."""
    u = a.reshape(-1).view(np.uint32)
    bias = (u >> np.uint32(16)) & np.uint32(1)
    bias += np.uint32(0x7FFF)
    bias += u  # in-place: bias IS the rounded word now
    if sys.byteorder == "little":
        # High half of each u32, gathered in one strided copy (the
        # >>16 + astype chain costs two more full passes).
        r = np.ascontiguousarray(bias.view(np.uint16)[1::2]).reshape(a.shape)
    else:
        r = (bias >> np.uint32(16)).astype(np.uint16).reshape(a.shape)
    nan = np.isnan(a)
    if nan.any():
        r[nan] = ((u.reshape(a.shape)[nan] >> np.uint32(16))
                  | np.uint32(0x0040)).astype(np.uint16)
    return r


def bf16_u16_to_f32(u: np.ndarray) -> np.ndarray:
    """Zero-extend u16 into the high half of a u32 word: one zeroed
    buffer + one strided 16-bit copy (little-endian hosts), ~5x the
    astype+shift chain at pull sizes. The big-endian fallback keeps the
    readable form."""
    flat = np.ascontiguousarray(u).reshape(-1)
    if sys.byteorder == "little":
        out = np.zeros(flat.size, np.uint32)
        out.view(np.uint16)[1::2] = flat
        return out.view(np.float32).reshape(u.shape)
    return (flat.astype(np.uint32) << np.uint32(16)).view(
        np.float32).reshape(u.shape)
