"""Prioritized replay as a device-resident ring (shared machinery).

The host topology keeps replay on the host (`data/replay.py` SumTree —
the re-design of `distributed_queue/buffer_queue.py:256-346`); the
Anakin runtimes keep it in device memory so sampling happens INSIDE the
compiled program. This module is the storage-agnostic core used by both
on-device replay families (`runtime/anakin_r2d2.py` sequences,
`runtime/anakin_apex.py` transitions): `storage` is any pytree whose
leaves are `[capacity, ...]` rings.

Math parity with `data/replay.py`: priority `(|err| + 0.001) ** 0.6`,
stratified sampling over `total/n` segments, IS weights `(N * p) **
-beta` batch-max-normalized, beta annealed 0.4 -> 1.0 by 0.001 per
sample. Writes are `write_width`-aligned (capacity must be a multiple),
overwriting oldest entries FIFO like the SumTree's write pointer.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

PER_EPS = 0.001
PER_ALPHA = 0.6
BETA0 = 0.4
BETA_INCREMENT = 0.001


class DeviceReplay(NamedTuple):
    storage: Any  # pytree of [capacity, ...] rings
    priorities: jax.Array  # [capacity] f32, alpha-transformed; 0 = empty
    ptr: jax.Array  # i32 next write slot (write_width-aligned)
    size: jax.Array  # i32 filled count
    beta: jax.Array  # f32 annealed IS exponent


def priority(err: jax.Array) -> jax.Array:
    """`(|err| + eps) ** alpha` (`data/replay.py` PrioritizedReplay)."""
    return jnp.power(jnp.abs(err) + PER_EPS, PER_ALPHA)


def make(storage_zeros: Any, capacity: int) -> DeviceReplay:
    return DeviceReplay(
        storage=storage_zeros,
        priorities=jnp.zeros((capacity,), jnp.float32),
        ptr=jnp.int32(0),
        size=jnp.int32(0),
        beta=jnp.float32(BETA0),
    )


def ingest(replay: DeviceReplay, batch: Any, errs: jax.Array) -> DeviceReplay:
    """Write `W` new entries (the leading dim of `batch`'s leaves) at
    `ptr` with priorities from raw errors `errs [W]`. Capacity is the
    ring's own (priorities.shape[0], static under jit) — never passed,
    so it cannot disagree with the arrays."""
    capacity = replay.priorities.shape[0]
    width = errs.shape[0]
    storage = jax.tree.map(
        lambda ring, new: jax.lax.dynamic_update_slice(
            ring, new.astype(ring.dtype),
            (replay.ptr,) + (0,) * (ring.ndim - 1)),
        replay.storage, batch)
    priorities = jax.lax.dynamic_update_slice(
        replay.priorities, priority(errs), (replay.ptr,))
    return replay._replace(
        storage=storage,
        priorities=priorities,
        ptr=(replay.ptr + width) % capacity,
        size=jnp.minimum(replay.size + width, capacity),
    )


def sample(replay: DeviceReplay, rng: jax.Array, n: int,
           axis_name: str | None = None):
    """-> (replay', batch, idx [n], is_weights [n]). Stratified over
    `total/n` segments; empty slots carry zero priority and are never
    drawn (the ring must hold at least one entry).

    `axis_name`: set by shard_map callers holding PER-DEVICE replay
    shards (the Anakin mesh runtimes). Sampling stays local — each shard
    stratifies over its own priorities with its own size N, the correct
    IS weight for the per-shard sampler — but the batch-max
    normalization runs over the GLOBAL batch (pmax over the axis) so the
    weight scale matches the single-device semantics."""
    capacity = replay.priorities.shape[0]
    p = replay.priorities
    cum = jnp.cumsum(p)
    total = cum[-1]
    seg = total / n
    u = (jnp.arange(n, dtype=jnp.float32) + jax.random.uniform(rng, (n,))) * seg
    idx = jnp.clip(jnp.searchsorted(cum, u, side="right"), 0, capacity - 1)
    probs = p[idx] / total
    weights = jnp.power(replay.size.astype(jnp.float32) * probs, -replay.beta)
    wmax = jnp.max(weights)
    if axis_name is not None:
        wmax = jax.lax.pmax(wmax, axis_name)
    weights = weights / wmax
    batch = jax.tree.map(lambda ring: ring[idx], replay.storage)
    new_replay = replay._replace(
        beta=jnp.minimum(1.0, replay.beta + BETA_INCREMENT))
    return new_replay, batch, idx, weights.astype(jnp.float32)


def update_priorities(replay: DeviceReplay, idx: jax.Array,
                      errs: jax.Array) -> DeviceReplay:
    """Refresh every sampled priority (the `update_batch` fix of
    `train_r2d2.py:159`)."""
    return replay._replace(
        priorities=replay.priorities.at[idx].set(priority(errs)))
