"""Sharded replay service with ingest-time prioritization.

Ape-X's core claim (arXiv:1803.00933) is that distributed prioritized
replay scales when priority computation moves OFF the learner — yet the
monolithic topology funnels every trajectory through the learner
thread's own ingest loop (`apex_runner.ingest_many`: decode + TD forward
+ sum-tree insert), and both committed honest-negative A/Bs
(benchmarks/codec_verdict.json, transport_verdict.json) diagnosed
exactly that learner-side path as the bound. "In-network experience
sampling" (arXiv:2110.13506) points the same way: compute priorities
and store experience on the TRANSPORT path, not the train path.

This module is that service, in-process form: N `ReplayShard`s, each
owned by one ingest thread (a TCP serve thread or a shm-ring drainer —
`runtime/replay_shard.py` wires the thread->shard affinity through the
`fifo.blob_ingest` seam). A shard decodes its blobs, computes INITIAL
priorities at ingest (max-priority by default, or a pluggable TD-proxy
scorer — same per-transition granularity and `(|err|+eps)^alpha`
transform as the reference learner's scoring at `train_apex.py:106-122`,
with the network TD replaced by a host-computable proxy), and inserts
into its local prioritized backend. The learner's ingest stages shrink
to a gather-from-shards sample call:

- `sample(n)` allocates the batch across shards PROPORTIONALLY to total
  shard priority mass (largest-remainder rounding, so the marginal
  per-item probability matches the monolithic sampler's p_i/total), each
  shard runs its own stratified pick, and IS weights are computed from
  the GLOBAL total/count and normalized by the global max — the exact
  `(N * p)^-beta / max` semantics of `data/replay.py`. Distribution
  equivalence and bit-identical trajectory contents against the
  monolithic backend are pinned by tests/test_replay_service.py.
- Sample indexes pack (shard id, shard epoch, tree idx) into one int64
  (`pack_index`), so `update_batch` can route each priority update back
  to its owning shard ASYNCHRONOUSLY (a router thread drains a bounded
  deque; under backlog the OLDEST pending batch is dropped — latest
  wins, matching the advisory nature of re-prioritization). An update
  whose epoch no longer matches its shard (the shard restarted) is
  dropped loss-free: restarted shards re-ingest at max-priority, so no
  item can be starved by a lost update.

Failure containment mirrors the repo's demote-on-failure transports
(shm ring -> TCP, weight board -> TCP): a shard whose ingest raises is
marked dead and excluded from sampling; when every shard is dead the
ingest facade (`runtime/replay_shard.ReplayIngestFifo`) demotes
PERMANENTLY to the learner's monolithic queue+replay path.

Gated by `DRL_REPLAY_SHARDS` (0 off, N>=1 forces N shards; unset defers
to the committed `benchmarks/replay_verdict.json` adjudication — the
repo's no-un-adjudicated-fast-path rule, bench.py `replay_compare`).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable

import numpy as np

from distributed_reinforcement_learning_tpu.data.replay import make_replay
from distributed_reinforcement_learning_tpu.data.replay_spill import ColdStoreEmpty
from distributed_reinforcement_learning_tpu.observability import TELEMETRY as _OBS

# -- packed sample indexes ----------------------------------------------------
#
# [tag:1][epoch:8][shard:8][tree_idx:46] in an int64. The tag bit keeps
# packed indexes disjoint from any monolithic tree index (< 2*capacity),
# so a learner that demoted mid-run can never mis-route an update from a
# pre-demotion batch into the monolithic tree.

_IDX_BITS = 46
_SHARD_BITS = 8
_EPOCH_BITS = 8
_TAG = np.int64(1) << np.int64(_IDX_BITS + _SHARD_BITS + _EPOCH_BITS)
_IDX_MASK = (np.int64(1) << np.int64(_IDX_BITS)) - np.int64(1)
_SHARD_MASK = (np.int64(1) << np.int64(_SHARD_BITS)) - np.int64(1)
_EPOCH_MASK = (np.int64(1) << np.int64(_EPOCH_BITS)) - np.int64(1)

MAX_SHARDS = 1 << _SHARD_BITS


def pack_index(shard: int, epoch: int, tree_idx):
    """(shard id, shard epoch, backend tree idx) -> tagged int64 (vectorized)."""
    idx = np.asarray(tree_idx, np.int64)
    return (_TAG
            | (np.int64(epoch & int(_EPOCH_MASK)) << np.int64(_IDX_BITS + _SHARD_BITS))
            | (np.int64(shard & int(_SHARD_MASK)) << np.int64(_IDX_BITS))
            | (idx & _IDX_MASK))


def unpack_index(packed):
    """Tagged int64 -> (shard ids, epochs, tree idxs) as int64 arrays."""
    p = np.asarray(packed, np.int64)
    return ((p >> np.int64(_IDX_BITS)) & _SHARD_MASK,
            (p >> np.int64(_IDX_BITS + _SHARD_BITS)) & _EPOCH_MASK,
            p & _IDX_MASK)


def is_packed_index(packed) -> np.ndarray:
    """Bool mask: which indexes carry the shard tag bit."""
    return (np.asarray(packed, np.int64) & _TAG) != 0


# -- ingest-time scorers ------------------------------------------------------


def _reward_done_of(tree: Any) -> tuple[np.ndarray, np.ndarray]:
    """(reward, done) leaves of a trajectory pytree (namedtuple or dict)."""
    if hasattr(tree, "reward"):
        return np.asarray(tree.reward), np.asarray(tree.done)
    return np.asarray(tree["reward"]), np.asarray(tree["done"])


def td_proxy_scorer(tree: Any, per_transition: bool) -> np.ndarray:
    """Host-computable stand-in for the learner's ingest-time TD score.

    Same granularity and downstream transform as the reference's
    learner-side scoring (`train_apex.py:106-122`: one |err| per
    transition through `(|err|+eps)^alpha`), with the network TD error
    replaced by |clip(r)| + terminal bonus — the reward-driven part of
    the one-step TD target, computable on the ingest thread without
    touching the net. Sequence-mode shards (R2D2: one priority per
    sequence) reduce the per-step proxy by its mean, mirroring the
    reference's |mean TD| sequence priority (`train_r2d2.py:100-119`).
    """
    reward, done = _reward_done_of(tree)
    per_step = np.abs(np.clip(reward, -1.0, 1.0)) + done.astype(np.float64)
    if per_transition:
        return per_step.astype(np.float64).reshape(-1)
    return np.atleast_1d(np.float64(per_step.mean()))


def make_scorer(name: str) -> Callable[[Any, bool], np.ndarray] | None:
    """'max' -> None (max-priority fill, the Ape-X default for items the
    learner has not yet seen: every new item is sampled at least once);
    'td_proxy' -> `td_proxy_scorer`."""
    if name in ("", "max"):
        return None
    if name == "td_proxy":
        return td_proxy_scorer
    raise ValueError(f"unknown replay scorer {name!r} (one of: max, td_proxy)")


# -- deferred-decode items ----------------------------------------------------


class LazyBlob:
    """A sequence-mode replay item stored as its (owned) wire blob.

    The sample-at-source fast accept (ISSUE 18): when the stamp already
    carries the sequence priority, an opaque-item backend has no reason
    to decode on the ingest thread at all — the blob is stored as-is and
    decoded ONCE at first materialization (sample gather or snapshot),
    which runs on the learner/checkpoint thread. Bytes are copied at
    construction: wire receive buffers are reused per connection.

    Materialization is deliberately lock-free: `_tree` is published
    before `_blob` is dropped, so a concurrent materializer either sees
    the tree or re-decodes the same bytes to an equal tree — duplicate
    work, never a torn read (decode is pure).
    """

    __slots__ = ("_blob", "_tree")

    def __init__(self, blob):
        self._blob = bytes(memoryview(blob))
        self._tree = None

    def materialize(self):
        tree = self._tree
        if tree is not None:
            return tree
        blob = self._blob
        if blob is None:  # lost a materialize race: the tree is set
            return self._tree
        from distributed_reinforcement_learning_tpu.data import codec

        tree = codec.decode(blob, copy=True, cache=True)
        self._tree = tree
        self._blob = None  # decode owns its arrays; drop the bytes
        return tree


def _materialize(item):
    # Duck-typed: LazyBlob here, and the spill tier's cold-segment
    # snapshot refs (data/replay_spill._SegmentRef) resolve the same way.
    return item.materialize() if hasattr(item, "materialize") else item


# -- one shard ----------------------------------------------------------------


class ReplayShard:
    """One ingest thread's local prioritized store.

    `mode` is "transition" (Ape-X: a decoded unroll's leading axis is
    the item axis — one priority per transition) or "sequence" (R2D2
    family: the whole decoded tree is one item). All backend access and
    the max-priority bookkeeping run under one lock: the owning ingest
    thread inserts, the learner thread gathers samples, and the update
    router re-prioritizes — three threads on one small mutex, which is
    exactly the contention the per-shard split bounds (vs the monolithic
    design's single global tree).
    """

    # Concurrency map (tools/drlint lock-discipline): the backend handle
    # itself is swapped on restart() and read by sample/update paths;
    # counters are bumped by ingest/router threads and read by telemetry
    # providers; `epoch`/`dead` gate the router's stale-update drop.
    _GUARDED_BY = {
        "backend": "_lock",
        "_max_error": "_lock",
        "epoch": "_lock",
        "dead": "_lock",
        "ingested_blobs": "_lock",
        "ingested_items": "_lock",
        "updates_applied": "_lock",
    }
    _NOT_GUARDED = {
        "tier_kick": "set once by the owning service before any "
                     "maintenance runs (None on standalone shards); "
                     "called to wake the router for a pending promote",
    }

    def __init__(self, shard_id: int, capacity: int, mode: str = "transition",
                 scorer: Callable[[Any, bool], np.ndarray] | None = None,
                 backend: str = "auto", seed: int = 0, spill=None):
        if mode not in ("transition", "sequence"):
            raise ValueError(f"unknown shard mode {mode!r}")
        self.shard_id = shard_id
        self.capacity = capacity
        self.mode = mode
        self.scorer = scorer
        self._backend_kind = backend
        self._seed = seed
        self._spill = spill.for_shard(shard_id) if spill is not None else None
        self._lock = threading.Lock()
        # Signaled by tier_step() commits; tiered sampling waits on it
        # (bounded) when a gather draws cold segments still promoting.
        self._tier_cv = threading.Condition(self._lock)
        self.tier_kick: Callable[[], None] | None = None
        self.backend = make_replay(capacity, backend=backend,
                                   seed=seed + 101 * shard_id,
                                   spill=self._spill, mode=mode)
        self.epoch = 0
        self.dead = False
        self._max_error = 1.0  # error-domain running max (transform is monotone)
        self.ingested_blobs = 0
        self.ingested_items = 0
        self.updates_applied = 0

    # -- ingest (owning drainer thread) -----------------------------------

    def ingest_blob(self, blob) -> int:
        """Decode one wire blob and insert it; returns items inserted.

        decode(cache=True) forces the layout cache regardless of the
        trajectory-path codec verdict: shard ingest sees one stable
        schema per run, the same argument that has the weight plane
        force its own encode cache (`runtime/weights.py`).
        """
        from distributed_reinforcement_learning_tpu.data import codec

        return self.ingest(codec.decode(blob, copy=True, cache=True))

    def ingest(self, tree: Any) -> int:
        """Score + insert one decoded trajectory pytree."""
        per_transition = self.mode == "transition"
        if self.scorer is not None:
            errors = np.asarray(self.scorer(tree, per_transition), np.float64)
        else:
            errors = None
        with self._lock:
            if self.dead:
                raise RuntimeError(f"replay shard {self.shard_id} is dead")
            if errors is None:
                n = (int(np.asarray(_first_leaf(tree)).shape[0])
                     if per_transition else 1)
                errors = np.full(n, self._max_error, np.float64)
            else:
                self._max_error = max(self._max_error, float(errors.max()))
            n = self._insert_locked(errors, tree, per_transition)
            self.ingested_blobs += 1
            self.ingested_items += n
        return n

    def ingest_stamped(self, errors, tree: Any = None, blob=None) -> int:
        """Insert with ACTOR-stamped initial priorities
        (data/admission.py), skipping this shard's scorer pass entirely.

        `errors` are error-domain float64 — the stamp's values, which
        are bit-equal to what `self.scorer` would have produced (or
        Horvitz-Thompson-corrected under admission subsampling).
        Transition mode requires the decoded `tree` (array backends
        gather per field) and validates its leading axis against the
        stamp length; sequence mode takes the decoded tree OR the raw
        `blob` — an opaque-item backend stores a `LazyBlob` and defers
        decode to first materialization. Raises ValueError on any
        stamp/tree mismatch so the caller can fall back to the scoring
        path (`ingest`)."""
        per_transition = self.mode == "transition"
        errors = np.asarray(errors, np.float64).reshape(-1)
        if errors.size == 0:
            raise ValueError("stamped ingest: empty priority list")
        if per_transition:
            if tree is None:
                raise ValueError(
                    "stamped ingest: transition mode needs the decoded tree")
            n_tree = int(np.asarray(_first_leaf(tree)).shape[0])
            if n_tree != errors.size:
                raise ValueError(
                    f"stamped ingest: {errors.size} priorities for "
                    f"{n_tree} transitions")
        else:
            if errors.size != 1:
                raise ValueError(
                    "stamped ingest: sequence mode takes ONE priority, "
                    f"got {errors.size}")
            if tree is None:
                if blob is None:
                    raise ValueError("stamped ingest: need a tree or a blob")
                from distributed_reinforcement_learning_tpu.data import codec

                with self._lock:  # backend binding is guarded; the flag
                    stacked = getattr(  # itself is construction-time
                        self.backend, "stacked_samples", False)
                if stacked:
                    # Stacked backends store per-field arrays — no
                    # opaque slot to defer into; decode here (still off
                    # the scorer pass).
                    tree = codec.decode(blob, copy=True, cache=True)
                else:
                    codec.check_blob(blob)  # poison fails HERE, not at
                    tree = LazyBlob(blob)   # sample-time materialization
        with self._lock:
            if self.dead:
                raise RuntimeError(f"replay shard {self.shard_id} is dead")
            self._max_error = max(self._max_error, float(errors.max()))
            n = self._insert_locked(errors, tree, per_transition)
            self.ingested_blobs += 1
            self.ingested_items += n
        return n

    def _insert_locked(self, errors: np.ndarray, tree: Any,
                       per_transition: bool) -> int:
        import jax

        if per_transition:
            if getattr(self.backend, "stacked_samples", False):
                self.backend.add_batch_stacked(errors, tree)
            else:
                self.backend.add_batch(
                    errors,
                    [jax.tree.map(lambda x: x[i], tree)
                     for i in range(len(errors))])
            return len(errors)
        self.backend.add(float(errors[0]), tree)
        return 1

    # -- gather-side (learner thread) -------------------------------------

    def stats(self) -> dict:
        """Fill / priority-mass / counters snapshot (telemetry providers
        and the obs_report 'Replay shards' section poll this)."""
        with self._lock:
            return {
                "count": len(self.backend),
                "fill": len(self.backend) / self.capacity,
                "priority_mass": float(self.backend.tree.total),
                "ingested_blobs": self.ingested_blobs,
                "ingested_items": self.ingested_items,
                "updates_applied": self.updates_applied,
                "epoch": self.epoch,
                "dead": self.dead,
            }

    def mass_count(self) -> tuple[float, int, bool]:
        with self._lock:
            if self.dead:
                return 0.0, 0, True
            return float(self.backend.tree.total), len(self.backend), False

    def sample_with_priorities(self, n: int, rng) -> tuple[Any, np.ndarray,
                                                           np.ndarray, int]:
        """-> (items_or_stacked, tree_idxs, raw priorities, epoch): this
        shard's slice of a gather. Raw (already-transformed) priorities,
        NOT IS weights — the service computes those globally.

        Tiered backends complete in steps: a draw landing on a cold
        segment queues it and the gather WAITS (bounded, on `_tier_cv`,
        which releases the shard lock) for the router/ingest threads to
        promote — the learn thread itself never touches disk. In steady
        state the draw-ahead prefetch window means promotes already
        overlap the previous train step and the wait is a no-op."""
        with self._lock:
            backend = self.backend
            step = getattr(backend, "sample_step", None)
            if step is None:
                out = backend.sample_with_priorities(n, rng)
                return (*out, self.epoch)
            deadline = time.monotonic() + self._spill.wait_s
            while True:
                out = step(n, rng, force=time.monotonic() >= deadline)
                if out is not None:
                    return (*out, self.epoch)
                kick = self.tier_kick
                if kick is not None:
                    kick()  # shard lock -> service _work; never reversed
                self._tier_cv.wait(timeout=0.05)

    # -- update router side ------------------------------------------------

    def update(self, tree_idxs: np.ndarray, errors: np.ndarray,
               epoch: int) -> int:
        """Apply a routed priority-update batch; stale-epoch batches are
        dropped loss-free (see module docstring). Returns applied count."""
        with self._lock:
            if self.dead or epoch != self.epoch:
                return 0
            self.backend.update_batch(tree_idxs, errors)
            self._max_error = max(self._max_error,
                                  float(np.abs(errors).max()))
            self.updates_applied += len(tree_idxs)
            return len(tree_idxs)

    # -- lifecycle ---------------------------------------------------------

    def mark_dead(self) -> None:
        with self._lock:
            self.dead = True

    def restart(self) -> None:
        """Fresh backend under a new epoch: in-flight updates against the
        old contents are dropped by the epoch check, and everything
        re-ingested starts at max-priority — nothing can be starved. A
        tiered backend's spill directory is wiped (`fresh=True`): restart
        is the post-death clean slate, distinct from process-restart
        RECOVERY, which reattaches the manifest at construction."""
        with self._lock:
            old = self.backend
            if hasattr(old, "close"):
                old.close()  # in-flight tier jobs no-op their commits
            spill = self._spill
            if spill is not None:
                from dataclasses import replace as _dc_replace

                spill = _dc_replace(spill, fresh=True)
            self.backend = make_replay(self.capacity, backend=self._backend_kind,
                                       seed=self._seed + 101 * self.shard_id,
                                       spill=spill, mode=self.mode)
            self.epoch = (self.epoch + 1) & int(_EPOCH_MASK)
            self.dead = False
            self._max_error = 1.0

    def snapshot(self) -> dict:
        with self._lock:
            snap = self.backend.snapshot()
        items = snap.get("items")
        if items is not None:
            # Materialize deferred blobs outside the shard lock — a
            # snapshot must persist decoded trees, not wire bytes.
            snap["items"] = [_materialize(it) for it in items]
        return snap

    def restore_part(self, priorities, items) -> None:
        with self._lock:
            self.backend.restore({"priorities": np.asarray(priorities, np.float64),
                                  "items": list(items),
                                  "beta": float(self.backend.beta)})
            # ingested_blobs stays in BLOB units (unrolls/sequences): a
            # transition-mode snapshot restores per-transition items
            # whose originating blob count is unknown here, and the
            # learner's own restored counter covers its warm gate — so
            # only sequence mode (item == blob) counts toward it.
            if self.mode == "sequence":
                self.ingested_blobs += len(items)
            self.ingested_items += len(items)

    # -- tier maintenance (ingest + router threads) ------------------------

    def tier_step(self) -> bool:
        """Run ONE unit of spill-tier maintenance (promote a sampled-cold
        segment, spill a cold-mass victim, unlink, or sync the manifest).
        Plan and commit bracket the shard lock; the file I/O in between
        holds NO lock — this is the only place replay bytes touch disk,
        and it rides the ingest/router threads, never the learn thread.
        Returns True when a job ran (callers loop while True)."""
        with self._lock:
            backend = self.backend
            plan = getattr(backend, "plan_tier_work", None)
            job = plan() if plan is not None and not self.dead else None
        if job is None:
            return False
        job.run_io()
        manifest = None
        events: list[tuple[str, float]] = []
        with self._lock:
            if self.backend is backend:  # restart() swapped the store:
                manifest = backend.commit_tier_work(job)  # stale job's
                events = backend.take_obs()               # commit no-ops
                self._tier_cv.notify_all()
        if manifest is not None:
            backend.write_manifest(manifest)
        if events and _OBS.enabled:
            sid = self.shard_id
            for name, value in events:
                if name.endswith(("_bytes",)):
                    _OBS.count(f"replay_spill/{sid}/{name}", int(value))
                    _OBS.count(
                        f"replay_spill/{sid}/"
                        f"{name.replace('_bytes', '_segments')}", 1)
                elif name == "promote_wait_ms":
                    _OBS.gauge(f"replay_spill/{sid}/promote_wait_ms", value)
                else:
                    _OBS.count(f"replay_spill/{sid}/{name}", int(value))
        return True

    def tier_pending(self) -> bool:
        with self._lock:
            pending = getattr(self.backend, "tier_pending", None)
            return pending is not None and pending()

    def tier_stats(self) -> dict | None:
        with self._lock:
            stats = getattr(self.backend, "tier_stats", None)
            return stats() if stats is not None else None


def _first_leaf(tree: Any):
    import jax

    return jax.tree.leaves(tree)[0]


# -- batch allocation ---------------------------------------------------------


def allocate_proportional(n: int, masses: np.ndarray) -> np.ndarray:
    """Split a batch of n across shards proportionally to priority mass,
    by largest remainder: sum(out) == n exactly, every share within 1 of
    n * mass_i / sum(masses), zero-mass shards get zero."""
    masses = np.asarray(masses, np.float64)
    total = masses.sum()
    if n <= 0 or total <= 0:
        return np.zeros(len(masses), np.int64)
    exact = n * masses / total
    out = np.floor(exact).astype(np.int64)
    remainder = n - int(out.sum())
    if remainder > 0:
        frac = exact - out
        frac[masses <= 0] = -1.0  # never round a zero-mass shard up
        for i in np.argsort(-frac)[:remainder]:
            out[i] += 1
    return out


def merge_is_weights(priorities: np.ndarray, global_total: float,
                     global_count: int, beta: float) -> np.ndarray:
    """Monolithic `(N * p / total)^-beta / max` IS semantics over a
    gathered batch: N and total are GLOBAL (summed over shards), the
    normalizing max is the merged batch's max — so a one-shard service
    reproduces `data/replay._is_weights` bit-for-bit."""
    probs = np.asarray(priorities, np.float64) / global_total
    weights = np.power(global_count * probs, -beta)
    weights /= weights.max()
    return weights.astype(np.float32)


# -- the service --------------------------------------------------------------


class ReplayServiceEmpty(RuntimeError):
    """sample() found no live, populated shard. Distinct from a generic
    RuntimeError so the learner's `_train_guarded` can treat it as a
    transient skip (a fleet-sweep `revive()` can empty the shards
    between the caller's len() guard and its sample()) rather than a
    learn-step fault that must propagate."""


class ShardedReplayService:
    """N-shard replay with the monolithic backend's sampling surface.

    Implements the slice of the `data/replay.py` interface the
    prioritized learners use — `sample`, `update_batch`, `__len__`,
    `beta`, `snapshot`/`restore`, `stacked_samples` — so
    `apex_runner`/`r2d2_runner`/`replay_train` swap it in for the
    monolithic backend without touching the train math.
    """

    EPS = 0.001
    ALPHA = 0.6
    BETA_INCREMENT = 0.001

    # Concurrency map (tools/drlint lock-discipline): `_pending` is the
    # async update queue (learner thread appends, router thread pops,
    # flush_updates waits on it); `_applying` marks a popped batch still
    # being applied so flush can't return early; `beta` anneals on the
    # learner thread but is read by checkpoint code; `healthy` latches
    # false on all-shards-dead demotion (facade + learner read it).
    _GUARDED_BY = {
        "_pending": ("_lock", "_work"),
        "_applying": ("_lock", "_work"),
        "_closed": ("_lock", "_work"),
        "_beta": ("_lock", "_work"),
        "_healthy": ("_lock", "_work"),
        "updates_dropped": ("_lock", "_work"),
    }
    _NOT_GUARDED = {
        "shards": "fixed fan-out list assigned once in __init__ and never "
                  "rebound; each ReplayShard synchronizes itself",
        "_tiered": "set once in __init__ (spill tier on/off), never rebound",
    }

    def __init__(self, num_shards: int, capacity: int,
                 mode: str = "transition", scorer: str = "max",
                 backend: str = "auto", beta: float = 0.4, seed: int = 0,
                 max_pending_updates: int = 256, spill=None):
        if not 1 <= num_shards <= MAX_SHARDS:
            raise ValueError(f"num_shards must be in [1, {MAX_SHARDS}]")
        per_shard = max(1, capacity // num_shards)
        score_fn = make_scorer(scorer)
        self.scorer_name = scorer or "max"
        self.shards = [
            ReplayShard(i, per_shard, mode=mode, scorer=score_fn,
                        backend=backend, seed=seed, spill=spill)
            for i in range(num_shards)
        ]
        self._tiered = spill is not None
        if self._tiered:
            for shard in self.shards:
                # Tiered gathers that draw cold segments wake the router
                # immediately instead of riding out its idle tick.
                shard.tier_kick = self._tier_kick
        self.mode = mode
        self.stacked_samples = bool(
            getattr(self.shards[0].backend, "stacked_samples", False))
        self._beta = beta
        self._healthy = True
        self.updates_dropped = 0
        self._np_rng = np.random.RandomState(seed + 7)
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        # Bounded latest-wins backlog: appends on the learner thread,
        # popleft on the router; a full deque drops the OLDEST batch.
        self._pending: deque = deque(maxlen=max_pending_updates)
        self._applying = False
        self._closed = False
        self._router = threading.Thread(target=self._route_loop, daemon=True,
                                        name="replay-update-router")
        self._router.start()

    # -- size / warm-gate accounting ---------------------------------------

    @property
    def beta(self) -> float:
        """Annealed IS exponent; a plain locked attribute so the generic
        learner checkpoint code (`replay.beta = ...`) works unchanged."""
        with self._lock:
            return self._beta

    @beta.setter
    def beta(self, value: float) -> None:
        with self._lock:
            self._beta = float(value)

    @property
    def healthy(self) -> bool:
        """False once every shard died — the learner and the ingest
        facade both demote PERMANENTLY to the monolithic path."""
        with self._lock:
            return self._healthy

    def __len__(self) -> int:
        return sum(s.mass_count()[1] for s in self.shards)

    def ingested_blobs(self) -> int:
        """Total blobs (unrolls / sequences) ingested across shards —
        the learners' warm-up gate unit."""
        return sum(s.stats()["ingested_blobs"] for s in self.shards)

    def live_shards(self) -> list[ReplayShard]:
        return [s for s in self.shards if not s.mass_count()[2]]

    def note_shard_death(self, shard: ReplayShard) -> None:
        """Ingest-side failure path: mark the shard dead; when none are
        left, latch the service unhealthy (the facade and the learner
        both demote to the monolithic path until `revive()` — the fleet
        supervisor's bounded re-promote ladder — restarts the shards)."""
        shard.mark_dead()
        if not self.live_shards():
            with self._lock:
                self._healthy = False

    def revive(self) -> int:
        """Restart every dead shard under a fresh epoch and re-latch the
        service healthy — the learner-side re-promotion the fleet
        supervisor's sweep drives (runtime/replay_shard.py). Contents of
        a restarted shard are gone by design (replay overwrites its
        oldest anyway; everything re-ingested starts at max priority)
        and in-flight priority updates against the old epoch drop
        loss-free. Returns how many shards were restarted."""
        restarted = 0
        for shard in self.shards:
            if shard.mass_count()[2]:
                shard.restart()
                restarted += 1
        with self._lock:
            self._healthy = True
        return restarted

    # -- sampling (learner thread) -----------------------------------------

    def sample(self, n: int, rng=None):
        """Gather a prioritized batch across shards; returns
        (items_or_stacked, packed_idxs, is_weights) with monolithic
        semantics (module docstring)."""
        import jax

        t0 = time.perf_counter()
        rng = rng or self._np_rng
        # ONE locked pass per shard: liveness rides the same snapshot
        # (this runs once per train step, contending with ingest and
        # router threads for the shard locks).
        stats = [s.mass_count() for s in self.shards]
        masses = np.array([m for m, _, dead in stats], np.float64)
        global_total = float(masses.sum())
        global_count = sum(c for _, c, _ in stats)
        if all(dead for _, _, dead in stats) or global_count == 0 \
                or global_total <= 0:
            raise ReplayServiceEmpty("sharded replay is empty or dead")
        with self._lock:
            self._beta = min(1.0, self._beta + self.BETA_INCREMENT)
            beta = self._beta
        alloc = allocate_proportional(n, masses)
        parts: list[Any] = []
        idx_parts: list[np.ndarray] = []
        prio_parts: list[np.ndarray] = []
        shortfall = 0
        served: list[tuple[ReplayShard, float]] = []
        for shard, k, mass in zip(self.shards, alloc, masses):
            if k == 0:
                continue
            try:
                items, idxs, prios, epoch = shard.sample_with_priorities(
                    int(k), rng)
            except ColdStoreEmpty:
                # All-cold tiered shard (restart recovery, promotes still
                # in flight): redistribute its slice below rather than
                # failing the whole gather.
                shortfall += int(k)
                continue
            served.append((shard, float(mass)))
            parts.append(items)
            idx_parts.append(pack_index(shard.shard_id, epoch, idxs))
            prio_parts.append(prios)
        if shortfall and served:
            shard = max(served, key=lambda sm: sm[1])[0]
            try:
                items, idxs, prios, epoch = shard.sample_with_priorities(
                    shortfall, rng)
            except ColdStoreEmpty:
                shard = None
            if shard is not None:
                shortfall = 0
                parts.append(items)
                idx_parts.append(pack_index(shard.shard_id, epoch, idxs))
                prio_parts.append(prios)
        if not parts or shortfall:
            # A short batch would change train-step shapes; a transient
            # skip is the contract the learners already honor.
            raise ReplayServiceEmpty(
                "cold-only tiered shards (promotes in flight)")
        priorities = np.concatenate(prio_parts)
        packed = np.concatenate(idx_parts)
        weights = merge_is_weights(priorities, global_total, global_count, beta)
        if self.stacked_samples:
            batch = (parts[0] if len(parts) == 1 else
                     jax.tree.map(lambda *xs: np.concatenate(xs), *parts))
        else:
            # Deferred-decode items (stamped sequence ingest) decode
            # here, on the learner thread, outside every shard lock.
            batch = [_materialize(item) for part in parts for item in part]
        if _OBS.enabled:
            _OBS.gauge("replay_shard/sample_ms",
                       (time.perf_counter() - t0) * 1e3)
            _OBS.count("replay_shard/samples", n)
        return batch, packed, weights

    # -- async priority updates --------------------------------------------

    def update_batch(self, packed_idxs, errors) -> None:
        """Enqueue a priority-update batch for the router thread; returns
        immediately (the learner thread never walks a sum tree here).
        Non-tagged indexes (a batch sampled from the monolithic fallback
        after demotion) are ignored — the caller routes those itself."""
        packed = np.asarray(packed_idxs, np.int64)
        errs = np.asarray(errors, np.float64)
        mask = is_packed_index(packed)
        if not mask.all():
            packed, errs = packed[mask], errs[mask]
            if packed.size == 0:
                return
        with self._work:
            if self._closed:
                return
            if len(self._pending) == self._pending.maxlen:
                self.updates_dropped += 1  # latest-wins: oldest falls out
            self._pending.append((packed, errs))
            self._work.notify()

    def _route_loop(self) -> None:
        tier_busy = False
        while True:
            with self._work:
                if not self._pending and not self._closed and not tier_busy:
                    # Bounded wait (drlint blocking-under-lock): the
                    # predicate is re-checked each iteration, so a notify
                    # lost to a close/enqueue race delays the router by
                    # at most one tick instead of parking it forever.
                    # Tiered services also ride this tick for spill-tier
                    # maintenance, so sampling kicks `_work` directly.
                    self._work.wait(timeout=0.05 if self._tiered else 0.5)
                if self._closed and not self._pending:
                    return
                batch = self._pending.popleft() if self._pending else None
                if batch is not None:
                    self._applying = True
            if batch is not None:
                try:
                    self._apply_update(*batch)
                finally:
                    with self._work:
                        self._applying = False
                        self._work.notify_all()
            tier_busy = bool(self._tier_tick()) if self._tiered else False

    def _tier_kick(self) -> None:
        with self._work:
            self._work.notify()

    def _tier_tick(self) -> int:
        """Run up to a few spill/promote/manifest jobs per shard (each
        shard's plan picks its own priority order); returns jobs done so
        the router skips its idle wait while a backlog remains."""
        done = 0
        for shard in self.shards:
            for _ in range(4):
                if not shard.tier_step():
                    break
                done += 1
        return done

    def flush_tier(self, timeout: float | None = 10.0) -> bool:
        """Drive spill-tier maintenance to quiescence on the CALLING
        thread (tests / benches / checkpoint barriers): safe alongside
        the router — every job is planned and committed under its
        shard's lock, so two maintenance threads interleave cleanly."""
        if not self._tiered:
            return True
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            busy = self._tier_tick()
            if not busy and not any(s.tier_pending() for s in self.shards):
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            if not busy:
                time.sleep(0.005)  # a router-held job is finishing its IO

    def _apply_update(self, packed: np.ndarray, errs: np.ndarray) -> None:
        shard_ids, epochs, idxs = unpack_index(packed)
        applied = 0
        for sid in np.unique(shard_ids):
            if not 0 <= sid < len(self.shards):
                continue
            pick = shard_ids == sid
            for epoch in np.unique(epochs[pick]):
                sel = pick & (epochs == epoch)
                applied += self.shards[int(sid)].update(
                    idxs[sel], errs[sel], int(epoch))
        if _OBS.enabled and applied:
            _OBS.count("replay_shard/updates_applied", applied)

    def flush_updates(self, timeout: float | None = 5.0) -> bool:
        """Block until every enqueued update batch has been applied (or
        dropped); tests and checkpoint snapshots use this barrier."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._work:
            while self._pending or self._applying:
                wait = (None if deadline is None
                        else max(0.0, deadline - time.monotonic()))
                if wait is not None and wait <= 0:
                    return False
                self._work.wait(timeout=wait)
            return True

    # -- checkpoint round trip ---------------------------------------------

    def snapshot(self) -> dict:
        """Merged shard snapshots in the list-backend format
        (`utils/checkpoint.encode_replay_snapshot` consumes it as-is).
        Pending updates are flushed first so priorities are current."""
        from distributed_reinforcement_learning_tpu.data.replay import _snapshot_items

        self.flush_updates()
        prios: list[np.ndarray] = []
        items: list[Any] = []
        for shard in self.shards:
            snap = shard.snapshot()
            prios.append(np.asarray(snap["priorities"], np.float64))
            items.extend(_snapshot_items(snap))
        with self._lock:
            beta = self._beta
        return {"priorities": (np.concatenate(prios) if prios
                               else np.zeros(0, np.float64)),
                "items": items, "beta": beta}

    def restore(self, snap: dict) -> None:
        """Round-robin a (possibly monolithic) snapshot across live
        shards; raw priorities are exact, shard placement is not part of
        replay semantics (sampling is proportional either way)."""
        from distributed_reinforcement_learning_tpu.data.replay import _snapshot_items

        live = self.live_shards() or self.shards
        items = _snapshot_items(snap)
        prios = np.asarray(snap["priorities"], np.float64)
        k = len(live)
        for i, shard in enumerate(live):
            sel = slice(i, len(items), k)
            if prios[sel].size:
                shard.restore_part(prios[sel], items[sel])
        with self._lock:
            self._beta = float(snap["beta"])

    def approx_snapshot_nbytes(self) -> int:
        """Sum of per-shard estimates when every backend can price its
        snapshot (the SoA backends); 0 = unknown, let the encoder measure."""
        total = 0
        for shard in self.shards:
            est = getattr(shard.backend, "approx_snapshot_nbytes", None)
            if est is None:
                return 0
            total += est()
        return total

    # -- telemetry / lifecycle ---------------------------------------------

    def shard_stats(self) -> list[dict]:
        return [s.stats() for s in self.shards]

    def tier_stats(self) -> list[dict] | None:
        """Per-shard spill-tier stats, or None when the tier is off."""
        if not self._tiered:
            return None
        return [s.tier_stats() or {} for s in self.shards]

    def close(self) -> None:
        with self._work:
            self._closed = True
            self._work.notify_all()
        self._router.join(timeout=2.0)
        for shard in self.shards:
            with shard._lock:
                backend = shard.backend
            backend_close = getattr(backend, "close", None)
            if backend_close is not None:
                backend_close()
