"""Tiered replay: hot/cold spill tier under the sharded replay service.

Every replay byte used to live in learner RAM, capping stored experience
far below what a production fleet generates (ROADMAP item 6). This
module gives each `ReplayShard` a `TieredStore` backend: a HOT set of
segments resident in RAM plus COLD segments spilled to disk as the
already-encoded codec blobs (the PR 18 `LazyBlob`/stamp machinery means
sequence-mode items arrive as wire blobs — spilling one is a write, not
an encode) with their priority summaries. Priorities for EVERY segment
stay resident (8 bytes/item — that is the whole point: the sampling
DISTRIBUTION fits in RAM even when the payload does not), so:

- proportional sampling is exact over the full store: draws walk the
  per-segment mass cumsum, then the in-segment priority cumsum;
- priority writebacks are loss-free across spill/promote by
  construction — the float64 priority array never moves to disk-only,
  the mover only copies it (same ledger discipline as the PR 18
  admission mass pin);
- eviction (capacity overwrite) and spill/promote VICTIM selection are
  by priority mass, the quantity the sampler actually consumes.

Draws that land on a cold segment are queued (a bounded draw-ahead FIFO)
and the segment is requested for promotion; the learn thread NEVER
touches disk — spill serialization and promote reads ride the ingest
threads (`ReplayShard.tier_step` after each insert) and the service's
update-router thread (`ShardedReplayService._tier_tick`). The queue is
also a prefetch window: `sample_step` tops it up with draws for the NEXT
batch, so promotes overlap the learner's train step instead of stalling
its sample. Exactness argument: every delivered item corresponds to
exactly one full-distribution draw (queued entries deliver later, order
does not affect counts), so aggregate frequencies match the all-RAM
backend — pinned by the chi-square test in tests/test_replay_spill.py.
Only the bounded-wait fallback (`forced_pads`, resident-only fill after
`wait_s`) can bias, and it is counted, not silent.

A learner restart recovers cold segments from `manifest.json` (atomic
rewrite, PR 9 pattern) with a crc32 per segment file verified at promote
time (PR 8 style): a corrupt file drops that one segment and counts it
(`crc_dropped`), never poisons the shard.

Gated by `DRL_REPLAY_SPILL*` (runtime/replay_shard.py) deferring to the
committed `benchmarks/replay_spill_verdict.json` adjudication
(bench.py `replay_spill_compare`), like every prior fast path.
"""

from __future__ import annotations

import io
import json
import os
import tempfile
import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass, replace
from pathlib import Path
from typing import Any

import numpy as np

from distributed_reinforcement_learning_tpu.data.replay import priority_transform

_MAGIC = b"DRLS"
_VERSION = 1

# Packed in-shard sample index: [segment sid : high bits][offset : 20].
# The shard packs this into the low 46 bits of the service-level index,
# so sid has 26 bits of headroom — at the 512-item default segment that
# is ~34e9 items of shard lifetime before wrap.
_OFF_BITS = 20
_SEG_CAP = 1 << _OFF_BITS


class ColdStoreEmpty(RuntimeError):
    """A sample could not complete from resident segments and the
    bounded promote wait expired (or nothing is resident at all — the
    all-cold state right after a restart recovery). The service converts
    this to `ReplayServiceEmpty`: a transient learner skip while the
    router thread promotes, never a learn-step fault."""


@dataclass(frozen=True)
class SpillConfig:
    """Knob bundle for a shard's spill tier (runtime/replay_shard.py
    resolves the DRL_REPLAY_SPILL* environment into one of these)."""

    directory: str
    hot_bytes: int = 256 * 1024 * 1024
    seg_items: int = 512
    wait_s: float = 2.0
    queue_cap: int = 4096
    max_inflight: int = 2
    fresh: bool = False  # True: wipe the directory (shard restart)

    def for_shard(self, shard_id: int) -> "SpillConfig":
        return replace(self,
                       directory=os.path.join(self.directory,
                                              f"shard_{shard_id:03d}"))


class _Segment:
    """One append-ordered run of items. Sealed segments are immutable in
    CONTENT (items/prios length); priorities mutate in place via
    writebacks. `items is None` means the payload is on disk only."""

    __slots__ = ("sid", "state", "gen", "items", "prios", "count", "mass",
                 "cumsum", "payload_bytes", "file", "file_crc", "file_nbytes",
                 "debt")

    def __init__(self, sid: int, seg_items: int):
        self.sid = sid
        self.state = "open"  # open -> hot -> spilling -> cold -> promoting
        self.gen = 0
        self.items: list[Any] | None = []
        self.prios = np.zeros(seg_items, np.float64)
        self.count = 0
        self.mass = 0.0
        self.cumsum: np.ndarray | None = None
        self.payload_bytes = 0
        self.file: str | None = None
        self.file_crc = 0
        self.file_nbytes = 0
        self.debt = 0  # queued draws referencing this segment (pin)

    @property
    def resident(self) -> bool:
        return self.items is not None


class _TierJob:
    """One planned unit of tier maintenance. Planned and committed under
    the owning shard's lock; `run_io` touches ONLY job-local state (the
    sealed segment's immutable items list, a priority COPY, file paths),
    so it runs with no lock held. Never raises: IO/parse failures land
    in `error` for the commit step to adjudicate."""

    __slots__ = ("kind", "sid", "gen", "mode", "items", "prios", "path",
                 "crc", "nbytes", "payload_bytes", "paths", "reuse",
                 "result", "error")

    def __init__(self, kind: str, **kw: Any):
        self.kind = kind
        self.sid = kw.get("sid", -1)
        self.gen = kw.get("gen", 0)
        self.mode = kw.get("mode", "transition")
        self.items = kw.get("items")
        self.prios = kw.get("prios")
        self.path = kw.get("path")
        self.crc = kw.get("crc", 0)
        self.nbytes = kw.get("nbytes", 0)
        self.payload_bytes = kw.get("payload_bytes", 0)
        self.paths = kw.get("paths", ())
        self.reuse = kw.get("reuse", False)
        self.result: Any = None
        self.error: str | None = None

    def run_io(self) -> None:
        try:
            if self.kind == "spill" and self.items is not None:
                self._write_segment()
            elif self.kind == "promote":
                self.result = self._read_segment()
            elif self.kind == "unlink":
                for p in self.paths:
                    try:
                        os.unlink(p)
                    except OSError:
                        pass  # already gone / racing a wipe: the goal state
        except Exception as e:  # adjudicated at commit (spill_errors /
            self.error = f"{type(e).__name__}: {e}"  # crc_dropped), not silent

    # -- segment file format ----------------------------------------------
    #
    # magic | u32 version | u32 header_len | header json | f64 prios |
    # payload records (concatenated codec blobs). header json:
    # {"sid", "mode", "count", "records": [nbytes, ...]}. The manifest
    # carries a crc32 of the WHOLE file, verified at promote time.

    def _write_segment(self) -> None:
        if self.reuse:
            # Re-spill of a previously spilled segment: the payload on
            # disk is still bit-identical (items are immutable); only
            # the RAM copy is dropped. Disk prios go stale — they are
            # advisory recovery seeds, the RAM array stays authoritative.
            self.result = (self.path, self.crc, self.nbytes)
            return
        records = _serialize_records(self.items, self.mode)
        header = json.dumps({"sid": self.sid, "mode": self.mode,
                             "count": int(self.count_items()),
                             "records": [len(r) for r in records]},
                            separators=(",", ":")).encode()
        buf = io.BytesIO()
        buf.write(_MAGIC)
        buf.write(int(_VERSION).to_bytes(4, "little"))
        buf.write(len(header).to_bytes(4, "little"))
        buf.write(header)
        buf.write(np.ascontiguousarray(self.prios, np.float64).tobytes())
        for r in records:
            buf.write(r)
        data = buf.getvalue()
        _atomic_write_bytes(Path(self.path), data)
        self.result = (self.path, zlib.crc32(data), len(data))

    def count_items(self) -> int:
        return len(self.prios) if self.prios is not None else 0

    def _read_segment(self):
        with open(self.path, "rb") as f:
            data = f.read()
        if len(data) != self.nbytes or zlib.crc32(data) != self.crc:
            raise ValueError(
                f"segment {self.sid}: crc/size mismatch "
                f"({len(data)}B vs manifest {self.nbytes}B)")
        header, prios, payload = _parse_segment(memoryview(data))
        if header["sid"] != self.sid:
            raise ValueError(f"segment file sid {header['sid']} != {self.sid}")
        items = _deserialize_records(payload, header["records"],
                                     header["mode"], header["count"])
        return items


def _serialize_records(items: list[Any], mode: str) -> list[bytes]:
    from distributed_reinforcement_learning_tpu.data import codec
    from distributed_reinforcement_learning_tpu.data.replay_service import LazyBlob

    if mode == "transition":
        # One blob for the whole segment: the item list IS a pytree, so
        # one encode/decode round-trips it bit-identically.
        return [bytes(memoryview(codec.encode(list(items))))]
    out = []
    for item in items:
        if isinstance(item, LazyBlob):
            blob = item._blob  # single read: materialize publishes _tree
            if blob is not None:  # before dropping _blob (lock-free pact)
                out.append(blob)  # already a wire blob: a write, not an
                continue          # encode
            item = item.materialize()
        out.append(bytes(memoryview(codec.encode(item))))
    return out


def _deserialize_records(payload: memoryview, lens: list[int], mode: str,
                         count: int) -> list[Any]:
    from distributed_reinforcement_learning_tpu.data import codec
    from distributed_reinforcement_learning_tpu.data.replay_service import LazyBlob

    blobs, pos = [], 0
    for n in lens:
        blobs.append(payload[pos:pos + n])
        pos += n
    if mode == "transition":
        items = codec.decode(blobs[0], copy=True, cache=True)
        if len(items) != count:
            raise ValueError(f"segment payload holds {len(items)} items, "
                             f"header says {count}")
        return list(items)
    # Sequence mode: re-wrap as LazyBlob — promote stays a read+copy,
    # decode is deferred to first materialization on the learner thread.
    for b in blobs:
        codec.check_blob(b)  # poison fails the promote, not the learner
    return [LazyBlob(b) for b in blobs]


def _parse_segment(view: memoryview):
    if bytes(view[:4]) != _MAGIC:
        raise ValueError("bad segment magic")
    if int.from_bytes(view[4:8], "little") != _VERSION:
        raise ValueError("unknown segment version")
    hlen = int.from_bytes(view[8:12], "little")
    header = json.loads(bytes(view[12:12 + hlen]))
    count = int(header["count"])
    p0 = 12 + hlen
    prios = np.frombuffer(view[p0:p0 + 8 * count], np.float64).copy()
    return header, prios, view[p0 + 8 * count:]


def _atomic_write_bytes(path: Path, data: bytes) -> None:
    """mkstemp + fsync + rename (the PR 9 `_atomic_write` discipline,
    local copy to keep data/ free of the flax-importing checkpoint
    module): a crash can lose the newest segment, never corrupt one."""
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=path.name, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _item_nbytes(item: Any) -> int:
    import jax

    blob = getattr(item, "_blob", None)  # unmaterialized LazyBlob
    if blob is not None:
        return len(blob)
    if hasattr(item, "materialize"):
        item = item.materialize()
    return sum(np.asarray(leaf).nbytes for leaf in jax.tree.leaves(item))


class TieredStore:
    """Hot/cold prioritized replay backend for one `ReplayShard`.

    Implements the backend surface the shard drives (`add`/`add_batch`,
    `sample_with_priorities`, `update_batch`, `snapshot`/`restore`,
    `__len__`, `beta`, `tree.total`) plus the tier-maintenance half
    (`plan_tier_work`/`commit_tier_work`, driven by
    `ReplayShard.tier_step`). See the module docstring for semantics.
    """

    # Concurrency map (tools/drlint lock-discipline): this store is
    # EXTERNALLY synchronized — every state-mutating entry point runs
    # under the owning ReplayShard's `_lock` (the shard brackets
    # plan/commit in tier_step; sample/update/add arrive already locked).
    # The IO half (`_TierJob.run_io`) runs lock-free on job-local state
    # only: sealed item lists are immutable and priority arrays are
    # copied into the job. The one cross-thread field this class owns is
    # the manifest write cursor, below.
    _GUARDED_BY = {
        "_manifest_written_ver": "_io_lock",
        "_closed": "_io_lock",
    }
    _NOT_GUARDED = {
        "_segments": "externally synchronized: accessed only under the "
                     "owning ReplayShard._lock (shard-bracketed calls)",
        "_order": "externally synchronized under ReplayShard._lock",
        "_ready": "externally synchronized under ReplayShard._lock",
        "_blocked": "externally synchronized under ReplayShard._lock",
        "_promote_req": "externally synchronized under ReplayShard._lock",
        "_promote_set": "externally synchronized under ReplayShard._lock",
        "_promote_t": "externally synchronized under ReplayShard._lock",
        "_promote_inflight": "externally synchronized under "
                             "ReplayShard._lock",
        "_open": "externally synchronized under ReplayShard._lock",
        "_next_sid": "externally synchronized under ReplayShard._lock",
        "_count": "externally synchronized under ReplayShard._lock",
        "_hot_bytes": "externally synchronized under ReplayShard._lock",
        "_cold_bytes": "externally synchronized under ReplayShard._lock",
        "_disk_bytes": "externally synchronized under ReplayShard._lock",
        "_partial": "externally synchronized under ReplayShard._lock",
        "_pending_unlinks": "externally synchronized under "
                            "ReplayShard._lock",
        "_manifest_dirty": "externally synchronized under "
                           "ReplayShard._lock",
        "_manifest_ver": "externally synchronized under ReplayShard._lock",
        "_obs_events": "externally synchronized under ReplayShard._lock",
        "stats": "externally synchronized under ReplayShard._lock",
        "beta": "externally synchronized under ReplayShard._lock",
    }

    stacked_samples = False

    def __init__(self, capacity: int, cfg: SpillConfig, mode: str = "transition",
                 beta: float = 0.4, seed: int = 0):
        if mode not in ("transition", "sequence"):
            raise ValueError(f"unknown tier mode {mode!r}")
        self.capacity = int(capacity)
        self.mode = mode
        self.beta = beta
        self.cfg = cfg
        self._dir = Path(cfg.directory)
        self._seg_items = max(1, min(int(cfg.seg_items),
                                     max(1, self.capacity // 4),
                                     _SEG_CAP - 1))
        self._segments: dict[int, _Segment] = {}
        self._order: deque[int] = deque()  # insertion order (eviction)
        self._open: _Segment | None = None
        self._next_sid = 0
        self._count = 0
        self._hot_bytes = 0   # resident payload bytes (open + hot)
        self._cold_bytes = 0  # payload bytes whose only copy is on disk
        self._disk_bytes = 0  # bytes of live segment files on disk
        self._ready: deque[tuple[int, int]] = deque()  # draw-ahead FIFO
        # Cold draws park here (keyed by sid) instead of churning the
        # ready FIFO: one promote request when parked, requeued in one
        # move when the promote commits — a drain never rescans them.
        self._blocked: dict[int, list[tuple[int, int]]] = {}
        self._partial: list[tuple[Any, int, float]] = []
        self._promote_req: deque[int] = deque()
        self._promote_set: set[int] = set()
        self._promote_t: dict[int, float] = {}
        self._promote_inflight = 0
        self._pending_unlinks: list[str] = []
        self._manifest_dirty = False
        self._manifest_ver = 0
        self._io_lock = threading.Lock()
        self._manifest_written_ver = -1
        self._closed = False
        # Owned, seeded sampling stream (same contract as the all-RAM
        # backends: callers passing an rng are unaffected).
        self._default_rng = np.random.RandomState(seed)
        self.stats = {
            "spilled_segments": 0, "spilled_bytes": 0,
            "promoted_segments": 0, "promoted_bytes": 0,
            "evicted_segments": 0, "evicted_items": 0,
            "crc_dropped": 0, "spill_errors": 0,
            "forced_pads": 0, "queue_dropped": 0,
            "updates_dropped_evicted": 0, "recovered_segments": 0,
            "recovered_items": 0, "promote_waits": 0,
        }
        self._obs_events: list[tuple[str, float]] = []
        self._dir.mkdir(parents=True, exist_ok=True)
        if cfg.fresh:
            self._wipe_dir()
        else:
            self._recover()
        self._new_open()

    # -- construction helpers ----------------------------------------------

    def _wipe_dir(self) -> None:
        for p in self._dir.glob("seg_*.bin"):
            try:
                p.unlink()
            except OSError:
                pass  # concurrent cleanup: absence is the goal state
        man = self._dir / "manifest.json"
        if man.exists():
            try:
                man.unlink()
            except OSError:
                pass  # ditto

    def _recover(self) -> None:
        """Register manifested cold segments: priorities load into RAM
        now (8B/item), payloads stay on disk until sampled-cold draws
        promote them. Unreadable entries are skipped and counted —
        recovery is best-effort by design (a lost segment is the same
        class of loss as RAM contents on any crash)."""
        man_path = self._dir / "manifest.json"
        if not man_path.exists():
            self._gc_orphans(set())
            return
        try:
            man = json.loads(man_path.read_text())
        except (ValueError, OSError):
            self._gc_orphans(set())
            return
        live: set[str] = set()
        for ent in man.get("segments", []):
            path = self._dir / ent["file"]
            try:
                with open(path, "rb") as f:
                    head = f.read(12)
                    if head[:4] != _MAGIC:
                        raise ValueError("bad magic")
                    if int.from_bytes(head[4:8], "little") != _VERSION:
                        raise ValueError("bad version")
                    hlen = int.from_bytes(head[8:12], "little")
                    header = json.loads(f.read(hlen))
                    count = int(header["count"])
                    if count != int(ent["count"]) or count <= 0:
                        raise ValueError("count mismatch")
                    prios = np.frombuffer(f.read(8 * count), np.float64).copy()
                    if prios.size != count:
                        raise ValueError("truncated priorities")
            except (OSError, ValueError, KeyError):
                self.stats["crc_dropped"] += 1
                continue
            seg = _Segment(ent["sid"], 0)
            seg.state = "cold"
            seg.items = None
            seg.prios = prios
            seg.count = count
            seg.mass = float(prios.sum())
            seg.payload_bytes = int(ent.get("payload_bytes", 0))
            seg.file = str(path)
            seg.file_crc = int(ent["crc"])
            seg.file_nbytes = int(ent["nbytes"])
            self._segments[seg.sid] = seg
            self._order.append(seg.sid)
            self._count += count
            self._cold_bytes += seg.payload_bytes
            self._disk_bytes += seg.file_nbytes
            live.add(ent["file"])
            self._next_sid = max(self._next_sid, seg.sid + 1)
            self.stats["recovered_segments"] += 1
            self.stats["recovered_items"] += count
        self._gc_orphans(live)
        # Evict down to capacity immediately: a shrunk-capacity restart
        # must not carry more items than the live config allows.
        self._evict_over_capacity()
        self._manifest_dirty = True
        self._manifest_ver += 1

    def _gc_orphans(self, live: set[str]) -> None:
        for p in self._dir.glob("seg_*.bin"):
            if p.name in live:
                continue
            self._pending_unlinks.append(str(p))
            try:
                # Keep sids ahead of any orphan (a crash between segment
                # write and manifest sync) so a fresh segment never spills
                # onto a stale file before its deferred unlink runs.
                self._next_sid = max(self._next_sid,
                                     int(p.stem.split("_")[1]) + 1)
            except (IndexError, ValueError):
                continue  # foreign file matching the glob: unlink only

    def _new_open(self) -> None:
        seg = _Segment(self._next_sid, self._seg_items)
        self._next_sid += 1
        self._open = seg
        self._segments[seg.sid] = seg
        self._order.append(seg.sid)

    # -- backend surface: size / mass --------------------------------------

    class _MassView:
        """`.tree.total` shim: ReplayShard's stats/mass_count read the
        backend's sum-tree total; here the total is the segment masses."""

        __slots__ = ("_store",)

        def __init__(self, store: "TieredStore"):
            self._store = store

        @property
        def total(self) -> float:
            return sum(s.mass for s in self._store._segments.values())

    @property
    def tree(self) -> "TieredStore._MassView":
        return TieredStore._MassView(self)

    def __len__(self) -> int:
        return self._count

    def ram_bytes(self) -> int:
        """Accounted replay RAM: resident payloads + the always-resident
        priority arrays and their cumsum caches (16B/item upper bound) —
        the honest denominator for stored-transitions-per-GB-RAM."""
        return self._hot_bytes + 16 * self._count

    def disk_bytes(self) -> int:
        return self._disk_bytes

    def approx_snapshot_nbytes(self) -> int:
        return self._hot_bytes + self._cold_bytes + 8 * self._count

    # -- backend surface: ingest -------------------------------------------

    def add(self, error: float, sample: Any) -> int:
        return self._append(float(priority_transform(
            np.asarray([error]))[0]), sample)

    def add_batch(self, errors: np.ndarray, samples: list[Any]) -> list[int]:
        prios = priority_transform(errors)
        return [self._append(float(p), s) for p, s in zip(prios, samples)]

    def _append(self, prio: float, item: Any) -> int:
        seg = self._open
        if seg is None or seg.count >= self._seg_items:
            if seg is not None:
                self._seal(seg)
            self._new_open()
            seg = self._open
        off = seg.count
        seg.items.append(item)
        seg.prios[off] = prio
        seg.count += 1
        seg.mass += prio
        seg.cumsum = None
        nb = _item_nbytes(item)
        seg.payload_bytes += nb
        self._hot_bytes += nb
        self._count += 1
        self._evict_over_capacity()
        return (seg.sid << _OFF_BITS) | off

    def _seal(self, seg: _Segment) -> None:
        seg.prios = seg.prios[:seg.count].copy()
        seg.state = "hot"
        seg.cumsum = None

    def _evict_over_capacity(self) -> None:
        """Drop the OLDEST sealed segment(s) while over capacity — the
        monolithic ring's overwrite-oldest semantic at segment grain."""
        while self._count > self.capacity:
            victim = None
            for sid in self._order:
                seg = self._segments[sid]
                if seg.state != "open":
                    victim = seg
                    break
            if victim is None:
                return  # only the open segment exists (capacity tiny)
            self._drop_segment(victim)
            self.stats["evicted_segments"] += 1
            self.stats["evicted_items"] += victim.count

    def _drop_segment(self, seg: _Segment) -> None:
        self._order.remove(seg.sid)
        del self._segments[seg.sid]
        seg.gen += 1  # in-flight jobs against it discard at commit
        self._count -= seg.count
        if seg.resident:
            self._hot_bytes -= seg.payload_bytes
        else:
            self._cold_bytes -= seg.payload_bytes
        if seg.file is not None:
            self._disk_bytes -= seg.file_nbytes
            self._pending_unlinks.append(seg.file)
        self._promote_set.discard(seg.sid)
        self._promote_t.pop(seg.sid, None)
        dropped = self._blocked.pop(seg.sid, None)
        if dropped:
            self.stats["queue_dropped"] += len(dropped)
        self._manifest_dirty = True
        self._manifest_ver += 1

    # -- backend surface: sampling -----------------------------------------

    def sample_with_priorities(self, n: int, rng=None):
        """One-shot completion path (monolithic surface parity — the
        shard's tiered sampling loop calls `sample_step` directly so it
        can wait for promotes between steps)."""
        out = self.sample_step(n, rng, force=True)
        assert out is not None  # force=True completes or raises
        return out

    def sample_step(self, n: int, rng, force: bool = False):
        """Advance one delivery attempt; returns (items, idxs, prios) or
        None when queued draws still await promotion (the caller kicks
        the router and waits on the shard condvar, then retries).
        `force=True` completes with resident-only pads (counted) or
        raises ColdStoreEmpty."""
        if rng is None:
            rng = self._default_rng
        got = self._partial
        self._drain_ready(got, n)
        seg_list, cumsum, total = self._mass_table()
        if total <= 0 and not got:
            self._partial = []
            raise ColdStoreEmpty("tiered store has no priority mass")
        attempts, cap = 0, 8 * n + 64
        while len(got) < n and attempts < cap:
            batch = self._draw_many(n - len(got), seg_list, cumsum, total,
                                    rng)
            if not batch:
                break
            attempts += len(batch)
            for sid, off in batch:
                seg = self._segments[sid]
                if seg.resident:
                    got.append((seg.items[off], (sid << _OFF_BITS) | off,
                                float(seg.prios[off])))
                else:
                    self._queue_draw(sid, off)
        if len(got) < n:
            if not force:
                self._partial = got
                return None
            self._forced_fill(got, n, rng)
        self._partial = []
        self._prefetch(n, seg_list, cumsum, total, rng)
        items = [item for item, _, _ in got]
        idxs = np.fromiter((idx for _, idx, _ in got), np.int64, len(got))
        prios = np.fromiter((p for _, _, p in got), np.float64, len(got))
        return items, idxs, prios

    def _drain_ready(self, got: list, n: int) -> None:
        scanned, qlen = 0, len(self._ready)
        while scanned < qlen and len(got) < n:
            scanned += 1
            sid, off = self._ready.popleft()
            seg = self._segments.get(sid)
            if seg is None or off >= seg.count:
                self.stats["queue_dropped"] += 1  # evicted under the draw
                continue
            if seg.resident:
                seg.debt -= 1
                got.append((seg.items[off], (sid << _OFF_BITS) | off,
                            float(seg.prios[off])))
            else:
                self._blocked.setdefault(sid, []).append((sid, off))
                self._request_promote(sid)

    def _mass_table(self):
        seg_list = [self._segments[sid] for sid in self._order
                    if self._segments[sid].mass > 0]
        if not seg_list:
            return [], np.zeros(0, np.float64), 0.0
        cumsum = np.cumsum(np.asarray([s.mass for s in seg_list], np.float64))
        return seg_list, cumsum, float(cumsum[-1])

    def _draw_many(self, k, seg_list, cumsum, total, rng):
        """k independent mass-proportional draws -> [(sid, off), ...].

        Vectorized two-level inverse-CDF: one searchsorted over the
        segment cumsum for all k, then ONE searchsorted per DISTINCT
        segment for the within-segment offsets — identical distribution
        to k scalar draws (same math, batched), at numpy-call cost
        O(segments touched) instead of O(k). Returned in segment-grouped
        order; draws are iid so order carries no information."""
        if total <= 0 or k <= 0:
            return []
        rs = rng.uniform(0.0, total, k)
        seg_is = np.minimum(np.searchsorted(cumsum, rs, side="right"),
                            len(seg_list) - 1)
        within = rs - np.where(seg_is > 0, cumsum[seg_is - 1], 0.0)
        order = np.argsort(seg_is, kind="stable")
        out = []
        i = 0
        while i < k:
            si = int(seg_is[order[i]])
            j = i
            while j < k and int(seg_is[order[j]]) == si:
                j += 1
            seg = seg_list[si]
            if seg.cumsum is None:
                seg.cumsum = np.cumsum(seg.prios[:seg.count])
            offs = np.minimum(
                np.searchsorted(seg.cumsum, within[order[i:j]],
                                side="right"),
                seg.count - 1)
            sid = seg.sid
            out.extend((sid, int(off)) for off in offs)
            i = j
        return out

    def _queue_draw(self, sid: int, off: int) -> None:
        if len(self._ready) >= self.cfg.queue_cap:
            self.stats["queue_dropped"] += 1
            return
        seg = self._segments[sid]
        seg.debt += 1
        self._blocked.setdefault(sid, []).append((sid, off))
        self._request_promote(sid)

    def _forced_fill(self, got: list, n: int, rng) -> None:
        res = [s for s in self._segments.values() if s.resident and s.mass > 0]
        if not res:
            self._partial = []
            raise ColdStoreEmpty(
                "no resident segment to sample (all-cold store: the "
                "router is still promoting)")
        cumsum = np.cumsum(np.asarray([s.mass for s in res], np.float64))
        while len(got) < n:
            for sid, off in self._draw_many(n - len(got), res, cumsum,
                                            float(cumsum[-1]), rng):
                seg = self._segments[sid]
                got.append((seg.items[off], (sid << _OFF_BITS) | off,
                            float(seg.prios[off])))
                self.stats["forced_pads"] += 1

    def _prefetch(self, n: int, seg_list, cumsum, total, rng) -> None:
        """Top up the draw-ahead window so next batch's cold picks are
        already promoting while the learner trains on this one."""
        target = min(max(2 * n, 16), self.cfg.queue_cap)
        need = target - len(self._ready)
        if need <= 0:
            return
        for sid, off in self._draw_many(need, seg_list, cumsum, total, rng):
            seg = self._segments[sid]
            seg.debt += 1
            if seg.resident:
                self._ready.append((sid, off))
            else:
                self._blocked.setdefault(sid, []).append((sid, off))
                self._request_promote(sid)

    def _request_promote(self, sid: int) -> None:
        if sid in self._promote_set:
            return
        seg = self._segments.get(sid)
        if seg is None or seg.state not in ("cold",):
            return
        self._promote_set.add(sid)
        self._promote_req.append(sid)
        self._promote_t.setdefault(sid, time.monotonic())
        if len(self._promote_req) > 4 * self.cfg.max_inflight + 16:
            dropped = self._promote_req.popleft()  # latest wins; its
            self._promote_set.discard(dropped)     # parked draws return
            self._promote_t.pop(dropped, None)     # to the FIFO so a
            #   later drain re-requests the promote (nothing strands)
            self._ready.extend(self._blocked.pop(dropped, ()))

    def has_queued_cold(self) -> bool:
        """True when completion is blocked on promotes (the shard's
        sampling loop uses this to decide to wait vs force)."""
        return bool(self._promote_req) or self._promote_inflight > 0

    # -- backend surface: priority writebacks ------------------------------

    def update_batch(self, idxs: np.ndarray, errors: np.ndarray) -> None:
        """Loss-free across spill/promote by construction: the priority
        array is RAM-resident for every live segment, whatever the
        payload tier. Writebacks to EVICTED segments are dropped and
        counted — the monolithic ring's overwrite-oldest semantic."""
        prios = np.asarray(priority_transform(errors), np.float64).reshape(-1)
        idxs = np.asarray(idxs, np.int64).reshape(-1)
        if idxs.size == 0:
            return
        sids = idxs >> _OFF_BITS
        offs = idxs & (_SEG_CAP - 1)
        order = np.argsort(sids, kind="stable")
        k = idxs.size
        i = 0
        while i < k:
            sid = int(sids[order[i]])
            j = i
            while j < k and int(sids[order[j]]) == sid:
                j += 1
            sel = order[i:j]
            i = j
            seg = self._segments.get(sid)
            if seg is None:
                self.stats["updates_dropped_evicted"] += len(sel)
                continue
            o = offs[sel]
            live = o < seg.count
            if not live.all():
                self.stats["updates_dropped_evicted"] += int((~live).sum())
                sel, o = sel[live], o[live]
                if o.size == 0:
                    continue
            # Duplicate offsets within a batch: numpy fancy assignment
            # keeps the LAST write, matching the sequential scalar
            # semantic; the full-array re-sum then makes the mass exact
            # (no incremental-delta drift).
            seg.prios[o] = prios[sel]
            seg.mass = float(np.sum(seg.prios[:seg.count]))
            seg.cumsum = None

    def update(self, idx: int, error: float) -> None:
        self.update_batch(np.asarray([idx]), np.asarray([error]))

    # -- tier maintenance (ingest + router threads, shard-bracketed) -------

    def tier_pending(self) -> bool:
        return bool(self._promote_req or self._promote_inflight
                    or self._pending_unlinks or self._manifest_dirty
                    or self._spill_victim() is not None
                    or any(s.state in ("spilling", "promoting")
                           for s in self._segments.values()))

    def _spill_victim(self) -> _Segment | None:
        if self._hot_bytes <= self.cfg.hot_bytes:
            return None
        eligible = [s for s in self._segments.values()
                    if s.state == "hot" and s.debt == 0
                    and s.payload_bytes > 0]
        if not eligible:
            return None
        victim = min(eligible, key=lambda s: s.mass)
        # Never spill the last resident mass: the forced-fill fallback
        # (and the all-cold ColdStoreEmpty) need something to stand on.
        resident_mass = sum(s.mass for s in self._segments.values()
                            if s.resident)
        if resident_mass - victim.mass <= 0:
            return None
        return victim

    def _plan_spill(self) -> _TierJob | None:
        victim = self._spill_victim()
        if victim is None:
            return None
        victim.state = "spilling"
        victim.gen += 1
        return _TierJob(
            "spill", sid=victim.sid, gen=victim.gen, mode=self.mode,
            items=victim.items,
            prios=victim.prios.copy(),  # RAM array stays authoritative
            path=victim.file or str(
                self._dir / f"seg_{victim.sid:010d}.bin"),
            crc=victim.file_crc, nbytes=victim.file_nbytes,
            payload_bytes=victim.payload_bytes,
            reuse=victim.file is not None)

    def plan_tier_work(self) -> _TierJob | None:
        """Pick ONE unit of maintenance (promote > spill > unlink >
        manifest sync). Runs under the shard lock; the returned job's
        `run_io` then runs with no lock held.

        Promotes lead because a queued cold draw is a learner waiting —
        EXCEPT under budget pressure (resident payload > 1.25x the hot
        budget): sustained cold sampling promotes faster than the idle
        spill slot drains, and strict promote priority would grow
        resident payload without bound. Past the pressure line spills
        go first; queued promotes run as soon as the tier is back near
        budget."""
        if self._hot_bytes > self.cfg.hot_bytes + self.cfg.hot_bytes // 4:
            job = self._plan_spill()
            if job is not None:
                return job
        while self._promote_req and self._promote_inflight < self.cfg.max_inflight:
            sid = self._promote_req.popleft()
            self._promote_set.discard(sid)
            seg = self._segments.get(sid)
            if seg is None or seg.state != "cold":
                self._promote_t.pop(sid, None)
                continue
            seg.state = "promoting"
            seg.gen += 1
            self._promote_inflight += 1
            return _TierJob("promote", sid=sid, gen=seg.gen, path=seg.file,
                            crc=seg.file_crc, nbytes=seg.file_nbytes,
                            mode=self.mode, payload_bytes=seg.payload_bytes)
        job = self._plan_spill()
        if job is not None:
            return job
        if self._pending_unlinks:
            paths = tuple(self._pending_unlinks)
            self._pending_unlinks.clear()
            return _TierJob("unlink", paths=paths)
        if self._manifest_dirty:
            return _TierJob("sync")
        return None

    def commit_tier_work(self, job: _TierJob) -> dict | None:
        """Apply a finished job under the shard lock; returns a manifest
        snapshot to persist (outside the lock) when tier state changed."""
        if job.kind == "promote":
            self._commit_promote(job)
        elif job.kind == "spill":
            self._commit_spill(job)
        # unlink/sync carry no state; fall through to the manifest check
        if self._manifest_dirty:
            self._manifest_dirty = False
            return self._manifest_snapshot()
        return None

    def _commit_promote(self, job: _TierJob) -> None:
        self._promote_inflight -= 1
        seg = self._segments.get(job.sid)
        if seg is None or seg.gen != job.gen or seg.state != "promoting":
            return  # evicted/restarted under the read: nothing to place
        if job.error is not None or job.result is None:
            # Poison isolation: ONE segment drops (crc/decode failure),
            # the shard keeps serving. Queued draws against it fall out
            # of the ready queue as queue_dropped.
            self.stats["crc_dropped"] += 1
            self._drop_segment(seg)
            self._obs_events.append(("crc_dropped", 1.0))
            return
        seg.items = list(job.result)
        seg.state = "hot"
        self._hot_bytes += seg.payload_bytes
        self._cold_bytes -= seg.payload_bytes
        # Parked draws jump the FIFO: they have waited a promote round
        # trip already, and delivering them clears the segment's debt so
        # it becomes spillable again.
        for entry in self._blocked.pop(job.sid, ()):
            self._ready.appendleft(entry)
        self.stats["promoted_segments"] += 1
        self.stats["promoted_bytes"] += seg.payload_bytes
        wait_ms = (time.monotonic()
                   - self._promote_t.pop(job.sid, time.monotonic())) * 1e3
        self._obs_events.append(("promote_wait_ms", wait_ms))
        self._obs_events.append(("promoted_bytes", float(seg.payload_bytes)))

    def _commit_spill(self, job: _TierJob) -> None:
        seg = self._segments.get(job.sid)
        if seg is None or seg.gen != job.gen or seg.state != "spilling":
            # Evicted while the write was in flight: the freshly written
            # file (if any) has no owner left — reclaim it.
            if seg is None and not job.reuse and job.result is not None:
                self._pending_unlinks.append(job.result[0])
            return
        if job.error is not None or job.result is None:
            seg.state = "hot"  # keep it resident; retry on a later pass
            self.stats["spill_errors"] += 1
            return
        path, crc, nbytes = job.result
        if seg.file is None:
            self._disk_bytes += nbytes
        seg.file, seg.file_crc, seg.file_nbytes = path, crc, nbytes
        seg.items = None
        seg.state = "cold"
        self._hot_bytes -= seg.payload_bytes
        self._cold_bytes += seg.payload_bytes
        self.stats["spilled_segments"] += 1
        self.stats["spilled_bytes"] += seg.payload_bytes
        self._manifest_dirty = True
        self._manifest_ver += 1
        self._obs_events.append(("spilled_bytes", float(seg.payload_bytes)))

    def _manifest_snapshot(self) -> dict:
        return {
            "ver": self._manifest_ver,
            "segments": [
                {"sid": s.sid, "file": os.path.basename(s.file),
                 "count": s.count, "mass": s.mass, "crc": s.file_crc,
                 "nbytes": s.file_nbytes, "payload_bytes": s.payload_bytes}
                for sid in self._order
                for s in (self._segments[sid],)
                # Any file-backed segment recovers, even if currently
                # hot (promoted copies keep their file for cheap
                # re-spill) — restart then re-reads it as cold.
                if s.file is not None
            ],
        }

    def write_manifest(self, snap: dict) -> None:
        """Persist a manifest snapshot (OUTSIDE the shard lock). Writes
        are version-ordered so two maintenance threads interleaving
        commits can never regress the file to an older snapshot."""
        with self._io_lock:
            if self._closed or snap["ver"] <= self._manifest_written_ver:
                return
            _atomic_write_bytes(
                self._dir / "manifest.json",
                json.dumps(snap, separators=(",", ":")).encode())
            self._manifest_written_ver = snap["ver"]

    def take_obs(self) -> list[tuple[str, float]]:
        events, self._obs_events = self._obs_events, []
        return events

    def close(self) -> None:
        with self._io_lock:
            self._closed = True

    # -- tier telemetry -----------------------------------------------------

    def tier_stats(self) -> dict:
        hot_items = sum(s.count for s in self._segments.values() if s.resident)
        return dict(self.stats,
                    hot_items=hot_items,
                    cold_items=self._count - hot_items,
                    hot_bytes=self._hot_bytes,
                    cold_bytes=self._cold_bytes,
                    disk_bytes=self._disk_bytes,
                    ram_bytes=self.ram_bytes(),
                    segments=len(self._segments),
                    queue_depth=(len(self._ready)
                                 + sum(len(v)
                                       for v in self._blocked.values())))

    # -- checkpoint round trip ----------------------------------------------

    def snapshot(self) -> dict:
        """List-backend snapshot format. Cold items come back as lazy
        per-item refs (`materialize()` loads the segment file ONCE, on
        the checkpoint thread, outside the shard lock — the shard's
        snapshot() materializes after releasing its lock)."""
        prios: list[np.ndarray] = []
        items: list[Any] = []
        for sid in self._order:
            seg = self._segments[sid]
            if seg.count == 0:
                continue
            prios.append(seg.prios[:seg.count].copy())
            if seg.resident:
                items.extend(seg.items)
            else:
                loader = _SegmentLoader(seg.file, seg.file_crc,
                                        seg.file_nbytes, self.mode, seg.count)
                items.extend(_SegmentRef(loader, i) for i in range(seg.count))
        return {"priorities": (np.concatenate(prios) if prios
                               else np.zeros(0, np.float64)),
                "items": items, "beta": float(self.beta)}

    def restore(self, snap: dict) -> None:
        from distributed_reinforcement_learning_tpu.data.replay import _snapshot_items

        for p, item in zip(np.asarray(snap["priorities"], np.float64),
                           _snapshot_items(snap)):
            self._append(float(p), item)  # raw: already transformed
        self.beta = float(snap.get("beta", self.beta))


class _SegmentLoader:
    """Shared one-shot loader behind a cold segment's snapshot refs —
    the file is read and decoded at most once per snapshot pass (single
    checkpoint thread by contract, like LazyBlob's materializer)."""

    __slots__ = ("_job", "_items")

    def __init__(self, path: str, crc: int, nbytes: int, mode: str,
                 count: int):
        self._job = _TierJob("promote", sid=-1, path=path, crc=crc,
                             nbytes=nbytes, mode=mode)
        self._items: list[Any] | None = None

    def get(self, i: int):
        if self._items is None:
            header, _, payload = _parse_segment(
                memoryview(Path(self._job.path).read_bytes()))
            self._items = _deserialize_records(
                payload, header["records"], header["mode"], header["count"])
        item = self._items[i]
        return item.materialize() if hasattr(item, "materialize") else item


class _SegmentRef:
    """One cold item inside a snapshot; duck-types LazyBlob's
    `materialize()` so `replay_service._materialize` resolves it on the
    checkpoint/learner thread."""

    __slots__ = ("_loader", "_i")

    def __init__(self, loader: _SegmentLoader, i: int):
        self._loader = loader
        self._i = i

    def materialize(self):
        return self._loader.get(self._i)
