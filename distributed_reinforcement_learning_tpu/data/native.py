"""ctypes bindings for the C++ data plane (cpp/ring_queue.cc, sumtree.cc).

Builds the shared library on first import if missing or stale (g++ is in
the image; pybind11 is not, so the ABI is plain C + ctypes). Public:

- `NativeByteQueue` — bounded MPMC blob queue (the reference's
  tf.FIFOQueue kernel role, SURVEY §2.2 E3), backpressure included.
- `NativeTrajectoryQueue` — same interface as `fifo.TrajectoryQueue`
  (put/get/get_batch/size/close) but pytrees cross through the C++
  queue as codec blobs; `put_bytes` lets the transport server enqueue
  wire payloads without a decode/encode round trip.
- `NativeSumTree` — batch add/sample/update priority tree
  (SURVEY §2.2 E7); payloads stay in Python.

`native_available()` gates tests and fallbacks.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import time
from typing import Any

import numpy as np

from distributed_reinforcement_learning_tpu.data import codec
from distributed_reinforcement_learning_tpu.data.fifo import stack_pytrees
from distributed_reinforcement_learning_tpu.observability import TELEMETRY as _OBS

_CPP_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "cpp")
_LIB_PATH = os.path.join(_CPP_DIR, "build", "libdistrl_native.so")
_SOURCES = ("ring_queue.cc", "sumtree.cc", "batch_stack.cc")

_RQ_OK, _RQ_TIMEOUT, _RQ_CLOSED, _RQ_TOO_SMALL = 0, -1, -2, -3

_lib = None
_lib_lock = threading.Lock()
_build_error: str | None = None


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(
        os.path.getmtime(os.path.join(_CPP_DIR, s)) > lib_mtime for s in _SOURCES
    )


def _build() -> None:
    os.makedirs(os.path.dirname(_LIB_PATH), exist_ok=True)
    # Compile to a per-process temp path and rename into place: run_role
    # launches learner + N actor processes at once, and a partially written
    # .so must never be CDLL'd by a sibling.
    tmp = f"{_LIB_PATH}.{os.getpid()}"
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O2", "-std=c++17", "-fPIC", "-shared",
        "-o", tmp,
        *[os.path.join(_CPP_DIR, s) for s in _SOURCES],
        "-lpthread",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _LIB_PATH)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _load():
    global _lib, _build_error
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            raise RuntimeError(f"native library unavailable: {_build_error}")
        try:
            if _needs_build():
                # Deliberate compile-under-lock: the exactly-once build
                # of the .so IS what _lib_lock exists to serialize —
                # sibling threads must wait for the artifact, not race
                # the compiler. Cold path, runs once per checkout.
                _build()  # drlint: disable=blocking-under-lock
            lib = ctypes.CDLL(_LIB_PATH)
        except (subprocess.CalledProcessError, OSError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            _build_error = detail
            raise RuntimeError(f"native library unavailable: {detail}") from e

        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f64p = ctypes.POINTER(ctypes.c_double)
        sigs = {
            "rq_create": ([ctypes.c_int64], ctypes.c_void_p),
            "rq_destroy": ([ctypes.c_void_p], None),
            "rq_size": ([ctypes.c_void_p], ctypes.c_int64),
            "rq_close": ([ctypes.c_void_p], None),
            "rq_put": ([ctypes.c_void_p, u8p, ctypes.c_int64, ctypes.c_double], ctypes.c_int64),
            "rq_peek_size": ([ctypes.c_void_p, ctypes.c_double], ctypes.c_int64),
            "rq_get": ([ctypes.c_void_p, u8p, ctypes.c_int64, ctypes.c_double], ctypes.c_int64),
            "rq_get_batch": (
                [ctypes.c_void_p, ctypes.c_int64, u8p, ctypes.c_int64, i64p, ctypes.c_double],
                ctypes.c_int64,
            ),
            "st_create": ([ctypes.c_int64], ctypes.c_void_p),
            "st_destroy": ([ctypes.c_void_p], None),
            "st_total": ([ctypes.c_void_p], ctypes.c_double),
            "st_size": ([ctypes.c_void_p], ctypes.c_int64),
            "st_leaf_priority": ([ctypes.c_void_p, ctypes.c_int64], ctypes.c_double),
            "st_leaf_priorities": (
                [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, f64p], None),
            "st_add_batch": ([ctypes.c_void_p, f64p, ctypes.c_int64, i64p], None),
            "st_update_batch": ([ctypes.c_void_p, i64p, f64p, ctypes.c_int64], None),
            "st_get_batch": ([ctypes.c_void_p, f64p, ctypes.c_int64, i64p, f64p], None),
            "bs_all_equal_prefix": (
                [u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64],
                ctypes.c_int64,
            ),
            "bs_gather": (
                [u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, u8p],
                None,
            ),
        }
        for name, (argtypes, restype) in sigs.items():
            fn = getattr(lib, name)
            fn.argtypes = argtypes
            fn.restype = restype
        _lib = lib
        return lib


def native_available() -> bool:
    try:
        _load()
        return True
    except RuntimeError:
        return False


def _as_u8p(buf) -> Any:
    if isinstance(buf, memoryview):
        buf = np.frombuffer(buf, np.uint8)  # zero-copy
    if isinstance(buf, np.ndarray):
        return buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    return (ctypes.c_uint8 * len(buf)).from_buffer(buf) if isinstance(buf, bytearray) else \
        ctypes.cast(ctypes.c_char_p(buf), ctypes.POINTER(ctypes.c_uint8))


class NativeByteQueue:
    """Bounded MPMC queue of byte blobs backed by cpp/ring_queue.cc."""

    def __init__(self, capacity: int):
        self._lib = _load()
        self._h = self._lib.rq_create(capacity)
        if not self._h:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._closed = False

    def __len__(self) -> int:
        return int(self._lib.rq_size(self._h))

    def size(self) -> int:
        return len(self)

    def close(self) -> None:
        self._closed = True
        self._lib.rq_close(self._h)

    @property
    def closed(self) -> bool:
        return self._closed

    def put(self, blob: bytes, timeout: float | None = None) -> bool:
        rc = self._lib.rq_put(
            self._h, _as_u8p(blob), len(blob), -1.0 if timeout is None else timeout
        )
        if rc == _RQ_CLOSED:
            raise RuntimeError("queue closed")
        return rc == _RQ_OK

    def peek_size(self, timeout: float | None = None) -> int | None:
        size = self._lib.rq_peek_size(self._h, -1.0 if timeout is None else timeout)
        return None if size < 0 else int(size)

    def get(self, timeout: float | None = None) -> bytes | None:
        # `timeout` is a total deadline across the peek + pop (+ regrow) calls.
        deadline = None if timeout is None else time.monotonic() + timeout
        remaining = lambda: -1.0 if deadline is None else max(0.0, deadline - time.monotonic())
        size = self._lib.rq_peek_size(self._h, remaining())
        if size < 0:
            return None
        buf = bytearray(int(size) + 256)  # slack: a racing consumer may swap heads
        while True:
            n = self._lib.rq_get(self._h, _as_u8p(buf), len(buf), remaining())
            if n == _RQ_TOO_SMALL:
                size = self._lib.rq_peek_size(self._h, remaining())
                if size < 0:
                    return None
                buf = bytearray(int(size) + 256)
                continue
            if n < 0:
                return None
            return bytes(buf[: int(n)])

    def get_batch_raw(self, n: int, item_cap: int, timeout: float | None = None,
                      scratch: np.ndarray | None = None):
        """Pop n blobs in ONE native call -> (buffer, stride, lens);
        None on timeout (nothing consumed).

        If an item exceeds `item_cap`, the stride doubles and the call
        retries within the same deadline (rather than masquerading as a
        timeout and livelocking the caller).

        `scratch`: optional reusable destination (grown copies are
        returned instead when too small). Callers that pass it must not
        let views of the returned buffer escape past their next call.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        lens = np.zeros(n, np.int64)
        while True:
            # np.empty, not bytearray: a bytearray memsets its whole
            # length, and at Atari shapes that zero-fill of ~2x the
            # payload dominated the entire batch pop (~10ms for a 72MB
            # stride buffer on this host). A reused scratch additionally
            # skips the page-fault cost of a fresh mapping per batch.
            if scratch is not None and len(scratch) >= n * item_cap:
                buf = scratch
            else:
                buf = np.empty(n * item_cap, np.uint8)
            rc = self._lib.rq_get_batch(
                self._h,
                n,
                _as_u8p(buf),
                item_cap,
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                -1.0 if deadline is None else max(0.0, deadline - time.monotonic()),
            )
            if rc == _RQ_TOO_SMALL:
                item_cap *= 2
                continue
            if rc != _RQ_OK:
                return None
            return buf, item_cap, lens

    def get_batch_blobs(self, n: int, item_cap: int, timeout: float | None = None):
        """Pop n blobs -> list of memoryviews; None on timeout."""
        raw = self.get_batch_raw(n, item_cap, timeout)
        if raw is None:
            return None
        buf, stride, lens = raw
        view = memoryview(buf)
        return [view[i * stride : i * stride + int(lens[i])] for i in range(n)]

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.rq_destroy(self._h)
            self._h = None


class NativeTrajectoryQueue:
    """`fifo.TrajectoryQueue` interface over the C++ byte queue.

    Pytrees are codec-encoded on put and decoded on get; the transport
    server can `put_bytes` wire payloads directly (no re-serialize). The
    blob size of the first item fixes the batch-dequeue stride, so all
    trajectories in one queue must share a schema — true by construction
    (fixed unroll shapes, like the reference's fixed-shape placeholders at
    `buffer_queue.py:40-50`).
    """

    supports_pooled_get = True  # DevicePrefetcher keys pooled dequeue on this
    # How many pooled output sets get_batch(pooled=True) rotates through.
    # A consumer that confirms the previous transfer completed before its
    # next pooled call (DevicePrefetcher does) needs only 2.
    POOL_SETS = 2

    # Concurrency map (tools/drlint lock-discipline): the reusable batch
    # scratch and the pooled output sets may only be touched by the
    # consumer that won the try-acquire on `_scratch_lock` (get_batch) —
    # losers fall back to fresh allocations. `_item_cap` is deliberately
    # unannotated: it is a monotonic int hint racily grown by producers
    # AND consumers, and a lost update only costs one stride-regrow
    # retry on a later pop, never correctness. The C++ queue itself is
    # internally synchronized (cpp/ring_queue.cc).
    _GUARDED_BY = {
        "_scratch": "_scratch_lock",
        "_pool": "_scratch_lock",
        "_pool_sig": "_scratch_lock",
        "_pool_idx": "_scratch_lock",
    }
    _NOT_GUARDED = {
        "_item_cap": "monotonic int hint racily grown by producers and "
                     "consumers; a lost update costs one stride-regrow "
                     "retry on a later pop, never correctness",
    }

    def __init__(self, capacity: int):
        self._q = NativeByteQueue(capacity)
        self.capacity = capacity
        self._item_cap = 0  # learned from the first put
        # Reused batch-pop destination: every view taken of it in
        # get_batch is copied into the returned arrays before the next
        # call can overwrite it. The try-lock keeps concurrent consumers
        # correct (the loser of the race pays a fresh allocation instead
        # of sharing the buffer) — the queue itself stays MPMC.
        self._scratch = np.empty(0, np.uint8)
        self._scratch_lock = threading.Lock()
        # Pooled field outputs (get_batch(pooled=True)): the decoded batch
        # arrays themselves are reused across calls, killing the
        # ~batch-sized np.empty + page-fault cost per dequeue. Rotates
        # POOL_SETS sets; callers own the safety contract (see get_batch).
        self._pool: list[list[np.ndarray] | None] = [None] * self.POOL_SETS
        self._pool_sig: tuple | None = None
        self._pool_idx = 0

    def __len__(self) -> int:
        return len(self._q)

    def size(self) -> int:
        return len(self._q)

    def close(self) -> None:
        self._q.close()

    @property
    def closed(self) -> bool:
        return self._q.closed

    def put(self, item: Any, timeout: float | None = None) -> bool:
        return self.put_bytes(codec.encode(item), timeout)

    def put_bytes(self, blob: bytes, timeout: float | None = None) -> bool:
        if len(blob) > self._item_cap:
            self._item_cap = len(blob)
        ok = self._q.put(blob, timeout)
        # Same fifo/* signals as the pure-Python TrajectoryQueue: the
        # default deployment uses THIS queue (native_available()), and
        # the transport server's raw path enters here via put_bytes.
        if ok and _OBS.enabled:
            _OBS.count("fifo/puts")
            _OBS.gauge("fifo/fill", len(self._q) / self.capacity)
        return ok

    def put_many(self, items: list[Any], timeout: float | None = None) -> int:
        return self.put_bytes_many([codec.encode(i) for i in items], timeout)

    def put_bytes_many(self, blobs: list[bytes], timeout: float | None = None) -> int:
        """Enqueue encoded blobs; returns how many were accepted (stops at
        the first refusal — the rest is NOT enqueued, callers may retry)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        accepted = 0
        for blob in blobs:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            if not self.put_bytes(blob, remaining):
                break
            accepted += 1
        return accepted

    def get(self, timeout: float | None = None) -> Any | None:
        blob = self._q.get(timeout)
        if blob is None:
            return None
        if _OBS.enabled:
            _OBS.count("fifo/gets")
        return codec.decode(blob, copy=True)

    def _pooled_outputs_locked(self, batch_size: int, metas: list[dict]) -> list[np.ndarray] | None:
        """Next rotation of reusable gather destinations, or None if the
        schema changed mid-stream (fall back to fresh allocations).
        Caller holds `_scratch_lock` (get_batch's winning try-acquire)."""
        sig = (batch_size, tuple((m["dtype"], tuple(m["shape"])) for m in metas))
        if sig != self._pool_sig:
            self._pool = [None] * self.POOL_SETS
            self._pool_sig = sig
        self._pool_idx = (self._pool_idx + 1) % self.POOL_SETS
        if self._pool[self._pool_idx] is None:
            self._pool[self._pool_idx] = [
                np.empty((batch_size, *codec.meta_layout(m)[1]), codec.meta_layout(m)[0])
                for m in metas
            ]
        return self._pool[self._pool_idx]

    def _take_scratch_locked(self, nbytes: int) -> np.ndarray:
        """Grow-and-return the shared pop destination. Caller holds
        `_scratch_lock` (get_batch's winning try-acquire)."""
        if len(self._scratch) < nbytes:
            self._scratch = np.empty(nbytes, np.uint8)
        return self._scratch

    def _keep_scratch_locked(self, buf: np.ndarray) -> None:
        """Adopt a buffer the native pop regrew past the scratch. Caller
        holds `_scratch_lock`."""
        if len(buf) > len(self._scratch):
            self._scratch = buf

    def get_batch(self, batch_size: int, timeout: float | None = None,
                  pooled: bool = False) -> Any | None:
        """Pop + assemble a `[B, ...]` batch (see class docstring).

        pooled=True returns arrays from a rotating pool of POOL_SETS
        reusable buffer sets instead of fresh allocations. Safety
        contract: the caller must be the queue's only pooled consumer
        and must be done with set k's memory (e.g. confirmed its H2D
        transfer completed) before its (k + POOL_SETS)'th call. Never
        use pooled batches with a backend that may alias host memory
        (JAX CPU arrays can) — the pool would overwrite live training
        data. DevicePrefetcher enforces both.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        item_cap = self._item_cap
        if item_cap == 0:
            # Nothing put through *this* wrapper yet (e.g. learner polling at
            # startup, or a fresh wrapper over a shared queue): size the
            # stride from the head item instead of guessing. Shares the one
            # total deadline with the batch pop below.
            head = self._q.peek_size(timeout)
            if head is None:
                return None
            item_cap = head + 256
        remaining = (
            None if deadline is None else max(0.0, deadline - time.monotonic())
        )
        # The try-lock decides whether this call may use the shared
        # scratch buffer; the lock is held through ASSEMBLY too, because
        # until the gathers/decodes finish, `buf` (== scratch) must not
        # be overwritten by another consumer. A loser of the race just
        # pays a fresh per-call allocation — the queue stays MPMC-safe.
        have_scratch = self._scratch_lock.acquire(blocking=False)
        try:
            scratch = (self._take_scratch_locked(batch_size * item_cap)
                       if have_scratch else None)
            raw = self._q.get_batch_raw(batch_size, item_cap, remaining,
                                        scratch=scratch)
            if raw is None:
                return None
            if _OBS.enabled:
                _OBS.count("fifo/gets", batch_size)
            buf, stride, lens = raw
            if have_scratch:
                self._keep_scratch_locked(buf)  # stride regrew in the pop
            # Persist a regrown stride so later batches don't repeat the
            # doomed small-stride native call (one wasted lock+retry each).
            self._item_cap = max(self._item_cap, stride)
            base = _as_u8p(buf)
            lib = self._q._lib
            skel, metas, payload_start = codec.parse_layout(
                memoryview(buf)[: int(lens[0])])
            # Fast path: every blob shares blob 0's header (one schema per
            # queue — true by construction), so the batch is assembled by L
            # native field gathers instead of N decodes + L np.stacks.
            if batch_size == 1 or lib.bs_all_equal_prefix(
                base, stride, batch_size, payload_start
            ):
                outs = (self._pooled_outputs_locked(batch_size, metas)
                        if pooled and have_scratch else None)
                arrays = []
                for j, meta in enumerate(metas):
                    dtype, shape, nbytes = codec.meta_layout(meta)
                    out = outs[j] if outs is not None else np.empty(
                        (batch_size, *shape), dtype)
                    lib.bs_gather(
                        base, stride, batch_size, payload_start + meta["offset"],
                        nbytes,
                        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    )
                    arrays.append(out)
                return codec.assemble(skel, arrays)
            # Mixed headers (shouldn't happen in practice): per-blob decode.
            view = memoryview(buf)
            blobs = [view[i * stride : i * stride + int(lens[i])]
                     for i in range(batch_size)]
            return stack_pytrees([codec.decode(b) for b in blobs])
        finally:
            if have_scratch:
                self._scratch_lock.release()


class NativeSumTree:
    """Priority tree backed by cpp/sumtree.cc; same surface as replay.SumTree
    plus batch entry points. Data payloads live in the Python caller."""

    def __init__(self, capacity: int):
        self._lib = _load()
        self._h = self._lib.st_create(capacity)
        if not self._h:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._closed = False

    def __len__(self) -> int:
        return int(self._lib.st_size(self._h))

    @property
    def total(self) -> float:
        return float(self._lib.st_total(self._h))

    def leaf_priority(self, tree_idx: int) -> float:
        return float(self._lib.st_leaf_priority(self._h, tree_idx))

    def leaf_priorities(self, start: int, n: int) -> np.ndarray:
        """Priorities of data slots [start, start+n) in ONE native call."""
        out = np.empty(n, np.float64)
        self._lib.st_leaf_priorities(
            self._h, start, n, out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        return out

    def add_batch(self, priorities: np.ndarray) -> np.ndarray:
        """Returns the data slots written (tree idx = slot + capacity - 1)."""
        p = np.ascontiguousarray(priorities, np.float64)
        out = np.empty(len(p), np.int64)
        self._lib.st_add_batch(
            self._h,
            p.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            len(p),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        return out

    def update_batch(self, tree_idxs: np.ndarray, priorities: np.ndarray) -> None:
        i = np.ascontiguousarray(tree_idxs, np.int64)
        p = np.ascontiguousarray(priorities, np.float64)
        self._lib.st_update_batch(
            self._h,
            i.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            p.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            len(i),
        )

    def get_batch(self, values: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Subtractive descent per value -> (tree_idxs, priorities)."""
        v = np.ascontiguousarray(values, np.float64)
        idxs = np.empty(len(v), np.int64)
        prios = np.empty(len(v), np.float64)
        self._lib.st_get_batch(
            self._h,
            v.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            len(v),
            idxs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            prios.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        )
        return idxs, prios

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.st_destroy(self._h)
            self._h = None
