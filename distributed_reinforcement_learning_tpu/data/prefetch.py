"""Host -> device batch prefetcher: overlap H2D transfer with compute.

SURVEY §7's first "hard part": keeping the learn step fed. The naive loop

    batch = queue.get_batch(B)      # host: dequeue + np.stack
    state, _ = agent.learn(state, batch)   # device: H2D THEN compute

serializes the host stacking + PCIe/ICI transfer with the device step —
the reference is even worse (32 sequential RPC dequeues + a feed_dict
upload per step, `buffer_queue.py:416-435`, SURVEY §3.1). This module
runs the dequeue+stack+`jax.device_put` of batch k+1 on a background
thread while batch k trains, so the device never waits on the host path
unless the actors genuinely can't keep up (which the `profile/dequeue_ms`
stage metric then shows).

`depth` bounds the number of batches resident on device beyond the one
in use (default 1 = classic double buffering; uint8 Atari batches are
~4.5 MB each at B=32,T=20 so HBM cost is negligible next to the overlap
win).
"""

from __future__ import annotations

import logging
import queue as _queue
import threading
import time
from typing import Any, Callable

import jax

_log = logging.getLogger(__name__)


class DevicePrefetcher:
    """Background dequeue + device_put pipeline over a TrajectoryQueue.

    `get_batch(timeout)` returns a device-resident batch (or None on
    timeout, matching the queue's surface so learners can swap it in
    transparently). `sharding` (e.g. a NamedSharding over the data axis)
    routes the transfer; None targets the default device.
    """

    def __init__(
        self,
        source: Any,  # TrajectoryQueue-like: get_batch(batch_size, timeout)
        batch_size: int,
        sharding: Any | None = None,
        depth: int = 1,
        transform: Callable[[Any], Any] | None = None,
        stack_calls: int = 1,
        stack_sharding: Any | None = None,
    ):
        self.source = source
        self.batch_size = batch_size
        self.sharding = sharding
        self.transform = transform
        # stack_calls=K: each get_batch yields a [K, B, ...] stack of K
        # dequeued batches (for learn_many / updates_per_call learners).
        # The stacking happens on this background thread, overlapped with
        # device compute like the H2D itself. Over a mesh the stack needs
        # its own spec (`stack_sharding`, B on the data axis, K
        # unsharded) — the per-batch `sharding` would put K there.
        #
        # The depth is RECONFIGURABLE post-construction (`reconfigure`):
        # the live config is one immutable (k, stack_sharding, epoch)
        # tuple swapped atomically by the controlling thread and read
        # once per round by the prefetch thread; queued batches carry
        # the epoch they were stacked under and get_batch drops
        # mismatches — a renegotiated K can never hand the learn path a
        # stale-shape stack.
        k = max(1, int(stack_calls))
        if k > 1 and sharding is not None and stack_sharding is None:
            raise ValueError(
                "stack_calls > 1 over a mesh needs stack_sharding "
                "(a [K, B, ...] spec with the batch dim on the data axis)")
        self._cfg: tuple[int, Any, int] = (k, stack_sharding, 0)
        self._out: _queue.Queue = _queue.Queue(maxsize=max(1, depth))
        self.dropped_batches = 0  # dequeued-but-untrained batches lost at stop
        self._error: BaseException | None = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="device-prefetch"
        )
        self._thread.start()

    @property
    def stack_calls(self) -> int:
        return self._cfg[0]

    @property
    def stack_sharding(self):
        return self._cfg[1]

    def reconfigure(self, stack_calls: int, stack_sharding: Any | None = None
                    ) -> None:
        """Renegotiate the K-stack depth post-construction.

        PR 13's tier attach REFUSED `updates_per_call>1` on a
        prefetching learner because flipping the learner's counter would
        feed the constructed [K, B, ...] stack into the K==1 learn path
        and shape-crash the first step. The epoch-tagged handoff makes
        the negotiation safe instead: batches already queued at the old
        depth are dropped at `get_batch` (counted in `dropped_batches`),
        and the next prefetch round stacks at the new depth. Called from
        the learner's controlling thread (tier attach / construction
        wiring) — a single atomic reference swap, no lock needed against
        the prefetch thread's per-round read."""
        k = max(1, int(stack_calls))
        cur_k, cur_sharding, epoch = self._cfg
        if stack_sharding is None:
            stack_sharding = cur_sharding
        if k > 1 and self.sharding is not None and stack_sharding is None:
            raise ValueError(
                "stack_calls > 1 over a mesh needs stack_sharding "
                "(a [K, B, ...] spec with the batch dim on the data axis)")
        if k == cur_k and stack_sharding is cur_sharding:
            return
        self._cfg = (k, stack_sharding, epoch + 1)

    def _loop(self) -> None:
        try:
            self._loop_inner()
        except BaseException as e:  # noqa: BLE001 — surfaced via get_batch
            # A dead prefetch pipeline must be distinguishable from slow
            # actors: record the failure so get_batch re-raises it instead
            # of the learner polling timeouts forever.
            self._error = e

    def _note_dropped(self, parts: list) -> None:
        # Stop/close arriving mid-stack drops the already-dequeued partial
        # stack (acceptable at shutdown, but make it visible — advisor r3).
        if parts:
            self.dropped_batches += len(parts)
            _log.info("prefetch stopped mid-stack: dropped %d "
                      "dequeued-but-untrained batches", len(parts))

    def _loop_inner(self) -> None:
        # Pooled dequeue: the source hands back REUSED host arrays (no
        # per-batch alloc + page faults). Safe only when (a) the device
        # backend copies on H2D (TPU/GPU do; JAX CPU may alias numpy
        # memory — pooling there would overwrite live training data) and
        # (b) we confirm each transfer completed before the pool can
        # rotate back onto its buffers — the block_until_ready below,
        # which waits on THIS background thread, not the learner.
        # Pooled sources rotate their buffers every few calls, so a K-stack
        # (which holds K dequeues alive at once) must copy out of the pool:
        # np.stack below already does, but the pool's rotation window may
        # be narrower than K — disable pooling when stacking.
        pool_ok = (getattr(self.source, "supports_pooled_get", False)
                   and jax.default_backend() not in ("cpu",))
        while not self._stop.is_set():
            # One config read per round: a reconfigure lands at the NEXT
            # round; this round's product carries this round's epoch.
            stack_calls, stack_sharding, epoch = self._cfg
            pooled = pool_ok and stack_calls == 1
            parts = []
            while len(parts) < stack_calls and not self._stop.is_set():
                try:
                    if pooled:
                        batch = self.source.get_batch(self.batch_size, timeout=0.2,
                                                      pooled=True)
                    else:
                        batch = self.source.get_batch(self.batch_size, timeout=0.2)
                except RuntimeError:
                    if getattr(self.source, "closed", False):
                        self._note_dropped(parts)  # orderly shutdown
                        return
                    raise  # genuine failure: record via _loop, don't die silently
                if batch is None:
                    # A closed+drained source returns None instantly — exit
                    # rather than hot-spin on it (closed is sticky).
                    if getattr(self.source, "closed", False):
                        self._note_dropped(parts)
                        return
                    continue
                parts.append(batch)
            if len(parts) < stack_calls:
                self._note_dropped(parts)  # stopped mid-stack
                return
            if stack_calls > 1:
                from distributed_reinforcement_learning_tpu.data.fifo import stack_pytrees

                batch = stack_pytrees(parts)
            else:
                batch = parts[0]
            if self.transform is not None:
                batch = self.transform(batch)
            # Async H2D: device_put returns immediately, the transfer
            # overlaps with whatever the device is computing. Multi-host
            # meshes route through make_array_from_process_local_data
            # (parallel.mesh.place_local_batch).
            sharding = stack_sharding if stack_calls > 1 else self.sharding
            if sharding is not None:
                from distributed_reinforcement_learning_tpu.parallel import place_local_batch

                batch = place_local_batch(batch, sharding)
            else:
                batch = jax.device_put(batch)
            if pooled:
                # The pool rotation contract: buffers of batch k may be
                # rewritten at call k + POOL_SETS, so the H2D of k must
                # have completed by then. Waiting here (background
                # thread) guarantees it one call early, and the transfer
                # still overlaps the device's compute on batch k-1.
                jax.block_until_ready(batch)
            while not self._stop.is_set():
                try:
                    self._out.put((epoch, stack_calls, batch), timeout=0.2)
                    break
                except _queue.Full:
                    continue

    def get_batch(self, timeout: float | None = None) -> Any | None:
        """Next device-resident batch; None on timeout (learner idles).

        Raises the prefetch thread's failure (if it died) rather than
        returning None forever. timeout=None blocks — but in slices, so a
        thread death still surfaces instead of hanging the blocking get.
        Batches stacked under a depth that `reconfigure` has since
        replaced are dropped here (their source batches counted in
        `dropped_batches`) — the caller only ever sees the live shape."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                epoch, stack_calls, batch = self._out.get(
                    timeout=0.2 if deadline is None
                    else max(0.0, min(0.2, deadline - time.monotonic())))
            except _queue.Empty:
                if self._error is not None:
                    raise RuntimeError("prefetch thread died") from self._error
                if deadline is not None and time.monotonic() >= deadline:
                    return None
                continue
            if epoch != self._cfg[2]:
                self.dropped_batches += stack_calls  # stale-depth stack
                _log.info("prefetch dropped a stale-depth stack "
                          "(%d batches) after reconfigure", stack_calls)
                continue
            return batch

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
