"""Client-side trajectory accumulators.

Batched re-design of the reference's per-env Python-list accumulators
(`utils.py:47-86` UnrolledTrajectory, `buffer_queue.py:94-134`
R2D2TrajectoryBuffer): one accumulator holds a whole vectorized actor's
unroll as `[T]`-lists of `[N, ...]` arrays and emits per-env trajectory
pytrees keyed to the agents' batch NamedTuples.
"""

from __future__ import annotations

import numpy as np

from distributed_reinforcement_learning_tpu.agents.apex import ApexBatch
from distributed_reinforcement_learning_tpu.agents.impala import ImpalaBatch
from distributed_reinforcement_learning_tpu.agents.r2d2 import R2D2Batch


class _StackedUnrollAccumulator:
    """Shared stack-and-split machinery: collect T steps of `[N, ...]`
    fields, emit one `[T, ...]` batch pytree per env slot (the queue
    stacks them into `[B, T, ...]`). Subclasses name the batch class."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self._steps: list[dict] = []

    def append(self, **step_fields: np.ndarray) -> None:
        self._steps.append(step_fields)

    def __len__(self) -> int:
        return len(self._steps)

    def _batch_cls(self):
        raise NotImplementedError

    def extract(self) -> list:
        cls = self._batch_cls()
        fields = {
            k: np.stack([s[k] for s in self._steps], axis=1)  # [N, T, ...]
            for k in self._steps[0]
        }
        n = next(iter(fields.values())).shape[0]
        return [cls(**{k: v[i] for k, v in fields.items()}) for i in range(n)]


class ImpalaTrajectoryAccumulator(_StackedUnrollAccumulator):
    """Collects T steps of a `[N]`-env actor, emits N `ImpalaBatch`-shaped
    pytrees with leading `[T]` axis (no batch dim — the queue stacks them)."""

    def _batch_cls(self):
        return ImpalaBatch


class R2D2SequenceAccumulator:
    """Collects seq_len steps + the sequence-start LSTM state per env.

    Mirrors `R2D2TrajectoryBuffer` (`buffer_queue.py:94-134`) but batched:
    the per-step (h, c) of the reference collapse to the sequence-start
    state, which is all the learner seeds from (`agent/r2d2.py:110-111`).
    """

    def __init__(self):
        self._steps: list[dict] = []
        self._initial_h: np.ndarray | None = None
        self._initial_c: np.ndarray | None = None

    def reset(self, initial_h: np.ndarray, initial_c: np.ndarray) -> None:
        self._steps = []
        self._initial_h = np.asarray(initial_h).copy()
        self._initial_c = np.asarray(initial_c).copy()

    def append(self, **step_fields: np.ndarray) -> None:
        self._steps.append(step_fields)

    def __len__(self) -> int:
        return len(self._steps)

    def extract(self) -> list[R2D2Batch]:
        fields = {
            k: np.stack([s[k] for s in self._steps], axis=1) for k in self._steps[0]
        }
        n = next(iter(fields.values())).shape[0]
        return [
            R2D2Batch(
                state=fields["state"][i],
                previous_action=fields["previous_action"][i],
                action=fields["action"][i],
                reward=fields["reward"][i],
                done=fields["done"][i],
                initial_h=self._initial_h[i],
                initial_c=self._initial_c[i],
            )
            for i in range(n)
        ]


def transitions_from_unroll(
    state: np.ndarray,
    next_state: np.ndarray,
    previous_action: np.ndarray,
    action: np.ndarray,
    reward: np.ndarray,
    done: np.ndarray,
) -> list[ApexBatch]:
    """Split `[T, ...]` unroll arrays into per-transition ApexBatch rows
    (the per-transition replay insertion of `train_apex.py:114-122`)."""
    return [
        ApexBatch(
            state=state[t],
            next_state=next_state[t],
            previous_action=previous_action[t],
            action=action[t],
            reward=reward[t],
            done=done[t],
        )
        for t in range(state.shape[0])
    ]


class XformerSequenceAccumulator(_StackedUnrollAccumulator):
    """Collects seq_len steps per env for the transformer family.

    Same queue payload as the R2D2 accumulator minus the stored LSTM
    state: the transformer re-attends over the stored sequence, so the
    sequence is its own state (agents/xformer.py).
    """

    def _batch_cls(self):
        from distributed_reinforcement_learning_tpu.agents.xformer import XformerBatch

        return XformerBatch


class SlicedAccumulators:
    """Per-slice accumulation for the pipelined actor data plane
    (runtime/actor_pipeline.py): k independent accumulators of any of
    the family classes in this module, one per env slice, so a slice
    can accumulate its own unroll while another slice's act is in
    flight and extract independently at round end. Indexing is by
    slice, never shared — the pipeline's lockstep handoff guarantees a
    slice's accumulator is only touched by one thread at a time."""

    def __init__(self, make_accumulator, num_slices: int):
        self._accs = [make_accumulator() for _ in range(num_slices)]

    def __len__(self) -> int:
        return len(self._accs)

    def slice(self, index: int):
        return self._accs[index]

    def reset_slice(self, index: int, *args) -> None:
        self._accs[index].reset(*args)

    def append_slice(self, index: int, **step_fields: np.ndarray) -> None:
        self._accs[index].append(**step_fields)

    def extract_slice(self, index: int) -> list:
        return self._accs[index].extract()


class XImpalaTrajectoryAccumulator(_StackedUnrollAccumulator):
    """Collects T steps per env for the Transformer-IMPALA family: the
    IMPALA unroll payload minus the stored (h, c) — the transformer
    re-attends over the unroll, so the sequence is its own state."""

    def _batch_cls(self):
        from distributed_reinforcement_learning_tpu.agents.ximpala import XImpalaBatch

        return XImpalaBatch
