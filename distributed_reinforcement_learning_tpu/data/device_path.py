"""Device-resident sample path: fused gather -> H2D -> scanned learn.

Every committed bench run says the same thing: the learn kernel is
~1000x faster than the host loop that feeds it (BENCH_r04 stage budget:
learn 736k f/s vs 705-820 f/s e2e, h2d 0.87 GB/s serial). PR 6 moved
prioritization to ingest; this module moves the REST of the per-update
host round-trip off the learn thread — the device-side mirror of
in-network experience sampling (arXiv:2110.13506) and the keep-it-on-
device discipline of Podracer (arXiv:2104.06272). The host path the
prioritized learners pay per train call is

    sample (shard gather) -> np stack -> H2D -> 1 jitted step
    -> D2H priorities -> host writeback

all serialized on the learn thread. `DeviceSamplePath` is the
`data/prefetch.DevicePrefetcher` of the REPLAY plane: a background
gather thread samples the next K prioritized batches from the
thread-safe sharded service (data/replay_service.py — per-shard locks
make concurrent gather safe; the single-thread monolithic backends stay
on the host path by contract), assembles the `[K, B, ...]` scan stack
on the host, and issues the `jax.device_put` on its own thread — so the
copy for call k+1 overlaps the jitted `learn_many` scan for call k,
while the shard ingest threads keep inserting concurrently. `depth`
bounds how many sampled calls sit device-resident beyond the one in
use (classic double buffering at the default 1).

The learn side (`runtime/replay_train.device_train_call`) runs the K
steps as ONE jitted `lax.scan` (`agent.learn_many`, the `learn_scan`
shape bench.py proved at per-step parity), materializes the `[K, B]`
priority stack in a SINGLE D2H per K, and fans it back to the sharded
writeback router through the existing packed (tag|epoch|shard|tree_idx)
int64 indexes — a shard death mid-K drops only that shard's stale-epoch
updates, loss-free, exactly as the router always did.

Semantics: sampled batches are bit-identical to the host gather at a
fixed RNG (`gather_scan_batch` IS the host path's gather —
`prioritized_train_call` calls the same function). The only delta is
priority staleness: with K scanned steps and `depth` buffered calls,
a batch can be sampled up to ~K+depth updates before its priorities
refresh — the same staleness class the host K>1 scan already accepts
(batches 2..K sampled before update 1 lands) and distributed Ape-X
accepts from its actors.

Degrade ladder (all permanent, logged once by the learner mixin):
an oversize stacked call (`DRL_DEVICE_PATH_MAX_MB`) or a gather fault
latches the path dead -> the learner demotes to the host loop; a
service demotion (all shards dead) closes the path before the learner
resumes host-side sampling (the RNG hand-back). A learner-tier attach
that forces K=1 (allreduce merges per train step) RECONFIGURES the
path instead: entries stacked at the old K are epoch-dropped, never
fed to the K==1 learn seam — double-buffered H2D only, cleanly.

Gate: `DRL_DEVICE_PATH` (0 off, 1 force; unset defers to the committed
`benchmarks/device_path_verdict.json` adjudication — the repo's
no-un-adjudicated-fast-path rule, bench.py `device_path_compare`).

Concurrency model (no class-owned locks, so the `_GUARDED_BY` map is
the documentation form): ONE gather thread produces, ONE learn thread
consumes. The handoff is a bounded `queue.Queue` (internally locked);
`_cfg` is an immutable `(k, epoch)` tuple swapped atomically by the
consumer (reconfigure) and read once per round by the producer —
entries carry the epoch they were stacked under, and the consumer
drops mismatches. `dead_reason` is a write-once str published by
whichever side latches the path; all remaining counters are
single-writer (noted per attribute in `_NOT_GUARDED`).
"""

from __future__ import annotations

import json
import os
import queue as _queue
import threading
import time
from typing import Any, Callable

import numpy as np

from distributed_reinforcement_learning_tpu.observability import TELEMETRY as _OBS

_VERDICT_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "benchmarks", "device_path_verdict.json")


def device_path_enabled(verdict_path: str = _VERDICT_PATH) -> bool:
    """Gate resolution: `DRL_DEVICE_PATH=1` forces on, `=0` forces off;
    unset defers to the committed `device_path_compare` adjudication
    (auto-enable only at >= 1.2x the host sample path — the repo's
    Pallas-LSTM rule)."""
    env = os.environ.get("DRL_DEVICE_PATH", "").strip()
    if env:
        return env != "0"
    try:
        with open(verdict_path) as f:
            return bool(json.load(f).get("auto_enable", False))
    except (OSError, ValueError):
        return False


def path_depth() -> int:
    """`DRL_DEVICE_PATH_DEPTH`: device-resident sampled calls beyond the
    one in use (1 = classic double buffering)."""
    env = os.environ.get("DRL_DEVICE_PATH_DEPTH", "").strip()
    if not env:
        return 1
    try:
        return max(1, int(env))
    except ValueError as e:
        raise ValueError(
            f"DRL_DEVICE_PATH_DEPTH must be an integer, got {env!r}") from e


def path_max_bytes() -> int:
    """`DRL_DEVICE_PATH_MAX_MB`: stacked-call size past which the path
    demotes to the host loop instead of risking a device OOM."""
    env = os.environ.get("DRL_DEVICE_PATH_MAX_MB", "").strip()
    if not env:
        return 256 * 1024 * 1024
    try:
        return max(1, int(float(env) * 1024 * 1024))
    except ValueError as e:
        raise ValueError(
            f"DRL_DEVICE_PATH_MAX_MB must be a number, got {env!r}") from e


# -- the gather (shared with the host path) -----------------------------------


def gather_scan_batch(replay, batch_size: int, k: int, rng
                      ) -> tuple[Any, np.ndarray, list[np.ndarray]]:
    """Sample `k` prioritized batches and assemble the scan inputs on
    the host: -> (stacked [k, B, ...] pytree, weights [k, B] f32,
    per-batch index arrays). THE single definition of the gather —
    `runtime/replay_train.prioritized_train_call` (host path) and the
    `DeviceSamplePath` gather thread both call it, so the device path's
    sampled batches are bit-identical to the host gather at a fixed RNG
    by construction (and test-pinned, tests/test_device_path.py)."""
    from distributed_reinforcement_learning_tpu.data.fifo import stack_pytrees

    import jax

    sampled = [replay.sample(batch_size, rng) for _ in range(k)]
    if getattr(replay, "stacked_samples", False):
        # SoA backend hands back already-stacked [B, ...] arrays.
        stacked = stack_pytrees([items for items, _, _ in sampled])
    else:
        # AoS: one copy — stack all k*B items once, view as [k, B, ...].
        flat = stack_pytrees([it for items, _, _ in sampled for it in items])
        stacked = jax.tree.map(
            lambda x: x.reshape((k, -1) + x.shape[1:]), flat)
    weights = np.stack([np.asarray(w, np.float32) for _, _, w in sampled])
    return stacked, weights, [idxs for _, idxs, _ in sampled]


def gather_single_batch(replay, batch_size: int, rng
                        ) -> tuple[Any, np.ndarray, list[np.ndarray]]:
    """The K==1 gather: -> ([B, ...] batch, weights [B] f32, [idxs]).
    No scan axis — the entry feeds the learner's `_learn` seam directly
    (which a learner tier may have wrapped with its collective), so the
    fused path under a tier-forced K=1 is H2D double buffering only."""
    from distributed_reinforcement_learning_tpu.data.fifo import stack_pytrees

    items, idxs, weights = replay.sample(batch_size, rng)
    batch = items if getattr(replay, "stacked_samples", False) \
        else stack_pytrees(items)
    return batch, np.asarray(weights, np.float32), [idxs]


def _tree_nbytes(tree: Any) -> int:
    import jax

    return sum(np.asarray(leaf).nbytes for leaf in jax.tree.leaves(tree))


# -- the path -----------------------------------------------------------------


class DeviceSamplePath:
    """Background sample + stack + device_put pipeline over a
    prioritized replay (the thread-safe sharded service in deployment).

    `next_entry(timeout)` returns `(k, device batch, device weights,
    idx_list)` — or None on timeout / after the path latched dead (the
    caller demotes to the host loop; `dead_reason` says why). `rng` is
    the learner's sampling stream: while the path is live the gather
    thread OWNS it (the learner must not host-sample), and `close()`
    joins the thread before the host path takes the stream back.
    """

    # Documentation-form concurrency map (tools/drlint lock-discipline):
    # no class-owned locks — see the module docstring's concurrency
    # model. Single-producer/single-consumer over a bounded queue.Queue;
    # `_cfg` / `dead_reason` are atomic reference swaps.
    _GUARDED_BY: dict = {}
    _NOT_GUARDED = {
        "_cfg": "immutable (k, epoch) tuple; consumer swaps the whole "
                "reference, producer reads it once per round",
        "dead_reason": "write-once latch reason (str reference), "
                       "whichever side latches first wins",
        "dropped_entries": "consumer-thread-only stale-epoch tally",
        "h2d_bytes": "gather-thread-only byte counter",
        "entries_out": "gather-thread-only entry counter",
        "gather_rounds": "gather-thread-only round counter",
    }

    def __init__(self, replay, batch_size: int, k: int, rng,
                 depth: int | None = None, max_bytes: int | None = None,
                 transfer: Callable[[Any], Any] | None = None):
        import jax

        self.replay = replay
        self.batch_size = batch_size
        self.rng = rng
        self.max_bytes = path_max_bytes() if max_bytes is None else max_bytes
        # Injectable H2D (tests stub a slow copy to pin that the overlap
        # actually overlaps); deployment is a plain device_put on this
        # background thread — the async transfer the learn dispatch then
        # waits on, never the learn THREAD.
        self._transfer = jax.device_put if transfer is None else transfer
        self._cfg: tuple[int, int] = (max(1, int(k)), 0)
        self._out: _queue.Queue = _queue.Queue(
            maxsize=path_depth() if depth is None else max(1, depth))
        self.dead_reason: str | None = None
        self.dropped_entries = 0  # stale-epoch entries (K renegotiated)
        self.h2d_bytes = 0
        self.entries_out = 0
        self.gather_rounds = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="device-sample-path")
        self._thread.start()

    # -- state -------------------------------------------------------------

    @property
    def k(self) -> int:
        return self._cfg[0]

    @property
    def dead(self) -> bool:
        return self.dead_reason is not None

    def stats(self) -> dict:
        return {"k": self._cfg[0], "depth": self._out.qsize(),
                "entries_out": self.entries_out,
                "h2d_bytes": self.h2d_bytes,
                "dropped_entries": self.dropped_entries,
                "gather_rounds": self.gather_rounds,
                "dead_reason": self.dead_reason}

    # -- consumer side -----------------------------------------------------

    def reconfigure(self, k: int) -> None:
        """Renegotiate the scan depth (the learner-tier attach forces
        K=1 under allreduce). Entries already stacked at the old K carry
        the old epoch and are dropped at `next_entry` — never fed to a
        learn path expecting the new shape (no silent K change, no
        shape crash; pinned in tests/test_device_path.py)."""
        k = max(1, int(k))
        cur_k, epoch = self._cfg
        if k == cur_k:
            return
        self._cfg = (k, epoch + 1)

    def next_entry(self, timeout: float | None = 0.5):
        """-> (k, device batch, device weights, idx_list) or None (the
        gather is behind, or the path died — check `dead`). Stale-epoch
        entries are consumed and dropped here; their sampled indexes
        lose only their (advisory) priority writeback."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                wait = (0.2 if deadline is None else
                        max(0.0, min(0.2, deadline - time.monotonic())))
                epoch, k, batch, weights, idxs = self._out.get(timeout=wait)
            except _queue.Empty:
                if self.dead:
                    return None
                if deadline is not None and time.monotonic() >= deadline:
                    return None
                continue
            if epoch != self._cfg[1]:
                self.dropped_entries += 1
                if _OBS.enabled:
                    _OBS.count("devpath/dropped_entries")
                continue
            return k, batch, weights, idxs

    def close(self) -> bool:
        """Stop and JOIN the gather thread; True when the join landed —
        only then is the learner's RNG stream exclusively the host
        path's again. A False return (the thread wedged past the
        budget, e.g. a device_put stalled behind queued device work)
        means the caller must NOT keep sampling the shared RNG
        (`ReplayTrainMixin._demote_device_path` swaps in a fresh stream
        in that case)."""
        self._stop.set()
        self._thread.join(timeout=5.0)
        return not self._thread.is_alive()

    # -- gather thread -----------------------------------------------------

    def _latch_dead(self, reason: str) -> None:
        if self.dead_reason is None:
            self.dead_reason = reason

    def _loop(self) -> None:
        try:
            self._loop_inner()
        except BaseException as e:  # noqa: BLE001 — surfaced via dead_reason
            self._latch_dead(f"gather thread died: {type(e).__name__}: {e}")

    def _loop_inner(self) -> None:
        from distributed_reinforcement_learning_tpu.data.replay_service import (
            ReplayServiceEmpty)

        while not self._stop.is_set():
            k, epoch = self._cfg
            t0 = time.perf_counter()
            try:
                if k > 1:
                    batch, weights, idxs = gather_scan_batch(
                        self.replay, self.batch_size, k, self.rng)
                else:
                    batch, weights, idxs = gather_single_batch(
                        self.replay, self.batch_size, self.rng)
            except ReplayServiceEmpty:
                # Transient while the service is healthy (a revive can
                # empty the shards mid-run); terminal once it demoted —
                # the learner is about to resolve the monolithic path.
                if not getattr(self.replay, "healthy", True):
                    self._latch_dead("replay service demoted (all shards "
                                     "dead)")
                    return
                self._stop.wait(0.005)
                continue
            gather_ms = (time.perf_counter() - t0) * 1e3
            self.gather_rounds += 1
            nbytes = _tree_nbytes(batch) + weights.nbytes
            if nbytes > self.max_bytes:
                self._latch_dead(
                    f"oversize sampled call: {nbytes / 1e6:.1f} MB > "
                    f"DRL_DEVICE_PATH_MAX_MB — demoting to the host path")
                return
            t1 = time.perf_counter()
            dev_batch, dev_weights = self._transfer((batch, weights))
            h2d_ms = (time.perf_counter() - t1) * 1e3
            self.h2d_bytes += nbytes
            if _OBS.enabled:
                _OBS.gauge("devpath/gather_ms", gather_ms)
                _OBS.gauge("devpath/h2d_ms", h2d_ms)
                _OBS.count("devpath/h2d_bytes", nbytes)
                _OBS.gauge("devpath/depth", self._out.qsize())
            entry = (epoch, k, dev_batch, dev_weights, idxs)
            while not self._stop.is_set():
                try:
                    self._out.put(entry, timeout=0.2)
                    self.entries_out += 1
                    if _OBS.enabled:
                        _OBS.count("devpath/entries")
                    break
                except _queue.Full:
                    continue
