"""Host-side data plane (reference layer L2): queues, replay, accumulators."""

from distributed_reinforcement_learning_tpu.data.fifo import TrajectoryQueue, stack_pytrees
from distributed_reinforcement_learning_tpu.data.replay import (
    NativePrioritizedReplay,
    PrioritizedReplay,
    SumTree,
    UniformBuffer,
    make_replay,
)
from distributed_reinforcement_learning_tpu.data.structures import (
    ImpalaTrajectoryAccumulator,
    R2D2SequenceAccumulator,
    transitions_from_unroll,
)

__all__ = [
    "TrajectoryQueue",
    "stack_pytrees",
    "PrioritizedReplay",
    "NativePrioritizedReplay",
    "make_replay",
    "SumTree",
    "UniformBuffer",
    "ImpalaTrajectoryAccumulator",
    "R2D2SequenceAccumulator",
    "transitions_from_unroll",
]
