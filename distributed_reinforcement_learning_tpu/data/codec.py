"""Zero-copy-ish pytree <-> bytes codec for the native queue and the wire.

The reference never serializes — TF's FIFOQueue kernel moves tensors
through its own gRPC runtime (`distributed_queue/buffer_queue.py:28-36`).
Our data plane is explicit: a trajectory pytree of numpy arrays is packed
into one contiguous blob (header + raw array bytes) that the C++ ring
queue and the TCP transport move without touching Python object graphs.

Layout: [u32 magic][u32 header_len][header json][payload]
  header = {"treedef": ..., "arrays": [{"dtype","shape","offset","nbytes"}]}
Payload arrays are C-contiguous raw bytes at 64-byte aligned offsets (so
a reader can np.frombuffer without copies and downstream device DMA sees
aligned hosts buffers).
"""

from __future__ import annotations

import json
from collections import namedtuple
from functools import lru_cache
from typing import Any

import numpy as np


@lru_cache(maxsize=None)
def _namedtuple_cls(name: str, fields: tuple[str, ...]):
    return namedtuple(name, fields)

_MAGIC = 0x445254A1  # "DRT" + version 1
_ALIGN = 64


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def _flatten(tree: Any, path: str, out: list[tuple[str, np.ndarray]]) -> Any:
    """Flatten nested dict/list/tuple/namedtuple of arrays; return skeleton."""
    if isinstance(tree, dict):
        return {k: _flatten(v, f"{path}.{k}", out) for k, v in sorted(tree.items())}
    if hasattr(tree, "_fields"):  # namedtuple
        vals = {f: _flatten(getattr(tree, f), f"{path}.{f}", out) for f in tree._fields}
        return {"__namedtuple__": type(tree).__name__, "fields": vals}
    if isinstance(tree, (list, tuple)):
        kind = "list" if isinstance(tree, list) else "tuple"
        return {
            "__seq__": kind,
            "items": [_flatten(v, f"{path}[{i}]", out) for i, v in enumerate(tree)],
        }
    arr = np.asarray(tree)
    if arr.ndim and not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)  # 0-d stays 0-d (ascontiguousarray would promote it)
    out.append((path, arr))
    return {"__leaf__": len(out) - 1}


def _unflatten(skel: Any, arrays: list[np.ndarray]) -> Any:
    if isinstance(skel, dict):
        if "__leaf__" in skel:
            return arrays[skel["__leaf__"]]
        if "__seq__" in skel:
            items = [_unflatten(v, arrays) for v in skel["items"]]
            return items if skel["__seq__"] == "list" else tuple(items)
        if "__namedtuple__" in skel:
            # Rebuilt as a structurally-equal namedtuple (same type name and
            # fields) so consumers' attribute access keeps working after a
            # queue/wire round trip.
            fields = skel["fields"]
            cls = _namedtuple_cls(skel["__namedtuple__"], tuple(fields))
            return cls(**{k: _unflatten(v, arrays) for k, v in fields.items()})
        return {k: _unflatten(v, arrays) for k, v in skel.items()}
    raise ValueError(f"corrupt skeleton node: {skel!r}")


def encode(tree: Any) -> bytearray:
    """Pack a pytree of numpy arrays into one contiguous blob.

    Returns a bytearray (bytes-like everywhere it's consumed) and writes
    each array exactly once via buffer assignment — the hot path moves
    every trajectory and every weight snapshot, so no intermediate
    `tobytes()` copies and no final `bytes()` copy.
    """
    leaves: list[tuple[str, np.ndarray]] = []
    skel = _flatten(tree, "$", leaves)
    metas = []
    offset = 0
    for _, arr in leaves:
        offset = _align(offset)
        metas.append(
            {"dtype": arr.dtype.str, "shape": list(arr.shape), "offset": offset}
        )
        offset += arr.nbytes
    header = json.dumps({"skel": skel, "arrays": metas}).encode()
    payload_start = _align(8 + len(header))
    total = payload_start + offset
    buf = bytearray(total)
    buf[0:4] = _MAGIC.to_bytes(4, "little")
    buf[4:8] = len(header).to_bytes(4, "little")
    buf[8 : 8 + len(header)] = header
    view = memoryview(buf)
    for meta, (_, arr) in zip(metas, leaves):
        start = payload_start + meta["offset"]
        view[start : start + arr.nbytes] = memoryview(arr.reshape(-1)).cast("B")
    return buf


def parse_layout(blob: bytes | memoryview) -> tuple[Any, list[dict], int]:
    """Header of a blob -> (skeleton, array metas, payload_start).

    The header fully determines the layout, so a consumer holding many
    same-schema blobs (the native queue's batch pop) can parse ONE
    header and gather every field across blobs — see
    `data/native.py` `NativeTrajectoryQueue.get_batch`.
    """
    view = memoryview(blob)
    if int.from_bytes(view[0:4], "little") != _MAGIC:
        raise ValueError("bad magic: not a codec blob")
    header_len = int.from_bytes(view[4:8], "little")
    header = json.loads(bytes(view[8 : 8 + header_len]))
    return header["skel"], header["arrays"], _align(8 + header_len)


def meta_layout(meta: dict) -> tuple[np.dtype, tuple[int, ...], int]:
    """Array meta dict -> (dtype, shape, nbytes): the single
    interpretation of the header's per-array encoding, shared by
    `decode` and the native batch-gather."""
    dtype = np.dtype(meta["dtype"])
    shape = tuple(meta["shape"])
    nbytes = dtype.itemsize * int(np.prod(shape, dtype=np.int64)) if shape else dtype.itemsize
    return dtype, shape, nbytes


def assemble(skel: Any, arrays: list[np.ndarray]) -> Any:
    """Rebuild the pytree from a skeleton and its (possibly batched)
    leaf arrays, in `parse_layout` order."""
    return _unflatten(skel, arrays)


def decode(blob: bytes | memoryview, copy: bool = False) -> Any:
    """Unpack a blob; arrays view the blob unless copy=True."""
    view = memoryview(blob)
    skel, metas, payload_start = parse_layout(view)
    arrays = []
    for meta in metas:
        dtype, shape, nbytes = meta_layout(meta)
        start = payload_start + meta["offset"]
        arr = np.frombuffer(view[start : start + nbytes], dtype=dtype).reshape(shape)
        arrays.append(arr.copy() if copy else arr)
    return _unflatten(skel, arrays)
