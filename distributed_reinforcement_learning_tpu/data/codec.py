"""Zero-copy-ish pytree <-> bytes codec for the native queue and the wire.

The reference never serializes — TF's FIFOQueue kernel moves tensors
through its own gRPC runtime (`distributed_queue/buffer_queue.py:28-36`).
Our data plane is explicit: a trajectory pytree of numpy arrays is packed
into one contiguous blob (header + raw array bytes) that the C++ ring
queue and the TCP transport move without touching Python object graphs.

Layout: [u32 magic][u32 header_len][header json][payload]
  header = {"treedef": ..., "arrays": [{"dtype","shape","offset","nbytes"}]}
Payload arrays are C-contiguous raw bytes at 64-byte aligned offsets (so
a reader can np.frombuffer without copies and downstream device DMA sees
aligned hosts buffers).

Two hot-path accelerations live here (telemetry-driven: the committed
transport adjudication showed this host is ENCODE-bound — the shm ring
cut PUT latency ~100x and still missed the throughput bar because the
producer was busy re-flattening pytrees):

1. **Schema cache** (`_CACHES`): an actor re-encodes the same pytree
   schema (skeleton + dtypes + shapes) thousands of times per run. The
   first encode of a schema runs the full `_flatten` walk + json header
   build and caches the frozen header bytes, leaf offsets, and total
   size; every later encode is one structural key walk (O(leaves) — the
   per-call verification that invalidates on any dtype/shape/structure
   change), one buffer allocation, and per-leaf memcpys. Decode mirrors
   it with a layout cache keyed by the exact header bytes. Cache-hit
   blobs are byte-identical to cold encodes (pinned by
   tests/test_codec_fastpath.py). Gated by `DRL_CODEC_CACHE` (1 on,
   0 off; unset defers to the committed
   `benchmarks/codec_verdict.json` adjudication — the repo's 1.2x rule).

2. **Frame-stack dedup** (`encode(..., dedup=True)`): Atari-style
   observations `[T, H, W, S]` stack S frames newest-last
   (`envs/atari.py`), so consecutive unroll steps share S-1 of S planes.
   Opt-in packing (`DRL_OBS_DEDUP`) transmits, per stacked leaf, the
   step-0 stack plus ONE new plane per step (a full stack again at each
   detected discontinuity, e.g. an episode reset zeroing the stack),
   ~S-fold cutting the dominant payload. Decode reconstructs
   BIT-IDENTICALLY before anything downstream sees the trajectory;
   leaves that don't match the stacking pattern (or save < 25%) are
   stored plain, so non-stacked schemas pass through unchanged. Packed
   blobs never enter a blob-native queue: `fifo.blob_ingest` routes them
   through `unpack_blob` first (the native batch-gather assumes the
   plain layout).
"""

from __future__ import annotations

import json
import os
import sys
import threading
from collections import namedtuple
from functools import lru_cache
from typing import Any

import numpy as np


@lru_cache(maxsize=None)
def _namedtuple_cls(name: str, fields: tuple[str, ...]):
    return namedtuple(name, fields)

_MAGIC = 0x445254A1  # "DRT" + version 1
_ALIGN = 64

# Stamp extension frame (ISSUE 18 sample-at-source): a self-delimiting
# prefix `[u32 ext_magic][u32 version][u32 ext_len][ext json]` carried
# IN FRONT of an unmodified codec blob. The per-blob priority summary
# must NOT ride the codec header json — the decode layout cache is
# keyed on exact header bytes, and per-blob content there would turn
# every lookup into a miss. The frame layout itself is pinned forever;
# `version` only versions the json semantics, so any reader can skip an
# extension it does not understand and fall through to the plain blob
# (forward compat: a v2 stamp decodes on a v1 learner as unstamped).
_EXT_MAGIC = 0x445254E5
_EXT_VERSION = 1
_EXT_HDR = 12  # magic + version + ext_len

# Below this, a 4-d uint8 leaf is not worth the per-call plane compare.
_DEDUP_MIN_BYTES = 4096
_PACK_FSTACK = "fstack"  # the one packing scheme: frame-stack delta planes


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def _flatten(tree: Any, path: str, out: list[tuple[str, np.ndarray]]) -> Any:
    """Flatten nested dict/list/tuple/namedtuple of arrays; return skeleton."""
    if isinstance(tree, dict):
        return {k: _flatten(v, f"{path}.{k}", out) for k, v in sorted(tree.items())}
    if hasattr(tree, "_fields"):  # namedtuple
        vals = {f: _flatten(getattr(tree, f), f"{path}.{f}", out) for f in tree._fields}
        return {"__namedtuple__": type(tree).__name__, "fields": vals}
    if isinstance(tree, (list, tuple)):
        kind = "list" if isinstance(tree, list) else "tuple"
        return {
            "__seq__": kind,
            "items": [_flatten(v, f"{path}[{i}]", out) for i, v in enumerate(tree)],
        }
    arr = np.asarray(tree)
    if arr.ndim and not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)  # 0-d stays 0-d (ascontiguousarray would promote it)
    out.append((path, arr))
    return {"__leaf__": len(out) - 1}


def _walk(tree: Any, leaves: list[np.ndarray]) -> tuple:
    """Cheap structural walk: collect leaf arrays in `_flatten` order and
    return a hashable schema key. This IS the per-call cache validation —
    the key covers structure, dtypes, and shapes, so a hit can only map
    to a layout that is correct for these leaves. No path strings, no
    skeleton dicts, no json: the whole point of the cache."""
    if isinstance(tree, dict):
        return ("d",) + tuple((k, _walk(v, leaves)) for k, v in sorted(tree.items()))
    if hasattr(tree, "_fields"):  # namedtuple
        return ("n", type(tree).__name__, tuple(tree._fields)) + tuple(
            _walk(getattr(tree, f), leaves) for f in tree._fields)
    if isinstance(tree, (list, tuple)):
        tag = "l" if isinstance(tree, list) else "t"
        return (tag,) + tuple(_walk(v, leaves) for v in tree)
    arr = np.asarray(tree)
    if arr.ndim and not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)
    leaves.append(arr)
    return ("a", arr.dtype.str, arr.shape)


def _unflatten(skel: Any, arrays: list[np.ndarray]) -> Any:
    if isinstance(skel, dict):
        if "__leaf__" in skel:
            return arrays[skel["__leaf__"]]
        if "__seq__" in skel:
            items = [_unflatten(v, arrays) for v in skel["items"]]
            return items if skel["__seq__"] == "list" else tuple(items)
        if "__namedtuple__" in skel:
            # Rebuilt as a structurally-equal namedtuple (same type name and
            # fields) so consumers' attribute access keeps working after a
            # queue/wire round trip.
            fields = skel["fields"]
            cls = _namedtuple_cls(skel["__namedtuple__"], tuple(fields))
            return cls(**{k: _unflatten(v, arrays) for k, v in fields.items()})
        return {k: _unflatten(v, arrays) for k, v in skel.items()}
    raise ValueError(f"corrupt skeleton node: {skel!r}")


# -- schema / layout caches ---------------------------------------------------

# (header bytes, payload_start, per-leaf payload offsets, total blob size,
#  alignment-gap byte ranges to zero — the blob buffer is np.empty, not a
#  bytearray, so only the pad gaps are memset instead of the whole blob)
_EncodePlan = namedtuple("_EncodePlan", ["header", "payload_start", "offsets",
                                         "total", "gaps"])
# (skel, metas, payload_start, per-leaf (dtype, shape, nbytes, offset, pack))
_DecodePlan = namedtuple("_DecodePlan", ["skel", "metas", "payload_start",
                                         "leaves", "packed", "payload_nbytes"])


class _CodecCaches:
    """Process-wide schema/layout caches + counters.

    Concurrency map (tools/drlint lock-discipline): encode runs on actor
    loop threads AND the learner's weight-publish/serve threads; decode
    runs on transport serve threads, ring drainers, and prefetchers —
    all hitting this one singleton. Every access to the three maps and
    the counter dict goes through `_lock`. The cached plans are handed
    out lock-free after lookup; their namedtuple fields are never
    mutated in-module, but `skel`/`metas` hold PLAIN DICTS — public
    surfaces that expose them (`parse_layout`) copy the metas and
    document the skeleton as read-only, so a caller cannot poison the
    cache process-wide.
    """

    _GUARDED_BY = {
        "_encode": "_lock",
        "_dedup": "_lock",
        "_decode": "_lock",
        "stats": "_lock",
    }

    # Per-map entry cap. Eviction is least-recently-USED, one entry at a
    # time (lookups promote via pop/reinsert on the insertion-ordered
    # dict): dedup/decode keys embed content-dependent reset-step lists,
    # and FIFO or clear-the-map policies would let that churn wipe the
    # hot plain-schema plans every traffic class shares.
    MAX_SCHEMAS = 64

    def __init__(self):
        self._lock = threading.Lock()
        self._encode: dict[tuple, _EncodePlan] = {}
        self._dedup: dict[tuple, _EncodePlan] = {}
        self._decode: dict[bytes, _DecodePlan] = {}
        # dedup_plan_* is kept SEPARATE from encode_*: dedup plans are
        # keyed by (schema, reset steps) — content, not schema — so
        # reset-bearing traffic legitimately misses them per blob, and
        # folding that into the schema-cache hit rate would read as a
        # broken cache to an operator tuning DRL_CODEC_CACHE.
        self.stats = {"encode_hits": 0, "encode_misses": 0,
                      "decode_hits": 0, "decode_misses": 0,
                      "dedup_plan_hits": 0, "dedup_plan_misses": 0,
                      "dedup_blobs": 0, "dedup_bytes_saved": 0}

    def lookup_encode(self, key, dedup_key=None):
        with self._lock:
            cache = self._dedup if dedup_key is not None else self._encode
            k = dedup_key if dedup_key is not None else key
            plan = cache.get(k)
            if plan is not None:
                cache.pop(k)  # promote: eviction below is oldest-first,
                cache[k] = plan  # and hot plans must outlive churny ones
            kind = "dedup_plan" if dedup_key is not None else "encode"
            self.stats[f"{kind}_hits" if plan is not None
                       else f"{kind}_misses"] += 1
            return plan

    def store_encode(self, key, plan, dedup_key=None) -> None:
        with self._lock:
            cache = self._dedup if dedup_key is not None else self._encode
            if len(cache) >= self.MAX_SCHEMAS:
                cache.pop(next(iter(cache)))  # least recently used
            cache[dedup_key if dedup_key is not None else key] = plan

    def lookup_decode(self, header: bytes):
        with self._lock:
            plan = self._decode.get(header)
            if plan is not None:
                self._decode.pop(header)  # promote (see lookup_encode):
                self._decode[header] = plan  # dedup headers with reset-step
                # lists are per-blob unique and would otherwise FIFO-evict
                # the hot plain-schema plans they can never replace
            self.stats["decode_hits" if plan is not None else "decode_misses"] += 1
            return plan

    def store_decode(self, header: bytes, plan: _DecodePlan) -> None:
        with self._lock:
            if len(self._decode) >= self.MAX_SCHEMAS:
                self._decode.pop(next(iter(self._decode)))  # least recently used
            self._decode[header] = plan

    def bump_dedup(self, bytes_saved: int) -> None:
        with self._lock:
            self.stats["dedup_blobs"] += 1
            self.stats["dedup_bytes_saved"] += bytes_saved

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self.stats)

    def stat(self, key: str) -> int:
        """One counter under the lock (telemetry counter providers poll
        this from the flush thread)."""
        with self._lock:
            return self.stats[key]

    def clear(self) -> None:
        with self._lock:
            self._encode.clear()
            self._dedup.clear()
            self._decode.clear()
            for k in self.stats:
                self.stats[k] = 0


_CACHES = _CodecCaches()


def cache_stats() -> dict:
    """Copy of the cache/dedup counters (telemetry providers, tests)."""
    return _CACHES.snapshot()


def cache_stat(key: str) -> int:
    return _CACHES.stat(key)


def clear_caches() -> None:
    """Drop all cached plans and zero the counters (tests, benchmarks)."""
    _CACHES.clear()


# -- feature gates ------------------------------------------------------------

_VERDICT_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "benchmarks", "codec_verdict.json")

_flag_lock = threading.Lock()
_flags: dict[str, bool | None] = {"cache": None, "dedup": None}


def _verdict_flag(key: str) -> bool:
    try:
        with open(_VERDICT_PATH) as f:
            return bool(json.load(f).get(key, False))
    except (OSError, ValueError):
        return False


def _resolve_flag(name: str, env_key: str, verdict_key: str) -> bool:
    with _flag_lock:
        cached = _flags[name]
    if cached is not None:
        return cached
    env = os.environ.get(env_key, "").strip().lower()
    if env in ("1", "true", "yes", "on"):
        value = True
    elif env in ("0", "false", "no", "off"):
        value = False
    else:
        value = _verdict_flag(verdict_key)
    with _flag_lock:
        _flags[name] = value
    return value


def cache_enabled() -> bool:
    """DRL_CODEC_CACHE=1 forces the schema cache on, =0 off; unset defers
    to the committed `benchmarks/codec_verdict.json` adjudication
    (`cache_auto_enable`) — the repo's no-un-adjudicated-fast-path rule.
    Resolved once per process; `refresh_flags()` re-reads (tests/bench)."""
    return _resolve_flag("cache", "DRL_CODEC_CACHE", "cache_auto_enable")


def obs_dedup_enabled() -> bool:
    """DRL_OBS_DEDUP=1 forces frame-stack dedup on the WIRE paths, =0
    off; unset defers to the committed verdict (`dedup_auto_enable`).
    Wire-only: in-process queues never see packed blobs."""
    return _resolve_flag("dedup", "DRL_OBS_DEDUP", "dedup_auto_enable")


def refresh_flags() -> None:
    """Re-resolve the env/verdict gates (after monkeypatching env)."""
    with _flag_lock:
        _flags["cache"] = None
        _flags["dedup"] = None


# -- frame-stack dedup plumbing ----------------------------------------------


def _segments(T: int, full: tuple[int, ...]):
    """Segment starts = step 0 + each full (discontinuity) step; yields
    (t0, t1) half-open step ranges, each stored as stack(t0)+deltas."""
    starts = [0, *full, T]
    for i in range(len(starts) - 1):
        yield starts[i], starts[i + 1]


def _packed_nbytes(shape: tuple[int, ...], itemsize: int,
                   full: tuple[int, ...]) -> int:
    T, H, W, S = shape
    n_full = 1 + len(full)  # step 0 + each discontinuity
    n_delta = T - n_full
    return itemsize * H * W * (n_full * S + n_delta)


def _shifted_same(arr: np.ndarray) -> np.ndarray:
    """Per-step `[T-1]` bool: did the stack shift exactly one plane
    (arr[t,:,:,:-1] == arr[t-1,:,:,1:])? For the dominant S=4 uint8 case
    on little-endian hosts the S axis collapses into one uint32 word and
    the shifted compare becomes mask/shift word ops — ~13x cheaper than
    the elementwise strided compare, which stays as the general path."""
    T = arr.shape[0]
    if arr.shape[3] == 4 and sys.byteorder == "little":
        # The word decomposition below assumes byte 0 is the low byte;
        # on a big-endian host the masks would test the REVERSED shift
        # and silently mis-pack, so such hosts take the general path.
        words = arr.view(np.uint32).reshape(T, -1)
        # word = p0 | p1<<8 | p2<<16 | p3<<24 (planes oldest-first), so
        # "planes 0..2 of t == planes 1..3 of t-1" is a mask/shift match.
        return ((words[1:] & np.uint32(0x00FFFFFF))
                == (words[:-1] >> np.uint32(8))).all(axis=1)
    same = np.equal(arr[1:, :, :, :-1], arr[:-1, :, :, 1:])
    return same.reshape(T - 1, -1).all(axis=1)


def _dedup_plan_for(leaves: list[np.ndarray]) -> tuple[tuple, int]:
    """-> (((leaf_idx, full_steps), ...), bytes_saved) for leaves worth
    packing. A step t >= 1 is a delta step when the leaf's planes shifted
    exactly one slot (arr[t,:,:,:-1] == arr[t-1,:,:,1:]) — newest-last
    stacking, `envs/atari.py`. Content-dependent, so computed per call;
    only the header build is cacheable."""
    packable = []
    saved_total = 0
    for i, arr in enumerate(leaves):
        if (arr.ndim != 4 or arr.dtype != np.uint8 or arr.shape[0] < 2
                or not 2 <= arr.shape[3] <= 8 or arr.nbytes < _DEDUP_MIN_BYTES):
            continue
        same = _shifted_same(arr)
        full = tuple(int(t) for t in np.flatnonzero(~same) + 1)
        saved = arr.nbytes - _packed_nbytes(arr.shape, arr.itemsize, full)
        if saved * 4 < arr.nbytes:  # < 25% saved: not worth the repack
            continue
        packable.append((i, full))
        saved_total += saved
    return tuple(packable), saved_total


def _write_packed_leaf(view: memoryview, start: int, arr: np.ndarray,
                       full: tuple[int, ...]) -> None:
    """Store stack(t0) + one new plane per delta step, per segment.
    `arr[t0]` is a contiguous slice of the C-order leaf (one memcpy);
    the delta planes of a segment are gathered in ONE strided copy."""
    T, H, W, S = arr.shape
    stack_nb = H * W * S * arr.itemsize
    plane_nb = H * W * arr.itemsize
    pos = start
    for t0, t1 in _segments(T, full):
        view[pos:pos + stack_nb] = memoryview(arr[t0].reshape(-1)).cast("B")
        pos += stack_nb
        if t1 - t0 > 1:
            deltas = np.ascontiguousarray(arr[t0 + 1:t1, :, :, S - 1])
            nb = (t1 - t0 - 1) * plane_nb
            view[pos:pos + nb] = memoryview(deltas.reshape(-1)).cast("B")
            pos += nb


def _read_packed_leaf(view: memoryview, start: int, dtype: np.dtype,
                      shape: tuple[int, ...], full: tuple[int, ...]) -> np.ndarray:
    """Reconstruct the full [T, H, W, S] leaf bit-identically. Per
    segment: the plane timeline is stack(t0)'s S planes followed by the
    stored deltas, and out[t,:,:,j] == planes[(t-t0)+j] — re-interleaved
    by one np.stack over S shifted timeline views straight into the
    output slice (measured ~3.5x faster than copying a sliding-window
    view, whose scattered 1-byte inner axis defeats the iterator)."""
    T, H, W, S = shape
    out = np.empty(shape, dtype)
    stack_n = H * W * S
    plane_n = H * W
    pos = start
    for t0, t1 in _segments(T, full):
        n_steps = t1 - t0
        n_planes = S + (n_steps - 1)
        planes = np.empty((n_planes, H, W), dtype)
        stack = np.frombuffer(view[pos:pos + stack_n * dtype.itemsize],
                              dtype=dtype).reshape(H, W, S)
        planes[:S] = np.moveaxis(stack, -1, 0)
        pos += stack_n * dtype.itemsize
        if n_steps > 1:
            nb = (n_steps - 1) * plane_n * dtype.itemsize
            planes[S:] = np.frombuffer(view[pos:pos + nb],
                                       dtype=dtype).reshape(n_steps - 1, H, W)
            pos += nb
        np.stack([planes[j:j + n_steps] for j in range(S)], axis=-1,
                 out=out[t0:t1])  # channel j of step t is plane (t-t0)+j
    return out


# -- encode -------------------------------------------------------------------


def _build_plan(leaves: list[np.ndarray], skel: Any,
                packable: tuple = ()) -> _EncodePlan:
    """Slow path: compute metas + header json for these leaves (packed
    per `packable`), freeze the reusable parts."""
    pack_map = dict(packable)
    metas = []
    gaps = []
    offset = 0
    for i, arr in enumerate(leaves):
        aligned = _align(offset)
        if aligned > offset:
            gaps.append((offset, aligned))  # payload-relative; fixed up below
        offset = aligned
        meta = {"dtype": arr.dtype.str, "shape": list(arr.shape),
                "offset": offset}
        if i in pack_map:
            meta["pack"] = _PACK_FSTACK
            meta["full"] = list(pack_map[i])
            offset += _packed_nbytes(arr.shape, arr.itemsize, pack_map[i])
        else:
            offset += arr.nbytes
        metas.append(meta)
    header = json.dumps({"skel": skel, "arrays": metas}).encode()
    payload_start = _align(8 + len(header))
    gaps = [(8 + len(header), payload_start)] + [
        (payload_start + a, payload_start + b) for a, b in gaps]
    return _EncodePlan(header, payload_start,
                       tuple(m["offset"] for m in metas), payload_start + offset,
                       tuple((a, b) for a, b in gaps if b > a))


def _blob_from_plan(plan: _EncodePlan, leaves: list[np.ndarray],
                    packable: tuple = ()) -> np.ndarray:
    header, payload_start, offsets, total, gaps = plan
    # np.empty, not bytearray: a bytearray memsets its whole length, and
    # at trajectory sizes that zero-fill was ~half the warm-encode cost.
    # Only the alignment gaps are zeroed (determinism: cache-hit blobs
    # stay byte-identical to cold encodes), every other byte is written.
    buf = np.empty(total, np.uint8)
    view = memoryview(buf)
    view[0:4] = _MAGIC.to_bytes(4, "little")
    view[4:8] = len(header).to_bytes(4, "little")
    view[8:8 + len(header)] = header
    for a, b in gaps:
        buf[a:b] = 0
    pack_map = dict(packable)
    for i, arr in enumerate(leaves):
        start = payload_start + offsets[i]
        if i in pack_map:
            _write_packed_leaf(view, start, arr, pack_map[i])
        else:
            view[start:start + arr.nbytes] = memoryview(arr.reshape(-1)).cast("B")
    return buf


def encode(tree: Any, dedup: bool = False, cache: bool | None = None) -> np.ndarray:
    """Pack a pytree of numpy arrays into one contiguous blob.

    Returns a uint8 ndarray (bytes-like everywhere it's consumed) and
    writes each array exactly once via buffer assignment — the hot path
    moves every trajectory and every weight snapshot, so no intermediate
    `tobytes()` copies and no final `bytes()` copy.

    `dedup=True` additionally packs frame-stacked observation leaves
    (see the module docstring); decode reconstructs bit-identically, and
    when no leaf qualifies the blob is byte-identical to a plain encode.
    Schema-cached when `cache_enabled()`: a warm encode skips the
    `_flatten` walk and the json header build entirely. `cache`
    overrides that gate per call (cache-hit blobs are byte-identical to
    cold encodes, so overriding changes cost, never bytes): the weight
    plane forces it on — its per-version publish encode has a stable
    schema and is not what the committed trajectory-path verdict
    adjudicated.
    """
    if not (cache_enabled() if cache is None else cache):
        # Pre-cache behavior, kept as the adjudication baseline and the
        # DRL_CODEC_CACHE=0 escape hatch.
        pairs: list[tuple[str, np.ndarray]] = []
        skel = _flatten(tree, "$", pairs)
        leaves = [arr for _, arr in pairs]
        packable, saved = _dedup_plan_for(leaves) if dedup else ((), 0)
        if packable:
            _note_dedup(saved)
        return _blob_from_plan(_build_plan(leaves, skel, packable),
                               leaves, packable)
    leaves = []
    key = _walk(tree, leaves)
    packable, saved = _dedup_plan_for(leaves) if dedup else ((), 0)
    dedup_key = (key, packable) if packable else None
    plan = _CACHES.lookup_encode(key, dedup_key)
    if plan is None:
        pairs: list[tuple[str, np.ndarray]] = []
        skel = _flatten(tree, "$", pairs)
        plan = _build_plan(leaves, skel, packable)
        _CACHES.store_encode(key, plan, dedup_key)
    if packable:
        _note_dedup(saved)
    return _blob_from_plan(plan, leaves, packable)


def _note_dedup(saved: int) -> None:
    # Telemetry rides the counter PROVIDERS run_role registers over
    # cache_stats() — a direct _OBS.count here would emit the same
    # cumulative series twice per flush (and the two would diverge after
    # a clear_caches()).
    _CACHES.bump_dedup(saved)


# -- stamp extension ----------------------------------------------------------


def stamp_frame(stamp: dict) -> bytes:
    """Serialize a priority-summary dict into the extension frame bytes
    (see `_EXT_MAGIC`). The frame is sent as a separate wire part in
    front of the blob (`runtime/transport.py` payload-parts path) or
    concatenated by `stamp_blob` where the consumer needs one buffer."""
    body = json.dumps(stamp, separators=(",", ":")).encode()
    return (_EXT_MAGIC.to_bytes(4, "little")
            + _EXT_VERSION.to_bytes(4, "little")
            + len(body).to_bytes(4, "little") + body)


def stamp_blob(blob, stamp: dict) -> np.ndarray:
    """Prepend a stamp extension frame to a codec blob -> one contiguous
    uint8 buffer (the shm ring path moves single buffers)."""
    frame = stamp_frame(stamp)
    view = memoryview(blob).cast("B")
    out = np.empty(len(frame) + len(view), np.uint8)
    mv = memoryview(out)
    mv[:len(frame)] = frame
    mv[len(frame):] = view
    return out


def split_stamp(buf) -> tuple[dict | None, "memoryview"]:
    """-> (stamp | None, inner blob view).

    Unstamped buffers return `(None, view)` untouched. A stamped buffer
    with the CURRENT extension version returns its parsed summary dict;
    an UNKNOWN (greater) version returns `(None, inner)` — the frame is
    self-delimiting, so old readers skip what they cannot interpret and
    treat the blob as plain (rolling-upgrade contract, pinned by
    tests/test_admission.py). Only true corruption raises: an extension
    frame whose declared length overruns the buffer, or whose json does
    not parse — those are poison, not version skew."""
    view = memoryview(buf).cast("B")
    if len(view) < _EXT_HDR or int.from_bytes(view[0:4], "little") != _EXT_MAGIC:
        return None, view
    version = int.from_bytes(view[4:8], "little")
    ext_len = int.from_bytes(view[8:12], "little")
    end = _EXT_HDR + ext_len
    if end > len(view):
        raise ValueError("corrupt stamp extension: length overruns buffer")
    inner = view[end:]
    if version != _EXT_VERSION:
        return None, inner  # future stamp: skip, decode inner as plain
    try:
        stamp = json.loads(bytes(view[_EXT_HDR:end]))
    except ValueError as e:
        raise ValueError(f"corrupt stamp extension: {e}") from e
    if not isinstance(stamp, dict):
        raise ValueError("corrupt stamp extension: summary not a dict")
    return stamp, inner


def _skip_ext(view: memoryview) -> memoryview:
    """Drop a leading stamp extension frame, any version (decode paths
    are stamp-transparent: the summary is ingest metadata, the tree is
    the inner blob). Malformed frames pass through untouched and fail
    at the blob magic check, exactly like any other junk bytes."""
    if len(view) >= _EXT_HDR and int.from_bytes(view[0:4], "little") == _EXT_MAGIC:
        end = _EXT_HDR + int.from_bytes(view[8:12], "little")
        if end <= len(view):
            return view[end:]
    return view


def strip_stamp(blob):
    """Drop a leading stamp extension frame (any version), returning the
    inner plain blob; an unstamped buffer is returned AS-IS (same
    object, no copy). Blob-native queues route through this — their
    batch-gather assumes the blob starts at the codec magic."""
    view = memoryview(blob).cast("B")
    inner = _skip_ext(view)
    return blob if len(inner) == len(view) else inner


def is_stamped(buf) -> bool:
    """True when this buffer carries a stamp extension frame (any
    version — use `split_stamp` to learn whether it is readable)."""
    view = memoryview(buf).cast("B")
    return (len(view) >= _EXT_HDR
            and int.from_bytes(view[0:4], "little") == _EXT_MAGIC)


# -- decode -------------------------------------------------------------------


def parse_layout(blob: bytes | memoryview) -> tuple[Any, list[dict], int]:
    """Header of a blob -> (skeleton, array metas, payload_start).

    The header fully determines the layout, so a consumer holding many
    same-schema blobs (the native queue's batch pop) can parse ONE
    header and gather every field across blobs — see
    `data/native.py` `NativeTrajectoryQueue.get_batch`.

    The metas are FRESH dicts with FRESH nested lists per call
    (pre-cache behavior: json.loads built new objects every time), so
    callers may annotate/mutate them. The skeleton is the cached plan's
    shared object — treat it as read-only.
    """
    plan = _layout_plan(memoryview(blob))
    metas = [dict(m, shape=list(m["shape"]),
                  **({"full": list(m["full"])} if "full" in m else {}))
             for m in plan.metas]
    return plan.skel, metas, plan.payload_start


def _layout_plan(view: memoryview, cache: bool | None = None) -> _DecodePlan:
    view = _skip_ext(view)
    if int.from_bytes(view[0:4], "little") != _MAGIC:
        raise ValueError("bad magic: not a codec blob")
    header_len = int.from_bytes(view[4:8], "little")
    header = bytes(view[8:8 + header_len])
    use_cache = cache_enabled() if cache is None else cache
    if use_cache:
        plan = _CACHES.lookup_decode(header)
        if plan is not None:
            return plan
    parsed = json.loads(header)
    skel, metas = parsed["skel"], parsed["arrays"]
    payload_start = _align(8 + header_len)
    leaves = []
    packed = False
    end = 0
    for meta in metas:
        dtype, shape, nbytes = meta_layout(meta)
        full = meta.get("full")
        pack = None
        stored = nbytes
        if meta.get("pack") == _PACK_FSTACK:
            packed = True
            pack = tuple(full or ())
            stored = _packed_nbytes(shape, dtype.itemsize, pack)
        leaves.append((dtype, shape, nbytes, meta["offset"], pack))
        end = max(end, meta["offset"] + stored)
    plan = _DecodePlan(skel, metas, payload_start, tuple(leaves), packed, end)
    if use_cache:
        _CACHES.store_decode(header, plan)
    return plan


def meta_layout(meta: dict) -> tuple[np.dtype, tuple[int, ...], int]:
    """Array meta dict -> (dtype, shape, nbytes): the single
    interpretation of the header's per-array encoding, shared by
    `decode` and the native batch-gather. For a PACKED meta these are
    the logical (reconstructed) values — packed blobs never reach the
    native gather (`fifo.blob_ingest` unpacks first)."""
    dtype = np.dtype(meta["dtype"])
    shape = tuple(meta["shape"])
    nbytes = dtype.itemsize * int(np.prod(shape, dtype=np.int64)) if shape else dtype.itemsize
    return dtype, shape, nbytes


def assemble(skel: Any, arrays: list[np.ndarray]) -> Any:
    """Rebuild the pytree from a skeleton and its (possibly batched)
    leaf arrays, in `parse_layout` order."""
    return _unflatten(skel, arrays)


def flatten_with_paths(tree: Any) -> tuple[Any, list[tuple[str, np.ndarray]]]:
    """Canonical flatten: (skeleton, [(path, array), ...]) in the EXACT
    leaf order every codec blob uses (sorted dict keys, namedtuple
    fields in declaration order). The sharded weight plane
    (parallel/partition.py, runtime/weight_shards.py) keys its shard
    plans off these paths so "leaf i" means the same array to the
    partition pass, the per-shard blobs, and a whole-blob encode —
    the agreement its bit-identity contract rests on."""
    pairs: list[tuple[str, np.ndarray]] = []
    skel = _flatten(tree, "$", pairs)
    return skel, pairs


def is_packed(blob: bytes | memoryview) -> bool:
    """True when any leaf of this blob is dedup-packed."""
    return _layout_plan(memoryview(blob)).packed


def check_blob(blob) -> None:
    """Raise ValueError unless the header parses and the payload extent
    fits — WITHOUT decoding. The stamped sequence ingest stores blobs
    for deferred decode (`data/replay_service.LazyBlob`), so poison must
    fail here on the ingest thread, not at sample time on the learner.

    cache=True for the same reason ingest's `decode` forces it: every
    caller is an ingest/promote path that sees one stable schema per
    run, and an uncached header parse costs ~3x the whole fast-accept
    it is guarding."""
    view = _skip_ext(memoryview(blob).cast("B"))
    plan = _layout_plan(view, cache=True)
    if plan.payload_start + plan.payload_nbytes > len(view):
        raise ValueError("truncated codec blob payload")


def unpack_blob(blob):
    """Dedup-packed blob -> plain-layout blob; a plain blob is returned
    AS-IS (same object, no copy). `fifo.blob_ingest` routes every wire
    blob through this before a blob-native queue, so the native
    batch-gather only ever sees the plain layout.

    The common (plain) case must cost what the old identity `prepare`
    cost: a `"pack"` substring scan over the header bytes decides
    without parsing json. A false positive (a schema whose key contains
    "pack") merely takes the exact parse below; malformed bytes pass
    through untouched, exactly like the pre-dedup ingest, and fail at
    decode time."""
    outer = memoryview(blob).cast("B")
    view = _skip_ext(outer)
    if len(view) < 8 or int.from_bytes(view[0:4], "little") != _MAGIC:
        return blob
    header_len = int.from_bytes(view[4:8], "little")
    if b'"pack"' not in bytes(view[8:8 + header_len]):
        return blob
    plan = _layout_plan(view)
    if not plan.packed:
        return blob
    plain = encode(decode(view))
    if len(view) != len(outer):  # stamped: keep the ext frame intact in
        #   front of the repacked inner blob (the stamp is ingest
        #   metadata about the SAME logical trajectory)
        return _reframe(outer, view, plain)
    return plain


def _reframe(outer: memoryview, inner: memoryview, plain) -> np.ndarray:
    """Re-attach `outer`'s leading extension frame bytes to a repacked
    inner blob (frame bytes copied verbatim — version-agnostic)."""
    frame_len = len(outer) - len(inner)
    pv = memoryview(plain).cast("B")
    out = np.empty(frame_len + len(pv), np.uint8)
    mv = memoryview(out)
    mv[:frame_len] = outer[:frame_len]
    mv[frame_len:] = pv
    return out


def decode(blob: bytes | memoryview, copy: bool = False,
           cache: bool | None = None) -> Any:
    """Unpack a blob; arrays view the blob unless copy=True (packed
    leaves are always materialized as owned arrays).

    copy=True allocates ONE owned payload buffer and copies the blob's
    payload region into it in a single memcpy — not one slice+copy per
    leaf, which double-touched multi-MB observation leaves. `cache`
    overrides the layout-cache gate per call (see `encode`): the weight
    plane and the replay shards' decode-at-ingest
    (data/replay_service.py) both force it on — each sees ONE stable
    schema per run, so the layout cache is a pure win there regardless
    of the committed trajectory-path verdict.
    """
    view = _skip_ext(memoryview(blob).cast("B"))
    plan = _layout_plan(view, cache)
    payload_start = plan.payload_start
    src = view
    base_off = payload_start
    if copy and plan.payload_nbytes:
        owned = np.empty(plan.payload_nbytes, np.uint8)
        memoryview(owned)[:] = view[payload_start:payload_start + plan.payload_nbytes]
        src = memoryview(owned)
        base_off = 0
    arrays = []
    for dtype, shape, nbytes, offset, pack in plan.leaves:
        start = base_off + offset
        if pack is not None:
            arrays.append(_read_packed_leaf(src, start, dtype, shape, pack))
        else:
            arr = np.frombuffer(src[start:start + nbytes], dtype=dtype).reshape(shape)
            arrays.append(arr)
    return _unflatten(plan.skel, arrays)
