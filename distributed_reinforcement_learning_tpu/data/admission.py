"""Actor-side prioritization + priority-mass admission (sample-at-source).

At production actor counts the learner's ingest CPU is spent scoring and
decoding transitions it will mostly never sample (PAPER topology: one
learner, hundreds of actors; in-network experience sampling,
arXiv:2110.13506, moves the sampling decision off the learner box).
This module moves the INITIAL prioritization to the PUT side of the
wire:

- **Actor-side scoring**: the actor computes the exact ingest-time
  scorer the learner would run (`data/replay_service.td_proxy_scorer`,
  selected by the same `DRL_REPLAY_SCORER` knob) and stamps the
  per-transition priorities into a versioned extension frame in front
  of the codec blob (`data/codec.stamp_frame`). Stamped values are in
  the scorer's ERROR domain and round-trip json bit-exactly (float64
  repr), so a stamped ingest is bit-equal to a learner-scored one —
  pinned by tests/test_admission.py. The 'max' scorer cannot be
  stamped (its fill value is learner-side `_max_error` state), so
  stamping silently stays off under it.

- **Priority-mass admission**: under learner backpressure (an ingest
  duty-cycle pressure signal fed back on PUT replies,
  `runtime/transport.py`), low-priority unrolls are thinned at the
  actor. High-priority unrolls (unroll mean transformed priority >= the
  running fleet mean) always ride in full. Below the mean, each
  transition keeps a Bernoulli survival probability
  `q_i = clip(f * p_i / mu, floor, 1)` (Horvitz-Thompson: kept
  transitions' priorities are inflated by `1/q_i` in the TRANSFORMED
  domain, so expected priority mass — and therefore the proportional
  sampling distribution — is unchanged; chi-square pinned). `q_i == 1`
  transitions pass through bitwise untouched. An unroll whose every
  transition loses its coin flip is dropped whole and its transformed
  priority mass folded into a ledger drained onto the NEXT stamp
  (`"folded"`), so no priority mass is ever silently lost — the
  zero-lost-mass conservation pin.

Gates follow the repo's adjudication rule: `DRL_ACTOR_PRIORITY` /
`DRL_ADMISSION` force on/off; unset defers to the committed
`benchmarks/admission_verdict.json` (bench.py admission_compare).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any

import numpy as np

from distributed_reinforcement_learning_tpu.data.replay import PrioritizedReplay
from distributed_reinforcement_learning_tpu.data.replay_service import make_scorer
from distributed_reinforcement_learning_tpu.observability import TELEMETRY as _OBS

# Priority transform constants — THE backend transform
# (data/replay.py): p = (|e| + EPS) ** ALPHA. Admission corrections are
# applied in the transformed domain and mapped back through the exact
# inverse so the learner's own transform reproduces them.
EPS = PrioritizedReplay.EPS
ALPHA = PrioritizedReplay.ALPHA

# Mirror of runtime/replay_shard._ALGO_MODE (layering: data/ must not
# import runtime/). tests/test_admission.py pins the two maps equal.
ALGO_MODES = {"apex": "transition", "r2d2": "sequence", "xformer": "sequence"}

_VERDICT_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "benchmarks", "admission_verdict.json")

_flag_lock = threading.Lock()
_flags: dict[str, bool | None] = {"priority": None, "admission": None}


def _verdict_flag(key: str) -> bool:
    try:
        with open(_VERDICT_PATH) as f:
            return bool(json.load(f).get(key, False))
    except (OSError, ValueError):
        return False


def _resolve_flag(name: str, env_key: str, verdict_key: str) -> bool:
    with _flag_lock:
        cached = _flags[name]
    if cached is not None:
        return cached
    env = os.environ.get(env_key, "").strip().lower()
    if env in ("1", "true", "yes", "on"):
        value = True
    elif env in ("0", "false", "no", "off"):
        value = False
    else:
        value = _verdict_flag(verdict_key)
    with _flag_lock:
        _flags[name] = value
    return value


def actor_priority_enabled() -> bool:
    """DRL_ACTOR_PRIORITY=1 forces actor-side scoring + stamping on, =0
    off; unset defers to the committed `benchmarks/admission_verdict.json`
    (`actor_priority_auto_enable`) — the repo's 1.2x adjudication rule.
    Resolved once per process; `refresh_flags()` re-reads."""
    return _resolve_flag("priority", "DRL_ACTOR_PRIORITY",
                         "actor_priority_auto_enable")


def admission_enabled() -> bool:
    """DRL_ADMISSION=1 forces priority-mass admission (backpressure
    thinning) on, =0 off; unset defers to the committed verdict
    (`admission_auto_enable`). Admission rides the stamp, so it is
    inert unless `actor_priority_enabled()` too."""
    return _resolve_flag("admission", "DRL_ADMISSION", "admission_auto_enable")


def refresh_flags() -> None:
    """Re-resolve the env/verdict gates (after monkeypatching env)."""
    with _flag_lock:
        _flags["priority"] = None
        _flags["admission"] = None


def _env_float(key: str, default: float) -> float:
    try:
        raw = os.environ.get(key, "").strip()
        return float(raw) if raw else default
    except ValueError:
        return default


def transform(errors: np.ndarray) -> np.ndarray:
    """Error domain -> transformed priority domain (the backend's own
    monotone map)."""
    return (np.abs(np.asarray(errors, np.float64)) + EPS) ** ALPHA


def inverse_transform(priorities: np.ndarray) -> np.ndarray:
    """Transformed domain -> the non-negative error whose transform is
    exactly `priorities` (used to stamp Horvitz-Thompson-corrected
    priorities in the error domain the stamp carries)."""
    return np.asarray(priorities, np.float64) ** (1.0 / ALPHA) - EPS


class DutyMeter:
    """Windowed busy-fraction meter: the learner's ingest pressure.

    The sharded ingest facade never blocks (that is its point), so
    queue depth is useless as a pressure signal there — what saturates
    is the ingest thread's CPU. Each `note(busy_s)` adds one ingest
    call's busy time; `value()` is an EWMA of busy/wall over ~half-second
    windows, 0.0 (idle) to 1.0 (the thread never sleeps).
    """

    # Concurrency map (tools/drlint lock-discipline): noted by transport
    # serve / drainer threads, read by reply builders on the same
    # threads and telemetry pollers.
    _GUARDED_BY = {
        "_busy": "_lock",
        "_t0": "_lock",
        "_ewma": "_lock",
        "_total": "_lock",
    }

    WINDOW_S = 0.5
    DECAY = 0.5  # per-window EWMA retention

    def __init__(self):
        self._lock = threading.Lock()
        self._busy = 0.0
        self._t0 = time.monotonic()
        self._ewma = 0.0
        self._total = 0.0

    def note(self, busy_s: float) -> None:
        now = time.monotonic()
        with self._lock:
            self._total += max(0.0, busy_s)
            self._busy += max(0.0, busy_s)
            window = now - self._t0
            if window >= self.WINDOW_S:
                duty = min(1.0, self._busy / window)
                self._ewma = self.DECAY * self._ewma + (1 - self.DECAY) * duty
                self._busy = 0.0
                self._t0 = now

    def value(self) -> float:
        now = time.monotonic()
        with self._lock:
            window = now - self._t0
            if window >= self.WINDOW_S:
                # Fold the straggling partial window so an idle meter
                # decays toward 0 even with no note() traffic.
                duty = min(1.0, self._busy / window)
                self._ewma = self.DECAY * self._ewma + (1 - self.DECAY) * duty
                self._busy = 0.0
                self._t0 = now
            return self._ewma

    def total(self) -> float:
        """Cumulative busy seconds since construction (bench.py
        admission_compare's ingest-CPU numerator)."""
        with self._lock:
            return self._total


class Decision:
    """One `admit()` outcome. `send` False means the unroll was dropped
    whole (mass folded into the ledger); otherwise `stamp` is the
    summary dict to frame in front of the blob and `tree` the thinned
    pytree to encode — None meaning "send the caller's original tree
    unchanged" (the full-admission fast path avoids re-touching it).
    `orig_t` is the pre-thinning transition count (`note_wire`'s
    bytes-saved estimate)."""

    __slots__ = ("send", "tree", "stamp", "orig_t")

    def __init__(self, send: bool, tree: Any = None, stamp: dict | None = None,
                 orig_t: int = 0):
        self.send = send
        self.tree = tree
        self.stamp = stamp
        self.orig_t = orig_t


class AdmissionController:
    """Per-queue actor-side scorer + admission ladder.

    One controller per PUT endpoint (`TransportClient` / `RingQueue`),
    attached by the actor runner via `configure(queue, algo)`. `admit`
    runs on the actor's publish thread; `observe_pressure` on whatever
    thread parses PUT replies (the same publish thread for the TCP
    client); stats/telemetry polls come from anywhere.
    """

    # Concurrency map (tools/drlint lock-discipline): every mutable
    # word — the pressure EWMA, the running unroll-mean, the folded-mass
    # ledger, the RNG, and the stats counters — lives under `_lock`.
    _GUARDED_BY = {
        "_pressure": "_lock",
        "_mu": "_lock",
        "_mu_n": "_lock",
        "_folded": "_lock",
        "_rng": "_lock",
        "_blob_ewma": "_lock",
        "stats": "_lock",
    }

    MU_DECAY = 0.98       # running fleet-mean priority EWMA retention
    PRESSURE_DECAY = 0.7  # per-reply pressure EWMA retention

    def __init__(self, mode: str, scorer_name: str = "td_proxy",
                 seed: int | None = None):
        if mode not in ("transition", "sequence"):
            raise ValueError(f"unknown admission mode {mode!r}")
        scorer = make_scorer(scorer_name)
        if scorer is None:
            raise ValueError(
                f"scorer {scorer_name!r} has no actor-computable value "
                "(max-priority fill is learner-side state)")
        self.mode = mode
        self.scorer_name = scorer_name
        self._scorer = scorer
        self.lo = _env_float("DRL_ADMISSION_LO", 0.5)
        self.hi = max(_env_float("DRL_ADMISSION_HI", 0.9), self.lo + 1e-6)
        self.floor = min(max(_env_float("DRL_ADMISSION_FLOOR", 0.1), 1e-3), 1.0)
        self._lock = threading.Lock()
        self._pressure = 0.0
        self._mu = 0.0
        self._mu_n = 0
        self._folded = 0.0
        self._rng = np.random.default_rng(seed)
        self._blob_ewma = 0.0  # full-unroll wire bytes (drop estimates)
        self.stats = {"stamped_puts": 0, "full_puts": 0, "subsampled_puts": 0,
                      "dropped_unrolls": 0, "sent_transitions": 0,
                      "subsample_dropped_transitions": 0,
                      "dropped_mass": 0.0, "folded_mass_sent": 0.0,
                      "wire_bytes_sent": 0, "wire_bytes_saved": 0}

    # -- pressure feedback (PUT-reply thread) ------------------------------

    def observe_pressure(self, permille: int) -> None:
        """Fold one learner pressure sample (0..1000, from a PUT reply)
        into the EWMA."""
        p = min(max(permille / 1000.0, 0.0), 1.0)
        with self._lock:
            self._pressure = (self.PRESSURE_DECAY * self._pressure
                              + (1 - self.PRESSURE_DECAY) * p)
            snap = self._pressure
        if _OBS.enabled:
            _OBS.gauge("admission/pressure", snap)

    def pressure(self) -> float:
        """Effective pressure 0..1: `DRL_ADMISSION_PRESSURE` override
        (tests/bench drive the ladder without a loaded learner) or the
        reply-fed EWMA."""
        override = _env_float("DRL_ADMISSION_PRESSURE", -1.0)
        if override >= 0.0:
            return min(override, 1.0)
        with self._lock:
            return self._pressure

    # -- the ladder (actor publish thread) ---------------------------------

    def admit(self, tree: Any) -> Decision:
        """Score one unroll, apply the admission ladder, and return what
        to send. See the module docstring for the ladder semantics."""
        per_transition = self.mode == "transition"
        errors = np.asarray(self._scorer(tree, per_transition), np.float64)
        pri = transform(errors)
        mean_p = float(pri.mean())
        with self._lock:
            # Running mean of unroll mean priorities — the "fleet mean"
            # this actor has observed; seeds from the first unroll.
            if self._mu_n == 0:
                self._mu = mean_p
            else:
                self._mu = self.MU_DECAY * self._mu + (1 - self.MU_DECAY) * mean_p
            self._mu_n += 1
            mu = self._mu
        p = self.pressure() if admission_enabled() else 0.0
        if p < self.lo or mean_p >= mu or mu <= 0.0:
            return self._full(errors)
        s = min(1.0, (p - self.lo) / (self.hi - self.lo))
        f = 1.0 - s * (1.0 - self.floor)
        q = np.minimum(np.maximum(f * pri / mu, self.floor), 1.0)
        with self._lock:
            coins = self._rng.random(q.shape)
        keep = coins < q
        if not keep.any():
            mass = float(pri.sum())
            with self._lock:
                self._folded += mass
                self.stats["dropped_unrolls"] += 1
                self.stats["dropped_mass"] += mass
                # A whole-dropped unroll never reaches encode: estimate
                # its wire cost from the running full-unroll size.
                saved = int(self._blob_ewma)
                self.stats["wire_bytes_saved"] += saved
            if _OBS.enabled:
                _OBS.count("admission/dropped_unrolls")
                _OBS.count("admission/dropped_mass", mass)
                if saved:
                    _OBS.count("admission/wire_bytes_saved", saved)
            return Decision(False)
        if bool(keep.all()):
            return self._full(errors)
        # Horvitz-Thompson: inflate kept priorities by 1/q in the
        # transformed domain; q==1 entries pass through BITWISE (the
        # inverse transform is exact only in expectation of float
        # rounding, and untouched entries must stay bit-equal).
        kept_q = q[keep]
        corrected = errors[keep].copy()
        adjust = kept_q < 1.0
        if adjust.any():
            corrected[adjust] = inverse_transform(pri[keep][adjust] / kept_q[adjust])
        if per_transition:
            idx = np.flatnonzero(keep)
            import jax

            sent_tree = jax.tree.map(lambda x: np.asarray(x)[idx], tree)
        else:
            sent_tree = tree  # sequence mode: keep is a single coin
        dropped = int(keep.size - keep.sum())
        with self._lock:
            self.stats["subsampled_puts"] += 1
            self.stats["subsample_dropped_transitions"] += dropped
        if _OBS.enabled:
            _OBS.count("admission/subsampled_puts")
            _OBS.count("admission/subsample_dropped_transitions", dropped)
        return self._sent(corrected, sent_tree, int(keep.size))

    def _full(self, errors: np.ndarray) -> Decision:
        with self._lock:
            self.stats["full_puts"] += 1
        return self._sent(errors, None, int(errors.size))

    def _sent(self, errors: np.ndarray, tree: Any, orig_t: int) -> Decision:
        stamp = {"scorer": self.scorer_name, "mode": self.mode,
                 "pri": [float(e) for e in errors], "t": int(errors.size)}
        with self._lock:
            folded, self._folded = self._folded, 0.0
            if folded:
                self.stats["folded_mass_sent"] += folded
            self.stats["stamped_puts"] += 1
            self.stats["sent_transitions"] += int(errors.size)
        if folded:
            stamp["folded"] = folded
        if _OBS.enabled:
            _OBS.count("admission/stamped_puts")
        return Decision(True, tree, stamp, orig_t)

    def note_wire(self, nbytes: int, decision: Decision) -> None:
        """Account one SENT blob's wire bytes (called by the PUT
        endpoint after encode). Payload bytes scale linearly with
        transitions, so a subsampled blob's saving is estimated
        proportionally: est_full = nbytes * orig_t / sent_t."""
        sent_t = max(int(decision.stamp["t"]), 1)
        orig_t = max(int(decision.orig_t), sent_t)
        est_full = nbytes * orig_t / sent_t
        saved = int(est_full) - nbytes
        with self._lock:
            # EWMA of FULL-unroll wire size seeds whole-drop estimates.
            self._blob_ewma = (0.9 * self._blob_ewma + 0.1 * est_full
                               if self._blob_ewma else est_full)
            self.stats["wire_bytes_sent"] += nbytes
            if saved:
                self.stats["wire_bytes_saved"] += saved
        if _OBS.enabled:
            _OBS.count("admission/wire_bytes_sent", nbytes)
            if saved:
                _OBS.count("admission/wire_bytes_saved", saved)

    def pending_folded_mass(self) -> float:
        """Transformed-domain mass dropped but not yet drained onto a
        stamp (conservation accounting: `dropped_mass ==
        folded_mass_sent + pending`)."""
        with self._lock:
            return self._folded

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self.stats)


def maybe_controller(algo: str, seed: int | None = None) -> AdmissionController | None:
    """Controller for an actor runner's PUT endpoint, or None when
    stamping is off: the gate resolves off, the algo has no shard mode,
    or the fleet's `DRL_REPLAY_SCORER` has no actor-computable scorer
    ('max'). The scorer knob is shared with the learner
    (runtime/replay_shard.build_service) so both sides agree by
    construction; the learner still validates each stamp's scorer/mode
    and falls back to scoring on mismatch."""
    if not actor_priority_enabled():
        return None
    mode = ALGO_MODES.get(algo)
    if mode is None:
        return None
    scorer_name = os.environ.get("DRL_REPLAY_SCORER", "max").strip() or "max"
    if make_scorer(scorer_name) is None:
        return None
    return AdmissionController(mode, scorer_name, seed=seed)


def configure(queue: Any, algo: str, seed: int | None = None) -> AdmissionController | None:
    """Attach an admission controller to a PUT endpoint that supports
    one (`set_admission`: TransportClient, RingQueue). In-process queues
    have no wire to save — stamping is skipped there."""
    set_admission = getattr(queue, "set_admission", None)
    if set_admission is None:
        return None
    ctrl = maybe_controller(algo, seed=seed)
    if ctrl is not None:
        set_admission(ctrl)
    return ctrl
