"""Prioritized experience replay: SumTree + Memory.

Re-design of `distributed_queue/buffer_queue.py:256-346`. Same sampling
semantics — priority `(|err| + 0.001) ** 0.6`, stratified sampling over
`total/n` segments, IS weights `(N * p) ** -beta` normalized by the batch
max, beta annealed 0.4 -> 1.0 by 0.001 per sample() call — but the tree
is array-based with *iterative* propagate/retrieve (the reference recurses
per-element, a Python hotspot flagged in SURVEY §2 E7) and supports batch
add/update. One reference bug is deliberately fixed: `train_r2d2.py:159`
updates only a single stale index per train step; `update_batch` here
updates every sampled index.

A C++ backend (cpp/sumtree) plugs in behind the same interface.
"""

from __future__ import annotations

from typing import Any

import numpy as np


class SumTree:
    """Array-backed binary sum tree over `capacity` leaf priorities."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._tree = np.zeros(2 * capacity - 1, np.float64)
        self._data: list[Any] = [None] * capacity
        self._write = 0
        self._count = 0

    def __len__(self) -> int:
        return self._count

    @property
    def total(self) -> float:
        return float(self._tree[0])

    def add(self, priority: float, data: Any) -> int:
        idx = self._write + self.capacity - 1
        self._data[self._write] = data
        self.set_priority(idx, priority)
        self._write = (self._write + 1) % self.capacity
        self._count = min(self._count + 1, self.capacity)
        return idx

    def set_priority(self, idx: int, priority: float) -> None:
        delta = priority - self._tree[idx]
        while True:
            self._tree[idx] += delta
            if idx == 0:
                break
            idx = (idx - 1) // 2

    def get(self, value: float) -> tuple[int, float, Any]:
        """Find the leaf whose cumulative-priority interval contains `value`."""
        idx = 0
        while True:
            left = 2 * idx + 1
            if left >= len(self._tree):
                break
            if value <= self._tree[left]:
                idx = left
            else:
                value -= self._tree[left]
                idx = left + 1
        data_idx = idx - (self.capacity - 1)
        return idx, float(self._tree[idx]), self._data[data_idx]


class PrioritizedReplay:
    """The reference's `Memory` surface: add / sample / update.

    `sample(n)` returns (items, tree_idxs, is_weights) with stratified
    sampling and annealed-beta importance weights
    (`buffer_queue.py:323-342`).
    """

    EPS = 0.001
    ALPHA = 0.6
    BETA_INCREMENT = 0.001

    def __init__(self, capacity: int, beta: float = 0.4):
        self.tree = SumTree(capacity)
        self.beta = beta

    def __len__(self) -> int:
        return len(self.tree)

    def _priority(self, error: float) -> float:
        return (abs(error) + self.EPS) ** self.ALPHA

    def add(self, error: float, sample: Any) -> int:
        return self.tree.add(self._priority(error), sample)

    def add_batch(self, errors: np.ndarray, samples: list[Any]) -> list[int]:
        return [self.tree.add(self._priority(e), s) for e, s in zip(errors, samples)]

    def sample(self, n: int, rng: np.random.RandomState | None = None):
        rng = rng or np.random
        self.beta = min(1.0, self.beta + self.BETA_INCREMENT)
        segment = self.tree.total / n
        idxs = np.empty(n, np.int64)
        priorities = np.empty(n, np.float64)
        items = []
        for i in range(n):
            # Retry guards against float64 rounding in the subtractive
            # descent landing on an unwritten zero-priority leaf while the
            # tree is partially filled.
            for _ in range(4):
                value = rng.uniform(segment * i, segment * (i + 1))
                idx, p, data = self.tree.get(value)
                if data is not None:
                    break
            if data is None:  # final fallback: a uniformly random filled leaf
                leaf = int(rng.randint(0, len(self.tree)))
                idx = leaf + self.tree.capacity - 1
                p = float(self.tree._tree[idx])
                data = self.tree._data[leaf]
            idxs[i] = idx
            priorities[i] = p
            items.append(data)
        probs = priorities / self.tree.total
        weights = np.power(len(self.tree) * probs, -self.beta)
        weights /= weights.max()
        return items, idxs, weights.astype(np.float32)

    def update(self, idx: int, error: float) -> None:
        self.tree.set_priority(int(idx), self._priority(error))

    def update_batch(self, idxs: np.ndarray, errors: np.ndarray) -> None:
        """Re-prioritize every sampled index (fixes `train_r2d2.py:159`)."""
        for idx, err in zip(idxs, errors):
            self.update(int(idx), float(err))


class UniformBuffer:
    """Actor-local uniform-random transition store.

    Parity with `LocalBuffer` (`buffer_queue.py:213-254`): bounded deque,
    uniform sample of `batch_size` transitions.
    """

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._items: list[Any] = []
        self._write = 0
        self._rng = np.random.RandomState(seed)

    def __len__(self) -> int:
        return len(self._items)

    def append(self, item: Any) -> None:
        if len(self._items) < self.capacity:
            self._items.append(item)
        else:
            self._items[self._write] = item
        self._write = (self._write + 1) % self.capacity

    def sample(self, batch_size: int) -> list[Any]:
        idx = self._rng.randint(0, len(self._items), size=batch_size)
        return [self._items[i] for i in idx]
