"""Prioritized experience replay: SumTree + Memory.

Re-design of `distributed_queue/buffer_queue.py:256-346`. Same sampling
semantics — priority `(|err| + 0.001) ** 0.6`, stratified sampling over
`total/n` segments, IS weights `(N * p) ** -beta` normalized by the batch
max, beta annealed 0.4 -> 1.0 by 0.001 per sample() call — but the tree
is array-based with *iterative* propagate/retrieve (the reference recurses
per-element, a Python hotspot flagged in SURVEY §2 E7) and supports batch
add/update. One reference bug is deliberately fixed: `train_r2d2.py:159`
updates only a single stale index per train step; `update_batch` here
updates every sampled index.

A C++ backend (cpp/sumtree) plugs in behind the same interface.
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np


class SumTree:
    """Array-backed binary sum tree over `capacity` leaf priorities."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._tree = np.zeros(2 * capacity - 1, np.float64)
        self._data: list[Any] = [None] * capacity
        self._write = 0
        self._count = 0

    def __len__(self) -> int:
        return self._count

    @property
    def total(self) -> float:
        return float(self._tree[0])

    def add(self, priority: float, data: Any) -> int:
        idx = self._write + self.capacity - 1
        self._data[self._write] = data
        self.set_priority(idx, priority)
        self._write = (self._write + 1) % self.capacity
        self._count = min(self._count + 1, self.capacity)
        return idx

    def set_priority(self, idx: int, priority: float) -> None:
        delta = priority - self._tree[idx]
        while True:
            self._tree[idx] += delta
            if idx == 0:
                break
            idx = (idx - 1) // 2

    def get(self, value: float) -> tuple[int, float, Any]:
        """Find the leaf whose cumulative-priority interval contains `value`."""
        idx = 0
        while True:
            left = 2 * idx + 1
            if left >= len(self._tree):
                break
            if value <= self._tree[left]:
                idx = left
            else:
                value -= self._tree[left]
                idx = left + 1
        data_idx = idx - (self.capacity - 1)
        return idx, float(self._tree[idx]), self._data[data_idx]


class PrioritizedReplay:
    """The reference's `Memory` surface: add / sample / update.

    `sample(n)` returns (items, tree_idxs, is_weights) with stratified
    sampling and annealed-beta importance weights
    (`buffer_queue.py:323-342`).
    """

    EPS = 0.001
    ALPHA = 0.6
    BETA_INCREMENT = 0.001

    def __init__(self, capacity: int, beta: float = 0.4):
        self.tree = SumTree(capacity)
        self.beta = beta

    def __len__(self) -> int:
        return len(self.tree)

    def _priority(self, error: float) -> float:
        return (abs(error) + self.EPS) ** self.ALPHA

    def add(self, error: float, sample: Any) -> int:
        return self.tree.add(self._priority(error), sample)

    def add_batch(self, errors: np.ndarray, samples: list[Any]) -> list[int]:
        return [self.tree.add(self._priority(e), s) for e, s in zip(errors, samples)]

    def sample(self, n: int, rng: np.random.RandomState | None = None):
        rng = rng or np.random
        self.beta = min(1.0, self.beta + self.BETA_INCREMENT)
        segment = self.tree.total / n
        idxs = np.empty(n, np.int64)
        priorities = np.empty(n, np.float64)
        items = []
        for i in range(n):
            # Retry guards against float64 rounding in the subtractive
            # descent landing on an unwritten zero-priority leaf while the
            # tree is partially filled.
            for _ in range(4):
                value = rng.uniform(segment * i, segment * (i + 1))
                idx, p, data = self.tree.get(value)
                if data is not None:
                    break
            if data is None:  # final fallback: a uniformly random filled leaf
                leaf = int(rng.randint(0, len(self.tree)))
                idx = leaf + self.tree.capacity - 1
                p = float(self.tree._tree[idx])
                data = self.tree._data[leaf]
            idxs[i] = idx
            priorities[i] = p
            items.append(data)
        probs = priorities / self.tree.total
        weights = np.power(len(self.tree) * probs, -self.beta)
        weights /= weights.max()
        return items, idxs, weights.astype(np.float32)

    def update(self, idx: int, error: float) -> None:
        self.tree.set_priority(int(idx), self._priority(error))

    def update_batch(self, idxs: np.ndarray, errors: np.ndarray) -> None:
        """Re-prioritize every sampled index (fixes `train_r2d2.py:159`)."""
        for idx, err in zip(idxs, errors):
            self.update(int(idx), float(err))

    def snapshot(self) -> dict:
        """Serializable state: payloads + already-transformed priorities.

        SURVEY §5.4's optional replay snapshot — without it a restarted
        Ape-X/R2D2 learner resumes with an empty Memory while actors keep
        pushing stale-policy re-samples.
        """
        n = len(self.tree)
        cap = self.tree.capacity
        return {
            "priorities": self.tree._tree[cap - 1 : cap - 1 + n].copy(),
            "items": [self.tree._data[i] for i in range(n)],
            "beta": float(self.beta),
        }

    def restore(self, snap: dict) -> None:
        """Rebuild from `snapshot()`. Contents and priorities are exact;
        the ring write cursor restarts at `count % capacity`, so after a
        wrapped buffer the future *eviction order* differs from the
        original — harmless for replay semantics."""
        for p, item in zip(snap["priorities"], snap["items"]):
            self.tree.add(float(p), item)  # raw: already |err|^alpha-transformed
        self.beta = float(snap["beta"])


class NativePrioritizedReplay:
    """`PrioritizedReplay` surface over the C++ SumTree (cpp/sumtree.cc).

    Same priority/IS-weight math; tree walks and priority propagation run
    in native code via batch FFI calls (one call per batch, not one per
    element — the learner-host hotspot of SURVEY §2.2 E7). Payloads stay
    in a Python slot list aligned with the native write cursor.
    """

    EPS = PrioritizedReplay.EPS
    ALPHA = PrioritizedReplay.ALPHA
    BETA_INCREMENT = PrioritizedReplay.BETA_INCREMENT

    def __init__(self, capacity: int, beta: float = 0.4):
        from distributed_reinforcement_learning_tpu.data.native import NativeSumTree

        self.tree = NativeSumTree(capacity)
        self.beta = beta
        self._data: list[Any] = [None] * capacity
        # Guards the slot-reserve (native) + payload-write (Python) pair so a
        # threaded ingest can't expose a priority whose payload isn't stored
        # yet (or has been wrapped over) to a concurrent sample().
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self.tree)

    def _priority(self, errors) -> np.ndarray:
        return (np.abs(np.asarray(errors, np.float64)) + self.EPS) ** self.ALPHA

    def add(self, error: float, sample: Any) -> int:
        return self.add_batch(np.array([error]), [sample])[0]

    def add_batch(self, errors: np.ndarray, samples: list[Any]) -> list[int]:
        with self._lock:
            slots = self.tree.add_batch(self._priority(errors))
            for slot, s in zip(slots, samples):
                self._data[slot] = s
            return [int(s) + self.tree.capacity - 1 for s in slots]

    def sample(self, n: int, rng: np.random.RandomState | None = None):
        with self._lock:
            return self._sample_locked(n, rng)

    def _sample_locked(self, n: int, rng):
        rng = rng or np.random
        self.beta = min(1.0, self.beta + self.BETA_INCREMENT)
        segment = self.tree.total / n
        lo = segment * np.arange(n)
        idxs = np.empty(n, np.int64)
        priorities = np.empty(n, np.float64)
        filled = np.zeros(n, bool)
        cap = self.tree.capacity
        # Same retry-then-fallback policy as the Python impl: rounding in
        # the descent can land on unwritten leaves while partially filled.
        for _ in range(4):
            todo = np.flatnonzero(~filled)
            if todo.size == 0:
                break
            values = lo[todo] + rng.uniform(0.0, segment, size=todo.size)
            got_idx, got_p = self.tree.get_batch(values)
            ok = np.array([self._data[int(i) - (cap - 1)] is not None for i in got_idx])
            hit = todo[ok]
            idxs[hit] = got_idx[ok]
            priorities[hit] = got_p[ok]
            filled[hit] = True
        for i in np.flatnonzero(~filled):
            leaf = int(rng.randint(0, len(self.tree)))
            idxs[i] = leaf + cap - 1
            priorities[i] = self.tree.leaf_priority(int(idxs[i]))
        items = [self._data[int(i) - (cap - 1)] for i in idxs]
        probs = priorities / self.tree.total
        weights = np.power(len(self.tree) * probs, -self.beta)
        weights /= weights.max()
        return items, idxs, weights.astype(np.float32)

    def update(self, idx: int, error: float) -> None:
        self.update_batch(np.array([idx]), np.array([error]))

    def update_batch(self, idxs: np.ndarray, errors: np.ndarray) -> None:
        self.tree.update_batch(np.asarray(idxs, np.int64), self._priority(errors))

    def snapshot(self) -> dict:
        """Same contract as `PrioritizedReplay.snapshot` over the C++ tree."""
        with self._lock:
            n = len(self.tree)
            cap = self.tree.capacity
            priorities = np.array(
                [self.tree.leaf_priority(slot + cap - 1) for slot in range(n)], np.float64
            )
            return {
                "priorities": priorities,
                "items": [self._data[i] for i in range(n)],
                "beta": float(self.beta),
            }

    def restore(self, snap: dict) -> None:
        with self._lock:
            slots = self.tree.add_batch(np.asarray(snap["priorities"], np.float64))
            for slot, item in zip(slots, snap["items"]):
                self._data[slot] = item
            self.beta = float(snap["beta"])


def make_replay(capacity: int, beta: float = 0.4, backend: str = "auto"):
    """Pick the replay implementation: 'python', 'native', or 'auto'."""
    if backend == "python":
        return PrioritizedReplay(capacity, beta)
    if backend == "native":
        return NativePrioritizedReplay(capacity, beta)
    if backend == "auto":
        from distributed_reinforcement_learning_tpu.data.native import native_available

        cls = NativePrioritizedReplay if native_available() else PrioritizedReplay
        return cls(capacity, beta)
    raise ValueError(f"unknown replay backend {backend!r}")


class UniformBuffer:
    """Actor-local uniform-random transition store.

    Parity with `LocalBuffer` (`buffer_queue.py:213-254`): bounded deque,
    uniform sample of `batch_size` transitions.
    """

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._items: list[Any] = []
        self._write = 0
        self._rng = np.random.RandomState(seed)

    def __len__(self) -> int:
        return len(self._items)

    def append(self, item: Any) -> None:
        if len(self._items) < self.capacity:
            self._items.append(item)
        else:
            self._items[self._write] = item
        self._write = (self._write + 1) % self.capacity

    def sample(self, batch_size: int) -> list[Any]:
        idx = self._rng.randint(0, len(self._items), size=batch_size)
        return [self._items[i] for i in idx]
