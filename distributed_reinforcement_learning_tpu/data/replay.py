"""Prioritized experience replay: SumTree + Memory.

Re-design of `distributed_queue/buffer_queue.py:256-346`. Same sampling
semantics — priority `(|err| + 0.001) ** 0.6`, stratified sampling over
`total/n` segments, IS weights `(N * p) ** -beta` normalized by the batch
max, beta annealed 0.4 -> 1.0 by 0.001 per sample() call — but the tree
is array-based with *iterative* propagate/retrieve (the reference recurses
per-element, a Python hotspot flagged in SURVEY §2 E7) and supports batch
add/update. One reference bug is deliberately fixed: `train_r2d2.py:159`
updates only a single stale index per train step; `update_batch` here
updates every sampled index.

A C++ backend (cpp/sumtree) plugs in behind the same interface.
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

from distributed_reinforcement_learning_tpu.observability import TELEMETRY as _OBS


def _observe_replay(replay, inserted: int = 0, sampled: int = 0) -> None:
    """Shared insert/sample-rate counters + fill-level gauge for every
    replay backend (one instrumentation point, three implementations).
    No-op (one attribute read) while telemetry is disabled."""
    if not _OBS.enabled:
        return
    if inserted:
        _OBS.count("replay/inserts", inserted)
    if sampled:
        _OBS.count("replay/samples", sampled)
    _OBS.gauge("replay/fill", len(replay.tree) / replay.tree.capacity)


class SumTree:
    """Array-backed binary sum tree over `capacity` leaf priorities."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._tree = np.zeros(2 * capacity - 1, np.float64)
        self._data: list[Any] = [None] * capacity
        self._write = 0
        self._count = 0

    def __len__(self) -> int:
        return self._count

    @property
    def total(self) -> float:
        return float(self._tree[0])

    def add(self, priority: float, data: Any) -> int:
        idx = self._write + self.capacity - 1
        self._data[self._write] = data
        self.set_priority(idx, priority)
        self._write = (self._write + 1) % self.capacity
        self._count = min(self._count + 1, self.capacity)
        return idx

    def set_priority(self, idx: int, priority: float) -> None:
        delta = priority - self._tree[idx]
        while True:
            self._tree[idx] += delta
            if idx == 0:
                break
            idx = (idx - 1) // 2

    def get(self, value: float) -> tuple[int, float, Any]:
        """Find the leaf whose cumulative-priority interval contains `value`."""
        idx = 0
        while True:
            left = 2 * idx + 1
            if left >= len(self._tree):
                break
            if value <= self._tree[left]:
                idx = left
            else:
                value -= self._tree[left]
                idx = left + 1
        data_idx = idx - (self.capacity - 1)
        return idx, float(self._tree[idx]), self._data[data_idx]


def _snapshot_items(snap: dict) -> list[Any]:
    """Per-item view of a snapshot dict, whichever backend wrote it
    (`items` list, or the array backend's `stacked` pytree)."""
    if snap.get("items") is not None:
        return snap["items"]
    stacked = snap.get("stacked")
    if stacked is None:
        return []
    import jax

    n = len(snap["priorities"])
    return [jax.tree.map(lambda x: x[i], stacked) for i in range(n)]


def priority_transform(errors) -> np.ndarray:
    """TD error -> sum-tree leaf priority, `(|err| + EPS) ** ALPHA` —
    the one transform every backend applies on add/update (the tiered
    store in data/replay_spill.py shares it so its leaf domain matches
    the all-RAM backends exactly)."""
    return (np.abs(np.asarray(errors, np.float64))
            + PrioritizedReplay.EPS) ** PrioritizedReplay.ALPHA


class PrioritizedReplay:
    """The reference's `Memory` surface: add / sample / update.

    `sample(n)` returns (items, tree_idxs, is_weights) with stratified
    sampling and annealed-beta importance weights
    (`buffer_queue.py:323-342`).
    """

    EPS = 0.001
    ALPHA = 0.6
    BETA_INCREMENT = 0.001

    # No locks on purpose (so no _GUARDED_BY map): this backend is
    # single-thread by contract — the learner thread both ingests and
    # samples; cross-thread traffic arrives through the queue, not here.
    # The threaded backends below declare their maps.

    def __init__(self, capacity: int, beta: float = 0.4, seed: int = 0):
        self.tree = SumTree(capacity)
        self.beta = beta
        # Owned, seeded sampling stream: defaulting to the process-global
        # np.random made replay composition depend on every other
        # consumer of the global state (drlint: nondeterminism).
        self._default_rng = np.random.RandomState(seed)

    def __len__(self) -> int:
        return len(self.tree)

    def _priority(self, error: float) -> float:
        return (abs(error) + self.EPS) ** self.ALPHA

    def add(self, error: float, sample: Any) -> int:
        idx = self.tree.add(self._priority(error), sample)
        _observe_replay(self, inserted=1)
        return idx

    def add_batch(self, errors: np.ndarray, samples: list[Any]) -> list[int]:
        idxs = [self.tree.add(self._priority(e), s) for e, s in zip(errors, samples)]
        _observe_replay(self, inserted=len(idxs))
        return idxs

    def _pick(self, n: int, rng) -> tuple[list, np.ndarray, np.ndarray]:
        """Stratified pick -> (items, tree_idxs, raw priorities); the
        sampling policy shared by sample() and the sharded gather."""
        segment = self.tree.total / n
        idxs = np.empty(n, np.int64)
        priorities = np.empty(n, np.float64)
        items = []
        for i in range(n):
            # Retry guards against float64 rounding in the subtractive
            # descent landing on an unwritten zero-priority leaf while the
            # tree is partially filled.
            for _ in range(4):
                value = rng.uniform(segment * i, segment * (i + 1))
                idx, p, data = self.tree.get(value)
                if data is not None:
                    break
            if data is None:  # final fallback: a uniformly random filled leaf
                leaf = int(rng.randint(0, len(self.tree)))
                idx = leaf + self.tree.capacity - 1
                p = float(self.tree._tree[idx])
                data = self.tree._data[leaf]
            idxs[i] = idx
            priorities[i] = p
            items.append(data)
        return items, idxs, priorities

    def sample(self, n: int, rng: np.random.RandomState | None = None):
        rng = rng or self._default_rng
        self.beta = min(1.0, self.beta + self.BETA_INCREMENT)
        items, idxs, priorities = self._pick(n, rng)
        probs = priorities / self.tree.total
        weights = np.power(len(self.tree) * probs, -self.beta)
        weights /= weights.max()
        _observe_replay(self, sampled=n)
        return items, idxs, weights.astype(np.float32)

    def sample_with_priorities(self, n: int, rng=None):
        """(items, tree_idxs, RAW priorities) — no IS weights, no beta
        annealing: the sharded service (data/replay_service.py) gathers
        slices from several backends and computes global IS weights with
        its own annealed beta."""
        return self._pick(n, rng or self._default_rng)

    def update(self, idx: int, error: float) -> None:
        self.tree.set_priority(int(idx), self._priority(error))

    def update_batch(self, idxs: np.ndarray, errors: np.ndarray) -> None:
        """Re-prioritize every sampled index (fixes `train_r2d2.py:159`)."""
        for idx, err in zip(idxs, errors):
            self.update(int(idx), float(err))

    def snapshot(self) -> dict:
        """Serializable state: payloads + already-transformed priorities.

        SURVEY §5.4's optional replay snapshot — without it a restarted
        Ape-X/R2D2 learner resumes with an empty Memory while actors keep
        pushing stale-policy re-samples.
        """
        n = len(self.tree)
        cap = self.tree.capacity
        return {
            "priorities": self.tree._tree[cap - 1 : cap - 1 + n].copy(),
            "items": [self.tree._data[i] for i in range(n)],
            "beta": float(self.beta),
        }

    def restore(self, snap: dict) -> None:
        """Rebuild from `snapshot()`. Contents and priorities are exact;
        the ring write cursor restarts at `count % capacity`, so after a
        wrapped buffer the future *eviction order* differs from the
        original — harmless for replay semantics."""
        for p, item in zip(snap["priorities"], _snapshot_items(snap)):
            self.tree.add(float(p), item)  # raw: already |err|^alpha-transformed
        self.beta = float(snap["beta"])


def _stratified_pick(tree, count: int, n: int, rng, is_written) -> tuple[np.ndarray, np.ndarray]:
    """Shared stratified-sampling policy over a batched sum-tree:
    one segment per sample, 4 retry rounds for descents that land on
    unwritten leaves (float64 rounding while partially filled), then a
    uniform-random written leaf as the final fallback. Returns
    (tree_idxs, priorities). ONE copy of the policy for the two
    native-tree backends — a fix here fixes both."""
    cap = tree.capacity
    segment = tree.total / n
    lo = segment * np.arange(n)
    idxs = np.empty(n, np.int64)
    priorities = np.empty(n, np.float64)
    filled = np.zeros(n, bool)
    for _ in range(4):
        todo = np.flatnonzero(~filled)
        if todo.size == 0:
            break
        values = lo[todo] + rng.uniform(0.0, segment, size=todo.size)
        got_idx, got_p = tree.get_batch(values)
        ok = is_written(got_idx - (cap - 1))
        hit = todo[ok]
        idxs[hit] = got_idx[ok]
        priorities[hit] = got_p[ok]
        filled[hit] = True
    for i in np.flatnonzero(~filled):
        leaf = int(rng.randint(0, count))
        idxs[i] = leaf + cap - 1
        priorities[i] = tree.leaf_priority(int(idxs[i]))
    return idxs, priorities


def _is_weights(priorities: np.ndarray, total: float, count: int,
                beta: float) -> np.ndarray:
    """`(N * p)^-beta`, batch-max-normalized (`buffer_queue.py:338-341`)."""
    probs = priorities / total
    weights = np.power(count * probs, -beta)
    weights /= weights.max()
    return weights.astype(np.float32)


class NativePrioritizedReplay:
    """`PrioritizedReplay` surface over the C++ SumTree (cpp/sumtree.cc).

    Same priority/IS-weight math; tree walks and priority propagation run
    in native code via batch FFI calls (one call per batch, not one per
    element — the learner-host hotspot of SURVEY §2.2 E7). Payloads stay
    in a Python slot list aligned with the native write cursor.
    """

    EPS = PrioritizedReplay.EPS
    ALPHA = PrioritizedReplay.ALPHA
    BETA_INCREMENT = PrioritizedReplay.BETA_INCREMENT

    # Concurrency map (tools/drlint lock-discipline). `tree` is NOT here:
    # the C++ SumTree carries its own internal mutex (cpp/sumtree.cc), so
    # bare tree calls (update_batch) are safe — `_lock` exists for the
    # slot-reserve + payload-write PAIR, which must be atomic together.
    _GUARDED_BY = {
        "_data": "_lock",
        "beta": "_lock",
    }

    def __init__(self, capacity: int, beta: float = 0.4, seed: int = 0):
        from distributed_reinforcement_learning_tpu.data.native import NativeSumTree

        self.tree = NativeSumTree(capacity)
        self.beta = beta
        self._default_rng = np.random.RandomState(seed)  # owned sampling stream
        self._data: list[Any] = [None] * capacity
        # Guards the slot-reserve (native) + payload-write (Python) pair so a
        # threaded ingest can't expose a priority whose payload isn't stored
        # yet (or has been wrapped over) to a concurrent sample().
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self.tree)

    def _priority(self, errors) -> np.ndarray:
        return (np.abs(np.asarray(errors, np.float64)) + self.EPS) ** self.ALPHA

    def add(self, error: float, sample: Any) -> int:
        return self.add_batch(np.array([error]), [sample])[0]

    def add_batch(self, errors: np.ndarray, samples: list[Any]) -> list[int]:
        with self._lock:
            slots = self.tree.add_batch(self._priority(errors))
            for slot, s in zip(slots, samples):
                self._data[slot] = s
            idxs = [int(s) + self.tree.capacity - 1 for s in slots]
        _observe_replay(self, inserted=len(idxs))
        return idxs

    def sample(self, n: int, rng: np.random.RandomState | None = None):
        with self._lock:
            out = self._sample_locked(n, rng)
        _observe_replay(self, sampled=n)
        return out

    def _pick_locked(self, n: int, rng) -> tuple[list, np.ndarray, np.ndarray]:
        cap = self.tree.capacity
        idxs, priorities = _stratified_pick(
            self.tree, len(self.tree), n, rng,
            is_written=lambda slots: np.array(
                [self._data[int(s)] is not None for s in slots]))
        items = [self._data[int(i) - (cap - 1)] for i in idxs]
        return items, idxs, priorities

    def _sample_locked(self, n: int, rng):
        rng = rng or self._default_rng
        self.beta = min(1.0, self.beta + self.BETA_INCREMENT)
        items, idxs, priorities = self._pick_locked(n, rng)
        return items, idxs, _is_weights(priorities, self.tree.total,
                                        len(self.tree), self.beta)

    def sample_with_priorities(self, n: int, rng=None):
        """See `PrioritizedReplay.sample_with_priorities`."""
        with self._lock:
            return self._pick_locked(n, rng or self._default_rng)

    def update(self, idx: int, error: float) -> None:
        self.update_batch(np.array([idx]), np.array([error]))

    def update_batch(self, idxs: np.ndarray, errors: np.ndarray) -> None:
        self.tree.update_batch(np.asarray(idxs, np.int64), self._priority(errors))

    def snapshot(self) -> dict:
        """Same contract as `PrioritizedReplay.snapshot` over the C++ tree."""
        with self._lock:
            n = len(self.tree)
            return {
                "priorities": self.tree.leaf_priorities(0, n),
                "items": [self._data[i] for i in range(n)],
                "beta": float(self.beta),
            }

    def restore(self, snap: dict) -> None:
        with self._lock:
            slots = self.tree.add_batch(np.asarray(snap["priorities"], np.float64))
            for slot, item in zip(slots, _snapshot_items(snap)):
                self._data[slot] = item
            self.beta = float(snap["beta"])


class ArrayPrioritizedReplay:
    """Structure-of-arrays prioritized replay over the C++ sum-tree.

    The backends above (and the reference's `Memory`) store one Python
    pytree per transition: every ingest slices a batch into N objects
    and every train step re-stacks batch_size of them — pure host
    overhead on the learner thread. Here payloads live in preallocated
    per-field numpy rings indexed by the native tree's write slots:

    - `add_batch_stacked(errors, batch)` is one vectorized slice-assign
      per field (no per-transition objects at all);
    - `sample(n)` returns an ALREADY-STACKED batch via one fancy-index
      gather per field (`stacked_samples = True` tells learners to skip
      `stack_pytrees`).

    Priority/IS math is identical to `PrioritizedReplay` (the parity
    contract with `buffer_queue.py:303-346`). numpy's `np.empty` maps
    pages lazily, so a capacity-1e5 Atari ring costs physical memory
    only as slots are written — same high-water mark as the list
    backends, paid gradually.
    """

    stacked_samples = True
    EPS = PrioritizedReplay.EPS
    ALPHA = PrioritizedReplay.ALPHA
    BETA_INCREMENT = PrioritizedReplay.BETA_INCREMENT

    # Concurrency map (tools/drlint lock-discipline): the lazily-built
    # field rings and the annealed beta are shared between a threaded
    # ingest and the sampling learner. The C++ tree locks internally
    # (see NativePrioritizedReplay).
    _GUARDED_BY = {
        "_store": "_lock",
        "beta": "_lock",
    }

    def __init__(self, capacity: int, beta: float = 0.4, seed: int = 0):
        from distributed_reinforcement_learning_tpu.data.native import NativeSumTree

        self.tree = NativeSumTree(capacity)
        self.beta = beta
        self._default_rng = np.random.RandomState(seed)  # owned sampling stream
        self._store = None  # pytree of [capacity, ...] arrays, lazy
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self.tree)

    def _priority(self, errors) -> np.ndarray:
        return (np.abs(np.asarray(errors, np.float64)) + self.EPS) ** self.ALPHA

    def _ensure_store_locked(self, batch: Any) -> None:
        import jax

        if self._store is None:
            cap = self.tree.capacity
            self._store = jax.tree.map(
                lambda x: np.empty((cap, *np.asarray(x).shape[1:]),
                                   np.asarray(x).dtype),
                batch,
            )

    def _write_locked(self, slots: np.ndarray, batch: Any) -> None:
        import jax

        jax.tree.map(lambda store, x: store.__setitem__(slots, np.asarray(x)),
                     self._store, batch)

    def add_batch_stacked(self, errors: np.ndarray, batch: Any) -> np.ndarray:
        """Insert a `[N, ...]`-stacked batch of transitions/sequences."""
        with self._lock:
            self._ensure_store_locked(batch)
            slots = self.tree.add_batch(self._priority(errors))
            self._write_locked(slots, batch)
            idxs = slots + self.tree.capacity - 1
        _observe_replay(self, inserted=len(idxs))
        return idxs

    def add_batch(self, errors: np.ndarray, samples: list[Any]) -> list[int]:
        from distributed_reinforcement_learning_tpu.data.fifo import stack_pytrees

        return list(self.add_batch_stacked(errors, stack_pytrees(samples)))

    def add(self, error: float, sample: Any) -> int:
        import jax

        return int(self.add_batch_stacked(
            np.array([error]), jax.tree.map(lambda x: np.asarray(x)[None], sample))[0])

    def _pick_locked(self, n: int, rng) -> tuple[Any, np.ndarray, np.ndarray]:
        import jax

        count = len(self.tree)
        idxs, priorities = _stratified_pick(
            self.tree, count, n, rng,
            is_written=lambda slots: slots < count)
        slots = idxs - (self.tree.capacity - 1)
        batch = jax.tree.map(lambda store: store[slots], self._store)
        return batch, idxs, priorities

    def sample(self, n: int, rng: np.random.RandomState | None = None):
        rng = rng or self._default_rng
        with self._lock:
            self.beta = min(1.0, self.beta + self.BETA_INCREMENT)
            batch, idxs, priorities = self._pick_locked(n, rng)
            out = batch, idxs, _is_weights(priorities, self.tree.total,
                                           len(self.tree), self.beta)
        _observe_replay(self, sampled=n)
        return out

    def sample_with_priorities(self, n: int, rng=None):
        """See `PrioritizedReplay.sample_with_priorities` (stacked batch
        instead of an item list, like sample())."""
        with self._lock:
            return self._pick_locked(n, rng or self._default_rng)

    def update(self, idx: int, error: float) -> None:
        self.update_batch(np.array([idx]), np.array([error]))

    def update_batch(self, idxs: np.ndarray, errors: np.ndarray) -> None:
        self.tree.update_batch(np.asarray(idxs, np.int64), self._priority(errors))

    def approx_snapshot_nbytes(self) -> int:
        """Snapshot payload size WITHOUT materializing it — from store
        dtypes/shapes only. checkpoint's size cap consults this first so
        an over-cap replay (a full Atari ring is ~5 GB) is rejected
        before snapshot() copies it under the lock."""
        import jax

        # Locked: a threaded ingest may be building _store right now, and
        # this races a half-assigned pytree otherwise.
        with self._lock:
            n = len(self.tree)
            if self._store is None or n == 0:
                return 0
            per_item = sum(
                int(np.prod(leaf.shape[1:], dtype=np.int64)) * leaf.dtype.itemsize
                for leaf in jax.tree.leaves(self._store))
        return n * per_item + n * 8  # + float64 priorities

    def snapshot(self) -> dict:
        """Checkpoint state; `stacked` replaces the list backends' `items`
        (decode handles both — utils/checkpoint.py)."""
        import jax

        with self._lock:
            n = len(self.tree)
            stacked = (None if self._store is None else
                       jax.tree.map(lambda store: store[:n].copy(), self._store))
            return {"priorities": self.tree.leaf_priorities(0, n),
                    "stacked": stacked, "beta": float(self.beta)}

    def restore(self, snap: dict) -> None:
        from distributed_reinforcement_learning_tpu.data.fifo import stack_pytrees

        batch = snap.get("stacked")
        if batch is None and snap.get("items"):  # list-backend snapshot
            batch = stack_pytrees(snap["items"])
        with self._lock:
            if batch is not None:
                self._ensure_store_locked(batch)
                slots = self.tree.add_batch(np.asarray(snap["priorities"], np.float64))
                self._write_locked(slots, batch)
            self.beta = float(snap["beta"])


def make_replay(capacity: int, beta: float = 0.4, backend: str = "auto",
                seed: int = 0, spill=None, mode: str = "transition"):
    """Pick the replay implementation: 'python', 'native', 'array', or
    'auto' (= structure-of-arrays over the C++ tree when the native lib
    builds, else the pure-Python Memory). `seed` fixes the backend's
    default sampling stream (callers passing their own rng to sample()
    are unaffected). A non-None `spill` (a `replay_spill.SpillConfig`)
    overrides `backend` with the tiered hot/cold store — the disk tier
    is a storage property, orthogonal to the sum-tree implementation."""
    if spill is not None:
        from distributed_reinforcement_learning_tpu.data.replay_spill import TieredStore

        return TieredStore(capacity, spill, mode=mode, beta=beta, seed=seed)
    if backend == "python":
        return PrioritizedReplay(capacity, beta, seed=seed)
    if backend == "native":
        return NativePrioritizedReplay(capacity, beta, seed=seed)
    if backend in ("array", "auto"):
        from distributed_reinforcement_learning_tpu.data.native import native_available

        if native_available():
            return ArrayPrioritizedReplay(capacity, beta, seed=seed)
        if backend == "array":
            raise RuntimeError("array replay backend needs the native library")
        return PrioritizedReplay(capacity, beta, seed=seed)
    raise ValueError(f"unknown replay backend {backend!r}")


class UniformBuffer:
    """Actor-local uniform-random transition store.

    Parity with `LocalBuffer` (`buffer_queue.py:213-254`): bounded deque,
    uniform sample of `batch_size` transitions.
    """

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._items: list[Any] = []
        self._write = 0
        self._rng = np.random.RandomState(seed)

    def __len__(self) -> int:
        return len(self._items)

    def append(self, item: Any) -> None:
        if len(self._items) < self.capacity:
            self._items.append(item)
        else:
            self._items[self._write] = item
        self._write = (self._write + 1) % self.capacity

    def sample(self, batch_size: int) -> list[Any]:
        idx = self._rng.randint(0, len(self._items), size=batch_size)
        return [self._items[i] for i in idx]
