"""Ape-X DQN agent: double-DQN on a dueling network with prioritized replay.

Re-design of `/root/reference/agent/apex.py` as jitted pure functions:

- `act`: epsilon-greedy over main-net Q (`agent/apex.py:92-107`); epsilon
  enters as data so one compiled function serves the whole schedule.
- `td_error`: priority scoring forward pass (`agent/apex.py:119-134`).
- `learn`: weighted double-DQN step (`agent/apex.py:136-153`), Adam +
  polynomial LR + global-norm clip, returning fresh |TD| for priority
  updates.
- `sync_target`: main -> target copy (`agent/apex.py:78,82`).

The main net is applied to s and s' in one stacked batch (single conv
pass over 2B frames) instead of the reference's two scoped graph copies
(`model/apex_value.py:42-58`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from distributed_reinforcement_learning_tpu.agents import common
from distributed_reinforcement_learning_tpu.models.apex_net import DuelingQNetwork, SimpleQNetwork
from distributed_reinforcement_learning_tpu.ops import dqn


@dataclasses.dataclass(frozen=True)
class ApexConfig:
    """Hyperparameters, mirroring the `apex` block of `config.json:68-106`."""

    obs_shape: tuple[int, ...] = (84, 84, 4)
    num_actions: int = 4
    discount_factor: float = 0.99
    reward_clipping: str = "abs_one"
    gradient_clip_norm: float = 40.0
    start_learning_rate: float = 1e-4
    end_learning_rate: float = 0.0
    learning_frame: int = 100_000_000_000_000
    dtype: Any = jnp.float32
    # Fold /255 into conv0's kernel; uint8 frames feed the model raw
    # (see ImpalaConfig.fold_normalize / models.torso.NatureConv).
    fold_normalize: bool = False


class ApexBatch(NamedTuple):
    """Flat transition batch (the per-transition replay of `train_apex.py:114-122`)."""

    state: jax.Array  # [B, *obs]
    next_state: jax.Array  # [B, *obs]
    previous_action: jax.Array  # [B] i32 (embedding input for s)
    action: jax.Array  # [B] i32 (taken at s; embedding input for s')
    reward: jax.Array  # [B] f32
    done: jax.Array  # [B] bool


class ApexAgent:
    def __init__(self, cfg: ApexConfig):
        self.cfg = cfg
        if len(cfg.obs_shape) == 1:
            self.model = SimpleQNetwork(num_actions=cfg.num_actions, dtype=cfg.dtype)
        else:
            self.model = DuelingQNetwork(
                num_actions=cfg.num_actions, dtype=cfg.dtype,
                fold_normalize=cfg.fold_normalize,
            )
        self._schedule = common.polynomial_lr(
            cfg.start_learning_rate, cfg.end_learning_rate, cfg.learning_frame
        )
        self.tx = common.adam_with_clip(self._schedule, cfg.gradient_clip_norm)
        self.act = jax.jit(self._act)
        self.td_error = jax.jit(self._td_error)
        self.learn = jax.jit(self._learn, donate_argnums=(0,))
        # Split learn step for the sharded learner tier
        # (runtime/learner_tier.py): grads computes, the host collective
        # merges, apply_grads commits. apply_grads does NOT donate state:
        # the tier may retry a round against the same state after a
        # membership change aborts the first attempt.
        self.grads = jax.jit(self._grads)
        self.apply_grads = jax.jit(self._apply_grads)
        # K prioritized steps per dispatch; priorities come back stacked
        # [K, B] and land K-1 steps stale (common.scan_learn_weighted).
        self.learn_many = jax.jit(
            common.scan_learn_weighted(self._learn), donate_argnums=(0,)
        )
        self.sync_target = jax.jit(lambda s: s.sync_target())

    def init_state(self, rng: jax.Array) -> common.TargetTrainState:
        obs = jnp.zeros((1, *self.cfg.obs_shape), jnp.float32)
        pa = jnp.zeros((1,), jnp.int32)
        params = self.model.init(rng, obs, pa)
        return common.TargetTrainState.create(params, self.tx)

    def _prep_obs(self, obs):
        """Normalize frames — or pass integer frames raw under `fold_normalize`."""
        if (
            self.cfg.fold_normalize
            and len(self.cfg.obs_shape) == 3
            and jnp.issubdtype(obs.dtype, jnp.integer)
        ):
            return obs
        return common.normalize_obs(obs, self.cfg.dtype)

    # -- act -------------------------------------------------------------
    def _act(self, params, obs, prev_action, epsilon, rng):
        """Batched epsilon-greedy: argmax Q with probability 1-eps."""
        q = self.model.apply(params, self._prep_obs(obs), prev_action)
        action = common.epsilon_greedy(q, epsilon, self.cfg.num_actions, rng)
        return action, q

    # -- shared target math ----------------------------------------------
    def _targets(self, params, target_params, batch: ApexBatch):
        cfg = self.cfg
        obs = self._prep_obs(batch.state)
        next_obs = self._prep_obs(batch.next_state)
        # One conv pass over [s; s'] for the main net.
        stacked = jnp.concatenate([obs, next_obs], axis=0)
        stacked_pa = jnp.concatenate([batch.previous_action, batch.action], axis=0)
        q_all = self.model.apply(params, stacked, stacked_pa)
        B = batch.state.shape[0]
        main_q, next_main_q = q_all[:B], q_all[B:]
        target_q = self.model.apply(target_params, next_obs, batch.action)

        clipped_r = common.clip_rewards(batch.reward, cfg.reward_clipping)
        discounts = (~batch.done).astype(jnp.float32) * cfg.discount_factor
        target_value = dqn.double_q_target(next_main_q, target_q, clipped_r, discounts)
        state_action_value = dqn.take_state_action_value(main_q, batch.action)
        return target_value, state_action_value

    def _td_error(self, state: common.TargetTrainState, batch: ApexBatch):
        tv, sav = self._targets(state.params, state.target_params, batch)
        return dqn.td_error(tv, sav)

    # -- learn -----------------------------------------------------------
    def _loss(self, params, target_params, batch: ApexBatch, is_weight):
        tv, sav = self._targets(params, target_params, batch)
        td_sq = jnp.square(tv - sav)
        loss = jnp.mean(td_sq * is_weight)
        return loss, dqn.td_error(tv, sav)

    def _grads(self, state: common.TargetTrainState, batch: ApexBatch, is_weight):
        """Gradient half of the learn step: (grads, td, loss) with NO
        update applied. The learner-tier allreduce (parallel/
        collective.py) runs between this and `_apply_grads`, so a seat's
        local-batch gradients can be mean-merged across the tier before
        the (identical-everywhere) Adam update — the host-side analogue
        of `_learn`'s in-graph pmean."""
        (loss, td), grads = jax.value_and_grad(self._loss, has_aux=True)(
            state.params, state.target_params, batch, is_weight
        )
        return grads, td, loss

    def _apply_grads(self, state: common.TargetTrainState, grads, loss):
        """Update half of the learn step: optimizer + param apply on
        (possibly tier-merged) gradients; metrics match `_learn`'s."""
        updates, opt_state = self.tx.update(grads, state.opt_state, state.params)
        params = jax.tree.map(lambda p, u: p + u, state.params, updates)
        new_state = state.replace(params=params, opt_state=opt_state, step=state.step + 1)
        metrics = {
            "loss": loss,
            "grad_norm": common.global_norm(grads),
            "learning_rate": self._schedule(state.step),
        }
        return new_state, metrics

    def _learn(self, state: common.TargetTrainState, batch: ApexBatch, is_weight,
               axis_name: str | None = None):
        grads, td, loss = self._grads(state, batch, is_weight)
        if axis_name is not None:
            # shard_map data-parallel callers (runtime/anakin_apex.py mesh
            # mode): each device grads its local prioritized batch; the
            # pmean makes the applied update the global-batch gradient and
            # keeps the replicated params bit-identical across devices.
            grads = jax.lax.pmean(grads, axis_name)
            loss = jax.lax.pmean(loss, axis_name)
        new_state, metrics = self._apply_grads(state, grads, loss)
        return new_state, td, metrics
