"""Transformer-R2D2 agent: attention-based recurrent replay.

Fourth algorithm family, extending the reference's three: R2D2's
distributed prioritized sequence replay (`/root/reference/agent/r2d2.py`,
`train_r2d2.py`) with the LSTM swapped for the causal transformer of
`models/transformer_net.py`. All replay-side semantics are kept
identical to the in-tree R2D2 agent so the two are drop-in alternates
behind the same runners/queues:

- burn-in: first `burn_in` steps sliced out of the loss, not the forward
  (`agent/r2d2.py:64-68`) — for a transformer they serve as attention
  context exactly as they warm the LSTM state;
- double-Q over sequences + value rescaling on the target
  (`agent/r2d2.py:70-87`); loss = IS-weighted mean over time of squared
  TD; priority = |mean TD| (`agent/r2d2.py:151-153`); plain Adam.

What replaces the stored (h, c): nothing needs storing — the sequence
IS the state. Acting runs the same forward over a rolling window of the
last `seq_len` steps (the actor keeps the window host-side); training
attends over the stored sequence with episode-segment masking standing
in for done-masked carry resets.

Long context is where this family pays: `seq_len` is a knob, and with
`attention="ring"|"ulysses"` + a mesh whose `seq` axis > 1 the learn
step shards the sequence dimension over devices
(`parallel/sequence.py`), which no recurrent model can do.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from distributed_reinforcement_learning_tpu.agents import common
from distributed_reinforcement_learning_tpu.models.transformer_net import TransformerQNet


@dataclasses.dataclass(frozen=True)
class XformerConfig:
    """R2D2 replay hyperparameters + transformer size knobs."""

    obs_shape: tuple[int, ...] = (2,)
    num_actions: int = 2
    seq_len: int = 10
    burn_in: int = 5
    d_model: int = 256
    num_heads: int = 4
    num_layers: int = 2
    discount_factor: float = 0.997
    learning_rate: float = 1e-4
    rescale_eps: float = 1e-3
    dtype: Any = jnp.float32
    # "dense" on one device; "ring" / "ring_zigzag" / "ulysses" shard the
    # sequence over the mesh's `seq` axis (pass the mesh at
    # construction). "ring_zigzag" is the balanced-causal ring: the model
    # holds its residual stream in zigzag layout for the whole forward.
    attention: str = "dense"
    # Mixture-of-experts MLPs: num_experts > 0 swaps every block's dense
    # MLP for a routed MoE (`ops/moe.py`); with a mesh whose `expert`
    # axis > 1 the experts run expert-parallel. The router's
    # load-balancing loss enters the TD loss scaled by moe_aux_weight.
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    moe_aux_weight: float = 1e-2
    # Pipeline parallelism: the learn step runs the blocks as GPipe
    # stages over the mesh's `pipe` axis (`parallel/pipeline.py`), each
    # stage owning num_layers/stages contiguous layers, splitting each
    # batch into this many microbatches. Uses the stacked-param body
    # (dense attention; exclusive with ring/ulysses and MoE).
    pipeline: bool = False
    pipeline_microbatches: int = 2
    # Number of pipeline stages (devices on the `pipe` axis); 0 means
    # one stage per layer, otherwise >= 2 and it must divide num_layers
    # (virtual stages).
    pipeline_stages: int = 0
    # Rematerialize each transformer block in the backward pass
    # (jax.checkpoint) — activation memory stops growing with
    # num_layers x seq_len at the cost of ~one extra forward.
    remat: bool = False
    # Stacked [num_layers, ...] param layout WITHOUT the pipeline
    # schedule (plain scan over layers). pipeline=True implies it; set
    # it alone on actor twins so they share a pipelined learner's
    # checkpoint/weight layout.
    stacked: bool = False
    # None = the reference's |mean TD| sequence priority (parity quirk);
    # a float (paper: 0.9) = eta*max|TD| + (1-eta)*mean|TD| stable mode
    # (common.SequenceReplayLearnMixin._seq_priority).
    priority_eta: float | None = None
    # None = plain unclipped Adam (R2D2-family reference parity); a float
    # adds global-norm clipping (stable mode, config key adam_clip_norm).
    gradient_clip_norm: float | None = None


class XformerBatch(NamedTuple):
    """Sequence batch — the R2D2 queue payload minus the stored (h, c)."""

    state: jax.Array  # [B, T, *obs]
    previous_action: jax.Array  # [B, T] i32
    action: jax.Array  # [B, T] i32
    reward: jax.Array  # [B, T] f32
    done: jax.Array  # [B, T] bool


def build_transformer_models(cfg, mesh, *, seq_len: int, head: str = "dueling_q"):
    """(model, plain_apply_twin) for any transformer-family config.

    Shared by the Transformer-R2D2 and Transformer-IMPALA agents: `cfg`
    supplies the body knobs (attention / num_experts+moe_* / pipeline* /
    stacked / remat / d_model / num_heads / num_layers / num_actions /
    dtype); `head` picks the output head. The twin applies the SAME
    params without collective schedules or sharding constraints — for
    acting on rolling windows and for scoring ragged ingest batches —
    and is the model itself when no sharded feature is on.
    """
    attention_fn = None
    sequence_perm = None
    if cfg.attention != "dense":
        if mesh is None:
            raise ValueError(f"attention={cfg.attention!r} needs a mesh")
        from distributed_reinforcement_learning_tpu.parallel import sequence as sp
        from distributed_reinforcement_learning_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS

        fns = {
            "ring": sp.ring_attention,
            # pre_permuted: the MODEL holds its stream in zigzag
            # layout for the whole forward (one reorder, not one per
            # layer) via sequence_perm below.
            "ring_zigzag": functools.partial(
                sp.ring_attention, schedule="zigzag", pre_permuted=True),
            "ulysses": sp.ulysses_attention,
        }
        if cfg.attention not in fns:
            raise ValueError(
                f"unknown attention {cfg.attention!r}; one of "
                f"['dense', {', '.join(map(repr, fns))}]")
        attention_fn = functools.partial(
            lambda f, q, k, v, segs: f(
                mesh, q, k, v, causal=True, batch_axis=DATA_AXIS, segment_ids=segs
            ),
            fns[cfg.attention],
        )
        if cfg.attention == "ring_zigzag":
            sequence_perm = sp.zigzag_permutation(seq_len, mesh.shape[SEQ_AXIS])
    moe_mesh = None
    if cfg.num_experts and mesh is not None:
        from distributed_reinforcement_learning_tpu.parallel.mesh import EXPERT_AXIS

        if mesh.shape.get(EXPERT_AXIS, 1) > 1:
            moe_mesh = mesh
    pipeline_mesh = None
    if cfg.pipeline:
        if mesh is None:
            raise ValueError("pipeline=True needs a mesh with a 'pipe' axis")
        if cfg.attention != "dense" or cfg.num_experts:
            raise ValueError(
                "pipeline is exclusive with sequence-parallel attention and MoE")
        if cfg.pipeline_stages < 0 or cfg.pipeline_stages == 1:
            raise ValueError(
                f"pipeline_stages must be 0 (one stage per layer) or >= 2, "
                f"got {cfg.pipeline_stages}")
        from distributed_reinforcement_learning_tpu.parallel.mesh import PIPE_AXIS

        want = cfg.pipeline_stages or cfg.num_layers
        if cfg.num_layers % want != 0:
            raise ValueError(
                f"pipeline_stages={cfg.pipeline_stages} must divide "
                f"num_layers={cfg.num_layers}")
        have = mesh.shape.get(PIPE_AXIS, 1)
        if have != want:
            raise ValueError(
                f"mesh pipe axis is {have} but the config asks for "
                f"{want} stages (pipeline_stages={cfg.pipeline_stages}, "
                f"num_layers={cfg.num_layers})")
        pipeline_mesh = mesh
    make_model = lambda fn, perm=None, pipe=None, moe_mesh=moe_mesh: TransformerQNet(
        num_actions=cfg.num_actions,
        d_model=cfg.d_model,
        num_heads=cfg.num_heads,
        num_layers=cfg.num_layers,
        max_len=max(seq_len, 16),
        dtype=cfg.dtype,
        attention_fn=fn,
        sequence_perm=perm,
        num_experts=cfg.num_experts,
        moe_top_k=cfg.moe_top_k,
        moe_capacity_factor=cfg.moe_capacity_factor,
        moe_mesh=moe_mesh,
        stack_layers=cfg.pipeline or cfg.stacked,
        pipeline_mesh=pipe,
        pipeline_microbatches=cfg.pipeline_microbatches,
        remat=cfg.remat,
        head=head,
    )
    model = make_model(attention_fn, sequence_perm, pipeline_mesh)
    # Plain-apply twin over the SAME params — see docstring. (For the
    # pipelined model the twin keeps stack_layers — same param layout —
    # but applies the stages with the plain scan; for expert-parallel
    # MoE it drops the sharding constraints.)
    twin = (
        make_model(None, moe_mesh=None)
        if (attention_fn is not None or pipeline_mesh is not None or moe_mesh is not None)
        else model
    )
    return model, twin


def init_transformer_params(model, cfg, mesh, *, seq_len: int, rng):
    """Trainable params for any transformer-family model.

    The dummy init batch must cover the mesh's data axis (sharded
    forwards run through shard_map at init too) and, when pipelined,
    split into microbatches; sown collections (MoE aux losses) are
    dropped so only trainables reach the optimizer. Shared by both
    transformer agents so the sizing rule cannot drift.
    """
    b = 1 if mesh is None else mesh.shape.get("data", 1)
    if cfg.pipeline:
        b *= cfg.pipeline_microbatches
    obs = jnp.zeros((b, seq_len, *cfg.obs_shape), jnp.float32)
    pa = jnp.zeros((b, seq_len), jnp.int32)
    done = jnp.zeros((b, seq_len), bool)
    variables = model.init(rng, obs, pa, done)
    return {"params": variables["params"]}


class XformerAgent(common.SequenceReplayLearnMixin):
    def __init__(self, cfg: XformerConfig, mesh=None):
        self.cfg = cfg
        self._mesh = mesh
        self.model, self._dense_model = build_transformer_models(
            cfg, mesh, seq_len=cfg.seq_len)
        self.tx = common.adam_with_clip(cfg.learning_rate,
                                        clip_norm=cfg.gradient_clip_norm)
        self.act = jax.jit(self._act)
        self.td_error = jax.jit(self._td_error)
        self.learn = jax.jit(self._learn, donate_argnums=(0,))
        self.learn_many = jax.jit(
            common.scan_learn_weighted(self._learn), donate_argnums=(0,)
        )
        self.sync_target = jax.jit(lambda s: s.sync_target())

    def init_state(self, rng: jax.Array) -> common.TargetTrainState:
        params = init_transformer_params(
            self.model, self.cfg, self._mesh, seq_len=self.cfg.seq_len, rng=rng)
        return common.TargetTrainState.create(params, self.tx)

    # -- act ---------------------------------------------------------------
    def _act(self, params, obs_win, prev_action_win, done_win, epsilon, rng):
        """Batched epsilon-greedy over the LAST step of a rolling window.

        `obs_win [N, W, *obs]`: the actor's recent history, a window the
        actor maintains host-side — the transformer counterpart of
        carrying (h, c) between steps.

        Acting always runs the plain-apply twin: a rolling window is
        small and host-local, where the learn step's collective
        schedules (ring/pipeline shard_maps) are wrong or impossible —
        same params, same math, no mesh.
        """
        q_seq = self._dense_model.apply(
            params, common.normalize_obs(obs_win, self.cfg.dtype), prev_action_win, done_win)
        q = q_seq[:, -1]
        action = common.epsilon_greedy(q, epsilon, self.cfg.num_actions, rng)
        return action, q

    # -- shared sequence target math --------------------------------------
    # _td_error/_loss/_learn come from SequenceReplayLearnMixin; this
    # supplies the transformer forward. Replay semantics live in
    # `common.sequence_double_q_td` — shared with the LSTM agent so the
    # two families cannot drift.
    def _sequence_td(self, params, target_params, batch: XformerBatch, model=None):
        cfg = self.cfg
        model = model or self.model
        obs = common.normalize_obs(batch.state, self.cfg.dtype)
        forward = lambda p: model.apply(p, obs, batch.previous_action, batch.done)
        discounts = (~batch.done).astype(jnp.float32) * cfg.discount_factor
        if cfg.num_experts:
            # The online forward collects the MoE routers' sown
            # load-balancing terms; the target forward doesn't need them.
            main_q, sown = model.apply(
                params, obs, batch.previous_action, batch.done, mutable=["losses"])
            aux = cfg.moe_aux_weight * sum(
                jnp.asarray(x) for x in jax.tree.leaves(sown.get("losses", {})))
            tv, sav = common.sequence_double_q_td(
                main_q, forward(target_params), batch.action, batch.reward,
                discounts, burn_in=cfg.burn_in, rescale_eps=cfg.rescale_eps)
            return tv, sav, aux
        return common.sequence_double_q_td(
            forward(params), forward(target_params), batch.action, batch.reward,
            discounts, burn_in=cfg.burn_in, rescale_eps=cfg.rescale_eps)

    def _td_error(self, state: common.TargetTrainState, batch: XformerBatch):
        tv, sav = self._sequence_td(
            state.params, state.target_params, batch, model=self._dense_model)[:2]
        return jnp.abs(jnp.mean(tv - sav, axis=1))
