"""Algorithm agents as pure init/act/learn functions (reference layer L4)."""

from distributed_reinforcement_learning_tpu.agents.apex import ApexAgent, ApexBatch, ApexConfig
from distributed_reinforcement_learning_tpu.agents.common import TargetTrainState, TrainState
from distributed_reinforcement_learning_tpu.agents.impala import ImpalaAgent, ImpalaBatch, ImpalaConfig
from distributed_reinforcement_learning_tpu.agents.r2d2 import R2D2Agent, R2D2Batch, R2D2Config
from distributed_reinforcement_learning_tpu.agents.xformer import XformerAgent, XformerBatch, XformerConfig

__all__ = [
    "ApexAgent",
    "ApexBatch",
    "ApexConfig",
    "ImpalaAgent",
    "ImpalaBatch",
    "ImpalaConfig",
    "R2D2Agent",
    "R2D2Batch",
    "R2D2Config",
    "TrainState",
    "XformerAgent",
    "XformerBatch",
    "XformerConfig",
    "TargetTrainState",
]
