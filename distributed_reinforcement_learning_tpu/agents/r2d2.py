"""R2D2 agent: recurrent Q-learning with stored state, burn-in, rescaling.

Re-design of `/root/reference/agent/r2d2.py` as jitted pure functions.
Semantics preserved:

- Main and target nets are unrolled over the full sequence from the
  **sequence-start stored state** h[0], c[0] (`agent/r2d2.py:110-111,135-136`),
  with done-masked state resets inside the unroll (`model/r2d2_lstm.py:78-80`).
- Burn-in: the first `burn_in` steps are sliced out of the loss, not the
  unroll (`agent/r2d2.py:64-68`).
- Double-Q over sequences + value-function rescaling on the target
  (`agent/r2d2.py:70-87`): target = h(h^{-1}(Q_target(s', a*)) * gamma + r).
- Loss: mean over time of squared TD, weighted per-sequence by IS weight
  (`agent/r2d2.py:88-89`); priority = |mean TD| per sequence
  (`agent/r2d2.py:151-153`).
- Optimizer: plain Adam(1e-4), no clipping (`agent/r2d2.py:91-92`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from distributed_reinforcement_learning_tpu.agents import common
from distributed_reinforcement_learning_tpu.models.r2d2_net import R2D2Net


@dataclasses.dataclass(frozen=True)
class R2D2Config:
    """Hyperparameters, mirroring the `r2d2` block of `config.json:2-24`."""

    obs_shape: tuple[int, ...] = (2,)
    num_actions: int = 2
    seq_len: int = 10
    burn_in: int = 5
    lstm_size: int = 512
    discount_factor: float = 0.997
    learning_rate: float = 1e-4
    rescale_eps: float = 1e-3
    dtype: Any = jnp.float32
    # None = the reference's |mean TD| sequence priority (parity quirk);
    # a float (paper: 0.9) = eta*max|TD| + (1-eta)*mean|TD| stable mode
    # (common.SequenceReplayLearnMixin._seq_priority).
    priority_eta: float | None = None
    # None = the reference's plain unclipped Adam (`agent/r2d2.py:91-92`);
    # a float adds global-norm clipping in front (stable mode — the
    # unclipped TD spikes at target syncs are a collapse driver).
    gradient_clip_norm: float | None = None
    # "mlp" = reference parity (its R2D2 is CartPole-only); "nature" /
    # "resnet" = conv torsos for pixel envs (the R2D2 paper's Atari
    # configuration — see models/r2d2_net.py).
    torso: str = "mlp"
    torso_width: int = 1
    # Fold /255 into conv0 (conv torsos): uint8 frames feed the model raw.
    fold_normalize: bool = False


class R2D2Batch(NamedTuple):
    """Sequence batch (queue payload of `distributed_queue/buffer_queue.py:7-91`)."""

    state: jax.Array  # [B, T, *obs] (int32-quantized *255 upstream, like the ref)
    previous_action: jax.Array  # [B, T] i32
    action: jax.Array  # [B, T] i32
    reward: jax.Array  # [B, T] f32
    done: jax.Array  # [B, T] bool
    initial_h: jax.Array  # [B, H] sequence-start stored h
    initial_c: jax.Array  # [B, H]


class R2D2Agent(common.SequenceReplayLearnMixin):
    def __init__(self, cfg: R2D2Config):
        self.cfg = cfg
        self.model = R2D2Net(num_actions=cfg.num_actions, lstm_size=cfg.lstm_size,
                             dtype=cfg.dtype, torso=cfg.torso,
                             torso_width=cfg.torso_width,
                             fold_normalize=cfg.fold_normalize)
        self.tx = common.adam_with_clip(cfg.learning_rate,
                                        clip_norm=cfg.gradient_clip_norm)
        self.act = jax.jit(self._act)
        self.td_error = jax.jit(self._td_error)
        self.learn = jax.jit(self._learn, donate_argnums=(0,))
        self.learn_many = jax.jit(
            common.scan_learn_weighted(self._learn), donate_argnums=(0,)
        )
        self.sync_target = jax.jit(lambda s: s.sync_target())

    def init_state(self, rng: jax.Array) -> common.TargetTrainState:
        dtype = jnp.uint8 if self.cfg.fold_normalize else jnp.float32
        obs = jnp.zeros((1, *self.cfg.obs_shape), dtype)
        pa = jnp.zeros((1,), jnp.int32)
        h = c = jnp.zeros((1, self.cfg.lstm_size), jnp.float32)
        params = self.model.init(rng, obs, pa, h, c)
        return common.TargetTrainState.create(params, self.tx)

    def _prep_obs(self, obs):
        """Normalize frames — or pass integer frames raw under
        `fold_normalize` (the conv owns the /255; ApexAgent._prep_obs)."""
        if (
            self.cfg.fold_normalize
            and len(self.cfg.obs_shape) == 3
            and jnp.issubdtype(obs.dtype, jnp.integer)
        ):
            return obs
        return common.normalize_obs(obs, self.cfg.dtype)

    def initial_lstm_state(self, batch_size: int) -> tuple[jax.Array, jax.Array]:
        z = jnp.zeros((batch_size, self.cfg.lstm_size), jnp.float32)
        return z, z

    # -- act -------------------------------------------------------------
    def _act(self, params, obs, h, c, prev_action, epsilon, rng):
        """Batched epsilon-greedy single step (`agent/r2d2.py:166-186`)."""
        q, new_h, new_c = self.model.apply(params, self._prep_obs(obs), prev_action, h, c)
        action = common.epsilon_greedy(q, epsilon, self.cfg.num_actions, rng)
        return action, q, new_h, new_c

    # -- shared sequence target math -------------------------------------
    # _td_error/_loss/_learn come from SequenceReplayLearnMixin; this
    # supplies the model forward. Burn-in, double-Q, and rescaling live
    # in `common.sequence_double_q_td` (`agent/r2d2.py:64-87`).
    def _sequence_td(self, params, target_params, batch: R2D2Batch):
        cfg = self.cfg
        obs = self._prep_obs(batch.state)
        unroll = lambda p: self.model.apply(
            p, obs, batch.previous_action, batch.done, batch.initial_h, batch.initial_c,
            method=self.model.unroll)
        discounts = (~batch.done).astype(jnp.float32) * cfg.discount_factor
        return common.sequence_double_q_td(
            unroll(params), unroll(target_params), batch.action, batch.reward,
            discounts, burn_in=cfg.burn_in, rescale_eps=cfg.rescale_eps)
