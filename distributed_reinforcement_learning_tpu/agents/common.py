"""Shared agent machinery: train states, optimizers, preprocessing.

Replaces the reference's TF1 graph plumbing (`tf.train.get_or_create_global_step`,
`tf.train.polynomial_decay`, `clip_by_global_norm` + optimizer at
`agent/impala.py:95-100`, `agent/apex.py:71-76`, `agent/r2d2.py:91-92`)
with optax transforms composed around jit-compiled pure loss functions.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import struct


@struct.dataclass
class TrainState:
    """Learner state: params + optimizer state + step counter.

    The reference kept these as TF global variables on the learner device;
    here it is an explicit pytree that pjit shards/replicates.
    """

    params: Any
    opt_state: Any
    step: jax.Array

    @classmethod
    def create(cls, params, tx: optax.GradientTransformation) -> "TrainState":
        return cls(params=params, opt_state=tx.init(params), step=jnp.zeros((), jnp.int32))


@struct.dataclass
class TargetTrainState:
    """TrainState plus a target network (Ape-X / R2D2)."""

    params: Any
    target_params: Any
    opt_state: Any
    step: jax.Array

    @classmethod
    def create(cls, params, tx: optax.GradientTransformation) -> "TargetTrainState":
        return cls(
            params=params,
            target_params=jax.tree.map(jnp.copy, params),
            opt_state=tx.init(params),
            step=jnp.zeros((), jnp.int32),
        )

    def sync_target(self) -> "TargetTrainState":
        """Copy main -> target, the reference's `main_to_target` grouped assign
        (`utils.py:23-31`)."""
        return self.replace(target_params=jax.tree.map(jnp.copy, self.params))


def polynomial_lr(start: float, end: float, transition_steps: int) -> optax.Schedule:
    """Linear (power-1 polynomial) decay, parity with `tf.train.polynomial_decay`
    as used at `agent/impala.py:96`.

    `transition_steps` is clamped to int32 range: the reference's apex config
    uses `learning_frame=1e14` (`config.json:102`), which no int32 step counter
    ever reaches — numerically identical, and keeps optax's schedule arithmetic
    in-range without enabling x64.
    """
    return optax.polynomial_schedule(
        init_value=start,
        end_value=end,
        power=1.0,
        transition_steps=min(int(transition_steps), 2**31 - 1),
    )


def rmsprop_with_clip(
    lr: optax.Schedule | float,
    clip_norm: float,
    decay: float = 0.99,
    eps: float = 0.1,
) -> optax.GradientTransformation:
    """IMPALA optimizer: global-norm clip -> RMSProp(decay, eps) -> lr.

    Matches `agent/impala.py:95-100`: RMSPropOptimizer(decay=.99, momentum=0,
    epsilon=.1) on globally-clipped gradients. optax's `scale_by_rms` uses
    `g * rsqrt(nu + eps)` — the same eps-inside-sqrt convention as TF1 —
    and `initial_scale=1.0` matches TF1's ones-initialized mean-square slot
    (optax defaults to 0, which would make the first updates ~3x larger).
    """
    return optax.chain(
        optax.clip_by_global_norm(clip_norm),
        optax.scale_by_rms(decay=decay, eps=eps, initial_scale=1.0),
        optax.scale_by_learning_rate(lr),
    )


def adam_with_clip(lr: optax.Schedule | float, clip_norm: float | None) -> optax.GradientTransformation:
    """Ape-X optimizer: global-norm clip -> Adam (`agent/apex.py:71-76`).

    Pass `clip_norm=None` for R2D2, whose reference applies plain Adam with
    no clipping (`agent/r2d2.py:91-92` — config's clip value is unused there).
    """
    steps = [optax.scale_by_adam(), optax.scale_by_learning_rate(lr)]
    if clip_norm is not None:
        steps.insert(0, optax.clip_by_global_norm(clip_norm))
    return optax.chain(*steps)


def clip_rewards(rewards: jax.Array, mode: str) -> jax.Array:
    """Reward clipping, parity with `agent/impala.py:45-49` / `agent/apex.py:38-42`.

    - `abs_one`: clip to [-1, 1]
    - `soft_asymmetric`: 5*tanh(r/5), scaled by 0.3 for negative rewards
    - `none`: pass through
    """
    if mode == "abs_one":
        return jnp.clip(rewards, -1.0, 1.0)
    if mode == "soft_asymmetric":
        squeezed = jnp.tanh(rewards / 5.0)
        return jnp.where(rewards < 0, 0.3 * squeezed, squeezed) * 5.0
    if mode == "none":
        return rewards
    raise ValueError(f"unknown reward_clipping mode: {mode!r}")


def normalize_obs(obs: jax.Array) -> jax.Array:
    """uint8 frames -> float32 in [0, 1]; float observations pass through.

    The reference normalizes `/255` at every feed (`agent/impala.py:119,133`);
    keeping frames uint8 until this point minimizes host->HBM bandwidth.
    """
    if jnp.issubdtype(obs.dtype, jnp.integer):
        return obs.astype(jnp.float32) / 255.0
    return obs.astype(jnp.float32)


def global_norm(tree) -> jax.Array:
    return optax.global_norm(tree)


def epsilon_greedy(
    q_values: jax.Array, epsilon: jax.Array | float, num_actions: int, rng: jax.Array
) -> jax.Array:
    """Batched epsilon-greedy action selection over `[N, A]` Q-values.

    Shared by Ape-X (`agent/apex.py:92-107`) and R2D2 (`agent/r2d2.py:166-186`);
    epsilon enters as data so one compiled act function serves the whole
    exploration schedule.
    """
    greedy = jnp.argmax(q_values, axis=-1)
    key_e, key_a = jax.random.split(rng)
    explore = jax.random.uniform(key_e, greedy.shape) <= epsilon
    random_action = jax.random.randint(key_a, greedy.shape, 0, num_actions)
    return jnp.where(explore, random_action, greedy)
