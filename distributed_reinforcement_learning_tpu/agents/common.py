"""Shared agent machinery: train states, optimizers, preprocessing.

Replaces the reference's TF1 graph plumbing (`tf.train.get_or_create_global_step`,
`tf.train.polynomial_decay`, `clip_by_global_norm` + optimizer at
`agent/impala.py:95-100`, `agent/apex.py:71-76`, `agent/r2d2.py:91-92`)
with optax transforms composed around jit-compiled pure loss functions.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import struct


@struct.dataclass
class TrainState:
    """Learner state: params + optimizer state + step counter.

    The reference kept these as TF global variables on the learner device;
    here it is an explicit pytree that pjit shards/replicates.
    """

    params: Any
    opt_state: Any
    step: jax.Array

    @classmethod
    def create(cls, params, tx: optax.GradientTransformation) -> "TrainState":
        return cls(params=params, opt_state=tx.init(params), step=jnp.zeros((), jnp.int32))


@struct.dataclass
class TargetTrainState:
    """TrainState plus a target network (Ape-X / R2D2)."""

    params: Any
    target_params: Any
    opt_state: Any
    step: jax.Array

    @classmethod
    def create(cls, params, tx: optax.GradientTransformation) -> "TargetTrainState":
        return cls(
            params=params,
            target_params=jax.tree.map(jnp.copy, params),
            opt_state=tx.init(params),
            step=jnp.zeros((), jnp.int32),
        )

    def sync_target(self) -> "TargetTrainState":
        """Copy main -> target, the reference's `main_to_target` grouped assign
        (`utils.py:23-31`)."""
        return self.replace(target_params=jax.tree.map(jnp.copy, self.params))


def polynomial_lr(start: float, end: float, transition_steps: int) -> optax.Schedule:
    """Linear (power-1 polynomial) decay, parity with `tf.train.polynomial_decay`
    as used at `agent/impala.py:96`.

    `transition_steps` is clamped to int32 range: the reference's apex config
    uses `learning_frame=1e14` (`config.json:102`), which no int32 step counter
    ever reaches — numerically identical, and keeps optax's schedule arithmetic
    in-range without enabling x64.
    """
    return optax.polynomial_schedule(
        init_value=start,
        end_value=end,
        power=1.0,
        transition_steps=min(int(transition_steps), 2**31 - 1),
    )


def rmsprop_with_clip(
    lr: optax.Schedule | float,
    clip_norm: float,
    decay: float = 0.99,
    eps: float = 0.1,
) -> optax.GradientTransformation:
    """IMPALA optimizer: global-norm clip -> RMSProp(decay, eps) -> lr.

    Matches `agent/impala.py:95-100`: RMSPropOptimizer(decay=.99, momentum=0,
    epsilon=.1) on globally-clipped gradients. optax's `scale_by_rms` uses
    `g * rsqrt(nu + eps)` — the same eps-inside-sqrt convention as TF1 —
    and `initial_scale=1.0` matches TF1's ones-initialized mean-square slot
    (optax defaults to 0, which would make the first updates ~3x larger).
    """
    return optax.chain(
        optax.clip_by_global_norm(clip_norm),
        optax.scale_by_rms(decay=decay, eps=eps, initial_scale=1.0),
        optax.scale_by_learning_rate(lr),
    )


def adam_with_clip(lr: optax.Schedule | float, clip_norm: float | None) -> optax.GradientTransformation:
    """Ape-X optimizer: global-norm clip -> Adam (`agent/apex.py:71-76`).

    Pass `clip_norm=None` for R2D2, whose reference applies plain Adam with
    no clipping (`agent/r2d2.py:91-92` — config's clip value is unused there).
    """
    steps = [optax.scale_by_adam(), optax.scale_by_learning_rate(lr)]
    if clip_norm is not None:
        steps.insert(0, optax.clip_by_global_norm(clip_norm))
    return optax.chain(*steps)


def clip_rewards(rewards: jax.Array, mode: str) -> jax.Array:
    """Reward clipping, parity with `agent/impala.py:45-49` / `agent/apex.py:38-42`.

    - `abs_one`: clip to [-1, 1]
    - `soft_asymmetric`: 5*tanh(r/5), scaled by 0.3 for negative rewards
    - `none`: pass through
    """
    if mode == "abs_one":
        return jnp.clip(rewards, -1.0, 1.0)
    if mode == "soft_asymmetric":
        squeezed = jnp.tanh(rewards / 5.0)
        return jnp.where(rewards < 0, 0.3 * squeezed, squeezed) * 5.0
    if mode == "none":
        return rewards
    raise ValueError(f"unknown reward_clipping mode: {mode!r}")


def normalize_obs(obs: jax.Array, dtype: Any = jnp.float32) -> jax.Array:
    """uint8 frames -> `dtype` in [0, 1]; float observations cast through.

    The reference normalizes `/255` at every feed (`agent/impala.py:119,133`);
    keeping frames uint8 until this point minimizes host->HBM bandwidth.

    `dtype` should be the model's compute dtype: normalizing straight
    into bf16 (a bf16 multiply by the constant 1/255) avoids
    materializing an fp32 copy of the frame tensor — 4x the uint8 batch
    in HBM traffic — when XLA does not fuse the convert chain into the
    first conv. The 1/255-scaled uint8 lattice is not exactly
    representable either way; in bf16 adjacent high-intensity levels can
    round together, which is the standard bf16-frames trade every TPU RL
    stack makes.
    """
    if jnp.issubdtype(obs.dtype, jnp.integer):
        return obs.astype(dtype) * jnp.asarray(1.0 / 255.0, dtype)
    return obs.astype(dtype)


def global_norm(tree) -> jax.Array:
    return optax.global_norm(tree)


def scan_learn(learn_fn):
    """Wrap `(state, batch) -> (state, metrics)` into a K-step
    `(state, stacked_batches[K, ...]) -> (state, stacked_metrics)`.

    `lax.scan` runs K optimizer steps back-to-back in ONE compiled
    dispatch — the math is identical to K sequential `learn` calls (the
    step counter, LR schedule, and optimizer moments all advance inside
    the scan), but the host never intervenes between steps. Through a
    remote or tunneled device, the per-step dispatch gap costs more than
    the step itself; this strips it. The trade is freshness: weights
    publish at K-step granularity (IMPALA's V-trace corrects exactly
    this off-policy staleness).
    """

    def many(state, batches):
        return jax.lax.scan(lambda s, b: learn_fn(s, b), state, batches)

    return many


def scan_learn_weighted(learn_fn):
    """`scan_learn` for the replay agents' `(state, batch, is_weight) ->
    (state, priorities, metrics)` signature.

    Returns `(state, stacked_priorities[K, B], stacked_metrics)`. Note
    the replay semantics under K>1: all K batches are sampled BEFORE any
    of the K updates, so priority updates land K-1 steps stale — the
    same staleness distributed Ape-X already accepts from its actors
    (`/root/reference/train_apex.py:207-217` pushes transitions scored
    by old weights); keep K well under the target-sync interval.
    """

    def many(state, batches, is_weights):
        def body(s, bw):
            s, priorities, metrics = learn_fn(s, *bw)
            return s, (priorities, metrics)

        state, (priorities, metrics) = jax.lax.scan(body, state, (batches, is_weights))
        return state, priorities, metrics

    return many


def epsilon_greedy(
    q_values: jax.Array, epsilon: jax.Array | float, num_actions: int, rng: jax.Array
) -> jax.Array:
    """Batched epsilon-greedy action selection over `[N, A]` Q-values.

    Shared by Ape-X (`agent/apex.py:92-107`) and R2D2 (`agent/r2d2.py:166-186`);
    epsilon enters as data so one compiled act function serves the whole
    exploration schedule.
    """
    greedy = jnp.argmax(q_values, axis=-1)
    key_e, key_a = jax.random.split(rng)
    explore = jax.random.uniform(key_e, greedy.shape) <= epsilon
    random_action = jax.random.randint(key_a, greedy.shape, 0, num_actions)
    return jnp.where(explore, random_action, greedy)


def sequence_double_q_td(main_q, target_q, action, reward, discounts,
                         *, burn_in: int, rescale_eps: float):
    """Shared R2D2-family target math (`agent/r2d2.py:64-87`).

    Burn-in slice, (t, t+1) alignment, double-Q action selection on the
    main net, value-function rescaling on the bootstrapped target.
    Inputs are full-sequence `[B, T, ...]`; returns (target_value, sav)
    over the supervised positions. One implementation serves both the
    LSTM and the transformer agents so the replay semantics cannot drift.
    """
    from distributed_reinforcement_learning_tpu.ops import dqn, value_rescale

    b = burn_in
    main_b, target_b = main_q[:, b:], target_q[:, b:]
    reward_b, disc_b, action_b = reward[:, b:], discounts[:, b:], action[:, b:]

    sav = dqn.take_state_action_value(main_b[:, :-1], action_b[:, :-1])
    next_action = jnp.argmax(main_b[:, 1:], axis=-1)
    next_sav = dqn.take_state_action_value(target_b[:, 1:], next_action)

    descaled = value_rescale.inverse_value_rescale(next_sav, rescale_eps)
    raw_target = jax.lax.stop_gradient(descaled * disc_b[:, :-1] + reward_b[:, :-1])
    target_value = value_rescale.value_rescale(raw_target, rescale_eps)
    return target_value, sav


class SequenceReplayLearnMixin:
    """td_error/loss/learn shared by the sequence-replay agents.

    Host class provides `_sequence_td(params, target_params, batch)`
    -> (target_value, sav) — optionally with a third scalar model aux
    loss (e.g. the MoE router's load-balancing term), added to the TD
    loss as-is — and `self.tx`. Loss = IS-weighted mean over time of
    squared TD (`agent/r2d2.py:88-89`).

    Priority: the reference's quirk |mean_t TD| (`agent/r2d2.py:151-153`
    — signed TDs cancel across the sequence, so a high-error sequence
    can score ~0 and starve) is the default for parity. Setting
    `cfg.priority_eta` switches to the R2D2 paper's stable mixture
    p = eta*max_t|TD| + (1-eta)*mean_t|TD| (Kapturowski et al. 2019,
    eta=0.9) — the known fix for the reference's replay-collapse cycles
    (VERDICT r3 item 5).
    """

    def _seq_priority(self, tv, sav):
        delta = tv - sav
        eta = getattr(self.cfg, "priority_eta", None)
        if eta is None:
            return jnp.abs(jnp.mean(delta, axis=1))  # reference parity
        ad = jnp.abs(delta)
        return eta * jnp.max(ad, axis=1) + (1.0 - eta) * jnp.mean(ad, axis=1)

    def _td_error(self, state, batch):
        tv, sav = self._sequence_td(state.params, state.target_params, batch)[:2]
        return self._seq_priority(tv, sav)

    def _loss(self, params, target_params, batch, is_weight):
        out = self._sequence_td(params, target_params, batch)
        tv, sav = out[:2]
        aux = out[2] if len(out) > 2 else 0.0
        per_seq = jnp.mean(jnp.square(tv - sav), axis=1)
        loss = jnp.mean(per_seq * is_weight) + aux
        priorities = self._seq_priority(tv, sav)
        return loss, priorities

    def _learn(self, state, batch, is_weight, axis_name: str | None = None):
        (loss, priorities), grads = jax.value_and_grad(self._loss, has_aux=True)(
            state.params, state.target_params, batch, is_weight
        )
        if axis_name is not None:
            # shard_map data-parallel callers (runtime/anakin_r2d2.py mesh
            # mode): pmean turns per-shard gradients into the global-batch
            # gradient so replicated params stay identical across devices.
            grads = jax.lax.pmean(grads, axis_name)
            loss = jax.lax.pmean(loss, axis_name)
        updates, opt_state = self.tx.update(grads, state.opt_state, state.params)
        params = jax.tree.map(lambda p, u: p + u, state.params, updates)
        new_state = state.replace(params=params, opt_state=opt_state, step=state.step + 1)
        metrics = {"loss": loss, "grad_norm": global_norm(grads)}
        return new_state, priorities, metrics
