"""Transformer-IMPALA agent: V-trace actor-critic on the causal
transformer trunk.

Fifth algorithm family, composing the framework's two halves: IMPALA's
off-policy-corrected actor-critic math (`agents/impala.py`, traced to
`/root/reference/agent/impala.py:63-100`) over the transformer trunk of
`models/transformer_net.py` (`head="actor_critic"`). What changes vs the
conv-LSTM IMPALA:

- No stored state at all. The conv-LSTM learner re-seeds every timestep
  from actor-recorded (h, c) (`model/impala_actor_critic.py:73-114`);
  here the unroll IS the context — one `[B, T]` forward with episode-
  segment masking standing in for done-masked state resets, and the
  queue payload drops the two `[B, T, H]` state tensors.
- The actor acts on a window of the CURRENT unroll's steps (reset at
  each unroll start, unlike the Transformer-R2D2 actor's persistent
  rolling window) and records the window-final softmax as the behavior
  policy — so V-trace's rho compares policies computed from identical
  context (`runtime/ximpala_runner.py`).
- Every transformer body feature applies: ring/zigzag/Ulysses sequence
  parallelism (V-trace over a sequence-sharded forward — a combination
  no recurrent IMPALA can express), MoE experts, GPipe pipelining,
  activation remat.

Loss math parity with `agents/impala.py:_loss` (same double V-trace over
first/middle views, pg advantage, sum-reduced losses, RMSProp + poly LR
+ global-norm clip); only the forward differs.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from distributed_reinforcement_learning_tpu.agents import common
from distributed_reinforcement_learning_tpu.agents.xformer import (
    build_transformer_models,
    init_transformer_params,
)
from distributed_reinforcement_learning_tpu.ops import vtrace


@dataclasses.dataclass(frozen=True)
class XImpalaConfig:
    """IMPALA hyperparameters + transformer body knobs.

    Field names deliberately mirror `ImpalaConfig` (loss/optimizer side)
    and `XformerConfig` (body side) so config sections and
    `build_transformer_models` serve all of them.
    """

    obs_shape: tuple[int, ...] = (2,)
    num_actions: int = 2
    trajectory: int = 20  # unroll length == acting window
    d_model: int = 256
    num_heads: int = 4
    num_layers: int = 2
    discount_factor: float = 0.99
    baseline_loss_coef: float = 1.0
    entropy_coef: float = 0.05
    gradient_clip_norm: float = 40.0
    reward_clipping: str = "abs_one"
    start_learning_rate: float = 6e-4
    end_learning_rate: float = 0.0
    learning_frame: int = 1_000_000_000
    dtype: Any = jnp.float32
    # Body knobs consumed by build_transformer_models:
    attention: str = "dense"
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    moe_aux_weight: float = 1e-2
    pipeline: bool = False
    pipeline_microbatches: int = 2
    pipeline_stages: int = 0
    stacked: bool = False
    remat: bool = False


class XImpalaBatch(NamedTuple):
    """One learner batch: `[B, T, ...]` unrolls — the IMPALA queue
    payload (`agents/impala.py` ImpalaBatch) minus the stored (h, c).

    `done` carries the RECORDED flags (life-loss shaping may set it
    where the episode continues, `train_impala.py:149-154`) and gates
    the V-trace discounts; `env_done` carries the true episode ends and
    gates the attention segments — the conv-LSTM parity point: its
    actor-recorded (h, c) only reset at env done, so the transformer's
    context must also span life losses, else the learn-time policy sees
    truncated context the behavior policy never saw."""

    state: jax.Array  # [B, T, *obs]
    reward: jax.Array  # [B, T] f32 raw rewards
    action: jax.Array  # [B, T] i32
    done: jax.Array  # [B, T] bool recorded (shaped) flags -> discounts
    env_done: jax.Array  # [B, T] bool true episode ends -> attention segments
    behavior_policy: jax.Array  # [B, T, A] f32 softmax at act time
    previous_action: jax.Array  # [B, T] i32


class XImpalaActOutput(NamedTuple):
    action: jax.Array  # [N]
    policy: jax.Array  # [N, A] window-final softmax (the behavior policy)


class XImpalaAgent:
    """Thin wrapper binding config + transformer model to jitted pure
    functions; learn signature matches ImpalaAgent's, so the IMPALA
    learner runner and ShardedLearner defaults apply unchanged."""

    def __init__(self, cfg: XImpalaConfig, mesh=None):
        self.cfg = cfg
        self._mesh = mesh
        self.model, self._dense_model = build_transformer_models(
            cfg, mesh, seq_len=cfg.trajectory, head="actor_critic")
        self._schedule = common.polynomial_lr(
            cfg.start_learning_rate, cfg.end_learning_rate, cfg.learning_frame)
        self.tx = common.rmsprop_with_clip(self._schedule, cfg.gradient_clip_norm)
        self.act = jax.jit(self._act)
        self.learn = jax.jit(self._learn, donate_argnums=(0,))
        self.learn_many = jax.jit(common.scan_learn(self._learn), donate_argnums=(0,))

    # -- init ------------------------------------------------------------
    def init_state(self, rng: jax.Array) -> common.TrainState:
        params = init_transformer_params(
            self.model, self.cfg, self._mesh, seq_len=self.cfg.trajectory, rng=rng)
        return common.TrainState.create(params, self.tx)

    # -- act -------------------------------------------------------------
    def _act(self, params, obs_win, prev_action_win, done_win, rng) -> XImpalaActOutput:
        """Sample from the window-final softmax policy.

        Same sampling parity as the conv-LSTM agent
        (`agents/impala.py:_act` <- `agent/impala.py:118-130`), with the
        rolling window as the recurrent state; always runs the
        plain-apply twin (collective schedules are wrong on an actor
        host).
        """
        policy, _ = self._dense_model.apply(
            params, common.normalize_obs(obs_win, self.cfg.dtype), prev_action_win, done_win)
        policy = policy[:, -1]
        action = jax.random.categorical(rng, jnp.log(policy + 1e-20), axis=-1)
        return XImpalaActOutput(action, policy)

    # -- learn -----------------------------------------------------------
    def _forward(self, params, batch: XImpalaBatch):
        obs = common.normalize_obs(batch.state, self.cfg.dtype)
        # env_done, not the shaped done: attention context follows true
        # episode boundaries (see XImpalaBatch).
        if self.cfg.num_experts:
            (policy, value), sown = self.model.apply(
                params, obs, batch.previous_action, batch.env_done,
                mutable=["losses"])
            aux = self.cfg.moe_aux_weight * sum(
                jnp.asarray(x) for x in jax.tree.leaves(sown.get("losses", {})))
            return policy, value, aux
        policy, value = self.model.apply(
            params, obs, batch.previous_action, batch.env_done)
        return policy, value, 0.0

    def _loss(self, params, batch: XImpalaBatch):
        cfg = self.cfg
        policy, value, aux = self._forward(params, batch)

        clipped_r = common.clip_rewards(batch.reward, cfg.reward_clipping)
        discounts = (~batch.done).astype(jnp.float32) * cfg.discount_factor

        first_p, middle_p, _ = vtrace.split_data(policy)
        first_v, middle_v, last_v = vtrace.split_data(value)
        first_a, middle_a, _ = vtrace.split_data(batch.action)
        first_r, middle_r, _ = vtrace.split_data(clipped_r)
        first_d, middle_d, _ = vtrace.split_data(discounts)
        first_b, middle_b, _ = vtrace.split_data(batch.behavior_policy)

        vs, rho = vtrace.from_softmax(
            behavior_policy=first_b, target_policy=first_p, actions=first_a,
            discounts=first_d, rewards=first_r, values=first_v, next_values=middle_v)
        vs_plus_1, _ = vtrace.from_softmax(
            behavior_policy=middle_b, target_policy=middle_p, actions=middle_a,
            discounts=middle_d, rewards=middle_r, values=middle_v, next_values=last_v)

        pg_adv = jax.lax.stop_gradient(rho * (first_r + first_d * vs_plus_1 - first_v))

        pi_loss = vtrace.policy_gradient_loss(first_p, first_a, pg_adv)
        v_loss = vtrace.baseline_loss(vs, first_v)
        ent_loss = vtrace.entropy_loss(first_p)
        total = (pi_loss + cfg.baseline_loss_coef * v_loss
                 + cfg.entropy_coef * ent_loss + aux)
        metrics = {
            "pi_loss": pi_loss,
            "baseline_loss": v_loss,
            "entropy": ent_loss,
            "total_loss": total,
        }
        return total, metrics

    def _learn(self, state: common.TrainState, batch: XImpalaBatch):
        grads, metrics = jax.grad(self._loss, has_aux=True)(state.params, batch)
        updates, opt_state = self.tx.update(grads, state.opt_state, state.params)
        params = jax.tree.map(lambda p, u: p + u, state.params, updates)
        metrics["grad_norm"] = common.global_norm(grads)
        metrics["learning_rate"] = self._schedule(state.step)
        new_state = state.replace(params=params, opt_state=opt_state, step=state.step + 1)
        return new_state, metrics
