"""IMPALA agent: V-trace actor-critic as pure init/act/learn functions.

Re-design of `/root/reference/agent/impala.py`. The reference's `Agent`
class builds a TF1 graph with a 1-step inference head plus 3*(T-2)
replicated training copies; here the same math is two jit-compiled pure
functions over one flax model:

- `act`: single-step policy/value + LSTM state advance (the actor hot
  path, `agent/impala.py:118-130`).
- `learn`: stored-state batched forward over `[B, T]`, double V-trace over
  the first/middle time views, sum-reduced losses, RMSProp + polynomial
  LR + global-norm clip (`agent/impala.py:63-100`).

Loss math parity (`agent/impala.py:63-93`):
    vs, rho     = vtrace(first view; next_values = middle values)
    vs_plus_1   = vtrace(middle view; next_values = last values)
    pg_adv      = rho * (r_first + gamma_first * vs_plus_1 - V_first)
    total = pi_loss + c_v * baseline_loss + c_e * entropy_loss
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from distributed_reinforcement_learning_tpu.agents import common
from distributed_reinforcement_learning_tpu.models.impala_net import ImpalaActorCritic, apply_stored_state
from distributed_reinforcement_learning_tpu.ops import vtrace


@dataclasses.dataclass(frozen=True)
class ImpalaConfig:
    """Hyperparameters, mirroring the `impala` block of `config.json:25-67`."""

    obs_shape: tuple[int, ...] = (84, 84, 4)
    num_actions: int = 18
    trajectory: int = 20
    lstm_size: int = 256
    discount_factor: float = 0.99
    baseline_loss_coef: float = 1.0
    entropy_coef: float = 0.05
    gradient_clip_norm: float = 40.0
    reward_clipping: str = "abs_one"
    start_learning_rate: float = 6e-4
    end_learning_rate: float = 0.0
    learning_frame: int = 1_000_000_000
    dtype: Any = jnp.float32
    # Rematerialize the [B*T] stored-state forward in the backward pass
    # (jax.checkpoint): trades ~1 extra forward of FLOPs for not holding
    # conv/LSTM activations of B*T frames in HBM — the knob that lets
    # batch size keep scaling once activations, not params, bound memory.
    remat: bool = False
    # Fold the /255 frame normalization into conv0's kernel (NatureConv
    # input_scale): uint8 frames feed the model raw, skipping the
    # full-frame elementwise normalize pass. Exact same math modulo one
    # rounding on the kernel; no-op for vector observations.
    fold_normalize: bool = False
    # "nature" (reference parity, model/impala_actor_critic.py:4-10) or
    # "resnet" — the IMPALA paper's deep torso, `torso_width`-multiplied
    # channels (models/torso.py ResNetTorso, the MXU-dense variant).
    torso: str = "nature"
    torso_width: int = 1


class ImpalaBatch(NamedTuple):
    """One learner batch: `[B, T, ...]` unrolls (queue payload, SURVEY §2 row 7)."""

    state: jax.Array  # [B, T, *obs] uint8 (or float for vector envs)
    reward: jax.Array  # [B, T] f32 raw rewards
    action: jax.Array  # [B, T] i32
    done: jax.Array  # [B, T] bool
    behavior_policy: jax.Array  # [B, T, A] f32 softmax at act time
    previous_action: jax.Array  # [B, T] i32
    initial_h: jax.Array  # [B, T, H] actor-recorded per-step LSTM h
    initial_c: jax.Array  # [B, T, H]


class ActOutput(NamedTuple):
    action: jax.Array
    policy: jax.Array
    h: jax.Array
    c: jax.Array


class ImpalaAgent:
    """Thin wrapper binding config + model to jitted pure functions."""

    def __init__(self, cfg: ImpalaConfig):
        self.cfg = cfg
        self.model = ImpalaActorCritic(
            num_actions=cfg.num_actions, lstm_size=cfg.lstm_size, dtype=cfg.dtype,
            fold_normalize=cfg.fold_normalize,
            torso=cfg.torso, torso_width=cfg.torso_width,
        )
        self._schedule = common.polynomial_lr(
            cfg.start_learning_rate, cfg.end_learning_rate, cfg.learning_frame
        )
        self.tx = common.rmsprop_with_clip(self._schedule, cfg.gradient_clip_norm)
        self.act = jax.jit(self._act)
        self.learn = jax.jit(self._learn, donate_argnums=(0,))
        # K optimizer steps per dispatch (lax.scan over stacked batches):
        # strips the per-step host->device dispatch gap, which through a
        # remote/tunneled device costs more than the step itself.
        self.learn_many = jax.jit(common.scan_learn(self._learn), donate_argnums=(0,))

    # -- init ------------------------------------------------------------
    def init_state(self, rng: jax.Array) -> common.TrainState:
        obs = jnp.zeros((1, *self.cfg.obs_shape), jnp.float32)
        pa = jnp.zeros((1,), jnp.int32)
        h = c = jnp.zeros((1, self.cfg.lstm_size), jnp.float32)
        params = self.model.init(rng, obs, pa, h, c)
        return common.TrainState.create(params, self.tx)

    def initial_lstm_state(self, batch_size: int) -> tuple[jax.Array, jax.Array]:
        z = jnp.zeros((batch_size, self.cfg.lstm_size), jnp.float32)
        return z, z

    def _prep_obs(self, obs: jax.Array) -> jax.Array:
        """Normalize frames — or pass integer frames raw when the model
        folds the /255 into conv0 (`fold_normalize`)."""
        if (
            self.cfg.fold_normalize
            and len(self.cfg.obs_shape) == 3
            and jnp.issubdtype(obs.dtype, jnp.integer)
        ):
            return obs
        return common.normalize_obs(obs, self.cfg.dtype)

    # -- act -------------------------------------------------------------
    def _act(self, params, obs, prev_action, h, c, rng) -> ActOutput:
        """Batched single-step act: sample from the softmax policy.

        Parity with `agent/impala.py:118-130` (np.random.choice(p=policy) ->
        jax.random.categorical over log-probabilities), batched over the
        actor's parallel envs instead of one `sess.run` per env.
        """
        out = self.model.apply(params, self._prep_obs(obs), prev_action, h, c)
        action = jax.random.categorical(rng, jnp.log(out.policy + 1e-20), axis=-1)
        return ActOutput(action, out.policy, out.h, out.c)

    # -- learn -----------------------------------------------------------
    def _loss(self, params, batch: ImpalaBatch):
        cfg = self.cfg
        forward = functools.partial(apply_stored_state, self.model)
        if cfg.remat:
            forward = jax.checkpoint(forward)
        policy, value = forward(
            params,
            self._prep_obs(batch.state),
            batch.previous_action,
            batch.initial_h,
            batch.initial_c,
        )

        clipped_r = common.clip_rewards(batch.reward, cfg.reward_clipping)
        discounts = (~batch.done).astype(jnp.float32) * cfg.discount_factor

        first_p, middle_p, _ = vtrace.split_data(policy)
        first_v, middle_v, last_v = vtrace.split_data(value)
        first_a, middle_a, _ = vtrace.split_data(batch.action)
        first_r, middle_r, _ = vtrace.split_data(clipped_r)
        first_d, middle_d, _ = vtrace.split_data(discounts)
        first_b, middle_b, _ = vtrace.split_data(batch.behavior_policy)

        vs, rho = vtrace.from_softmax(
            behavior_policy=first_b, target_policy=first_p, actions=first_a,
            discounts=first_d, rewards=first_r, values=first_v, next_values=middle_v)
        vs_plus_1, _ = vtrace.from_softmax(
            behavior_policy=middle_b, target_policy=middle_p, actions=middle_a,
            discounts=middle_d, rewards=middle_r, values=middle_v, next_values=last_v)

        pg_adv = jax.lax.stop_gradient(rho * (first_r + first_d * vs_plus_1 - first_v))

        pi_loss = vtrace.policy_gradient_loss(first_p, first_a, pg_adv)
        v_loss = vtrace.baseline_loss(vs, first_v)
        ent_loss = vtrace.entropy_loss(first_p)
        total = pi_loss + cfg.baseline_loss_coef * v_loss + cfg.entropy_coef * ent_loss
        metrics = {
            "pi_loss": pi_loss,
            "baseline_loss": v_loss,
            "entropy": ent_loss,
            "total_loss": total,
        }
        return total, metrics

    def _learn(self, state: common.TrainState, batch: ImpalaBatch):
        grads, metrics = jax.grad(self._loss, has_aux=True)(state.params, batch)
        updates, opt_state = self.tx.update(grads, state.opt_state, state.params)
        params = jax.tree.map(lambda p, u: p + u, state.params, updates)
        metrics["grad_norm"] = common.global_norm(grads)
        metrics["learning_rate"] = self._schedule(state.step)
        new_state = state.replace(params=params, opt_state=opt_state, step=state.step + 1)
        return new_state, metrics
