"""distributed_reinforcement_learning_tpu — a TPU-native distributed RL framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
``kiminh/distributed_reinforcement_learning`` (TF1 actor/learner RL):

- Three algorithms: IMPALA (V-trace), Ape-X DQN (prioritized replay),
  R2D2 (recurrent replay with stored LSTM state + burn-in).
- N-actor / 1-learner topology, generalized to a multi-chip data-parallel
  learner over a ``jax.sharding.Mesh``.
- Host-side data plane (FIFO trajectory queue, prioritized replay,
  socket transport) replacing TF1's distributed runtime.

Layout (mirrors the layer map in SURVEY.md §1):

- ``ops``      — pure losses/returns: V-trace, double-Q, value rescaling.
- ``models``   — flax networks: conv-LSTM actor-critic, dueling CNN, recurrent Q.
- ``agents``   — pure ``init/act/learn`` functions + train states per algorithm.
- ``envs``     — numpy CartPole (+POMDP), Atari preprocessing, synthetic envs.
- ``data``     — trajectory structures, FIFO queue, prioritized replay.
- ``parallel`` — device mesh, sharding rules, multi-chip learn steps.
- ``runtime``  — actor/learner loops, transport, launchers.
- ``utils``    — config, checkpointing, metrics, timing.
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("DRL_SANITIZE", "") == "1":
    # Runtime concurrency sanitizer (tools/drlint/rt, docs/
    # static_analysis.md "Runtime sanitizer"): must install BEFORE any
    # submodule body runs so every threading ctor site in the package
    # hands out instrumented locks. Zero overhead when the gate is off
    # — this block is the only thing the unsanitized import pays.
    try:
        from tools.drlint.rt import install as _drlint_rt_install
    except ImportError:
        import sys as _sys

        print("drlint-rt: DRL_SANITIZE=1 but tools.drlint is not "
              "importable (run from the repo root); sanitizer disabled",
              file=_sys.stderr)
    else:
        _drlint_rt_install()
