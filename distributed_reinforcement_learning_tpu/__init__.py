"""distributed_reinforcement_learning_tpu — a TPU-native distributed RL framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
``kiminh/distributed_reinforcement_learning`` (TF1 actor/learner RL):

- Three algorithms: IMPALA (V-trace), Ape-X DQN (prioritized replay),
  R2D2 (recurrent replay with stored LSTM state + burn-in).
- N-actor / 1-learner topology, generalized to a multi-chip data-parallel
  learner over a ``jax.sharding.Mesh``.
- Host-side data plane (FIFO trajectory queue, prioritized replay,
  socket transport) replacing TF1's distributed runtime.

Layout (mirrors the layer map in SURVEY.md §1):

- ``ops``      — pure losses/returns: V-trace, double-Q, value rescaling.
- ``models``   — flax networks: conv-LSTM actor-critic, dueling CNN, recurrent Q.
- ``agents``   — pure ``init/act/learn`` functions + train states per algorithm.
- ``envs``     — numpy CartPole (+POMDP), Atari preprocessing, synthetic envs.
- ``data``     — trajectory structures, FIFO queue, prioritized replay.
- ``parallel`` — device mesh, sharding rules, multi-chip learn steps.
- ``runtime``  — actor/learner loops, transport, launchers.
- ``utils``    — config, checkpointing, metrics, timing.
"""

__version__ = "0.1.0"
