"""Chrome-trace/Perfetto span emitter: a host-side timeline per process.

The learner's `StageTimer` already measures dequeue/learn/publish, but it
reduces everything to windowed means — "publish averaged 3 ms" cannot
show the one 400 ms stall that starved the chip. A `TraceEmitter`
records every stage invocation as a complete-duration event (`ph: "X"`)
in the Trace Event Format, so `trace-<role>-<rank>.json` opens directly
in Perfetto (ui.perfetto.dev) or chrome://tracing — next to the XLA
device trace `ProfilerSession` captures, giving host timeline + device
timeline side by side.

Timestamps are wall-clock epoch microseconds (not perf_counter): spans
from different PROCESSES of one run then align on a shared axis, which
is what makes the merged cross-role trace of `scripts/obs_report.py`
meaningful (actor enqueue stalls visibly overlapping learner queue
waits). Durations come from `perf_counter` deltas, so they stay
monotonic even if the wall clock steps.

The file is streamed: events append as a JSON array that `close()`
terminates, so a crashed process still leaves a loadable trace
(`load_trace` tolerates the missing `]`; a clean close writes strictly
valid JSON). A bounded event cap (`DRL_TRACE_MAX_EVENTS`) keeps a
long run from growing the trace without limit — past it, new events are
counted as dropped, not stored.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Iterator

DEFAULT_MAX_EVENTS = 100_000


class TraceEmitter:
    """Buffered Chrome-trace writer for one process's host spans."""

    # Concurrency map (tools/drlint lock-discipline): every span
    # emitter shares the buffer with the telemetry flush thread; all
    # five fields only move under `_lock` (emit/flush/close).
    _GUARDED_BY = {
        "dropped": "_lock",
        "_pending": "_lock",
        "_written": "_lock",
        "_file": "_lock",
        "_closed": "_lock",
    }

    def __init__(
        self,
        path: str,
        label: str,
        pid: int | None = None,
        max_events: int | None = None,
    ):
        self.path = path
        self.label = label
        self.pid = os.getpid() if pid is None else pid
        if max_events is None:
            max_events = int(os.environ.get("DRL_TRACE_MAX_EVENTS",
                                            str(DEFAULT_MAX_EVENTS)))
        self.max_events = max_events
        self.dropped = 0
        self._lock = threading.Lock()
        self._pending: list[dict] = []
        self._written = 0
        self._file = None
        self._closed = False

    def emit(self, name: str, wall_start_s: float, duration_s: float,
             tid: int | None = None, args: dict | None = None) -> None:
        """Record one complete span (start wall-clock seconds + duration)."""
        event = {
            "name": name,
            "ph": "X",
            "ts": round(wall_start_s * 1e6, 1),
            "dur": round(duration_s * 1e6, 1),
            "pid": self.pid,
            "tid": tid if tid is not None else threading.get_ident(),
            "cat": "host",
        }
        if args:
            event["args"] = args
        with self._lock:
            if self._closed or self._written + len(self._pending) >= self.max_events:
                self.dropped += 1
                return
            self._pending.append(event)

    @contextlib.contextmanager
    def span(self, name: str, args: dict | None = None) -> Iterator[None]:
        wall = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.emit(name, wall, time.perf_counter() - t0, args=args)

    def _open(self):
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        f = open(self.path, "w")
        f.write("[\n")
        # Process metadata so viewers label the track by role, not pid.
        f.write(json.dumps({"ph": "M", "name": "process_name", "pid": self.pid,
                            "tid": 0, "args": {"name": self.label}}))
        return f

    def flush(self) -> None:
        """Append pending events to the on-disk (still-open) JSON array."""
        with self._lock:
            if self._closed or not self._pending:
                return
            if self._file is None:
                self._file = self._open()
            for event in self._pending:
                self._file.write(",\n" + json.dumps(event))
            self._written += len(self._pending)
            self._pending.clear()
            self._file.flush()

    def close(self) -> None:
        """Terminate the array: the file becomes strictly valid JSON."""
        self.flush()
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._file is None:
                self._file = self._open()
            if self.dropped:
                self._file.write(",\n" + json.dumps(
                    {"ph": "M", "name": "trace_dropped_events", "pid": self.pid,
                     "tid": 0, "args": {"dropped": self.dropped}}))
            self._file.write("\n]\n")
            self._file.close()
            self._file = None


def load_trace(path: str) -> list[dict]:
    """Load a trace written by `TraceEmitter` (or any Chrome-trace JSON).

    Tolerates the streaming form a crashed process leaves behind (open
    array, no terminator) and the `{"traceEvents": [...]}` wrapper some
    tools produce.
    """
    with open(path) as f:
        text = f.read().strip()
    try:
        data = json.loads(text)
    except json.JSONDecodeError:
        try:
            data = json.loads(text.rstrip().rstrip(",") + "\n]")
        except json.JSONDecodeError:
            # A SIGTERM mid-flush can cut the final event at an arbitrary
            # byte. Events are one-per-line on disk, so recover every
            # complete line and drop the torn tail — one mangled shard
            # must not abort the whole run's report.
            data = []
            for line in text.splitlines():
                line = line.strip().rstrip(",")
                if not line or line in ("[", "]"):
                    continue
                try:
                    data.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    if isinstance(data, dict):
        data = data.get("traceEvents", [])
    return data
