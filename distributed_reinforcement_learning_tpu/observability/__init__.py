"""Run-wide distributed telemetry: spans, counters/gauges, per-role shards.

Every process of a topology (learner, actors, anakin drivers) writes its
own `telemetry/<role>-<rank>.jsonl` shard plus a Chrome-trace timeline
`telemetry/trace-<role>-<rank>.json`; `scripts/obs_report.py` merges all
shards of a run directory into one report + one merged trace.

OFF by default: the module-level `TELEMETRY` singleton starts disabled
and every instrumentation call short-circuits on one attribute read —
no files, no threads, no per-step allocations (`span()` returns a shared
no-op context manager; tests/test_observability.py pins this). Enable
with:

    DRL_TELEMETRY_DIR=/path/to/run/telemetry   # explicit shard dir
    DRL_TELEMETRY=1                            # + a run_dir the process
                                               # already has -> <run_dir>/telemetry

See docs/performance.md ("Observability") for the shard layout and the
report CLI.
"""

from distributed_reinforcement_learning_tpu.observability.metrics import (
    TELEMETRY,
    Telemetry,
    maybe_configure,
)
from distributed_reinforcement_learning_tpu.observability.trace import (
    TraceEmitter,
    load_trace,
)

__all__ = ["TELEMETRY", "Telemetry", "TraceEmitter", "load_trace", "maybe_configure"]
