"""Counters/gauges with periodic flush to per-role JSONL shards.

One `Telemetry` object per process (the module-level `TELEMETRY`
singleton), writing `<role>-<rank>.jsonl` under the configured
directory. Three instrument kinds, all safe to call from any thread:

- `count(name, by)`   — monotonic counter; each flush writes the
  cumulative value, so a reader derives rates from consecutive records;
- `gauge(name, value)` — windowed observation; each flush writes the
  window's {n, last, mean, min, max} and resets it, so hot gauges
  (per-enqueue wait, per-publish latency) cost one dict update, not one
  file line, per observation;
- `sample(name, fn, kind="gauge"|"counter")` — registered provider
  polled once per flush (queue depth, weight version, an existing
  cumulative stats dict): a timeline with zero hot-path cost.

Record shapes (one JSON object per line):

    {"kind": "meta",    "t", "role", "rank", "pid"}
    {"kind": "counter", "t", "name", "value"}
    {"kind": "gauge",   "t", "name", "n", "last", "mean", "min", "max"}

The singleton starts DISABLED: every instrument short-circuits on one
attribute read, `span()` hands back a shared no-op context manager, and
nothing is allocated or written (tests/test_observability.py's
disabled-path test pins this, per-train-step hot paths rely on it).
`configure()` — or `maybe_configure()`, the env-gated form used by
`run_role` and the anakin drivers — opens the shard, attaches a
`TraceEmitter` (trace.py), and starts the flush thread
(`DRL_TELEMETRY_FLUSH_S`, default 1 s).
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Any, Callable

from distributed_reinforcement_learning_tpu.observability.trace import TraceEmitter

# Weight-staleness histogram edges — the single source of truth for the
# write side (transport server's observation-time `staleness_bucket/*`
# counters) and the read side (scripts/obs_report.py's display order).
STALENESS_BUCKETS = ((0, "0"), (1, "1"), (2, "2"), (4, "3-4"), (8, "5-8"),
                     (16, "9-16"))
STALENESS_BUCKET_NAMES = tuple(name for _, name in STALENESS_BUCKETS) + (">16",)


def stale_bucket(staleness: float) -> str:
    for edge, name in STALENESS_BUCKETS:
        if staleness <= edge:
            return name
    return ">16"


class _NullSpan:
    """Shared no-op context manager: the disabled `span()` result.

    A singleton so the disabled path allocates nothing per call."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Window:
    """One gauge's flush-window aggregate. `weight` lets one call stand
    for N identical observations (a batched PUT's staleness covers K
    unrolls) without N dict updates."""

    __slots__ = ("n", "total", "lo", "hi", "last")

    def __init__(self, value: float, weight: int = 1):
        self.n = weight
        self.total = value * weight
        self.lo = value
        self.hi = value
        self.last = value

    def add(self, value: float, weight: int = 1) -> None:
        self.n += weight
        self.total += value * weight
        if value < self.lo:
            self.lo = value
        if value > self.hi:
            self.hi = value
        self.last = value


class Telemetry:
    # Concurrency map (tools/drlint lock-discipline): the instrument
    # maps are shared between every hot-path caller and the flush
    # thread; the identity/config fields are written by configure()/
    # close() around the threaded phase, with `enabled` read lock-free
    # on hot paths as a deliberate no-op fast check.
    _GUARDED_BY = {
        "_counters": "_lock",
        "_gauges": "_lock",
        "_providers": "_lock",
        "_flush_errors": "_lock",
        "_provider_errors": "_lock",
    }
    _NOT_GUARDED = {
        "enabled": "flipped by configure()/close() around the threaded "
                   "phase; hot-path reads are deliberately lock-free "
                   "no-op checks (stale False costs one dropped sample)",
        "trace": "bound in configure() before the flush thread starts; "
                 "close() is the only other writer",
        "role": "configure()-once identity string",
        "rank": "configure()-once identity int",
        "_file": "opened in configure() before the flush thread starts; "
                 "closed only after the flush thread joins",
        "_thread": "start/stop lifecycle handle, controlling thread only",
    }

    def __init__(self):
        self.enabled = False
        self.trace: TraceEmitter | None = None
        self.role = "proc"
        self.rank = 0
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, _Window] = {}
        # name -> (provider fn, record kind: "gauge" | "counter")
        self._providers: dict[str, tuple[Callable[[], Any], str]] = {}
        self._flush_errors = 0     # whole-flush failures (first one warns)
        self._provider_errors = 0  # dead providers, surfaced as a counter
        self._file = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- configuration ----------------------------------------------------

    def configure(
        self,
        out_dir: str,
        role: str,
        rank: int = 0,
        flush_interval: float | None = None,
        trace: bool = True,
    ) -> "Telemetry":
        """Open the shard + trace for this process and start flushing.

        Idempotent: a second configure on an enabled instance is a no-op
        (first role wins — a process has one identity per run)."""
        if self.enabled:
            return self
        if flush_interval is None:
            flush_interval = float(os.environ.get("DRL_TELEMETRY_FLUSH_S", "1.0"))
        os.makedirs(out_dir, exist_ok=True)
        self.role, self.rank = role, int(rank)
        # "w", matching the trace: one shard file describes one process
        # lifetime. Appending across reused run dirs would splice two
        # runs' cumulative counters into one series (negative rates in
        # the report) while the trace silently truncated to the new run.
        self._file = open(os.path.join(out_dir, f"{role}-{rank}.jsonl"), "w")
        self._file.write(json.dumps({
            "kind": "meta", "t": time.time(), "role": role, "rank": int(rank),
            "pid": os.getpid()}) + "\n")
        self._file.flush()
        if trace:
            self.trace = TraceEmitter(
                os.path.join(out_dir, f"trace-{role}-{rank}.json"),
                label=f"{role}-{rank}")
        self._stop.clear()
        self.enabled = True
        if flush_interval > 0:
            self._thread = threading.Thread(
                target=self._flush_loop, args=(flush_interval,),
                daemon=True, name="telemetry-flush")
            self._thread.start()
        atexit.register(self.close)
        return self

    # -- instruments (all no-ops while disabled) --------------------------

    def count(self, name: str, by: float = 1) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + by

    def gauge(self, name: str, value: float, weight: int = 1) -> None:
        if not self.enabled or weight <= 0:
            return
        with self._lock:
            window = self._gauges.get(name)
            if window is None:
                self._gauges[name] = _Window(float(value), weight)
            else:
                window.add(float(value), weight)

    def sample(self, name: str, fn: Callable[[], Any],
               kind: str = "gauge") -> None:
        """Register `fn` to be polled once per flush (e.g. queue depth):
        a timeline with zero hot-path cost. kind="counter" writes the
        polled value as a cumulative counter record instead of a gauge —
        the way to surface an existing cumulative stats dict (e.g. the
        transport server's / client's) as report throughput without
        double-counting it on the hot path."""
        if not self.enabled:
            return
        with self._lock:
            self._providers[name] = (fn, kind)

    def span(self, name: str):
        trace = self.trace
        if trace is None:
            return _NULL_SPAN
        return trace.span(name)

    # -- flushing ----------------------------------------------------------

    def _flush_loop(self, interval: float) -> None:
        import sys

        while not self._stop.wait(interval):
            try:
                self.flush()
            except Exception as e:  # noqa: BLE001 — telemetry must never
                with self._lock:    # kill a run; count it, warn ONCE
                    self._flush_errors += 1
                    first = self._flush_errors == 1
                if first:
                    print(f"[telemetry] WARNING: flush failed (further "
                          f"failures counted silently): {e!r}",
                          file=sys.stderr)

    def flush(self) -> None:
        if not self.enabled or self._file is None:
            return
        now = time.time()
        with self._lock:
            counters = dict(self._counters)
            gauges, self._gauges = self._gauges, {}
            providers = dict(self._providers)
        lines = []
        for name, value in sorted(counters.items()):
            lines.append({"kind": "counter", "t": now, "name": name, "value": value})
        for name, w in sorted(gauges.items()):
            lines.append({"kind": "gauge", "t": now, "name": name, "n": w.n,
                          "last": w.last, "mean": w.total / w.n,
                          "min": w.lo, "max": w.hi})
        for name, (fn, kind) in sorted(providers.items()):
            try:
                value = float(fn())
            except Exception:  # noqa: BLE001 — a dead provider (closed queue
                with self._lock:        # at shutdown) must not poison the
                    self._provider_errors += 1  # flush; counted + emitted
                continue
            if kind == "counter":
                lines.append({"kind": "counter", "t": now, "name": name,
                              "value": value})
            else:
                lines.append({"kind": "gauge", "t": now, "name": name, "n": 1,
                              "last": value, "mean": value, "min": value,
                              "max": value})
        with self._lock:
            perrs, ferrs = self._provider_errors, self._flush_errors
        if perrs:
            lines.append({"kind": "counter", "t": now,
                          "name": "telemetry.provider_errors",
                          "value": perrs})
        if ferrs:
            lines.append({"kind": "counter", "t": now,
                          "name": "telemetry.flush_errors", "value": ferrs})
        if lines:
            self._file.write("".join(json.dumps(line) + "\n" for line in lines))
            self._file.flush()
        if self.trace is not None:
            self.trace.flush()

    def close(self) -> None:
        """Final flush, terminate the trace, release files; re-disables."""
        if not self.enabled:
            return
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.flush()
        self.enabled = False
        if self.trace is not None:
            self.trace.close()
            self.trace = None
        if self._file is not None:
            self._file.close()
            self._file = None
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._providers.clear()


TELEMETRY = Telemetry()


def telemetry_dir(run_dir: str | None = None) -> str | None:
    """Resolve the shard directory from the env (None = stay disabled).

    `DRL_TELEMETRY_DIR` names it outright (what the cluster launcher
    exports to every child); `DRL_TELEMETRY=1` derives it from a run
    directory the process already has."""
    out = os.environ.get("DRL_TELEMETRY_DIR")
    if out:
        return out
    if run_dir and os.environ.get(
            "DRL_TELEMETRY", "").strip().lower() in ("1", "true", "yes", "on"):
        return os.path.join(run_dir, "telemetry")
    return None


def maybe_configure(role: str, rank: int = 0, run_dir: str | None = None) -> bool:
    """Env-gated configure of the global TELEMETRY; False = left disabled."""
    out = telemetry_dir(run_dir)
    if out is None:
        return False
    TELEMETRY.configure(out, role, rank)
    return True
