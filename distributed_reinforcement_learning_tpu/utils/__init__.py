"""Config, logging, checkpointing, profiling utilities (reference layer L5)."""

from distributed_reinforcement_learning_tpu.utils.config import RuntimeConfig, check_config, load_config
from distributed_reinforcement_learning_tpu.utils.logger import MetricsLogger

__all__ = ["RuntimeConfig", "check_config", "load_config", "MetricsLogger"]
