"""Config system: JSON schema parity with the reference's `config.json`.

Loads the same per-algorithm JSON sections (`config.json:2,25,68`) into
typed runtime configs and applies the reference's validation rules
(`utils.py:33-44` check_properties). Extra fields introduced by this
framework (actor batching, transport ports) have defaults so reference
configs load unchanged.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Any

from distributed_reinforcement_learning_tpu.agents.apex import ApexConfig
from distributed_reinforcement_learning_tpu.agents.impala import ImpalaConfig
from distributed_reinforcement_learning_tpu.agents.r2d2 import R2D2Config
from distributed_reinforcement_learning_tpu.agents.xformer import XformerConfig


@dataclasses.dataclass(frozen=True)
class RuntimeConfig:
    """Topology + data-plane settings shared by all three algorithms."""

    algorithm: str
    server_ip: str = "localhost"
    server_port: int = 8000
    num_actors: int = 1
    envs: tuple[str, ...] = ("CartPole-v0",)
    available_action: tuple[int, ...] = (2,)
    queue_size: int = 128
    batch_size: int = 32
    envs_per_actor: int = 1  # actor-side env batching (new: one jitted act serves all)
    replay_capacity: int = 100_000
    target_sync_interval: int = 100  # `train_apex.py:151-152`, `train_r2d2.py:163-164`
    train_start_factor: int = 3  # learner trains when queue > factor*batch (`train_impala.py:94`)
    publish_interval: int = 1  # IMPALA weight-publish cadence (1 = reference parity)
    updates_per_call: int = 1  # K optimizer steps per learn_many dispatch (all families)
    seq_parallel: int = 1  # xformer: devices carving the mesh's `seq` axis
    expert_parallel: int = 1  # xformer MoE: devices carving the `expert` axis
    epsilon_floor: float | None = None  # r2d2/xformer actors: residual
    # exploration floor. None = each family's own default (r2d2 0.0 =
    # reference-parity decay to ~greedy, xformer 0.15); stable-R2D2 mode
    # uses e.g. 0.02.
    timeout_nonterminal: bool = False  # r2d2/xformer actors: record
    # time-limit truncations as non-terminal (stable mode; removes the
    # time-limit-aliasing collapse cycle. False = reference parity)


def check_config(rt: RuntimeConfig, num_actions: int) -> None:
    """Validation parity with `utils.py:33-44`."""
    for a in rt.available_action:
        if num_actions < a:
            raise ValueError(f"available_action {a} exceeds model_output {num_actions}")
    if rt.num_actors != len(rt.available_action):
        raise ValueError("num_actors != len(available_action)")
    if rt.num_actors != len(rt.envs):
        raise ValueError("num_actors != len(env)")


def _runtime_from_section(algo: str, d: dict[str, Any]) -> RuntimeConfig:
    return RuntimeConfig(
        algorithm=algo,
        server_ip=d.get("server_ip", "localhost"),
        server_port=d.get("server_port", 8000),
        num_actors=d.get("num_actors", 1),
        envs=tuple(d.get("env", ("CartPole-v0",))),
        available_action=tuple(d.get("available_action", (d.get("model_output", 2),))),
        queue_size=d.get("queue_size", 128),
        batch_size=d.get("batch_size", 32),
        envs_per_actor=d.get("envs_per_actor", 1),
        replay_capacity=int(d.get("replay_capacity", 1e5)),
        target_sync_interval=d.get("target_sync_interval", 100),
        train_start_factor=d.get("train_start_factor", 3),
        publish_interval=d.get("publish_interval", 1),
        updates_per_call=d.get("updates_per_call", 1),
        seq_parallel=d.get("seq_parallel", 1),
        expert_parallel=d.get("expert_parallel", 1),
        epsilon_floor=d.get("epsilon_floor"),
        timeout_nonterminal=d.get("timeout_nonterminal", False),
    )


def load_config(path: str | Path, section: str):
    """Load one config section -> (agent_config, runtime_config).

    Accepts the reference's `config.json` verbatim (same keys:
    `config.json:2-24` r2d2, `:25-67` impala, `:68-106` apex). Extra
    sections like `impala_cartpole` resolve their algorithm from the
    section-name prefix (or an explicit `"algorithm"` key).
    """
    data = json.loads(Path(path).read_text())
    d = data[section]
    algorithm = d.get("algorithm", section.split("_")[0])
    rt = _runtime_from_section(algorithm, d)

    if algorithm == "impala":
        agent_cfg = ImpalaConfig(
            obs_shape=tuple(d["model_input"]),
            num_actions=d["model_output"],
            trajectory=d.get("trajectory", 20),
            lstm_size=d.get("lstm_size", 256),
            discount_factor=d.get("discount_factor", 0.99),
            baseline_loss_coef=d.get("baseline_loss_coef", 1.0),
            entropy_coef=d.get("entropy_coef", 0.05),
            gradient_clip_norm=d.get("gradient_clip_norm", 40.0),
            reward_clipping=d.get("reward_clipping", "abs_one"),
            start_learning_rate=d.get("start_learning_rate", 6e-4),
            end_learning_rate=d.get("end_learning_rate", 0.0),
            learning_frame=int(d.get("learning_frame", 1e9)),
            fold_normalize=d.get("fold_normalize", False),
            torso=d.get("torso", "nature"),
            torso_width=d.get("torso_width", 1),
        )
    elif algorithm == "apex":
        agent_cfg = ApexConfig(
            obs_shape=tuple(d["model_input"]),
            num_actions=d["model_output"],
            discount_factor=d.get("discount_factor", 0.99),
            reward_clipping=d.get("reward_clipping", "abs_one"),
            gradient_clip_norm=d.get("gradient_clip_norm", 40.0),
            start_learning_rate=d.get("start_learning_rate", 1e-4),
            end_learning_rate=d.get("end_learning_rate", 0.0),
            learning_frame=int(d.get("learning_frame", 1e9)),
            fold_normalize=d.get("fold_normalize", False),
        )
    elif algorithm == "r2d2":
        agent_cfg = R2D2Config(
            obs_shape=tuple(d["model_input"]),
            num_actions=d["model_output"],
            seq_len=d.get("seq_len", 10),
            burn_in=d.get("burn_in", 5),
            lstm_size=d.get("lstm_size", 512),
            discount_factor=d.get("discount_factor", 0.997),
            learning_rate=d.get("start_learning_rate", 1e-4),
            priority_eta=d.get("priority_eta", None),
            # NOT the section's `gradient_clip_norm`: the reference
            # carries that key but never applies it to R2D2
            # (`agent/r2d2.py:91-92`), and honoring it would silently
            # change reference-config behavior. Stable mode opts in via
            # the distinct `adam_clip_norm` key.
            gradient_clip_norm=d.get("adam_clip_norm", None),
            # Pixel-R2D2 extensions (models/r2d2_net.py): the reference's
            # R2D2 is MLP/CartPole-only, so these keys have no reference
            # counterpart.
            torso=d.get("torso", "mlp"),
            torso_width=d.get("torso_width", 1),
            fold_normalize=d.get("fold_normalize", False),
        )
    elif algorithm == "xformer":
        agent_cfg = XformerConfig(
            obs_shape=tuple(d["model_input"]),
            num_actions=d["model_output"],
            seq_len=d.get("seq_len", 10),
            burn_in=d.get("burn_in", 5),
            d_model=d.get("d_model", 128),
            num_heads=d.get("num_heads", 4),
            num_layers=d.get("num_layers", 2),
            discount_factor=d.get("discount_factor", 0.997),
            learning_rate=d.get("start_learning_rate", 1e-4),
            attention=d.get("attention", "dense"),
            num_experts=d.get("num_experts", 0),
            moe_top_k=d.get("moe_top_k", 2),
            moe_capacity_factor=d.get("moe_capacity_factor", 2.0),
            moe_aux_weight=d.get("moe_aux_weight", 1e-2),
            pipeline=d.get("pipeline", False),
            pipeline_microbatches=d.get("pipeline_microbatches", 2),
            pipeline_stages=d.get("pipeline_stages", 0),
            remat=d.get("remat", False),
            priority_eta=d.get("priority_eta", None),
            gradient_clip_norm=d.get("adam_clip_norm", None),
        )
    elif algorithm == "ximpala":
        from distributed_reinforcement_learning_tpu.agents.ximpala import XImpalaConfig

        agent_cfg = XImpalaConfig(
            obs_shape=tuple(d["model_input"]),
            num_actions=d["model_output"],
            trajectory=d.get("trajectory", 20),
            d_model=d.get("d_model", 128),
            num_heads=d.get("num_heads", 4),
            num_layers=d.get("num_layers", 2),
            discount_factor=d.get("discount_factor", 0.99),
            baseline_loss_coef=d.get("baseline_loss_coef", 1.0),
            entropy_coef=d.get("entropy_coef", 0.05),
            gradient_clip_norm=d.get("gradient_clip_norm", 40.0),
            reward_clipping=d.get("reward_clipping", "abs_one"),
            start_learning_rate=d.get("start_learning_rate", 6e-4),
            end_learning_rate=d.get("end_learning_rate", 0.0),
            learning_frame=int(d.get("learning_frame", 1e9)),
            attention=d.get("attention", "dense"),
            num_experts=d.get("num_experts", 0),
            moe_top_k=d.get("moe_top_k", 2),
            moe_capacity_factor=d.get("moe_capacity_factor", 2.0),
            moe_aux_weight=d.get("moe_aux_weight", 1e-2),
            pipeline=d.get("pipeline", False),
            pipeline_microbatches=d.get("pipeline_microbatches", 2),
            pipeline_stages=d.get("pipeline_stages", 0),
            remat=d.get("remat", False),
        )
    else:
        raise ValueError(f"unknown algorithm {algorithm!r}")

    check_config(rt, agent_cfg.num_actions)
    return agent_cfg, rt
