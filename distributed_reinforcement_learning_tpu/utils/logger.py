"""Metrics logging: JSONL scalar streams per run directory.

Replaces the reference's tensorboardX `SummaryWriter` usage
(`train_impala.py:91,109-113`): same add_scalar surface, but writes
newline-delimited JSON records (`{"tag", "value", "step", "time"}`) that
need no external dependency to read or plot. If tensorboardX is present
it mirrors scalars there too.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import IO

try:  # optional dependency (present in this image; guarded anyway)
    from tensorboardX import SummaryWriter  # type: ignore
except Exception:  # pragma: no cover
    SummaryWriter = None


class MetricsLogger:
    def __init__(self, run_dir: str | Path | None, print_every: int = 0):
        self._file: IO[str] | None = None
        self._tb = None
        self._print_every = print_every
        self._counts: dict[str, int] = {}
        if run_dir is not None:
            path = Path(run_dir)
            path.mkdir(parents=True, exist_ok=True)
            self._file = (path / "metrics.jsonl").open("a")
            if SummaryWriter is not None:
                self._tb = SummaryWriter(str(path))

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        value = float(value)
        if self._file is not None:
            self._file.write(
                json.dumps({"tag": tag, "value": value, "step": int(step), "time": time.time()})
                + "\n"
            )
        if self._tb is not None:
            self._tb.add_scalar(tag, value, step)
        if self._print_every:
            n = self._counts.get(tag, 0)
            if n % self._print_every == 0:
                print(f"[{tag}] step={step} {value:.4g}", flush=True)
            self._counts[tag] = n + 1

    def add_scalars(self, scalars: dict[str, float], step: int) -> None:
        for tag, value in scalars.items():
            self.add_scalar(tag, value, step)

    def flush(self) -> None:
        if self._file is not None:
            self._file.flush()

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None
        if self._tb is not None:
            self._tb.close()
