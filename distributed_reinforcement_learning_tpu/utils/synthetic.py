"""Synthetic batch builders shared by bench.py, __graft_entry__.py and tests.

One parameterized constructor per batch type so a field change in the
agents' Batch NamedTuples breaks every consumer at the same place.
"""

from __future__ import annotations

import numpy as np


def synthetic_impala_batch(
    B: int,
    T: int,
    obs_shape: tuple[int, ...],
    num_actions: int,
    lstm_size: int,
    seed: int = 0,
    obs_dtype=np.uint8,
    uniform_behavior: bool = True,
):
    """Random ImpalaBatch ([B, T] unrolls with actor-recorded LSTM state)."""
    from distributed_reinforcement_learning_tpu.agents.impala import ImpalaBatch

    rng = np.random.default_rng(seed)
    if np.issubdtype(obs_dtype, np.integer):
        state = rng.integers(0, 255, (B, T, *obs_shape)).astype(obs_dtype)
    else:
        state = rng.random((B, T, *obs_shape), dtype=np.float32)
    if uniform_behavior:
        behavior = np.full((B, T, num_actions), 1.0 / num_actions, np.float32)
    else:
        behavior = rng.dirichlet(np.ones(num_actions), (B, T)).astype(np.float32)
    return ImpalaBatch(
        state=state,
        reward=rng.random((B, T), dtype=np.float32),
        action=rng.integers(0, num_actions, (B, T)).astype(np.int32),
        done=rng.random((B, T)) < 0.05,
        behavior_policy=behavior,
        previous_action=rng.integers(0, num_actions, (B, T)).astype(np.int32),
        initial_h=(rng.standard_normal((B, T, lstm_size)) * 0.1).astype(np.float32),
        initial_c=(rng.standard_normal((B, T, lstm_size)) * 0.1).astype(np.float32),
    )


def synthetic_apex_batch(
    B: int,
    obs_shape: tuple[int, ...],
    num_actions: int,
    seed: int = 0,
    obs_dtype=np.float32,
):
    """Random ApexBatch (flat transitions) + IS weights."""
    from distributed_reinforcement_learning_tpu.agents.apex import ApexBatch

    rng = np.random.default_rng(seed)

    def obs():
        if np.issubdtype(obs_dtype, np.integer):
            return rng.integers(0, 255, (B, *obs_shape)).astype(obs_dtype)
        return rng.random((B, *obs_shape), dtype=np.float32)

    batch = ApexBatch(
        state=obs(),
        next_state=obs(),
        previous_action=rng.integers(0, num_actions, (B,)).astype(np.int32),
        action=rng.integers(0, num_actions, (B,)).astype(np.int32),
        reward=rng.random((B,), dtype=np.float32),
        done=rng.random((B,)) < 0.1,
    )
    return batch, rng.random((B,), dtype=np.float32)


def synthetic_r2d2_batch(
    B: int,
    T: int,
    obs_shape: tuple[int, ...],
    num_actions: int,
    lstm_size: int,
    seed: int = 0,
):
    """Random R2D2Batch (sequences with stored start state) + IS weights."""
    from distributed_reinforcement_learning_tpu.agents.r2d2 import R2D2Batch

    rng = np.random.default_rng(seed)
    batch = R2D2Batch(
        state=rng.integers(0, 255, (B, T, *obs_shape)).astype(np.int32),
        previous_action=rng.integers(0, num_actions, (B, T)).astype(np.int32),
        action=rng.integers(0, num_actions, (B, T)).astype(np.int32),
        reward=rng.random((B, T), dtype=np.float32),
        done=rng.random((B, T)) < 0.1,
        initial_h=(rng.standard_normal((B, lstm_size)) * 0.1).astype(np.float32),
        initial_c=(rng.standard_normal((B, lstm_size)) * 0.1).astype(np.float32),
    )
    return batch, rng.random((B,), dtype=np.float32)


def synthetic_xformer_batch(
    B: int,
    T: int,
    obs_shape: tuple[int, ...],
    num_actions: int,
    seed: int = 0,
):
    """Random XformerBatch (sequences, no stored state) + IS weights."""
    from distributed_reinforcement_learning_tpu.agents.xformer import XformerBatch

    rng = np.random.default_rng(seed)
    batch = XformerBatch(
        state=rng.integers(0, 255, (B, T, *obs_shape)).astype(np.int32),
        previous_action=rng.integers(0, num_actions, (B, T)).astype(np.int32),
        action=rng.integers(0, num_actions, (B, T)).astype(np.int32),
        reward=rng.random((B, T), dtype=np.float32),
        done=rng.random((B, T)) < 0.1,
    )
    return batch, rng.random((B,), dtype=np.float32)


def synthetic_ximpala_batch(
    B: int,
    T: int,
    obs_shape: tuple[int, ...],
    num_actions: int,
    seed: int = 0,
    uniform_behavior: bool = True,
):
    """Random XImpalaBatch (IMPALA unrolls, no stored state)."""
    from distributed_reinforcement_learning_tpu.agents.ximpala import XImpalaBatch

    rng = np.random.default_rng(seed)
    logits = rng.random((B, T, num_actions)).astype(np.float32)
    behavior = (
        np.full((B, T, num_actions), 1.0 / num_actions, np.float32)
        if uniform_behavior
        else np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    )
    done = rng.random((B, T)) < 0.1
    return XImpalaBatch(
        state=rng.random((B, T, *obs_shape), dtype=np.float32),
        reward=rng.random((B, T), dtype=np.float32),
        action=rng.integers(0, num_actions, (B, T)).astype(np.int32),
        done=done,
        env_done=done.copy(),  # no shaping in synthetic data
        behavior_policy=behavior,
        previous_action=rng.integers(0, num_actions, (B, T)).astype(np.int32),
    )
