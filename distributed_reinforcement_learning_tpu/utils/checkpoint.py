"""Checkpoint/resume: params + optimizer state + step, actually wired in.

The reference constructs `tf.train.Saver`s but never calls them from any
training loop (`agent/impala.py:103,105-109`, `agent/apex.py:80`; R2D2 has
none — SURVEY §5.4), so a crashed learner loses everything. Here
checkpointing is a first-class subsystem:

- the serialized unit is the learner's whole `TrainState` pytree (params,
  optimizer moments, device step counter) via flax msgpack serialization,
  plus a JSON sidecar of host-side counters (train steps, replay beta, ...),
- writes are atomic (tmp file + `os.replace`), the payload file is the
  commit marker, and the newest `retain` checkpoints are kept,
- learners expose `save_checkpoint`/`restore_checkpoint`; the multi-process
  entrypoint (`runtime/transport.run_role`) saves on an interval and
  restores on startup, which is the learner half of crash recovery
  (actors already reconnect through the transport layer).
"""

from __future__ import annotations

import json
import os
import pickle
import re
import sys
import tempfile
from pathlib import Path
from typing import Any

from flax import serialization

_CKPT_RE = re.compile(r"^ckpt_(\d{10})\.msgpack$")


def encode_replay_snapshot(replay) -> bytes | None:
    """Pickle a replay buffer's `snapshot()` for checkpointing, or None.

    SURVEY §5.4's optional replay snapshot: without it a restarted
    Ape-X/R2D2 learner resumes with an empty Memory. Disabled with
    `DRL_CKPT_REPLAY=0`; skipped (with a log line) above
    `DRL_CKPT_REPLAY_MAX_MB` (default 512) because a full Atari replay at
    capacity 1e5 is ~5 GB and would dominate every checkpoint write.
    """
    if os.environ.get("DRL_CKPT_REPLAY", "1") == "0":
        return None
    cap_mb = float(os.environ.get("DRL_CKPT_REPLAY_MAX_MB", "512"))

    def over_cap(nbytes: int) -> bool:
        if nbytes > cap_mb * 1e6:
            print(f"[checkpoint] replay snapshot {nbytes / 1e6:.0f} MB exceeds "
                  f"DRL_CKPT_REPLAY_MAX_MB={cap_mb:.0f}; skipping (set higher "
                  f"to keep it)", file=sys.stderr)
            return True
        return False

    # The SoA backend can price its snapshot without materializing it —
    # reject an over-cap replay BEFORE copying ~GBs under its lock.
    estimate = getattr(replay, "approx_snapshot_nbytes", None)
    if estimate is not None and over_cap(estimate()):
        return None
    snap = replay.snapshot()
    payload = snap.get("items", snap.get("stacked"))  # list vs SoA backend
    nbytes = sum(
        x.nbytes for x in _iter_array_leaves(payload)
    ) + snap["priorities"].nbytes
    if over_cap(nbytes):
        return None
    return pickle.dumps(snap, protocol=pickle.HIGHEST_PROTOCOL)


def decode_replay_snapshot(data: bytes) -> dict:
    return pickle.loads(data)


def _iter_array_leaves(tree):
    if hasattr(tree, "nbytes"):
        yield tree
    elif isinstance(tree, dict):
        for v in tree.values():
            yield from _iter_array_leaves(v)
    elif isinstance(tree, (list, tuple)):
        for v in tree:
            yield from _iter_array_leaves(v)


def _atomic_write(path: Path, data: bytes) -> None:
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=path.name, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            # fsync before replace: os.replace is atomic in the namespace
            # but not on disk — without the flush a power loss can commit
            # a truncated payload under the final name.
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        dirfd = os.open(path.parent, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


class Checkpointer:
    """Step-numbered, atomic, retain-N checkpoint store on a directory.

    Layout: `ckpt_{step:010d}.msgpack` (the TrainState, written last =
    commit marker) and `ckpt_{step:010d}.extra.json` (host counters,
    written first). A checkpoint is visible only once its msgpack exists.
    """

    def __init__(self, directory: str | Path, retain: int = 3):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.retain = retain
        # Sweep tmp files orphaned by a hard kill (SIGKILL/OOM between
        # mkstemp and os.replace) — nothing else ever deletes them, and a
        # crash-looping learner would otherwise accumulate one
        # TrainState-sized blob per crash.
        for stale in self.directory.glob("*.tmp"):
            try:
                stale.unlink()
            except OSError:
                pass
        # Sweep sidecars (extra.json, auxiliary blobs) without a committed
        # payload: save() writes them before the msgpack (the msgpack is
        # the commit marker), so a crash between the writes leaves orphans
        # that _prune — which iterates committed steps only — would never
        # delete.
        for side in list(self.directory.glob("ckpt_*.extra.json")) + list(
            self.directory.glob("ckpt_*.blob.*")
        ):
            m = re.match(r"^ckpt_(\d{10})\.", side.name)
            if m and not self._payload_path(int(m.group(1))).exists():
                try:
                    side.unlink()
                except OSError:
                    pass

    def _payload_path(self, step: int) -> Path:
        return self.directory / f"ckpt_{step:010d}.msgpack"

    def _extra_path(self, step: int) -> Path:
        return self.directory / f"ckpt_{step:010d}.extra.json"

    def _blob_path(self, step: int, name: str) -> Path:
        return self.directory / f"ckpt_{step:010d}.blob.{name}"

    def steps(self) -> list[int]:
        """Committed checkpoint steps, ascending."""
        out = []
        for p in self.directory.iterdir():
            m = _CKPT_RE.match(p.name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    def save(
        self,
        step: int,
        state: Any,
        extra: dict | None = None,
        blobs: dict[str, bytes] | None = None,
    ) -> Path:
        """Persist `state` (+ host `extra`, + named auxiliary `blobs` such
        as a replay-buffer snapshot) as checkpoint `step`. Sidecars are
        written first; the msgpack payload is the commit marker."""
        _atomic_write(self._extra_path(step), json.dumps(extra or {}).encode())
        for name, data in (blobs or {}).items():
            _atomic_write(self._blob_path(step, name), data)
        path = self._payload_path(step)
        _atomic_write(path, serialization.to_bytes(state))
        self._prune()
        return path

    def load_blob(self, step: int, name: str) -> bytes | None:
        path = self._blob_path(step, name)
        return path.read_bytes() if path.exists() else None

    def restore(self, template: Any, step: int | None = None) -> tuple[Any, dict, int] | None:
        """-> (state, extra, step) for `step` (default latest), or None.

        `template` must be a pytree with the same structure as the saved
        state (a freshly-initialized TrainState); flax deserializes into it.
        """
        if step is None:
            step = self.latest_step()
            if step is None:
                return None
        payload = self._payload_path(step)
        if not payload.exists():
            return None
        data = payload.read_bytes()
        try:
            state = serialization.from_bytes(template, data)
        except (ValueError, KeyError) as e:
            # Layout migration: pre-r3 image models nested conv params as
            # nn.Conv's `Conv_{i}/{kernel,bias}`; the explicit NatureConv
            # layout (models/torso.py) flattens them. Retry the restore
            # through the upgrade map before giving up — chained to the
            # original error so a genuinely corrupt checkpoint surfaces
            # both failures, not just the retry's.
            from distributed_reinforcement_learning_tpu.models.torso import (
                upgrade_nature_conv_params)

            try:
                raw = upgrade_nature_conv_params(serialization.msgpack_restore(data))
                state = serialization.from_state_dict(template, raw)
            except Exception as retry_err:
                raise retry_err from e
        extra_path = self._extra_path(step)
        extra = json.loads(extra_path.read_text()) if extra_path.exists() else {}
        return state, extra, step

    def _prune(self) -> None:
        for step in self.steps()[: -self.retain]:
            sides = list(self.directory.glob(f"ckpt_{step:010d}.blob.*"))
            for p in (self._payload_path(step), self._extra_path(step), *sides):
                try:
                    p.unlink()
                except FileNotFoundError:
                    pass
