"""Checkpoint/resume: params + optimizer state + step, actually wired in.

The reference constructs `tf.train.Saver`s but never calls them from any
training loop (`agent/impala.py:103,105-109`, `agent/apex.py:80`; R2D2 has
none — SURVEY §5.4), so a crashed learner loses everything. Here
checkpointing is a first-class subsystem:

- the serialized unit is the learner's whole `TrainState` pytree (params,
  optimizer moments, device step counter) via flax msgpack serialization,
  plus a JSON sidecar of host-side counters (train steps, replay beta, ...),
- writes are atomic (tmp file + `os.replace`), the payload file is the
  commit marker, and the newest `retain` checkpoints are kept,
- learners expose `save_checkpoint`/`restore_checkpoint`; the multi-process
  entrypoint (`runtime/transport.run_role`) saves on an interval and
  restores on startup, which is the learner half of crash recovery
  (actors already reconnect through the transport layer).
"""

from __future__ import annotations

import json
import os
import re
import tempfile
from pathlib import Path
from typing import Any

from flax import serialization

_CKPT_RE = re.compile(r"^ckpt_(\d{10})\.msgpack$")


def _atomic_write(path: Path, data: bytes) -> None:
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=path.name, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            # fsync before replace: os.replace is atomic in the namespace
            # but not on disk — without the flush a power loss can commit
            # a truncated payload under the final name.
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        dirfd = os.open(path.parent, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


class Checkpointer:
    """Step-numbered, atomic, retain-N checkpoint store on a directory.

    Layout: `ckpt_{step:010d}.msgpack` (the TrainState, written last =
    commit marker) and `ckpt_{step:010d}.extra.json` (host counters,
    written first). A checkpoint is visible only once its msgpack exists.
    """

    def __init__(self, directory: str | Path, retain: int = 3):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.retain = retain
        # Sweep tmp files orphaned by a hard kill (SIGKILL/OOM between
        # mkstemp and os.replace) — nothing else ever deletes them, and a
        # crash-looping learner would otherwise accumulate one
        # TrainState-sized blob per crash.
        for stale in self.directory.glob("*.tmp"):
            try:
                stale.unlink()
            except OSError:
                pass
        # Sweep sidecars without a committed payload: save() writes the
        # extra.json first (the msgpack is the commit marker), so a crash
        # between the two leaves an orphan that _prune — which iterates
        # committed steps only — would never delete.
        for extra in self.directory.glob("ckpt_*.extra.json"):
            payload = extra.with_name(extra.name.replace(".extra.json", ".msgpack"))
            if not payload.exists():
                try:
                    extra.unlink()
                except OSError:
                    pass

    def _payload_path(self, step: int) -> Path:
        return self.directory / f"ckpt_{step:010d}.msgpack"

    def _extra_path(self, step: int) -> Path:
        return self.directory / f"ckpt_{step:010d}.extra.json"

    def steps(self) -> list[int]:
        """Committed checkpoint steps, ascending."""
        out = []
        for p in self.directory.iterdir():
            m = _CKPT_RE.match(p.name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    def save(self, step: int, state: Any, extra: dict | None = None) -> Path:
        """Persist `state` (+ host `extra`) as checkpoint `step`."""
        _atomic_write(self._extra_path(step), json.dumps(extra or {}).encode())
        path = self._payload_path(step)
        _atomic_write(path, serialization.to_bytes(state))
        self._prune()
        return path

    def restore(self, template: Any, step: int | None = None) -> tuple[Any, dict, int] | None:
        """-> (state, extra, step) for `step` (default latest), or None.

        `template` must be a pytree with the same structure as the saved
        state (a freshly-initialized TrainState); flax deserializes into it.
        """
        if step is None:
            step = self.latest_step()
            if step is None:
                return None
        payload = self._payload_path(step)
        if not payload.exists():
            return None
        state = serialization.from_bytes(template, payload.read_bytes())
        extra_path = self._extra_path(step)
        extra = json.loads(extra_path.read_text()) if extra_path.exists() else {}
        return state, extra, step

    def _prune(self) -> None:
        for step in self.steps()[: -self.retain]:
            for p in (self._payload_path(step), self._extra_path(step)):
                try:
                    p.unlink()
                except FileNotFoundError:
                    pass
