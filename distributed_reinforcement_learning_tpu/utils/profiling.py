"""Profiling: per-stage learner timing + JAX device-trace capture.

The reference's only performance signal is one wall-clock delta per train
step logged as `data/time` (`train_impala.py:99,113` — SURVEY §5.1). Here
profiling is first-class:

- `StageTimer`: named host-side stages (dequeue / learn / publish / ...)
  accumulated per train step and emitted through the MetricsLogger as
  `profile/<stage>_ms` means every `log_every` steps. This splits "the
  step took 40ms" into queue-wait vs device-compute vs weight-publication
  — the split that tells you whether the data plane or the chip is the
  bottleneck (SURVEY §7 hard part (a)). When the run-wide telemetry is
  enabled (observability/), every stage invocation additionally becomes
  a span on the process's Chrome-trace timeline — the TIMELINE the means
  cannot show (one 400 ms publish stall vs "publish averaged 3 ms") —
  and each flush mirrors the stage means as `stage/<name>_ms` gauges
  into the telemetry shard.
- `ProfilerSession`: captures a real `jax.profiler` device trace (XLA op
  timeline, viewable in TensorBoard/Perfetto) for a configured window of
  train steps. Enabled via env vars so any launcher/run picks it up:
      DRL_PROFILE_DIR=/tmp/trace DRL_PROFILE_START=50 DRL_PROFILE_STEPS=5
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Iterator

from distributed_reinforcement_learning_tpu.observability import TELEMETRY as _OBS
from distributed_reinforcement_learning_tpu.utils.logger import MetricsLogger


class StageTimer:
    """Accumulates wall-clock per named stage; logs means periodically.

    Usage in a learner loop:
        with timer.stage("dequeue"): batch = queue.get_batch(...)
        with timer.stage("learn"):   state, m = agent.learn(...)
        timer.step_done(train_steps)
    """

    def __init__(
        self,
        logger: MetricsLogger | None = None,
        prefix: str = "profile/",
        log_every: int = 100,
    ):
        self.logger = logger
        self.prefix = prefix
        self.log_every = log_every
        self._sums: dict[str, float] = {}
        self._counts: dict[str, int] = {}
        self._steps = 0
        self.last_means_ms: dict[str, float] = {}

    def reset(self) -> None:
        """Drop accumulated sums (e.g. to exclude a warm-up/compile step)."""
        self._sums.clear()
        self._counts.clear()
        self._steps = 0

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        # Trace handle read once: disabled telemetry costs one attribute
        # load here, no wall-clock read, no allocation.
        trace = _OBS.trace
        wall = time.time() if trace is not None else 0.0
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._sums[name] = self._sums.get(name, 0.0) + dt
            self._counts[name] = self._counts.get(name, 0) + 1
            if trace is not None:
                trace.emit(name, wall, dt)

    def step_done(self, step: int) -> None:
        """Mark one train step; every `log_every` steps emit + reset means.

        Means are per stage INVOCATION, not per train step: replay-path
        learners run many ingest stages before their first train step
        (warm-up gate), and a per-step divisor would smear that warm-up
        into a wildly inflated first flush.
        """
        self._steps += 1
        if self._steps < self.log_every:
            return
        self.last_means_ms = {
            name: 1e3 * total / self._counts[name] for name, total in self._sums.items()
        }
        if self.logger is not None:
            self.logger.add_scalars(
                {f"{self.prefix}{n}_ms": ms for n, ms in self.last_means_ms.items()},
                step,
            )
        if _OBS.enabled:
            for name, ms in self.last_means_ms.items():
                _OBS.gauge(f"stage/{name}_ms", ms)
        self._sums.clear()
        self._counts.clear()
        self._steps = 0


class ProfilerSession:
    """Window-triggered `jax.profiler` trace around train steps.

    `on_step(step)` is called once per train step; the trace starts when
    `step` reaches `start_step` and stops `num_steps` later (or at
    `close()`, whichever comes first). Inactive (no-op) unless `out_dir`
    is set, so learners can call it unconditionally.
    """

    def __init__(self, out_dir: str | None, start_step: int = 10, num_steps: int = 5):
        self.out_dir = out_dir
        self.start_step = start_step
        self.num_steps = num_steps
        self._active = False
        self._done = out_dir is None

    @classmethod
    def from_env(cls) -> "ProfilerSession":
        """DRL_PROFILE_DIR / DRL_PROFILE_START / DRL_PROFILE_STEPS."""
        return cls(
            os.environ.get("DRL_PROFILE_DIR") or None,
            start_step=int(os.environ.get("DRL_PROFILE_START", "10")),
            num_steps=int(os.environ.get("DRL_PROFILE_STEPS", "5")),
        )

    def on_step(self, step: int) -> None:
        if self._done:
            return
        if not self._active and step >= self.start_step:
            import jax

            jax.profiler.start_trace(self.out_dir)
            self._active = True
            self._stop_at = step + self.num_steps
        elif self._active and step >= self._stop_at:
            self._stop()

    def _stop(self) -> None:
        import jax

        jax.profiler.stop_trace()
        self._active = False
        self._done = True
        print(f"[profiler] device trace written to {self.out_dir}", flush=True)

    def close(self) -> None:
        if self._active:
            self._stop()
