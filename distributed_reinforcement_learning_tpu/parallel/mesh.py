"""Device mesh construction and named shardings.

The reference has no multi-device learner at all — one `/job:learner/task:0`
process owns the weights (`train_impala.py:33,37`), and "distributed" means
actor processes over gRPC. The TPU-native generalization (SURVEY §2.3, §5.8)
is a learner spanning a `jax.sharding.Mesh` of chips:

- `data` axis: batch-dimension data parallelism. Params replicated (or
  model-sharded, below), batch split; XLA inserts the gradient `psum` over
  ICI automatically because the output params must be consistent.
- `model` axis: optional tensor parallelism for large kernels (LSTM and
  head matmuls sharded on their output feature dim, Megatron column style).
  Size 1 by default — the reference-parity configs are small enough that
  DP is the only axis that pays.
- `seq` axis: optional sequence/context parallelism for long-context
  attention (`parallel/sequence.py` ring / all-to-all). Size 1 by
  default; sized >1 it sits between `data` and `model` so neighboring
  devices carry adjacent sequence shards and the ring's `ppermute`
  rides nearest ICI links.
- `pipe` axis: optional pipeline parallelism (`parallel/pipeline.py`) —
  one stage per device, GPipe microbatch schedule. OUTERMOST: pipeline
  hops move one activation microbatch per tick, the lightest traffic of
  any axis, so it can ride the slowest links (incl. DCN on multi-host
  meshes).
- `expert` axis: optional expert parallelism for MoE layers
  (`ops/moe.py`) — expert weights and the dispatched token buffer shard
  over it; GSPMD inserts the all-to-alls.

Everything here is plain `jax.sharding`; no torch-style process groups.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PIPE_AXIS = "pipe"
DATA_AXIS = "data"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"
MODEL_AXIS = "model"


def make_mesh(
    n_devices: int | None = None,
    model_parallel: int = 1,
    seq_parallel: int = 1,
    pipe_parallel: int = 1,
    expert_parallel: int = 1,
    devices: list | None = None,
) -> Mesh:
    """Build a `(pipe, data, seq, expert, model)` mesh over the first
    `n_devices` devices.

    `model_parallel` chips are adjacent in device order so the model axis
    rides the fastest ICI links on real TPU topologies; `expert` and
    `seq` are next-innermost for the same reason, and `pipe` is
    outermost (lightest traffic on the slowest links).
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"requested {n_devices} devices but only {len(devices)} visible; "
                "set XLA_FLAGS=--xla_force_host_platform_device_count=N for CPU simulation"
            )
        devices = devices[:n_devices]
    n = len(devices)
    inner = model_parallel * seq_parallel * expert_parallel
    if n % (inner * pipe_parallel) != 0:
        raise ValueError(
            f"{n} devices not divisible by pipe*seq*expert*model="
            f"{inner * pipe_parallel}"
        )
    arr = np.array(devices).reshape(
        pipe_parallel, n // (inner * pipe_parallel), seq_parallel, expert_parallel,
        model_parallel,
    )
    return Mesh(arr, (PIPE_AXIS, DATA_AXIS, SEQ_AXIS, EXPERT_AXIS, MODEL_AXIS))


def pcast_varying(x, axes: tuple[str, ...]):
    """`lax.pcast(..., to="varying")` over exactly the axes `x` is not
    already varying on (pcast rejects already-varying axes). The shared
    idiom for typing shard_map carries whose loop bodies write
    shard-dependent values into an invarying init — used by the ring
    attention accumulators and the pipeline schedule."""
    have = set(getattr(jax.typeof(x), "vma", ()))
    need = tuple(a for a in axes if a not in have)
    return jax.lax.pcast(x, need, to="varying") if need else x


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over the `data` axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def model_kernel_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard a kernel's last (output-feature) dim over the `model` axis."""
    return NamedSharding(mesh, P(*([None] * (ndim - 1)), MODEL_AXIS))


def place_local_batch(tree, sharding: NamedSharding | None):
    """Put this process's host batch onto the mesh as (its shard of) the
    global batch.

    Single-process: a plain `device_put` into the sharding. Multi-process
    (a mesh spanning hosts, after `parallel.distributed.initialize`): each
    process holds only its local rows, so the global array is assembled
    with `jax.make_array_from_process_local_data` — the per-host batch
    feed of the multi-host learner. Local row count follows the sharding:
    when the batch axis spans processes (the usual data-parallel feed),
    each process supplies `global_batch / process_count` rows; when the
    processes sit on an axis the batch is REPLICATED over (e.g. hosts on
    `pipe`, batch sharded over a within-host `data` axis), each process
    supplies the full, identical global batch (see the pipeline step in
    tests/multihost_worker.py).
    """
    if sharding is None:
        return jax.device_put(tree)
    if jax.process_count() == 1:
        return jax.device_put(tree, sharding)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, np.asarray(x)),
        tree,
    )
