"""Partition-rule pytree sharding: name-keyed PartitionSpecs + shard plans.

`parallel/learner.py` already shards the TrainState structurally (big
kernels over `model`, `moe_*` over `expert`, `blocks_stacked` over
`pipe`) — but that rule lives inside the pjit wiring and only exists
when a mesh does. The weight PLANE needs the same partition knowledge on
the host side, mesh or no mesh: publication splits the params pytree
into named shards keyed by partition spec, so per-shard encode/broadcast
(runtime/weight_shards.py, runtime/weights.py) follows the same axes the
learner compiles over. This module is the repo-native
`match_partition_rules` pass (the SNIPPETS.md exemplars' idiom: regex
rules over `/`-joined leaf names -> PartitionSpec, scalars always
replicated), plus the shard-plan grouping the weight plane consumes.

Leaf NAMING AND ORDER come from the codec's canonical flatten
(`data/codec.flatten_with_paths` — sorted dict keys, namedtuple fields
in declaration order), so shard plans, encoded shard blobs, and the
whole-blob codec layout all agree on leaf index `i` meaning the same
array. That shared ordering is what makes per-shard decode bit-identical
to whole-blob decode (pinned by tests/test_weight_sharding.py).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Sequence

import numpy as np
from jax.sharding import PartitionSpec as P

from distributed_reinforcement_learning_tpu.parallel.mesh import (
    EXPERT_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
)

# Mirrors parallel/learner._MIN_SHARD_SIZE: leaves below this many
# elements stay replicated no matter what rule their name matches —
# splitting a 256-float bias costs more than it saves, and the weight
# plane wants small leaves pooled into the replicated shard, not one
# micro-shard per LayerNorm scale.
MIN_PARTITION_SIZE = 4096

REPLICATED_KEY = "rep"


def leaf_name(codec_path: str) -> str:
    """codec `_flatten` path -> rule-matching name: `$.a.b[2].c` ->
    `a/b[2]/c` (the `/`-separated convention of the exemplar passes)."""
    name = codec_path[2:] if codec_path.startswith("$.") else codec_path.lstrip("$")
    return name.replace(".", "/")


def named_tree_map(fn: Callable[[str, Any], Any], tree: Any, sep: str = "/") -> Any:
    """Map `fn(name, leaf)` over a pytree with `/`-joined path names —
    the exemplars' `named_tree_map`, over the codec's canonical order."""
    from distributed_reinforcement_learning_tpu.data import codec

    skel, pairs = codec.flatten_with_paths(tree)
    out = [fn(leaf_name(path).replace("/", sep), arr) for path, arr in pairs]
    return codec.assemble(skel, out)


def default_partition_rules() -> tuple[tuple[str, P], ...]:
    """(regex, PartitionSpec) rules keyed off `parallel/mesh.py` axis
    names, first match wins — the host-side mirror of
    `parallel/learner.train_state_sharding`:

    - the pipelined transformer body (`blocks_stacked/*`) stacks layers
      on its leading dim -> shard over `pipe`;
    - expert-stacked MoE tensors (`moe_w*`/`moe_b*`, router gate
      excluded) shard their leading expert dim over `expert`;
    - kernels/matmul weights shard their output-feature (last) dim over
      `model` (Megatron column style);
    - everything else — biases, LayerNorm scales, embeddings small
      enough to broadcast, counters — replicates (the catch-all, so
      this rule set never raises).
    """
    return (
        (r"blocks_stacked/", P(PIPE_AXIS)),
        (r"(^|/)moe_(w|b)\d*$", P(EXPERT_AXIS)),
        (r"(^|/)(w|kernel|qkv(_kernel)?|proj(_kernel)?|moe_gate|embed\w*)$",
         P(None, MODEL_AXIS)),
        (r".*", P()),
    )


def leaf_spec(rules: Sequence[tuple[str, P]], name: str, leaf) -> P:
    """THE per-leaf partition decision (single source — shard keys and
    manifests derive from it): scalar / size-1 / sub-
    `MIN_PARTITION_SIZE` leaves are never partitioned; otherwise the
    first rule whose regex `search`es the `/`-joined leaf name wins.
    Raises ValueError when no rule matches (supply a catch-all
    `(".*", P())` to opt out, as `default_partition_rules` does)."""
    arr = np.asarray(leaf)
    if arr.ndim == 0 or arr.size <= 1 or arr.size < MIN_PARTITION_SIZE:
        return P()  # don't partition scalars / tiny leaves
    for rule, spec in rules:
        if re.search(rule, name) is not None:
            return spec
    raise ValueError(f"partition rule not found for param: {name}")


def match_partition_rules(rules: Sequence[tuple[str, P]], params: Any) -> Any:
    """Pytree of PartitionSpec per leaf (the exemplar pass), via
    `leaf_spec`."""
    return named_tree_map(
        lambda name, leaf: leaf_spec(rules, name, leaf), params)


def spec_key(spec: P) -> str:
    """Stable, wire-safe shard key for a PartitionSpec: `P()` -> "rep",
    `P(None, "model")` -> "-,model", `P("expert")` -> "expert". Keys
    are manifest/protocol identifiers — renaming one invalidates every
    reader's shard cache, so keep them derived, never hand-written."""
    dims = tuple(spec)
    if not dims or all(d is None for d in dims):
        return REPLICATED_KEY
    return ",".join("-" if d is None else str(d) for d in dims)


class ShardPlan:
    """How one params schema splits into named shards.

    `skel` is the codec skeleton (global leaf indices), `paths`/`specs`
    are per-leaf in that same order, and `shards` maps each stable shard
    key to its ascending global leaf indices. Every leaf lands in
    exactly ONE shard, so gathering the shards' leaf lists back into
    global order and unflattening `skel` reproduces the pytree
    bit-identically. Plans are immutable once built (the weight store
    caches one per schema)."""

    __slots__ = ("skel", "paths", "specs", "shards")

    def __init__(self, skel: Any, paths: list[str], specs: list[P],
                 shards: dict[str, list[int]]):
        self.skel = skel
        self.paths = paths
        self.specs = specs
        self.shards = shards

    @property
    def keys(self) -> list[str]:
        return list(self.shards)


def build_exchange_plan(params: Any,
                        rules: Sequence[tuple[str, P]] | None = None,
                        quant: str = "f32", overlap: int = 0,
                        tail: int = 0):
    """Classify every leaf of `params` (a gradient-shaped pytree) into
    its partition-spec class and lay the classes out over the learner
    tier's FLAT vector — a `parallel/collective.ExchangePlan`.

    Order alignment is the load-bearing part: the tier's
    `flatten_tree` walks `jax.tree.flatten` order, while the rules
    match `/`-joined names from the codec's canonical flatten. So the
    per-leaf class is computed name-keyed (`named_tree_map`) into a
    same-shaped tree, and THAT tree is `jax.tree.flatten`ed — the
    class list comes out in exactly the order the flat vector
    concatenates leaves, whatever the two flattens' relative key
    ordering. `tail` appends that many replicated elements for the
    values the tier rides on the vector's tail (the loss float).

    Two seats building a plan from the same params schema, rules, and
    config produce byte-identical entries and therefore the same
    `plan_hash` — the agreement HELLO pins (tested at k=2/k=3)."""
    import jax

    from distributed_reinforcement_learning_tpu.parallel.collective import (
        ExchangePlan,
    )

    if rules is None:
        rules = default_partition_rules()
    keyed = named_tree_map(
        lambda name, leaf: (spec_key(leaf_spec(rules, name, leaf)),
                            int(np.asarray(leaf).size)),
        params)
    entries, _ = jax.tree.flatten(
        keyed, is_leaf=lambda x: isinstance(x, tuple))
    entries = list(entries)
    if tail:
        entries.append((REPLICATED_KEY, int(tail)))
    return ExchangePlan(entries, quant=quant, overlap=overlap)


def shard_plan(params: Any,
               rules: Sequence[tuple[str, P]] | None = None) -> ShardPlan:
    """Split `params` into partition-keyed shards (sorted keys, so two
    processes planning the same schema agree byte-for-byte on shard
    identity and leaf order)."""
    from distributed_reinforcement_learning_tpu.data import codec

    if rules is None:
        rules = default_partition_rules()
    skel, pairs = codec.flatten_with_paths(params)
    paths = [leaf_name(p) for p, _ in pairs]
    specs: list[P] = []
    groups: dict[str, list[int]] = {}
    for i, (name, (_, arr)) in enumerate(zip(paths, pairs)):
        spec = leaf_spec(rules, name, arr)
        specs.append(spec)
        groups.setdefault(spec_key(spec), []).append(i)
    return ShardPlan(skel, paths, specs,
                     {k: groups[k] for k in sorted(groups)})
