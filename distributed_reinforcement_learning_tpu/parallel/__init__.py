from distributed_reinforcement_learning_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    data_sharding,
    make_mesh,
    model_kernel_sharding,
    replicated,
)
from distributed_reinforcement_learning_tpu.parallel.learner import (
    ShardedLearner,
    train_state_sharding,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "ShardedLearner",
    "data_sharding",
    "make_mesh",
    "model_kernel_sharding",
    "replicated",
    "train_state_sharding",
]
