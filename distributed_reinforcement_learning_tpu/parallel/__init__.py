from distributed_reinforcement_learning_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    data_sharding,
    make_mesh,
    model_kernel_sharding,
    place_local_batch,
    replicated,
)
from distributed_reinforcement_learning_tpu.parallel.learner import (
    ShardedLearner,
    train_state_sharding,
)
from distributed_reinforcement_learning_tpu.parallel import distributed

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "distributed",
    "ShardedLearner",
    "data_sharding",
    "make_mesh",
    "model_kernel_sharding",
    "place_local_batch",
    "replicated",
    "train_state_sharding",
]
