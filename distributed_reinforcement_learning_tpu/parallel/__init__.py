from distributed_reinforcement_learning_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    data_sharding,
    make_mesh,
    model_kernel_sharding,
    place_local_batch,
    replicated,
)
from distributed_reinforcement_learning_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_stage_params,
)
from distributed_reinforcement_learning_tpu.parallel.learner import (
    ShardedLearner,
    train_state_sharding,
)
from distributed_reinforcement_learning_tpu.parallel.sequence import (
    ring_attention,
    ulysses_attention,
)
from distributed_reinforcement_learning_tpu.parallel import distributed

__all__ = [
    "DATA_AXIS",
    "EXPERT_AXIS",
    "MODEL_AXIS",
    "PIPE_AXIS",
    "SEQ_AXIS",
    "pipeline_apply",
    "stack_stage_params",
    "distributed",
    "ShardedLearner",
    "data_sharding",
    "make_mesh",
    "model_kernel_sharding",
    "place_local_batch",
    "replicated",
    "ring_attention",
    "train_state_sharding",
    "ulysses_attention",
]
