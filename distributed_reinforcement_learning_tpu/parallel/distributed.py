"""Multi-host runtime initialization over DCN.

The reference's notion of a cluster is TF1 `ClusterSpec` static membership
on one machine (`train_impala.py:31-35`). The TPU-native equivalent splits
two planes (SURVEY §5.8):

- **data plane** (actor<->learner trajectories/weights): the socket
  transport in `runtime/transport.py`, host-level, works across any
  machines — nothing here changes for multi-host.
- **compute plane** (learner gradient collectives): on a multi-host TPU
  pod slice, every learner process must join one JAX distributed runtime
  so `jax.devices()` spans all hosts and the `(data, model)` mesh from
  `parallel.mesh.make_mesh` lays collectives over ICI (intra-slice) and
  DCN (inter-slice) automatically. This module is that join.

`runtime/transport.run_role --mode learner` builds on this join: when it
returns True the learn step pjits over the GLOBAL mesh, each process
dequeues `batch_size / process_count` from its own socket data plane,
and `parallel.mesh.place_local_batch` assembles the global batch via
`jax.make_array_from_process_local_data` (tested 2 processes x 4 virtual
CPU devices in tests/test_multihost.py). Usage, one call before any
other jax use in each process:

    from distributed_reinforcement_learning_tpu.parallel import distributed
    distributed.initialize()          # env-driven, no-op single-host

Env contract (mirrors `jax.distributed.initialize`'s own variables, with
a DRL_ prefix so launch scripts can't collide with other JAX users):
    DRL_COORDINATOR=host0:9900  DRL_NUM_PROCESSES=4  DRL_PROCESS_ID=0
On GKE/Cloud-TPU the three can be omitted entirely: jax auto-detects from
the TPU metadata and this reduces to `jax.distributed.initialize()`.
"""

from __future__ import annotations

import os

import jax

_initialized = False


def is_initialized() -> bool:
    return _initialized


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Join the multi-host JAX runtime; returns True if a join happened.

    Explicit args win over DRL_* env vars. With neither present this is a
    single-host no-op, so launchers may call it unconditionally. Safe to
    call twice (second call is a no-op).
    """
    global _initialized
    if _initialized:
        return True
    coordinator_address = coordinator_address or os.environ.get("DRL_COORDINATOR")
    if num_processes is None and "DRL_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["DRL_NUM_PROCESSES"])
    if process_id is None and "DRL_PROCESS_ID" in os.environ:
        process_id = int(os.environ["DRL_PROCESS_ID"])

    if coordinator_address is None and num_processes is None:
        return False  # single-host
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True


def process_info() -> tuple[int, int]:
    """(process_index, process_count) — (0, 1) when single-host."""
    return jax.process_index(), jax.process_count()
