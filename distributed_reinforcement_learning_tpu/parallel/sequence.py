"""Sequence/context parallelism: ring attention + all-to-all (Ulysses).

The reference caps context at T=20 LSTM unrolls on one device (SURVEY
§5.7); the TPU-native framework treats long context as a first-class
parallelism axis. Two standard strategies over a `seq` mesh axis, both
pure `shard_map` + XLA collectives (no NCCL-style process groups):

- **Ring attention** (`ring_attention`): Q/K/V stay sequence-sharded
  `[B, T/n, H, D]` per device; KV blocks rotate around the ring with
  `lax.ppermute` while each device folds them into a flash-attention
  online-softmax accumulator (`ops/attention.py`). After n-1 rotations
  every query has seen every key. Peak memory is O(T/n) per device and
  the ppermute rides neighbor ICI links, overlapping with the block
  matmuls. Works for any head count.

- **Ulysses all-to-all** (`ulysses_attention`): two `lax.all_to_all`
  reshards — sequence-sharded -> head-sharded, dense attention on full
  sequences for H/n local heads, then back. Fewer collective hops than
  the ring when heads divide the axis; needs H % n == 0.

Both support per-row episode segment ids (attention confined within an
episode, the transformer counterpart of done-masked (h, c) resets) and
are differentiable (ppermute/all_to_all have transpose rules), so the
same code path serves training — verified against dense attention,
values and grads, in tests/test_sequence.py on an 8-virtual-device mesh.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributed_reinforcement_learning_tpu.ops import attention as att
from distributed_reinforcement_learning_tpu.parallel.mesh import SEQ_AXIS, pcast_varying


def _varying_acc(q, axis_name: str, varying_axes=()):
    """Online-softmax accumulator typed as varying over every sharded
    mesh axis: the scan writes shard-dependent values into it, and
    shard_map's VMA typing rejects an unvarying init against a varying
    carry. One helper so both ring bodies share the workaround."""
    return jax.tree.map(
        lambda x: pcast_varying(x, (axis_name, *varying_axes)),
        att.attention_block_init(q),
    )


def _ring_shard(q, k, v, seg, *, axis_name: str, causal: bool, varying_axes=()):
    """Per-device body: local Q against the rotating KV ring.

    `seg` is the per-shard segment-id slice `[B, T/n]` or None; it
    rotates around the ring alongside its KV block.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    t_local = q.shape[1]
    q_pos = idx * t_local + jnp.arange(t_local)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, hop):
        k_blk, v_blk, k_seg, acc = carry
        # After `hop` rotations this device holds the block that started
        # on device (idx - hop) mod n; its global positions follow.
        src = (idx - hop) % n
        k_pos = src * t_local + jnp.arange(t_local)

        def attend(acc):
            return att.attention_block_step(
                acc, q, k_blk, v_blk, causal=causal, q_pos=q_pos, k_pos=k_pos,
                q_seg=seg, k_seg=k_seg,
            )

        if causal:
            # A block strictly in this shard's future is fully masked:
            # skip its matmuls entirely (lax.cond, predicate uniform per
            # device). The ring itself stays synchronous — each hop still
            # waits on some device that does attend — so this trims FLOPs
            # /energy, not worst-case latency; a balanced (zig-zag /
            # striped) block placement is the known fix for the latter.
            acc = jax.lax.cond(src > idx, lambda a: a, attend, acc)
        else:
            acc = attend(acc)
        # Rotate even on the last hop: a static-shape scan body keeps XLA
        # free to overlap the permute with the next block's matmul, and
        # the final (unused) hop costs one neighbor copy.
        rotate = lambda x: jax.lax.ppermute(x, axis_name, perm)
        k_blk, v_blk = rotate(k_blk), rotate(v_blk)
        k_seg = None if k_seg is None else rotate(k_seg)
        return (k_blk, v_blk, k_seg, acc), None

    acc0 = _varying_acc(q, axis_name, varying_axes)
    (_, _, _, acc), _ = jax.lax.scan(step, (k, v, seg, acc0), jnp.arange(n))
    return att.attention_block_finish(acc, q.dtype)


def _ring_shard_zigzag(q, k, v, seg, *, axis_name: str, causal: bool, varying_axes=()):
    """Balanced causal ring: each device owns chunks (i, 2n-1-i).

    The contiguous ring's causal skip trims FLOPs but not latency — the
    device holding the last shard still attends every block, so every
    hop waits on it. With the zig-zag placement each device's local
    sequence is one globally-early chunk `e` (chunk i) and one
    globally-late chunk `l` (chunk 2n-1-i); of the four quadrant
    interactions per hop, `e x late` is ALWAYS fully future (skipped
    statically), `l x early` is always fully past (computed unmasked),
    and the two same-half quadrants are needed for about half the hops —
    2n+1 chunk-matmuls per device regardless of i. Work is uniform, so
    the synchronous ring's critical path drops ~2x at large n.
    """
    assert causal, "zigzag schedule is causal-only (guarded in ring_attention)"
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    c = q.shape[1] // 2  # chunk length
    ar = jnp.arange(c)
    qe, ql = q[:, :c], q[:, c:]
    qe_pos, ql_pos = idx * c + ar, (2 * n - 1 - idx) * c + ar
    seg_e = None if seg is None else seg[:, :c]
    seg_l = None if seg is None else seg[:, c:]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def quadrant(acc, q_half, k_blk, v_blk, q_pos, k_pos, q_seg, k_seg, masked):
        return att.attention_block_step(
            acc, q_half, k_blk, v_blk, causal=masked, q_pos=q_pos, k_pos=k_pos,
            q_seg=q_seg, k_seg=k_seg,
        )

    def step(carry, hop):
        k_blk, v_blk, k_seg, acc_e, acc_l = carry
        src = (idx - hop) % n
        ke, kl = k_blk[:, :c], k_blk[:, c:]
        ve, vl = v_blk[:, :c], v_blk[:, c:]
        ke_pos, kl_pos = src * c + ar, (2 * n - 1 - src) * c + ar
        ks_e = None if k_seg is None else k_seg[:, :c]
        ks_l = None if k_seg is None else k_seg[:, c:]

        # e x early: needed iff src <= idx (diagonal masked inside).
        acc_e = jax.lax.cond(
            src > idx, lambda a: a,
            lambda a: quadrant(a, qe, ke, ve, qe_pos, ke_pos, seg_e, ks_e, True),
            acc_e)
        # e x late: always strictly future — statically skipped.
        # l x early: always strictly past — full attend, no causal mask
        # (segment mask still applies).
        acc_l = quadrant(acc_l, ql, ke, ve, ql_pos, ke_pos, seg_l, ks_e, False)
        # l x late: needed iff src >= idx.
        acc_l = jax.lax.cond(
            src < idx, lambda a: a,
            lambda a: quadrant(a, ql, kl, vl, ql_pos, kl_pos, seg_l, ks_l, True),
            acc_l)

        rotate = lambda x: jax.lax.ppermute(x, axis_name, perm)
        k_blk, v_blk = rotate(k_blk), rotate(v_blk)
        k_seg = None if k_seg is None else rotate(k_seg)
        return (k_blk, v_blk, k_seg, acc_e, acc_l), None

    init = (k, v, seg, _varying_acc(qe, axis_name, varying_axes),
            _varying_acc(ql, axis_name, varying_axes))
    (_, _, _, acc_e, acc_l), _ = jax.lax.scan(step, init, jnp.arange(n))
    return jnp.concatenate(
        [att.attention_block_finish(acc_e, q.dtype),
         att.attention_block_finish(acc_l, q.dtype)], axis=1)


def _zigzag_perm(t: int, n: int) -> "jnp.ndarray":
    """Global time permutation placing chunks (i, 2n-1-i) on device i."""
    import numpy as np

    c = t // (2 * n)
    out = []
    for i in range(n):
        out.append(np.arange(i * c, (i + 1) * c))
        out.append(np.arange((2 * n - 1 - i) * c, (2 * n - i) * c))
    return jnp.asarray(np.concatenate(out))


def _ulysses_shard(q, k, v, seg, *, axis_name: str, causal: bool):
    """Per-device body: reshard seq->heads, dense attention, reshard back."""

    def seq_to_heads(x):  # [B, T/n, H, D] -> [B, T, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):  # [B, T, H/n, D] -> [B, T/n, H, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    if seg is not None:
        # Segments have no head axis to scatter; every device needs the
        # full-length ids for its full-sequence local heads.
        seg = jax.lax.all_gather(seg, axis_name, axis=1, tiled=True)
    out = att.dense_attention(
        seq_to_heads(q), seq_to_heads(k), seq_to_heads(v), causal=causal,
        q_seg=seg, k_seg=seg,
    )
    return heads_to_seq(out)


def _sp_attention(
    mesh: Mesh,
    body: Callable,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    segment_ids: jax.Array | None,
    *,
    causal: bool,
    batch_axis: str | None,
) -> jax.Array:
    spec = P(batch_axis, SEQ_AXIS, None, None)
    seg_spec = P(batch_axis, SEQ_AXIS)
    kwargs = dict(axis_name=SEQ_AXIS, causal=causal)
    if body in (_ring_shard, _ring_shard_zigzag) and batch_axis is not None:
        kwargs["varying_axes"] = (batch_axis,)
    f = jax.shard_map(
        functools.partial(body, **kwargs),
        mesh=mesh,
        in_specs=(spec, spec, spec, None if segment_ids is None else seg_spec),
        out_specs=spec,
    )
    return f(q, k, v, segment_ids)


def ring_attention(
    mesh: Mesh,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    batch_axis: str | None = None,
    segment_ids: jax.Array | None = None,
    schedule: str = "contiguous",
    pre_permuted: bool = False,
) -> jax.Array:
    """Causal MHA with Q/K/V sharded over `mesh`'s `seq` axis.

    Global shapes `[B, T, H, D]`; T must divide by the seq-axis size.
    Optionally also batch-sharded over `batch_axis` (e.g. `data`), and
    episode-confined via `segment_ids` `[B, T]`.

    `schedule="zigzag"` (causal only; needs T % 2n == 0) uses the
    balanced chunk placement — see `_ring_shard_zigzag` — which halves
    the ring's critical-path compute at large seq-axis sizes. The inputs
    are permuted into zigzag layout here (and the output back) unless
    `pre_permuted=True` — a multi-layer caller should permute its
    residual stream ONCE with `zigzag_permutation` and pass
    `pre_permuted` so the resharding gathers don't recur per layer
    (models/transformer_net.py does this).
    """
    _check(mesh, q, heads_divide=False)
    if schedule == "zigzag":
        n = mesh.shape[SEQ_AXIS]
        t = q.shape[1]
        if not causal:
            raise ValueError("zigzag schedule only pays for causal attention")
        if t % (2 * n) != 0:
            raise ValueError(f"zigzag needs T ({t}) divisible by 2*seq axis ({2 * n})")
        if pre_permuted:
            return _sp_attention(
                mesh, _ring_shard_zigzag, q, k, v, segment_ids,
                causal=causal, batch_axis=batch_axis,
            )
        perm = _zigzag_perm(t, n)
        inv = jnp.argsort(perm)
        take = lambda x: jnp.take(x, perm, axis=1)
        out = _sp_attention(
            mesh, _ring_shard_zigzag, take(q), take(k), take(v),
            None if segment_ids is None else jnp.take(segment_ids, perm, axis=1),
            causal=causal, batch_axis=batch_axis,
        )
        return jnp.take(out, inv, axis=1)
    if schedule != "contiguous":
        raise ValueError(f"unknown schedule {schedule!r}")
    return _sp_attention(
        mesh, _ring_shard, q, k, v, segment_ids, causal=causal, batch_axis=batch_axis
    )


def zigzag_permutation(t: int, n: int) -> tuple[tuple[int, ...], tuple[int, ...]]:
    """(perm, inverse) time-axis permutations for the zigzag layout, as
    hashable int tuples (usable as static flax module fields)."""
    import numpy as np

    perm = np.asarray(_zigzag_perm(t, n))
    inv = np.argsort(perm)
    return tuple(int(i) for i in perm), tuple(int(i) for i in inv)


def ulysses_attention(
    mesh: Mesh,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    batch_axis: str | None = None,
    segment_ids: jax.Array | None = None,
) -> jax.Array:
    """All-to-all sequence parallelism; needs heads % seq-axis == 0."""
    _check(mesh, q, heads_divide=True)
    return _sp_attention(
        mesh, _ulysses_shard, q, k, v, segment_ids, causal=causal, batch_axis=batch_axis
    )


def _check(mesh: Mesh, q: jax.Array, *, heads_divide: bool) -> None:
    n = mesh.shape.get(SEQ_AXIS)
    if n is None:
        raise ValueError(f"mesh {dict(mesh.shape)} has no '{SEQ_AXIS}' axis")
    if q.shape[1] % n != 0:
        raise ValueError(f"sequence length {q.shape[1]} not divisible by seq axis {n}")
    if heads_divide and q.shape[2] % n != 0:
        raise ValueError(
            f"ulysses needs heads ({q.shape[2]}) divisible by seq axis ({n}); "
            "use ring_attention otherwise"
        )
