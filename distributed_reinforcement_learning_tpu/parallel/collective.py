"""Host-side collective for the sharded learner tier (runtime/learner_tier.py).

Podracer's Sebulba architecture (arXiv:2104.06272) splits the learner
into cooperating seats; this module is the seats' exchange plane — a
TCP peer mesh on the repo's existing transport framing
(`runtime/transport._send_msg`/`_recv_msg`: [u8 op][u32 len][payload]
requests, [u8 status][u32 len][payload] replies) carrying two traffic
classes:

- **ring allreduce** (`allreduce_mean`): the lockstep gradient exchange
  of `DRL_LEARNER_SYNC=allreduce`. Classic 2(k-1)-step ring over the
  seats' flat f32 vectors: k-1 reduce-scatter steps (each seat ends up
  owning one fully-summed chunk) then k-1 allgather steps, sum divided
  by k at the end. Every PART message carries (membership epoch, round
  seq, phase, step, chunk) — a receiver in a different epoch NAKs, and
  the sender raises `RoundAborted` so the learner retries the round
  under the re-formed membership instead of deadlocking on a dead ring.

- **async delta push** (`push_merge`/`take_merges`): the bounded-wait
  IMPACT-style fallback (arXiv:1912.00167) of `DRL_LEARNER_SYNC=async`.
  A seat pushes its params vector to every live peer without waiting
  for anyone (the ack is the only synchronization); each endpoint keeps
  the LATEST vector per sender with its merge-step stamp, and the
  consumer drops contributions staler than its bounded-staleness
  budget (`runtime/learner_tier.py` pins the bound).

**Membership** is the tier's failure model: the live-rank set plus an
integer epoch. A peer that fails an exchange or a liveness probe is
marked dead — the epoch bumps, every in-flight round aborts (inbox
purged, round seq reset), and the NEXT round runs over the survivors'
ring at k-1, down to solo (a one-member ring returns its input — the
demote-to-solo path). Dead ranks stay dead for the life of this
collective: seat re-admission is a whole-tier restart (the launcher
respawn pattern), because a rejoining seat's params have diverged and
silently averaging them back in would corrupt every survivor.

Consistency note, documented not hidden: at a membership-change
boundary survivors can apply ONE round asymmetrically (a seat that
completed the dying round vs one that aborted and retried it under the
new epoch). Every later round merges the same vector on every
survivor, so the divergence is bounded to that single update — the
same order of off-policyness the replay family already tolerates.

**Partition-aware rounds** (`allreduce_mean(vec, plan=...)`): when the
tier attaches a mesh-sharded learner, `parallel/partition.py` classifies
every gradient leaf by its partition spec and builds an `ExchangePlan` —
the flat vector's segments grouped by spec class. Only the REPLICATED
(data-parallel) segments ride the ring; each sharded class (model /
expert / pipe) is exchanged owner-scoped: members send their class
segment point-to-point to one deterministic owner seat (phase 2), the
owner accumulates in f32, divides by k, and fans the merged segment back
(phase 3) — same OP_COLL_PART framing, same epoch/NAK failure model.
The plan (leaf classes + sizes + quant/overlap config) is hashed and
pinned EQUAL across seats: HELLO carries the hash, and a mismatch is a
loud `CollectiveError` refusal (`check_plan_agreement`), never silent
divergence. Optional bf16 transport (`ExchangePlan(quant="bf16")`)
quantizes every hop through the shared RNE codec (`data/bf16.py`) at
half the wire bytes; accumulation stays f32 (master accumulation), and
each seat roundtrips its self-owned chunk so all seats still end
bit-identical. A plan-less call is byte-for-byte today's f32 ring.

This module is numpy + sockets only (no jax): the flatten/unflatten of
gradient pytrees lives with the tier, and the bench/test children keep
a jax-free import footprint.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import struct
import threading
import time

import numpy as np

from distributed_reinforcement_learning_tpu.data.bf16 import (
    bf16_u16_to_f32,
    f32_to_bf16_u16,
)
from distributed_reinforcement_learning_tpu.runtime.transport import (
    ST_ERROR,
    ST_OK,
    TransportError,
    _recv_msg,
    _send_msg,
)

# Collective op namespace (disjoint from runtime/transport's 1..9; the
# endpoint below is the dispatcher, PeerClient._exchange the sender).
OP_COLL_HELLO = 40  # liveness probe + peer identification + plan hash
OP_COLL_PART = 41   # one allreduce chunk (ring phases 0/1, star 2/3)
OP_COLL_MERGE = 42  # async-mode params push (latest-wins per sender)

# PART: (sender_rank, epoch, seq, phase, step, chunk_idx, fmt) + payload.
# phase 0/1 = ring reduce-scatter/allgather (step = ring step, chunk =
# chunk index); phase 2 = member -> class-owner contribution (step =
# class index, chunk = sender rank); phase 3 = owner -> member merged
# segment (step = class index, chunk = destination rank). fmt tags the
# payload encoding so a receiver never guesses.
_PART_HDR = struct.Struct("<IIqIIII")
# MERGE: (sender_rank, epoch, merge_step) + f32 payload.
_MERGE_HDR = struct.Struct("<IIq")

FMT_F32 = 0   # payload = raw little-endian f32
FMT_BF16 = 1  # payload = u16-carried bf16 (data/bf16.py RNE codec)

_ACCEPT = b"\x01"
_NAK = b"\x00"


def wait_budget_s() -> float:
    """Bounded wait for one collective exchange (`DRL_LEARNER_WAIT_S`):
    past it the blocked seat probes the peer and either keeps waiting
    (peer alive, one extension) or declares it dead and re-forms."""
    env = os.environ.get("DRL_LEARNER_WAIT_S", "").strip()
    try:
        return max(0.1, float(env)) if env else 10.0
    except ValueError as e:
        raise ValueError(
            f"DRL_LEARNER_WAIT_S must be a number, got {env!r}") from e


class CollectiveError(RuntimeError):
    """Base class for collective failures the tier handles."""


class RoundAborted(CollectiveError):
    """The membership epoch changed under an in-flight round (a NAK
    from a re-formed peer, or this seat observed the bump itself).
    Retry the round: the next attempt runs over the new membership."""


class PeerLost(CollectiveError):
    """A peer died mid-exchange (connection failure or a probe-confirmed
    wedge). The membership already marked it dead and bumped the epoch
    by the time this raises — retry the round over the survivors."""


class PlanMismatch(CollectiveError):
    """Two seats negotiated DIFFERENT exchange plans (partition rules,
    quant mode, or overlap depth diverge). Exchanging under skewed plans
    would silently merge mismatched segments — the tier must refuse
    loudly instead (check_plan_agreement raises this)."""


# Spec-class byte accounting: the dynamic spec keys from
# parallel/partition.spec_key ("rep", "-,model", "expert", "pipe", ...)
# fold into a FIXED stat-key vocabulary so telemetry names are stable
# from construction (register_telemetry snapshots the keys once).
_CLASS_LABELS = ("rep", "model", "expert", "pipe", "other")


def class_label(key: str) -> str:
    """Stable stats label for a partition spec class key: the non-None
    axis names joined by `_` ("-,model" -> "model"), "rep" for the
    replicated class, "other" for any axis vocabulary outside the
    default mesh rules."""
    if key == "rep":
        return "rep"
    axes = [a for a in key.split(",") if a and a != "-"]
    label = "_".join(axes) or "other"
    return label if label in _CLASS_LABELS else "other"


class ExchangePlan:
    """Partition classes of the flat exchange vector, leaf by leaf in
    the tier's flatten order (`runtime/learner_tier.flatten_tree` —
    jax.tree.flatten; the builder in parallel/partition.py guarantees
    the per-leaf class assignment walks the SAME order).

    `entries` is [(spec_class_key, size), ...] per leaf; consecutive
    leaves of one class become (start, stop) segments of the flat
    vector. `quant` ("f32" | "bf16") and `overlap` (in-flight round
    depth) ride the plan because every seat must run the SAME exchange
    arithmetic — all three are folded into `plan_hash`, the value HELLO
    pins equal across seats. Plans are immutable once built."""

    __slots__ = ("entries", "quant", "overlap", "length", "segments",
                 "classes", "plan_hash")

    def __init__(self, entries: list[tuple[str, int]], quant: str = "f32",
                 overlap: int = 0):
        if quant not in ("f32", "bf16"):
            raise ValueError(f"ExchangePlan quant must be f32|bf16, "
                             f"got {quant!r}")
        self.entries = [(str(k), int(n)) for k, n in entries]
        self.quant = quant
        self.overlap = int(overlap)
        self.segments: dict[str, list[tuple[int, int]]] = {}
        off = 0
        for key, n in self.entries:
            segs = self.segments.setdefault(key, [])
            if segs and segs[-1][1] == off:  # merge adjacent same-class
                segs[-1] = (segs[-1][0], off + n)
            else:
                segs.append((off, off + n))
            off += n
        self.length = off
        # "rep" first (the ring class), sharded classes in sorted order
        # — the deterministic class walk every seat follows.
        sharded = sorted(k for k in self.segments if k != "rep")
        self.classes = (["rep"] if "rep" in self.segments else []) + sharded
        blob = json.dumps({"leaves": self.entries, "quant": self.quant,
                           "overlap": self.overlap},
                          separators=(",", ":")).encode()
        self.plan_hash = hashlib.sha256(blob).hexdigest()

    @property
    def fmt(self) -> int:
        return FMT_BF16 if self.quant == "bf16" else FMT_F32

    def sharded_classes(self) -> list[str]:
        return [k for k in self.classes if k != "rep"]

    def gather(self, vec: np.ndarray, key: str) -> np.ndarray:
        """Contiguous f32 copy of one class's segments."""
        segs = self.segments[key]
        if len(segs) == 1:
            a, b = segs[0]
            return np.ascontiguousarray(vec[a:b], np.float32)
        return np.concatenate([vec[a:b] for a, b in segs]).astype(
            np.float32, copy=False)

    def scatter(self, vec: np.ndarray, key: str, data: np.ndarray) -> None:
        """Inverse of `gather`: write one class's merged segments back
        into the flat vector."""
        off = 0
        for a, b in self.segments[key]:
            vec[a:b] = data[off:off + (b - a)]
            off += b - a
        if off != data.size:
            raise CollectiveError(
                f"class {key!r} segment size mismatch: {off} != {data.size}")


def _encode_part(arr: np.ndarray, fmt: int) -> bytes:
    if fmt == FMT_BF16:
        return f32_to_bf16_u16(arr).tobytes()
    return arr.tobytes()


def _decode_part(buf: bytes, fmt: int) -> np.ndarray:
    """Wire payload -> f32 (accumulation is ALWAYS f32 — the master-
    accumulation contract that keeps quantized rounds inside the rtol
    pin: only the transported values are rounded, never the sums)."""
    if fmt == FMT_BF16:
        return bf16_u16_to_f32(np.frombuffer(buf, np.uint16))
    if fmt != FMT_F32:
        raise CollectiveError(f"unknown PART payload fmt {fmt}")
    return np.frombuffer(buf, np.float32)


def _roundtrip(arr: np.ndarray, fmt: int) -> np.ndarray:
    """What a receiver of `arr` would hold after decode: the self-owned
    copy every sender applies to ITSELF so quantized rounds stay
    bit-identical across seats (bf16 roundtrip is idempotent, so
    re-quantized forwards carry the exact same u16 words)."""
    if fmt == FMT_BF16:
        return bf16_u16_to_f32(f32_to_bf16_u16(arr))
    return arr


class Membership:
    """Live-rank set + epoch, the collective's failure ground truth.

    Concurrency map (tools/drlint lock-discipline): the learn thread
    (allreduce abort paths), the endpoint serve threads (epoch checks
    on every PART/MERGE), and the tier's liveness sweep all read/write
    this state — everything lives under `_lock`.
    """

    _GUARDED_BY = {
        "_live": "_lock",
        "_epoch": "_lock",
    }

    def __init__(self, ranks, rank: int):
        if rank not in ranks:
            raise ValueError(f"own rank {rank} not in roster {sorted(ranks)}")
        self.rank = rank
        self._lock = threading.Lock()
        self._live = set(ranks)
        self._epoch = 0

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def live(self) -> list[int]:
        with self._lock:
            return sorted(self._live)

    def is_live(self, rank: int) -> bool:
        with self._lock:
            return rank in self._live

    @property
    def solo(self) -> bool:
        with self._lock:
            return len(self._live) == 1

    def snapshot(self) -> tuple[list[int], int]:
        """(live ranks, epoch) under ONE lock hold — a round must pin
        both coherently; two separate reads could span a bump."""
        with self._lock:
            return sorted(self._live), self._epoch

    def mark_dead(self, rank: int) -> bool:
        """Remove `rank`; True (and an epoch bump) when it was live.
        Own rank never dies through here — a seat cannot outlive its
        own membership."""
        if rank == self.rank:
            return False
        with self._lock:
            if rank not in self._live:
                return False
            self._live.discard(rank)
            self._epoch += 1
            return True


class PeerClient:
    """Framed point-to-point client for one peer endpoint: connect on
    first use, one bounded reconnect-and-resend per exchange (every
    collective op is idempotent: PART/MERGE re-delivery overwrites the
    same inbox key with identical bytes; HELLO is a pure probe).

    NOT thread-safe and deliberately lock-free: each instance belongs
    to exactly one calling thread (the learn thread's per-rank send
    clients, or a transient probe client) — the collective never shares
    one across threads, so a serializing lock would only buy the
    blocking-under-lock hazards transport's client pays for its shared
    surface.
    """

    def __init__(self, host: str, port: int, timeout: float = 10.0,
                 connect_retries: int = 1, retry_interval: float = 0.1):
        self.host, self.port = host, port
        self.timeout = timeout
        self.connect_retries = max(1, connect_retries)
        self.retry_interval = retry_interval
        self._sock: socket.socket | None = None

    def _connect(self) -> None:
        last: Exception | None = None
        for _ in range(self.connect_retries):
            try:
                sock = socket.create_connection((self.host, self.port),
                                                timeout=self.timeout)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._sock = sock
                return
            except OSError as e:
                last = e
                time.sleep(self.retry_interval)
        raise TransportError(
            f"cannot reach collective peer {self.host}:{self.port}: {last}")

    def _exchange(self, op: int, payload) -> tuple[int, bytes]:
        parts = payload if isinstance(payload, list) else [payload]
        if self._sock is None:
            self._connect()
        try:
            _send_msg(self._sock, op, *parts)
            return _recv_msg(self._sock)
        except (TransportError, OSError):
            self.close()
            self._connect()
            _send_msg(self._sock, op, *parts)
            return _recv_msg(self._sock)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


class CollectiveEndpoint:
    """One seat's listening side: accepts connections from the ring's
    prev peer (PART traffic), async merge pushers, and probe clients,
    dispatching each framed request to the owning HostCollective's
    inbox under ITS synchronization.

    Concurrency map (tools/drlint lock-discipline): the accept loop and
    the per-connection serve threads share the connection bookkeeping
    exactly like TransportServer (same stop() contract: close every
    accepted socket so blocked recvs unwedge now).
    """

    _GUARDED_BY = {
        "_conns": "_lock",
        "_threads": "_lock",
    }
    _NOT_GUARDED = {
        "_sock": "bound in start() before the accept thread spawns; "
                 "stop() closes it cross-thread ON PURPOSE to break the "
                 "accept loop out of its timed accept()",
    }

    def __init__(self, owner: "HostCollective", host: str, port: int):
        self._owner = owner
        self.host, self.port = host, port
        self._sock: socket.socket | None = None
        self._lock = threading.Lock()
        self._conns: set[socket.socket] = set()
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()

    def start(self) -> "CollectiveEndpoint":
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self._sock.listen(16)
        self._sock.settimeout(0.5)
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"coll-accept-{self._owner.rank}")
        t.start()
        with self._lock:
            self._threads.append(t)
        return self

    def _accept_loop(self) -> None:
        assert self._sock is not None
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                if self._stop.is_set():
                    conn.close()
                    return
                self._conns.add(conn)
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            with self._lock:
                self._threads = [x for x in self._threads if x.is_alive()]
                self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                try:
                    op, payload = _recv_msg(conn)
                except (TransportError, OSError):
                    return
                try:
                    if op == OP_COLL_HELLO:
                        reply = self._owner._on_hello(
                            json.loads(bytes(payload)))
                        _send_msg(conn, ST_OK,
                                  json.dumps(reply,
                                             separators=(",", ":")).encode())
                    elif op == OP_COLL_PART:
                        accepted = self._owner._on_part(payload)
                        _send_msg(conn, ST_OK,
                                  _ACCEPT if accepted else _NAK)
                    elif op == OP_COLL_MERGE:
                        accepted = self._owner._on_merge(payload)
                        _send_msg(conn, ST_OK,
                                  _ACCEPT if accepted else _NAK)
                    else:
                        _send_msg(conn, ST_ERROR)
                except (TransportError, OSError):
                    return
                except Exception:  # noqa: BLE001 — malformed peer bytes
                    # must not kill the endpoint: answer ST_ERROR and
                    # count it (snapshot_stats/"serve_errors").
                    self._owner._bump("serve_errors")
                    try:
                        _send_msg(conn, ST_ERROR)
                    except OSError:
                        return
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            self._sock.close()
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=2.0)


class HostCollective:
    """The seat-side collective: one endpoint (this seat's listen
    address), lazy per-peer send clients, the membership, and the two
    exchange primitives the tier drives (`allreduce_mean`,
    `push_merge`/`take_merges`). See the module docstring for the
    failure model.

    Concurrency map (tools/drlint lock-discipline): `_cond` is a
    Condition over `_lock` (alias) — the endpoint serve threads fill
    `_inbox`/`_merges` and notify, the learn thread consumes under
    timed waits; `_seq` shares the lock because the epoch-bump reset
    races the learn thread's increment. `stats` follows the repo's
    locked-stats convention. `_clients` is learn/merge-thread-only by
    contract (probe paths build transient clients instead — see
    PeerClient's docstring).
    """

    _GUARDED_BY = {
        "_inbox": ("_lock", "_cond"),
        "_merges": ("_lock", "_cond"),
        "_peer_pids": ("_lock", "_cond"),
        "_peer_plans": ("_lock", "_cond"),
        "_plan_hash": ("_lock", "_cond"),
        "_plan_warned": ("_lock", "_cond"),
        "_seq": ("_lock", "_cond"),
        "stats": "_stats_lock",
    }
    _NOT_GUARDED = {
        "_clients": "single-caller contract: only the learn/merge "
                    "thread sends parts or pushes merges; probes use "
                    "transient clients",
        "_plan": "learn-thread-only exchange layout (set_plan at attach "
                 "time, read by allreduce callers); serve threads read "
                 "only the guarded _plan_hash",
        "_endpoint": "start()/close() lifecycle handle, controlling "
                     "thread only",
        "addrs": "immutable after construction: the seat roster is "
                 "fixed for the life of the collective (membership "
                 "tracks liveness separately)",
    }

    def __init__(self, rank: int, addrs: list[str],
                 wait_s: float | None = None):
        self.rank = rank
        self.addrs = [self._parse(a) for a in addrs]
        if rank < 0 or rank >= len(self.addrs):
            raise ValueError(
                f"rank {rank} outside the {len(self.addrs)}-seat roster")
        self.wait_s = wait_budget_s() if wait_s is None else wait_s
        self.membership = Membership(range(len(self.addrs)), rank)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._inbox: dict[tuple, np.ndarray] = {}
        self._merges: dict[int, tuple[int, np.ndarray]] = {}
        self._peer_pids: dict[int, int] = {}
        self._peer_plans: dict[int, str] = {}
        self._plan_hash: str | None = None
        self._plan_warned: set[int] = set()
        self._plan: ExchangePlan | None = None
        self._seq = 0
        self._clients: dict[int, PeerClient] = {}
        host, port = self.addrs[rank]
        self._endpoint = CollectiveEndpoint(self, host, port)
        self.stats = {"rounds_ok": 0, "rounds_aborted": 0, "peer_deaths": 0,
                      "serve_errors": 0,
                      "solo_rounds": 0, "bytes_sent": 0, "bytes_received": 0,
                      "merges_sent": 0, "merges_received": 0,
                      "merge_naks": 0, "probes_failed": 0,
                      "recv_waits_extended": 0,
                      # Partition-aware rounds: count + per-spec-class
                      # wire bytes SENT (the obs_report bytes/round
                      # breakdown; labels are the fixed _CLASS_LABELS
                      # vocabulary so telemetry names never churn).
                      "coll_rounds_part": 0, "coll_quant_rounds": 0,
                      "coll_bytes_rep": 0, "coll_bytes_model": 0,
                      "coll_bytes_expert": 0, "coll_bytes_pipe": 0,
                      "coll_bytes_other": 0}
        self._stats_lock = threading.Lock()

    @staticmethod
    def _parse(addr: str) -> tuple[str, int]:
        host, _, port = addr.rpartition(":")
        return host or "127.0.0.1", int(port)

    def start(self) -> "HostCollective":
        self._endpoint.start()
        return self

    def _bump(self, key: str, by: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] += by

    def stat(self, key: str) -> int:
        with self._stats_lock:
            return self.stats[key]

    def snapshot_stats(self) -> dict:
        with self._stats_lock:
            return dict(self.stats)

    # -- exchange-plan negotiation -----------------------------------------

    def set_plan(self, plan: ExchangePlan | None) -> None:
        """Pin this seat's partition-aware exchange plan (attach-time,
        before rounds run). The hash becomes part of every HELLO so
        peers can refuse a skewed plan; None reverts to the plan-less
        ring."""
        self._plan = plan
        with self._lock:
            self._plan_hash = None if plan is None else plan.plan_hash
            self._plan_warned.clear()

    @property
    def plan(self) -> "ExchangePlan | None":
        return self._plan

    def plan_hash(self) -> str | None:
        with self._lock:
            return self._plan_hash

    def check_plan_agreement(self) -> None:
        """Loud refusal of plan skew: raise PlanMismatch when any LIVE
        peer has reported (via HELLO, either direction) a non-None plan
        hash different from ours. A peer that has not negotiated yet
        (None) is NOT a mismatch — attach order races are expected; the
        check re-runs at every partitioned round."""
        with self._lock:
            mine = self._plan_hash
            peers = dict(self._peer_plans)
        if mine is None:
            return
        for rank in sorted(peers):
            theirs = peers[rank]
            if (theirs is not None and theirs != mine
                    and self.membership.is_live(rank)):
                raise PlanMismatch(
                    f"seat {self.rank} exchange plan {mine[:16]}... != "
                    f"seat {rank} plan {theirs[:16]}... — the seats were "
                    f"launched with different partition rules, quant "
                    f"mode, or overlap depth; refusing to merge under "
                    f"skewed plans")

    def _note_peer_plan(self, peer: int, plan_hash) -> bool:
        """Record a peer's advertised plan hash; True when it clashes
        with ours (both non-None, different). The first clash per peer
        logs loudly — the serve-side half of the refusal."""
        if not (0 <= peer < len(self.addrs)):
            return False
        with self._lock:
            if plan_hash is not None:
                self._peer_plans[peer] = str(plan_hash)
            mine = self._plan_hash
            clash = (mine is not None and plan_hash is not None
                     and str(plan_hash) != mine)
            warn = clash and peer not in self._plan_warned
            if warn:
                self._plan_warned.add(peer)
        if warn:
            import sys

            print(f"[collective] seat {self.rank}: REFUSING seat {peer} — "
                  f"exchange plan hash {str(plan_hash)[:16]}... != ours "
                  f"{mine[:16]}... (partition rules / quant / overlap "
                  f"skew)", file=sys.stderr)
        return clash

    # -- endpoint callbacks (serve threads) --------------------------------

    def _on_hello(self, info: dict) -> dict:
        peer = int(info.get("rank", -1))
        pid = int(info.get("pid", 0))
        if pid and 0 <= peer < len(self.addrs):
            with self._lock:
                self._peer_pids[peer] = pid
        clash = self._note_peer_plan(peer, info.get("plan"))
        live = self.membership.is_live(peer)
        return {"rank": self.rank, "epoch": self.membership.epoch,
                "pid": os.getpid(), "plan": self.plan_hash(),
                "accepted": live and not clash}

    def _on_part(self, payload) -> bool:
        sender, epoch, seq, phase, step, chunk, fmt = _PART_HDR.unpack_from(
            payload, 0)
        wire = len(payload) - _PART_HDR.size
        arr = _decode_part(bytes(payload[_PART_HDR.size:]), fmt)
        with self._cond:
            # Epoch gate: a PART from a past membership must NAK so the
            # lagging sender aborts its round instead of wedging ours.
            if epoch != self.membership.epoch \
                    or not self.membership.is_live(sender):
                return False
            self._inbox[(epoch, seq, phase, step, chunk)] = arr
            self._cond.notify_all()
        self._bump("bytes_received", wire)
        return True

    def _on_merge(self, payload) -> bool:
        sender, epoch, step = _MERGE_HDR.unpack_from(payload, 0)
        arr = np.frombuffer(bytes(payload[_MERGE_HDR.size:]), np.float32)
        if not self.membership.is_live(sender):
            self._bump("merge_naks")
            return False
        with self._cond:
            # Latest-wins per sender; epoch is informational for merges
            # (async mode tolerates cross-epoch contributions — the
            # staleness bound is in merge STEPS, the consumer's filter).
            prev = self._merges.get(sender)
            if prev is None or step >= prev[0]:
                self._merges[sender] = (step, arr)
        self._bump("merges_received")
        return True

    # -- membership / liveness ---------------------------------------------

    def _note_dead(self, rank: int) -> None:
        if self.membership.mark_dead(rank):
            self._bump("peer_deaths")
            self._on_epoch_change()
            import sys

            print(f"[collective] seat {self.rank}: peer seat {rank} marked "
                  f"dead; membership now {self.membership.live()} "
                  f"(epoch {self.membership.epoch})", file=sys.stderr)

    def _on_epoch_change(self) -> None:
        """Purge round state: in-flight PART keys belong to the dead
        epoch, and the per-epoch round seq restarts so survivors
        re-align on (epoch, seq=0)."""
        with self._cond:
            self._inbox.clear()
            self._seq = 0
            self._cond.notify_all()

    def probe_peer(self, rank: int, timeout: float = 2.0) -> bool:
        """One transient HELLO probe (sweep/timeout paths; never the
        learn thread's cached send clients — see PeerClient)."""
        host, port = self.addrs[rank]
        client = PeerClient(host, port, timeout=timeout)
        try:
            status, resp = client._exchange(
                OP_COLL_HELLO,
                json.dumps({"rank": self.rank, "pid": os.getpid(),
                            "epoch": self.membership.epoch,
                            "plan": self.plan_hash()}).encode())
            if status != ST_OK:
                raise TransportError(f"hello answered status {status}")
            reply = json.loads(bytes(resp))
            pid = int(reply.get("pid", 0))
            if pid:
                with self._lock:
                    self._peer_pids[rank] = pid
            self._note_peer_plan(rank, reply.get("plan"))
            return bool(reply.get("accepted", False))
        except (TransportError, OSError, ValueError):
            self._bump("probes_failed")
            return False
        finally:
            client.close()

    def peer_pid(self, rank: int) -> int | None:
        """Last pid a HELLO exchange proved for `rank` (publisher-pid
        resolution for the fleet's board validation); None before any
        contact."""
        with self._lock:
            return self._peer_pids.get(rank)

    # -- ring allreduce (learn thread) -------------------------------------

    def _client(self, rank: int) -> PeerClient:
        client = self._clients.get(rank)
        if client is None:
            host, port = self.addrs[rank]
            client = PeerClient(host, port, timeout=self.wait_s)
            self._clients[rank] = client
        return client

    def _send_part(self, to_rank: int, epoch: int, seq: int, phase: int,
                   step: int, chunk_idx: int, arr: np.ndarray,
                   fmt: int = FMT_F32, cls: str | None = None) -> None:
        payload = _encode_part(arr, fmt)
        hdr = _PART_HDR.pack(self.rank, epoch, seq, phase, step, chunk_idx,
                             fmt)
        try:
            status, resp = self._client(to_rank)._exchange(
                OP_COLL_PART, [hdr, payload])
        except (TransportError, OSError):
            self._note_dead(to_rank)
            raise PeerLost(f"peer seat {to_rank} died mid-send") from None
        if status != ST_OK or bytes(resp) != _ACCEPT:
            # The peer lives in a different epoch (it re-formed without
            # us, or we re-formed without it): abort and retry under
            # OUR current membership — if the peer really dropped us,
            # its own sends to us will NAK symmetrically.
            raise RoundAborted(
                f"peer seat {to_rank} rejected round part (epoch skew)")
        self._bump("bytes_sent", len(payload))
        if cls is not None:
            self._bump(f"coll_bytes_{cls}", len(payload))

    def _recv_part(self, from_rank: int, epoch: int, seq: int, phase: int,
                   step: int, chunk_idx: int, deadline: float) -> np.ndarray:
        key = (epoch, seq, phase, step, chunk_idx)
        while True:
            with self._cond:
                arr = self._inbox.pop(key, None)
                if arr is None and self.membership.epoch == epoch:
                    self._cond.wait(timeout=0.2)
                    arr = self._inbox.pop(key, None)
                if arr is not None:
                    return arr
            if self.membership.epoch != epoch:
                raise RoundAborted("membership changed under the round")
            if time.monotonic() < deadline:
                continue
            if self.probe_peer(from_rank):
                # Alive but not contributing yet (a starved seat waiting
                # for data, a long jit compile): lockstep allreduce
                # WAITS — that is the BSP contract, and `async` mode is
                # the documented escape when it is too tight. Only an
                # UNREACHABLE peer is dead; each successful probe renews
                # the wait budget.
                self._bump("recv_waits_extended")
                deadline = time.monotonic() + self.wait_s
                continue
            self._note_dead(from_rank)
            raise PeerLost(
                f"peer seat {from_rank} unreachable past the wait budget")

    def allreduce_mean(self, vec: np.ndarray,
                       plan: "ExchangePlan | None" = None) -> np.ndarray:
        """Mean of `vec` across the live seats. Solo membership returns
        a float32 copy of the input (demote-to-solo: the mean of one).
        Raises RoundAborted/PeerLost on membership churn — the caller
        retries, and the next attempt runs over the survivors.

        Plan-less (`plan=None`): today's full-vector f32 ring allreduce,
        byte-for-byte. With an ExchangePlan: the replicated class rides
        the ring, every sharded class goes owner-scoped (phase 2/3 star
        under the same round seq), hops optionally bf16 per the plan's
        quant — and the round first re-checks plan agreement so skewed
        seats refuse loudly instead of merging garbage."""
        ranks, epoch = self.membership.snapshot()
        k = len(ranks)
        vec = np.ascontiguousarray(vec, np.float32)
        if plan is not None and plan.length != vec.size:
            raise CollectiveError(
                f"exchange plan covers {plan.length} elements but the "
                f"vector has {vec.size} — stale plan for this learner")
        if k == 1:
            self._bump("solo_rounds")
            return vec.copy()
        with self._cond:
            seq = self._seq
        if plan is None:
            merged = self._ring_exchange(vec, ranks, epoch, seq)
        else:
            self.check_plan_agreement()
            fmt = plan.fmt
            merged = vec.copy()
            if "rep" in plan.segments:
                rep = self._ring_exchange(plan.gather(vec, "rep"), ranks,
                                          epoch, seq, fmt=fmt, cls="rep")
                plan.scatter(merged, "rep", rep)
            for ci, key in enumerate(plan.sharded_classes()):
                seg = self._star_exchange(plan.gather(vec, key), key, ci,
                                          ranks, epoch, seq, fmt)
                plan.scatter(merged, key, seg)
            self._bump("coll_rounds_part")
            if fmt == FMT_BF16:
                self._bump("coll_quant_rounds")
        with self._cond:
            # Advance only if the epoch survived the round: an abort
            # path resets seq to 0 and this increment must not undo it.
            if self.membership.epoch == epoch:
                self._seq = seq + 1
        self._bump("rounds_ok")
        return merged

    def _ring_exchange(self, vec: np.ndarray, ranks: list[int], epoch: int,
                       seq: int, fmt: int = FMT_F32,
                       cls: str | None = None) -> np.ndarray:
        """Classic 2(k-1)-step ring over `vec` -> elementwise mean.
        Quantized hops (`fmt=FMT_BF16`) decode to f32 at the receiver
        before accumulating (master accumulation); the allgather then
        forwards exactly-roundtripping bf16 words, and each seat
        roundtrips its self-owned chunk at the end, so every seat holds
        bit-identical bytes either way."""
        k = len(ranks)
        p = ranks.index(self.rank)
        nxt, prv = ranks[(p + 1) % k], ranks[(p - 1) % k]
        chunks = [c.copy() for c in np.array_split(vec, k)]
        deadline = time.monotonic() + self.wait_s
        for phase in (0, 1):  # 0 = reduce-scatter, 1 = allgather
            for s in range(k - 1):
                if phase == 0:
                    send_i, recv_i = (p - s) % k, (p - s - 1) % k
                else:
                    send_i, recv_i = (p + 1 - s) % k, (p - s) % k
                self._send_part(nxt, epoch, seq, phase, s, send_i,
                                chunks[send_i], fmt=fmt, cls=cls)
                got = self._recv_part(prv, epoch, seq, phase, s, recv_i,
                                      deadline)
                if got.shape != chunks[recv_i].shape:
                    raise CollectiveError(
                        f"chunk shape mismatch from seat {prv}: "
                        f"{got.shape} != {chunks[recv_i].shape}")
                chunks[recv_i] = chunks[recv_i] + got if phase == 0 else got
        if fmt != FMT_F32:
            # The chunk this seat reduced (never received back) is still
            # raw f32 — roundtrip it so our bytes match what every peer
            # decoded from the wire.
            own = (p + 1) % k
            chunks[own] = _roundtrip(chunks[own], fmt)
        return np.concatenate(chunks) / np.float32(k)

    def _star_exchange(self, seg: np.ndarray, key: str, class_idx: int,
                       ranks: list[int], epoch: int, seq: int,
                       fmt: int) -> np.ndarray:
        """Owner-scoped exchange of one sharded class: members send
        their segment to the class's deterministic owner seat (phase 2),
        the owner f32-accumulates, divides by k, and fans the merged
        segment back (phase 3). The owner applies the same wire
        roundtrip to its own copy, so all seats end bit-identical. Owner
        assignment rotates over the LIVE ranks by class index — every
        seat derives it from the same epoch-pinned snapshot."""
        k = len(ranks)
        owner = ranks[class_idx % k]
        cls = class_label(key)
        deadline = time.monotonic() + self.wait_s
        if self.rank == owner:
            acc = seg.astype(np.float32, copy=True)
            for r in ranks:
                if r == owner:
                    continue
                got = self._recv_part(r, epoch, seq, 2, class_idx, r,
                                      deadline)
                if got.size != seg.size:
                    raise CollectiveError(
                        f"class {key!r} segment size mismatch from seat "
                        f"{r}: {got.size} != {seg.size}")
                acc += got
            merged = acc / np.float32(k)
            for r in ranks:
                if r == owner:
                    continue
                self._send_part(r, epoch, seq, 3, class_idx, r, merged,
                                fmt=fmt, cls=cls)
            return _roundtrip(merged, fmt)
        self._send_part(owner, epoch, seq, 2, class_idx, self.rank, seg,
                        fmt=fmt, cls=cls)
        got = self._recv_part(owner, epoch, seq, 3, class_idx, self.rank,
                              deadline)
        if got.size != seg.size:
            raise CollectiveError(
                f"class {key!r} merged segment size mismatch from owner "
                f"seat {owner}: {got.size} != {seg.size}")
        return got

    # -- async merge plane (learn thread) ----------------------------------

    def push_merge(self, vec: np.ndarray, step: int) -> int:
        """Fire this seat's params vector at every live peer; returns
        how many accepted. Never waits beyond the per-send socket
        timeout — a dead peer is marked and skipped, a NAK (the peer
        dropped us) just doesn't count."""
        vec = np.ascontiguousarray(vec, np.float32)
        hdr = _MERGE_HDR.pack(self.rank, self.membership.epoch, step)
        accepted = 0
        for peer in self.membership.live():
            if peer == self.rank:
                continue
            try:
                status, resp = self._client(peer)._exchange(
                    OP_COLL_MERGE, [hdr, vec.tobytes()])
            except (TransportError, OSError):
                self._note_dead(peer)
                continue
            if status == ST_OK and bytes(resp) == _ACCEPT:
                accepted += 1
                self._bump("merges_sent")
                self._bump("bytes_sent", vec.nbytes)
            else:
                self._bump("merge_naks")
        return accepted

    def take_merges(self, min_step: int) -> dict[int, tuple[int, np.ndarray]]:
        """Latest contribution per live peer at merge-step >= `min_step`
        (the bounded-staleness filter); staler entries are left in place
        (a future push overwrites them) but never returned."""
        live = set(self.membership.live())
        with self._cond:
            return {rank: (step, arr)
                    for rank, (step, arr) in self._merges.items()
                    if rank in live and step >= min_step}

    def close(self) -> None:
        self._endpoint.stop()
        for client in self._clients.values():
            client.close()
        self._clients.clear()
