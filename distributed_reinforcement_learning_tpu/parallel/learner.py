"""Multi-chip sharded learner: pjit any agent's learn step over a mesh.

Replaces nothing in the reference (its learner is a single process holding
TF variables, `train_impala.py:37-62`) — this is the capability the TPU
design adds: the same pure `learn(state, batch, ...)` function compiled
once over an N-chip mesh, with

- the batch sharded over the `data` axis (each chip grads its shard; XLA
  emits the `psum` over ICI because the returned params are consistent),
- params / optimizer moments either replicated or, when the mesh has a
  `model` axis > 1, sharded on their output-feature dim (tensor
  parallelism; XLA GSPMD inserts the activation collectives).

The sharding rule is structural — any ≥2-D leaf whose last dim divides the
model axis and is big enough to be worth splitting — so it applies to the
whole TrainState pytree (params *and* Adam/RMSProp moments) without
per-model annotations.
"""

from __future__ import annotations

from typing import Any

import jax

from distributed_reinforcement_learning_tpu.parallel import mesh as mesh_lib
from distributed_reinforcement_learning_tpu.parallel.mesh import (
    EXPERT_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    Mesh,
    NamedSharding,
    P,
)

# Leaves smaller than this stay replicated: splitting a 256-float bias over
# ICI costs more in collective latency than the shard saves.
_MIN_SHARD_SIZE = 4096


def _leaf_sharding(mesh: Mesh, leaf: jax.ShapeDtypeStruct) -> NamedSharding:
    m = mesh.shape.get(MODEL_AXIS, 1)
    if (
        m > 1
        and leaf.ndim >= 2
        and leaf.shape[-1] % m == 0
        and leaf.size >= _MIN_SHARD_SIZE
    ):
        return mesh_lib.model_kernel_sharding(mesh, leaf.ndim)
    return mesh_lib.replicated(mesh)


def train_state_sharding(mesh: Mesh, abstract_state: Any):
    """Sharding pytree for a TrainState, from its `jax.eval_shape` skeleton.

    Three rules, first match wins, applied to params AND optimizer
    moments (the moments mirror the params tree, so the same path keys
    appear):
    - leaves under a `blocks_stacked` key (the pipelined transformer
      body) shard their leading layer dim over `pipe`;
    - expert-stacked MoE leaves (`moe_w*`/`moe_b*`) shard their leading
      expert dim over `expert`;
    - any other big 2-D+ kernel shards its output-feature dim over
      `model` (Megatron column style); the rest replicate.
    """
    pipe = mesh.shape.get(PIPE_AXIS, 1)
    ep = mesh.shape.get(EXPERT_AXIS, 1)

    def rule(path, leaf):
        keys = [str(k) for k in path]
        if (
            pipe > 1
            and any("blocks_stacked" in k for k in keys)
            and leaf.ndim >= 1
            and leaf.shape[0] % pipe == 0
        ):
            # % not ==: with virtual stages the stored layout stays
            # [num_layers, ...] and each pipe shard holds its stage's
            # contiguous layers-per-stage group.
            return NamedSharding(mesh, P(PIPE_AXIS))
        if (
            ep > 1
            and any("moe_" in k and "moe_gate" not in k for k in keys)
            and leaf.ndim >= 2
            and leaf.shape[0] % ep == 0
        ):
            return NamedSharding(mesh, P(EXPERT_AXIS))
        return _leaf_sharding(mesh, leaf)

    return jax.tree_util.tree_map_with_path(rule, abstract_state)


class ShardedLearner:
    """Bind an agent's `_learn` to a mesh.

    `num_data_args`: learn-args after the state that carry a leading batch
    dim (IMPALA: 1 = batch; Ape-X/R2D2: 2 = batch + is_weight).
    `num_aux_outputs`: outputs after the new state (metrics, and for the
    replay agents the per-element TD/priority vector) — these are gathered
    to replicated form since the host consumes them.
    """

    def __init__(
        self,
        agent,
        mesh: Mesh,
        num_data_args: int = 1,
        num_aux_outputs: int = 1,
    ):
        self.agent = agent
        self.mesh = mesh
        abstract_state = jax.eval_shape(agent.init_state, jax.random.PRNGKey(0))
        self.state_sharding = train_state_sharding(mesh, abstract_state)
        self._data_sh = mesh_lib.data_sharding(mesh)
        self._repl = mesh_lib.replicated(mesh)
        in_shardings = (self.state_sharding,) + (self._data_sh,) * num_data_args
        out_shardings = (self.state_sharding,) + (self._repl,) * num_aux_outputs
        self.learn = jax.jit(
            agent._learn,
            in_shardings=in_shardings,
            out_shardings=out_shardings,
            donate_argnums=(0,),
        )
        # Split learn step on the mesh (learner-tier allreduce seam):
        # gradients come OUT in the params' sharding — the host-side
        # partition plan (parallel/partition.py) then exchanges each
        # spec class owner-scoped instead of ring-reducing the full
        # vector. apply_grads does NOT donate state, mirroring the
        # agents' own split jits (the tier holds state across the
        # exchange). Only the replay families' (state, batch,
        # is_weight) arity carries the seam.
        if (num_data_args == 2 and hasattr(agent, "_grads")
                and hasattr(agent, "_apply_grads")):
            params_sh = self.state_sharding.params
            self.grads = jax.jit(
                agent._grads,
                in_shardings=(self.state_sharding,) + (self._data_sh,) * 2,
                out_shardings=(params_sh, self._repl, self._repl),
            )
            self.apply_grads = jax.jit(
                agent._apply_grads,
                in_shardings=(self.state_sharding, params_sh, self._repl),
                out_shardings=(self.state_sharding, self._repl),
            )
        # K-step scanned learn over [K, B, ...] stacks (agents/common
        # scan_learn): the scan carries the sharded TrainState, each
        # iteration's batch slice shards its B dim over `data`. Only the
        # (state, batch) signature — replay agents' weighted learn stays
        # per-step at the runner level.
        if num_data_args == 1:
            from distributed_reinforcement_learning_tpu.agents.common import scan_learn

            self.stacked_data_sharding = NamedSharding(mesh, P(None, mesh_lib.DATA_AXIS))
            self.learn_many = jax.jit(
                scan_learn(agent._learn),
                in_shardings=(self.state_sharding, self.stacked_data_sharding),
                out_shardings=(self.state_sharding, self._repl),
                donate_argnums=(0,),
            )

    def init_state(self, rng: jax.Array):
        """Initialize the TrainState directly into its mesh sharding."""
        init = jax.jit(self.agent.init_state, out_shardings=self.state_sharding)
        return init(rng)

    def place_state(self, state):
        return jax.device_put(state, self.state_sharding)

    def shard_batch(self, tree):
        """Host batch -> device, leading dim split over the `data` axis."""
        return jax.device_put(tree, self._data_sh)
