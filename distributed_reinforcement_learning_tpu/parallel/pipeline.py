"""Pipeline parallelism: GPipe microbatch schedule over a `pipe` mesh axis.

The reference has no model partitioning of any kind — its learner is one
process holding every variable (`/root/reference/train_impala.py:33-62`).
This module adds the pipeline axis of the standard TPU parallelism
toolkit (DP/TP/SP/PP/EP): a stack of identical stages is laid out one
stage per device along `pipe`, microbatches stream through the stages,
and activations hop stage-to-stage with `lax.ppermute` — the collective
rides one neighbor ICI link per hop, which is why the `pipe` axis is
outermost in `make_mesh` (pipeline traffic is the lightest, so it can
take the slowest links, including DCN on multi-host meshes).

Idiomatic-JAX formulation (no schedules-as-frameworks): one `shard_map`
over the mesh, a `lax.scan` over the M + S - 1 ticks of the GPipe
schedule, and `where(stage == 0, fresh_microbatch, received)` to source
each stage's input. Everything is statically shaped and differentiable
(`ppermute`/`where`/`dynamic_update_slice` all have transpose rules), so
the same code path serves training; `tests/test_pipeline.py` verifies
values AND grads against the sequential stack on an 8-virtual-device
mesh.

Contract:
- `stage_params`: pytree whose leaves carry a leading stage dimension of
  size `pipe` (one stage per device — build with `stack_stage_params` or
  `jax.vmap(init)`).
- `stage_fn(params_i, act) -> act`: one stage; activation pytree
  structure and shapes are invariant across stages (true for
  transformer blocks; broadcast side inputs like segment ids ride
  through the activation pytree unchanged).
- The global batch (leading dim of every activation leaf) must divide
  into `num_microbatches` equal microbatches.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributed_reinforcement_learning_tpu.parallel.mesh import PIPE_AXIS, pcast_varying


def stack_stage_params(init_fn: Callable[[jax.Array], Any], rng: jax.Array, n_stages: int):
    """[n_stages, ...]-stacked params from a per-stage init, split rngs."""
    return jax.vmap(init_fn)(jax.random.split(rng, n_stages))


def _pipeline_shard(
    stage_params: Any,
    acts: Any,
    *,
    stage_fn: Callable[[Any, Any], Any],
    num_microbatches: int,
    axis_name: str,
    varying_axes: tuple[str, ...] = (),
):
    """Per-device body: run this device's stage over the microbatch stream."""
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    params_local = jax.tree.map(lambda p: p[0], stage_params)  # [1, ...] shard

    m = num_microbatches
    split = lambda a: a.reshape(m, a.shape[0] // m, *a.shape[1:])
    mb = jax.tree.map(split, acts)  # [M, B/M, ...]

    # ppermute fills unsourced entries (stage 0's receive) with zeros;
    # they are dead — stage 0 always selects the fresh microbatch.
    shift = [(i, i + 1) for i in range(n_stages - 1)]
    varying = lambda x: pcast_varying(x, (axis_name, *varying_axes))
    zero_mb = jax.tree.map(lambda a: varying(jnp.zeros_like(a[0])), mb)

    def tick(carry, t):
        recv, out_buf = carry
        # Ticks past the last microbatch keep feeding the final one; its
        # duplicate outputs land outside the valid collect window below.
        x_t = jax.tree.map(lambda a: a[jnp.clip(t, 0, m - 1)], mb)
        inp = jax.tree.map(lambda a, b: jnp.where(stage == 0, a, b), x_t, recv)
        out = stage_fn(params_local, inp)
        recv = jax.tree.map(lambda a: jax.lax.ppermute(a, axis_name, shift), out)
        # The last stage finishes microbatch t - (S-1) at tick t.
        o = t - (n_stages - 1)
        valid = (o >= 0) & (stage == n_stages - 1)
        out_buf = jax.tree.map(
            lambda buf, a: jnp.where(
                valid,
                jax.lax.dynamic_update_index_in_dim(buf, a, jnp.maximum(o, 0), 0),
                buf,
            ),
            out_buf,
            out,
        )
        return (recv, out_buf), None

    out_buf0 = jax.tree.map(lambda a: varying(jnp.zeros_like(a)), mb)
    ticks = jnp.arange(m + n_stages - 1)
    (_, out_buf), _ = jax.lax.scan(tick, (zero_mb, out_buf0), ticks)
    # Only the last stage holds real outputs; a masked psum broadcasts
    # them so every pipe rank returns the full result (out_specs can then
    # keep the batch sharding identical to the input's).
    out_buf = jax.tree.map(
        lambda a: jax.lax.psum(
            jnp.where(stage == n_stages - 1, a, jnp.zeros_like(a)), axis_name
        ),
        out_buf,
    )
    return jax.tree.map(lambda a: a.reshape(a.shape[0] * a.shape[1], *a.shape[2:]), out_buf)


def pipeline_apply(
    mesh: Mesh,
    stage_fn: Callable[[Any, Any], Any],
    stage_params: Any,
    acts: Any,
    *,
    num_microbatches: int,
    batch_axis: str | None = None,
) -> Any:
    """Apply `n_stages` chained stages to `acts` with the GPipe schedule.

    `stage_params` leaves are `[n_stages, ...]` with n_stages equal to
    the mesh's `pipe` axis size; `acts` is a pytree of `[B, ...]` arrays
    (optionally batch-sharded over `batch_axis`). Returns
    `stage_{S-1}(... stage_0(acts))` with the input's sharding.
    """
    n = mesh.shape.get(PIPE_AXIS, 1)
    if n < 2:
        raise ValueError(f"mesh {dict(mesh.shape)} has no '{PIPE_AXIS}' axis > 1")
    lead = {leaf.shape[0] for leaf in jax.tree.leaves(stage_params)}
    if lead != {n}:
        raise ValueError(f"stage_params leading dims {lead} != pipe axis size {n}")
    batch = {leaf.shape[0] for leaf in jax.tree.leaves(acts)}
    if len(batch) != 1:
        raise ValueError(f"activation leaves disagree on batch dim: {batch}")
    (b,) = batch
    per = b if batch_axis is None else b // mesh.shape[batch_axis]
    if per % num_microbatches != 0:
        raise ValueError(
            f"per-device batch {per} not divisible by num_microbatches={num_microbatches}"
        )
    act_spec = jax.tree.map(lambda _: P(batch_axis), acts)
    f = jax.shard_map(
        lambda p, a: _pipeline_shard(
            p,
            a,
            stage_fn=stage_fn,
            num_microbatches=num_microbatches,
            axis_name=PIPE_AXIS,
            varying_axes=() if batch_axis is None else (batch_axis,),
        ),
        mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(PIPE_AXIS), stage_params), act_spec),
        out_specs=act_spec,
    )
    return f(stage_params, acts)
