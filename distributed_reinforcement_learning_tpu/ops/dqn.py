"""Q-value selection and double-DQN target construction.

Re-design of `/root/reference/optimizer/dqn.py:3-7` and the inline target
math of `agent/apex.py:60-69` as pure jit-safe functions. The reference's
flat-batch (`axis=1`) and sequence-batch (`axis=2`,
`optimizer/burn_in.py:17-21`) variants collapse into one gather over the
trailing action axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def take_state_action_value(q_values: jax.Array, actions: jax.Array) -> jax.Array:
    """Q(s, a) gather over the trailing action axis.

    Works for `[B, A]` and `[B, T, A]` q-values alike (the reference needed
    two copies: `optimizer/dqn.py:6` axis=1 and `optimizer/burn_in.py:20`
    axis=2).
    """
    taken = jnp.take_along_axis(q_values, actions[..., None].astype(jnp.int32), axis=-1)
    return taken[..., 0]


def double_q_target(
    next_main_q: jax.Array,
    next_target_q: jax.Array,
    rewards: jax.Array,
    discounts: jax.Array,
) -> jax.Array:
    """Double-DQN target: r + gamma * Q_target(s', argmax_a Q_main(s', a)).

    Parity with `agent/apex.py:60-65`: action selection by the main net,
    evaluation by the target net, stop-gradiented.
    """
    next_action = jnp.argmax(next_main_q, axis=-1)
    next_value = take_state_action_value(next_target_q, next_action)
    return jax.lax.stop_gradient(rewards + discounts * next_value)


def td_error(target_value: jax.Array, state_action_value: jax.Array) -> jax.Array:
    """|target - Q(s,a)|, the priority signal (`agent/apex.py:131-133`)."""
    return jnp.abs(target_value - state_action_value)
