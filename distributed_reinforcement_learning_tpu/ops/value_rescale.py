"""R2D2 value-function rescaling h(x) and its closed-form inverse.

Parity with `/root/reference/optimizer/burn_in.py:23-32` (R2D2 paper
table 2 / "Observe and Look Further" Prop. A.2).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def value_rescale(x: jax.Array, eps: float = 1e-3) -> jax.Array:
    """h(x) = sign(x) * (sqrt(|x| + 1) - 1) + eps * x."""
    return jnp.sign(x) * (jnp.sqrt(jnp.abs(x) + 1.0) - 1.0) + eps * x


def inverse_value_rescale(x: jax.Array, eps: float = 1e-3) -> jax.Array:
    """h^{-1}(x), exact closed form for the eps-regularized rescaling."""
    return jnp.sign(x) * (
        jnp.square((jnp.sqrt(1.0 + 4.0 * eps * (jnp.abs(x) + 1.0 + eps)) - 1.0) / (2.0 * eps)) - 1.0
    )
