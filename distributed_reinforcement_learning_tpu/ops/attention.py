"""Multi-head attention ops: dense reference + blockwise online-softmax.

The reference has no attention at all — its long-context strategy is
short LSTM unrolls with stored state and burn-in (SURVEY §5.7,
`/root/reference/model/r2d2_lstm.py:65-112`). This module is the
TPU-native long-context generalization: a causal multi-head attention
primitive whose blockwise form (online-softmax accumulation over KV
blocks, the flash-attention recurrence) is exactly the per-device step
of ring attention (`parallel/sequence.py`), so the sequence-parallel
path and the single-device path share one numerics core.

Conventions: `q/k/v` are `[B, T, H, D]` (batch, time, heads, head_dim);
positions are absolute so sequence-sharded callers can pass global
offsets for causal masking.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Finite stand-in for -inf in masked logits: big enough that exp(x - m)
# underflows against any real logit, small enough that subtracting two of
# them is exact (no nan from inf - inf in the online-softmax rescale).
_MASK_VALUE = -0.5 * float(jnp.finfo(jnp.float32).max)


def _causal_mask(q_pos: jax.Array, k_pos: jax.Array) -> jax.Array:
    """[Tq, Tk] bool: query at global position i may attend keys <= i."""
    return q_pos[:, None] >= k_pos[None, :]


def _combined_mask(causal, q_pos, k_pos, q_seg, k_seg):
    """[B|1, 1, Tq, Tk] bool mask, or None when nothing constrains.

    Segment ids (per batch row, e.g. episode indices from cumsum(done))
    confine attention within an episode: RL sequences cross episode
    boundaries mid-unroll, and a transformer must not attend across a
    reset the way the recurrent nets zero their (h, c) carries.
    """
    mask = None
    if causal:
        mask = _causal_mask(q_pos, k_pos)[None, None]
    if q_seg is not None:
        seg = (q_seg[:, None, :, None] == k_seg[:, None, None, :])
        mask = seg if mask is None else (mask & seg)
    return mask


def dense_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    q_offset: int | jax.Array = 0,
    kv_offset: int | jax.Array = 0,
    q_seg: jax.Array | None = None,
    k_seg: jax.Array | None = None,
) -> jax.Array:
    """Plain softmax(QKᵀ/√d)V — the golden reference the blockwise and
    ring paths are tested against, and the fast path for short sequences
    where one fused XLA softmax beats any blocking."""
    dim = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (dim**-0.5)
    q_pos = q_offset + jnp.arange(q.shape[1])
    k_pos = kv_offset + jnp.arange(k.shape[1])
    mask = _combined_mask(causal, q_pos, k_pos, q_seg, k_seg)
    if mask is not None:
        logits = jnp.where(mask, logits, _MASK_VALUE)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    if mask is not None:
        # A fully-masked row (no same-segment key) must output zeros, not
        # a uniform average of _MASK_VALUE logits.
        probs = jnp.where(mask, probs, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def attention_block_init(q: jax.Array):
    """(m, l, o) accumulator for online-softmax over KV blocks.

    m: running row max of logits `[B, H, Tq]` (f32); l: running softmax
    denominator `[B, H, Tq]` (f32); o: unnormalized numerator
    `[B, Tq, H, D]` (f32 — accumulating in the compute dtype loses the
    small-probability tail in bf16).
    """
    b, t, h, _ = q.shape
    m = jnp.full((b, h, t), _MASK_VALUE, jnp.float32)
    l = jnp.zeros((b, h, t), jnp.float32)
    o = jnp.zeros(q.shape, jnp.float32)
    return m, l, o


def attention_block_step(
    acc,
    q: jax.Array,
    k_block: jax.Array,
    v_block: jax.Array,
    *,
    causal: bool,
    q_pos: jax.Array,
    k_pos: jax.Array,
    q_seg: jax.Array | None = None,
    k_seg: jax.Array | None = None,
):
    """Fold one KV block into the accumulator (flash-attention recurrence).

    `q_pos`/`k_pos` are global positions (`[Tq]`, `[Tk]`), so a
    sequence-sharded caller gets correct causal masking across shards;
    `q_seg`/`k_seg` (`[B, Tq]`, `[B, Tk]`) optionally confine attention
    within episode segments. Masked probabilities are zeroed explicitly
    (not just pushed to `_MASK_VALUE`) so a fully-masked block
    contributes exactly nothing.
    """
    m, l, o = acc
    dim = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_block).astype(jnp.float32) * (dim**-0.5)
    mask = _combined_mask(causal, q_pos, k_pos, q_seg, k_seg)
    if mask is not None:
        s = jnp.where(mask, s, _MASK_VALUE)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    scale = jnp.exp(m - m_new)
    l_new = l * scale + jnp.sum(p, axis=-1)
    o_new = o * scale.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v_block.astype(jnp.float32)
    )
    return m_new, l_new, o_new


def attention_block_finish(acc, dtype) -> jax.Array:
    """Normalize the accumulator into the attention output `[B, T, H, D]`."""
    _, l, o = acc
    denom = jnp.maximum(l, jnp.finfo(jnp.float32).tiny)
    return (o / denom.transpose(0, 2, 1)[..., None]).astype(dtype)


def causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    q_seg: jax.Array | None = None,
    k_seg: jax.Array | None = None,
    backend: str = "auto",
) -> jax.Array:
    """Causal (optionally segment-masked) MHA with backend dispatch.

    `auto` resolves to the fused Pallas flash kernels on TPU
    (`ops/pallas/attention.py`) when T divides by a >=8 power-of-two
    block — VMEM holds only per-block operands, so T is HBM-bound —
    else to plain dense softmax for short sequences or the blockwise
    online-softmax path for long ones. All paths share the same
    numerics contract (validated against dense in tests).
    """
    from distributed_reinforcement_learning_tpu.ops.pallas import resolve_backend
    from distributed_reinforcement_learning_tpu.ops.pallas.attention import flash_blocks

    if (q_seg is None) != (k_seg is None):
        raise ValueError("q_seg and k_seg must be provided together")
    b, t, h, d = q.shape
    resolved = resolve_backend(backend)
    block = flash_blocks(t)
    if resolved in ("pallas", "pallas_interpret") and block > 0:
        from distributed_reinforcement_learning_tpu.ops.pallas.attention import (
            flash_attention_bhtd)

        zeros = jnp.zeros((b, t), jnp.int32)
        qs = zeros if q_seg is None else q_seg.astype(jnp.int32)
        ks = zeros if k_seg is None else k_seg.astype(jnp.int32)
        flat = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        seg_flat = lambda s: jnp.repeat(s, h, axis=0)
        out = flash_attention_bhtd(
            flat(q), flat(k), flat(v), seg_flat(qs), seg_flat(ks),
            block_q=min(block, 128), block_kv=min(block, 128),
            interpret=(resolved == "pallas_interpret"),
        )
        return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    if t <= 1024:
        return dense_attention(q, k, v, causal=True, q_seg=q_seg, k_seg=k_seg)
    return blockwise_attention(
        q, k, v, causal=True, block_size=512,
        segment_ids=q_seg, kv_segment_ids=k_seg,
    )


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_size: int = 512,
    segment_ids: jax.Array | None = None,
    kv_segment_ids: jax.Array | None = None,
) -> jax.Array:
    """Single-device attention computed block-by-block over keys.

    Memory is O(T·block) instead of O(T²) — the long-context path when a
    full logits matrix would blow HBM. Same numerics core as ring
    attention; used as its single-device functional test double.
    `segment_ids` `[B, Tq]` optionally confines attention within
    episodes (`kv_segment_ids` defaults to it for self-attention).
    """
    t_kv = k.shape[1]
    block_size = min(block_size, t_kv)
    if t_kv % block_size != 0:
        raise ValueError(f"kv length {t_kv} not divisible by block {block_size}")
    n_blocks = t_kv // block_size
    q_pos = jnp.arange(q.shape[1])
    kb = k.reshape(k.shape[0], n_blocks, block_size, *k.shape[2:])
    vb = v.reshape(v.shape[0], n_blocks, block_size, *v.shape[2:])
    kv_seg = segment_ids if kv_segment_ids is None else kv_segment_ids
    segb = (
        None
        if kv_seg is None
        else kv_seg.reshape(kv_seg.shape[0], n_blocks, block_size)
    )

    def step(acc, blk):
        k_blk, v_blk, seg_blk, i = blk
        k_pos = i * block_size + jnp.arange(block_size)
        return (
            attention_block_step(
                acc, q, k_blk, v_blk, causal=causal, q_pos=q_pos, k_pos=k_pos,
                q_seg=segment_ids, k_seg=seg_blk,
            ),
            None,
        )

    xs = (
        kb.swapaxes(0, 1),
        vb.swapaxes(0, 1),
        None if segb is None else segb.swapaxes(0, 1),
        jnp.arange(n_blocks),
    )
    acc, _ = jax.lax.scan(step, attention_block_init(q), xs)
    return attention_block_finish(acc, q.dtype)
