"""Fused LSTM sequence kernels: forward recursion + hand-derived BPTT.

Design (see `ops/lstm.py` for the op-level contract):

- The input projection is done outside (one big MXU matmul over [B*T]).
- The forward kernel owns the sequential part only: T dependent steps of
  `gates_t = xg_t + h @ Wh` -> gate nonlinearities -> done-masked carry,
  entirely in VMEM. The time loop is a static Python unroll (T <= ~20:
  IMPALA `config.json:40`, R2D2 seq_len 10 `config.json:16`), so each
  step's [B, H] x [H, 4H] matmul hits the MXU with no HBM round-trip of
  the carries between steps — the lax.scan baseline is an XLA while-loop
  whose carries live in HBM.
- The backward kernel replays the recursion in reverse, recomputing gate
  activations from the saved (xg, h_all, c_all) residuals (cheaper than
  storing four activated gate arrays), and emits per-step dgates. The two
  weight-gradient contractions (dWh, and dxg -> dWx outside) are NOT in
  the kernel: they are batch-parallel einsums over the emitted dgates,
  which XLA schedules on the MXU better than a serialized in-loop
  accumulation would.
- `jax.custom_vjp` glues the pair together; gradient correctness is
  tested against autodiff of the lax.scan reference (tests/test_pallas.py).

Grid: 1-D over batch tiles; each program runs all T steps for its slice,
with `Wh` replicated (read-only) across programs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_reinforcement_learning_tpu.ops.pallas import pick_block

_BLOCK_B = 128


def _sig(x):
    return jax.nn.sigmoid(x)


def _fwd_kernel(xg_ref, wh_ref, keep_ref, h0_ref, c0_ref,
                hall_ref, call_ref, hT_ref, cT_ref):
    T = xg_ref.shape[0]
    wh = wh_ref[:]
    h = h0_ref[:]
    c = c0_ref[:]
    for t in range(T):  # static unroll; T is a compile-time constant
        gates = xg_ref[t] + jnp.dot(h, wh, preferred_element_type=jnp.float32)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        new_c = _sig(f + 1.0) * c + _sig(i) * jnp.tanh(g)
        new_h = _sig(o) * jnp.tanh(new_c)
        hall_ref[t] = new_h
        call_ref[t] = new_c
        k = keep_ref[t]  # [B, 1], broadcasts over H lanes
        h = new_h * k
        c = new_c * k
    hT_ref[:] = h
    cT_ref[:] = c


def _bwd_kernel(xg_ref, wh_ref, keep_ref, h0_ref, c0_ref, hall_ref, call_ref,
                dhall_ref, dhT_ref, dcT_ref,
                dxg_ref, dh0_ref, dc0_ref):
    T = xg_ref.shape[0]
    wh = wh_ref[:]
    dH = dhT_ref[:]  # grad wrt the POST-mask carried h (keep applied below)
    dC = dcT_ref[:]
    for t in reversed(range(T)):
        if t == 0:
            h_prev, c_in = h0_ref[:], c0_ref[:]
        else:
            k_prev = keep_ref[t - 1]
            h_prev, c_in = hall_ref[t - 1] * k_prev, call_ref[t - 1] * k_prev
        # Recompute gate activations (forward stores only h_all/c_all).
        gates = xg_ref[t] + jnp.dot(h_prev, wh, preferred_element_type=jnp.float32)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        si, sf, sg, so = _sig(i), _sig(f + 1.0), jnp.tanh(g), _sig(o)
        tc = jnp.tanh(call_ref[t])

        k = keep_ref[t]
        dh = dhall_ref[t] + k * dH  # pre-mask h_t grad: emitted + carried paths
        dc = k * dC + dh * so * (1.0 - tc * tc)
        d_o = dh * tc * so * (1.0 - so)
        d_i = dc * sg * si * (1.0 - si)
        d_f = dc * c_in * sf * (1.0 - sf)
        d_g = dc * si * (1.0 - sg * sg)
        dgates = jnp.concatenate([d_i, d_f, d_g, d_o], axis=-1)
        dxg_ref[t] = dgates
        # Contract dgates' 4H dim against Wh's 4H dim: dgates @ Wh^T.
        dH = jax.lax.dot_general(
            dgates, wh, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dC = dc * sf
    dh0_ref[:] = dH
    dc0_ref[:] = dC


def _specs(T: int, B: int, H: int, block_b: int):
    seq3 = lambda d: pl.BlockSpec((T, block_b, d), lambda i: (0, i, 0), memory_space=pltpu.VMEM)
    mat = lambda d: pl.BlockSpec((block_b, d), lambda i: (i, 0), memory_space=pltpu.VMEM)
    full = pl.BlockSpec((H, 4 * H), lambda i: (0, 0), memory_space=pltpu.VMEM)
    return seq3, mat, full


def _fwd_call(xg, wh, keep, h0, c0, interpret: bool):
    T, B, G = xg.shape
    H = G // 4
    block_b = pick_block(B, _BLOCK_B)
    seq3, mat, full = _specs(T, B, H, block_b)
    return pl.pallas_call(
        _fwd_kernel,
        grid=(B // block_b,),
        in_specs=[seq3(G), full, seq3(1), mat(H), mat(H)],
        out_specs=[seq3(H), seq3(H), mat(H), mat(H)],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H), jnp.float32),
            jax.ShapeDtypeStruct((T, B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
        ],
        interpret=interpret,
    )(xg, wh, keep, h0, c0)


def _bwd_call(xg, wh, keep, h0, c0, h_all, c_all, dh_all, dhT, dcT, interpret: bool):
    T, B, G = xg.shape
    H = G // 4
    block_b = pick_block(B, _BLOCK_B)
    seq3, mat, full = _specs(T, B, H, block_b)
    return pl.pallas_call(
        _bwd_kernel,
        grid=(B // block_b,),
        in_specs=[seq3(G), full, seq3(1), mat(H), mat(H), seq3(H), seq3(H),
                  seq3(H), mat(H), mat(H)],
        out_specs=[seq3(G), mat(H), mat(H)],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, G), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
        ],
        interpret=interpret,
    )(xg, wh, keep, h0, c0, h_all, c_all, dh_all, dhT, dcT)


@functools.cache
def _make_lstm(interpret: bool):
    """custom_vjp-wrapped (forward, backward) pallas pair."""

    @jax.custom_vjp
    def f(xg, wh, keep, h0, c0):
        h_all, _, hT, cT = _fwd_call(xg, wh, keep, h0, c0, interpret)
        return h_all, hT, cT

    def f_fwd(xg, wh, keep, h0, c0):
        h_all, c_all, hT, cT = _fwd_call(xg, wh, keep, h0, c0, interpret)
        return (h_all, hT, cT), (xg, wh, keep, h0, c0, h_all, c_all)

    def f_bwd(res, grads):
        xg, wh, keep, h0, c0, h_all, c_all = res
        dh_all, dhT, dcT = grads
        dxg, dh0, dc0 = _bwd_call(
            xg, wh, keep, h0, c0, h_all, c_all, dh_all, dhT, dcT, interpret)
        # dWh: batch-parallel contraction over the emitted per-step dgates
        # against each step's (masked) input h — outside the kernel, where
        # XLA runs it as one [H, T*B] x [T*B, 4H] MXU matmul.
        h_prev = jnp.concatenate([h0[None], h_all[:-1] * keep[:-1]], axis=0)
        dwh = jnp.einsum("tbh,tbg->hg", h_prev, dxg)
        return dxg, dwh, jnp.zeros_like(keep), dh0, dc0

    f.defvjp(f_fwd, f_bwd)
    return f


def lstm_pallas(xg, wh, keep, h0, c0, interpret: bool = False):
    """Time-major fused recursion. xg [T,B,4H], keep [T,B,1] float.

    -> (h_all [T,B,H], hT, cT); differentiable via the BPTT kernel."""
    f = _make_lstm(interpret)
    return f(
        xg.astype(jnp.float32), wh.astype(jnp.float32), keep.astype(jnp.float32),
        h0.astype(jnp.float32), c0.astype(jnp.float32),
    )
