"""Fused LSTM sequence kernels: forward recursion + hand-derived BPTT.

Design (see `ops/lstm.py` for the op-level contract):

- The input projection is done outside (one big MXU matmul over [B*T]).
- The kernels own the sequential part only: T dependent steps of
  `gates_t = xg_t + h @ Wh` -> gate nonlinearities -> done-masked carry.
- The grid runs (batch-tiles, T) with the TIME axis innermost: each grid
  step sees only its [b, 4H] slice of the projected inputs while the
  carries (h, c) persist across time steps in VMEM scratch. Pallas
  pipelines the HBM<->VMEM block transfers of the time-indexed operands
  (double-buffered) behind the MXU work, so per-step VMEM residency is
  O(b * H) regardless of T and the batch tile stays large enough to fill
  the MXU's 128 rows — the earlier whole-[T,b,4H]-in-VMEM design forced
  b down to 16 at IMPALA/R2D2 replay shapes and starved the systolic
  array (measured 2.7x slower than XLA's scan; this layout beats it).
- The backward kernel replays the recursion in reverse (time index map
  t -> T-1-t), recomputing gate activations from the saved (xg, h_all,
  c_all) residuals, and emits per-step dgates. The two weight-gradient
  contractions (dWh, and dxg -> dWx outside) are NOT in the kernel: they
  are batch-parallel einsums over the emitted dgates, which XLA
  schedules on the MXU better than a serialized in-loop accumulation.
- `jax.custom_vjp` glues the pair together; gradient correctness is
  tested against autodiff of the lax.scan reference (tests/test_pallas.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_reinforcement_learning_tpu.ops.pallas import pick_block

_BLOCK_B = 256


def _sig(x):
    return jax.nn.sigmoid(x)


def _fwd_kernel(xg_ref, wh_ref, keep_ref, h0_ref, c0_ref,
                hall_ref, call_ref, hT_ref, cT_ref, h_scr, c_scr):
    t = pl.program_id(1)
    T = pl.num_programs(1)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    gates = xg_ref[0] + jnp.dot(h_scr[:], wh_ref[:],
                                preferred_element_type=jnp.float32)
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    new_c = _sig(f + 1.0) * c_scr[:] + _sig(i) * jnp.tanh(g)
    new_h = _sig(o) * jnp.tanh(new_c)
    hall_ref[0] = new_h
    call_ref[0] = new_c
    k = keep_ref[0]  # [b, 1], broadcasts over H lanes
    h_scr[:] = new_h * k
    c_scr[:] = new_c * k

    @pl.when(t == T - 1)
    def _():
        hT_ref[:] = h_scr[:]
        cT_ref[:] = c_scr[:]


def _bwd_kernel(xg_ref, wh_ref, keep_ref, keep_prev_ref, h0_ref, c0_ref,
                hall_prev_ref, call_prev_ref, call_ref, dhall_ref,
                dhT_ref, dcT_ref,
                dxg_ref, dh0_ref, dc0_ref, dh_scr, dc_scr):
    tr = pl.program_id(1)  # 0 .. T-1, walking time BACKWARD (tt = T-1-tr)
    T = pl.num_programs(1)
    wh = wh_ref[:]

    @pl.when(tr == 0)
    def _():
        dh_scr[:] = dhT_ref[:]  # grad wrt the POST-mask carried h
        dc_scr[:] = dcT_ref[:]

    first = tr == T - 1  # logical time 0: previous state is (h0, c0)
    k_prev = jnp.where(first, 1.0, keep_prev_ref[0])
    h_prev = jnp.where(first, h0_ref[:], hall_prev_ref[0] * k_prev)
    c_in = jnp.where(first, c0_ref[:], call_prev_ref[0] * k_prev)

    # Recompute gate activations (forward stores only h_all/c_all).
    gates = xg_ref[0] + jnp.dot(h_prev, wh, preferred_element_type=jnp.float32)
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    si, sf, sg, so = _sig(i), _sig(f + 1.0), jnp.tanh(g), _sig(o)
    tc = jnp.tanh(call_ref[0])

    k = keep_ref[0]
    dh = dhall_ref[0] + k * dh_scr[:]  # pre-mask h_t grad: emitted + carried
    dc = k * dc_scr[:] + dh * so * (1.0 - tc * tc)
    d_o = dh * tc * so * (1.0 - so)
    d_i = dc * sg * si * (1.0 - si)
    d_f = dc * c_in * sf * (1.0 - sf)
    d_g = dc * si * (1.0 - sg * sg)
    dgates = jnp.concatenate([d_i, d_f, d_g, d_o], axis=-1)
    dxg_ref[0] = dgates
    # Contract dgates' 4H dim against Wh's 4H dim: dgates @ Wh^T.
    dh_scr[:] = jax.lax.dot_general(
        dgates, wh, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dc_scr[:] = dc * sf

    @pl.when(tr == T - 1)
    def _():
        dh0_ref[:] = dh_scr[:]
        dc0_ref[:] = dc_scr[:]


def _specs(T: int, H: int, block_b: int, reverse: bool):
    """Block builders for a (batch-tiles, T) grid; `reverse` walks time
    backward and `shift` reads the previous logical step (clamped at 0 —
    the kernel substitutes h0/c0 there)."""

    def seq(d, shift=0):
        def imap(b, t):
            tt = (T - 1 - t) if reverse else t
            return (jnp.clip(tt - shift, 0, T - 1), b, 0)

        return pl.BlockSpec((1, block_b, d), imap, memory_space=pltpu.VMEM)

    mat = pl.BlockSpec((block_b, H), lambda b, t: (b, 0), memory_space=pltpu.VMEM)
    full = pl.BlockSpec((H, 4 * H), lambda b, t: (0, 0), memory_space=pltpu.VMEM)
    return seq, mat, full


def _fwd_call(xg, wh, keep, h0, c0, interpret: bool):
    T, B, G = xg.shape
    H = G // 4
    # Per-row VMEM: double-buffered time blocks (xg + keep + h_all +
    # c_all) + batch-indexed carries/ios + scratch; Wh is the fixed cost.
    block_b = pick_block(
        B, _BLOCK_B,
        per_row_bytes=4 * (2 * (4 * H + 1 + 2 * H) + 6 * H),
        fixed_bytes=4 * H * 4 * H,
    )
    seq, mat, full = _specs(T, H, block_b, reverse=False)
    return pl.pallas_call(
        _fwd_kernel,
        grid=(B // block_b, T),
        in_specs=[seq(G), full, seq(1), mat, mat],
        out_specs=[seq(H), seq(H), mat, mat],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H), jnp.float32),
            jax.ShapeDtypeStruct((T, B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_b, H), jnp.float32),
            pltpu.VMEM((block_b, H), jnp.float32),
        ],
        interpret=interpret,
    )(xg, wh, keep, h0, c0)


def _bwd_call(xg, wh, keep, h0, c0, h_all, c_all, dh_all, dhT, dcT, interpret: bool):
    T, B, G = xg.shape
    H = G // 4
    block_b = pick_block(
        B, _BLOCK_B,
        per_row_bytes=4 * (2 * (2 * 4 * H + 2 + 4 * H) + 8 * H),
        fixed_bytes=4 * H * 4 * H,
    )
    seq, mat, full = _specs(T, H, block_b, reverse=True)
    return pl.pallas_call(
        _bwd_kernel,
        grid=(B // block_b, T),
        in_specs=[seq(G), full, seq(1), seq(1, shift=1), mat, mat,
                  seq(H, shift=1), seq(H, shift=1), seq(H), seq(H), mat, mat],
        out_specs=[seq(G), mat, mat],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, G), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_b, H), jnp.float32),
            pltpu.VMEM((block_b, H), jnp.float32),
        ],
        interpret=interpret,
    )(xg, wh, keep, keep, h0, c0, h_all, c_all, c_all, dh_all, dhT, dcT)


@functools.cache
def _make_lstm(interpret: bool):
    """custom_vjp-wrapped (forward, backward) pallas pair."""

    @jax.custom_vjp
    def f(xg, wh, keep, h0, c0):
        h_all, _, hT, cT = _fwd_call(xg, wh, keep, h0, c0, interpret)
        return h_all, hT, cT

    def f_fwd(xg, wh, keep, h0, c0):
        h_all, c_all, hT, cT = _fwd_call(xg, wh, keep, h0, c0, interpret)
        return (h_all, hT, cT), (xg, wh, keep, h0, c0, h_all, c_all)

    def f_bwd(res, grads):
        xg, wh, keep, h0, c0, h_all, c_all = res
        dh_all, dhT, dcT = grads
        dxg, dh0, dc0 = _bwd_call(
            xg, wh, keep, h0, c0, h_all, c_all, dh_all, dhT, dcT, interpret)
        # dWh: batch-parallel contraction over the emitted per-step dgates
        # against each step's (masked) input h — outside the kernel, where
        # XLA runs it as one [H, T*B] x [T*B, 4H] MXU matmul.
        h_prev = jnp.concatenate([h0[None], h_all[:-1] * keep[:-1]], axis=0)
        dwh = jnp.einsum("tbh,tbg->hg", h_prev, dxg)
        return dxg, dwh, jnp.zeros_like(keep), dh0, dc0

    f.defvjp(f_fwd, f_bwd)
    return f


def lstm_pallas(xg, wh, keep, h0, c0, interpret: bool = False):
    """Time-major fused recursion. xg [T,B,4H], keep [T,B,1] float.

    -> (h_all [T,B,H], hT, cT); differentiable via the BPTT kernel."""
    f = _make_lstm(interpret)
    return f(
        xg.astype(jnp.float32), wh.astype(jnp.float32), keep.astype(jnp.float32),
        h0.astype(jnp.float32), c0.astype(jnp.float32),
    )
