"""Fused V-trace kernel: rho-clipping + deltas + reverse scan in one pass.

The recursion (`/root/reference/optimizer/vtrace.py:71-103`):

    delta_t = min(rho_bar, rho_t) * (r_t + gamma_t * V_{t+1} - V_t)
    acc_t   = delta_t + gamma_t * min(c_bar, rho_t) * acc_{t+1}
    vs_t    = acc_t + V_t

The lax.scan baseline compiles to an XLA while-loop whose carry bounces
through HBM every step; here the whole [T, B] problem lives in VMEM and
the time loop is unrolled inside one kernel (T is a small static unroll
length — 20 for IMPALA, `config.json:40`). Outputs are consumed under
`stop_gradient` by every caller (the reference sets `back_prop=False`),
so no backward kernel is needed.

Grid: 1-D over batch tiles; each program owns all T steps of its batch
slice, so programs are independent and the grid parallelizes freely.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_reinforcement_learning_tpu.ops.pallas import pick_block

# Batch tile: multiple of the fp32 lane width; the whole [T, BLOCK_B]
# working set (6 arrays x T<=64 x 256 x 4B ~ 400 KB) sits far under VMEM.
_BLOCK_B = 256


def _vtrace_kernel(
    log_rhos_ref,  # [T, Bb]
    discounts_ref,  # [T, Bb]
    rewards_ref,  # [T, Bb]
    values_ref,  # [T, Bb]
    bootstrap_ref,  # [1, Bb]
    vs_ref,  # [T, Bb] out
    rhos_ref,  # [T, Bb] out
    *,
    clip_rho: float | None,
    clip_c: float,
):
    rhos = jnp.exp(log_rhos_ref[:])
    clipped = jnp.minimum(clip_rho, rhos) if clip_rho is not None else rhos
    cs = discounts_ref[:] * jnp.minimum(clip_c, rhos)  # fused gamma_t * c_t
    values = values_ref[:]
    next_values = jnp.concatenate([values[1:], bootstrap_ref[:]], axis=0)
    deltas = clipped * (rewards_ref[:] + discounts_ref[:] * next_values - values)

    T = values.shape[0]
    acc = jnp.zeros_like(bootstrap_ref[:])  # [1, Bb]
    rows = [None] * T
    for t in reversed(range(T)):  # static unroll: T is a compile-time constant
        acc = deltas[t : t + 1] + cs[t : t + 1] * acc
        rows[t] = acc
    vs_ref[:] = jnp.concatenate(rows, axis=0) + values
    rhos_ref[:] = clipped


@functools.partial(
    jax.jit, static_argnames=("clip_rho_threshold", "clip_c_threshold", "interpret")
)
def vtrace_pallas(
    log_rhos: jax.Array,  # [T, B] time-major, like the lax.scan core
    discounts: jax.Array,
    rewards: jax.Array,
    values: jax.Array,
    bootstrap_value: jax.Array,  # [B]
    clip_rho_threshold: float | None = 1.0,
    clip_c_threshold: float = 1.0,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """-> (vs [T, B], clipped_rhos [T, B]), both to be stop-gradiented by
    the caller (`ops.vtrace.from_importance_weights` does)."""
    T, B = log_rhos.shape
    block_b = pick_block(B, _BLOCK_B)
    grid = (B // block_b,)
    seq_spec = pl.BlockSpec((T, block_b), lambda i: (0, i), memory_space=pltpu.VMEM)
    boot_spec = pl.BlockSpec((1, block_b), lambda i: (0, i), memory_space=pltpu.VMEM)
    kernel = functools.partial(
        _vtrace_kernel, clip_rho=clip_rho_threshold, clip_c=clip_c_threshold
    )
    vs, rhos = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[seq_spec, seq_spec, seq_spec, seq_spec, boot_spec],
        out_specs=[seq_spec, seq_spec],
        out_shape=[
            jax.ShapeDtypeStruct((T, B), jnp.float32),
            jax.ShapeDtypeStruct((T, B), jnp.float32),
        ],
        interpret=interpret,
    )(
        log_rhos.astype(jnp.float32),
        discounts.astype(jnp.float32),
        rewards.astype(jnp.float32),
        values.astype(jnp.float32),
        bootstrap_value.astype(jnp.float32)[None, :],
    )
    return vs, rhos
