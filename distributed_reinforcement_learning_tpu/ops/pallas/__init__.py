"""Pallas TPU kernels for the sequential hot ops.

The two ops XLA cannot fuse well on its own are the framework's only
truly sequential recursions (SURVEY §7 "hard parts" b):

- the V-trace backward recursion (`pallas/vtrace.py`) — the reference
  serialized a `tf.scan(parallel_iterations=1)` over it
  (`/root/reference/optimizer/vtrace.py:86-100`),
- the LSTM sequence unroll with done-masking (`pallas/lstm.py`) — the
  reference replicated the whole network per timestep in Python
  (`/root/reference/model/r2d2_lstm.py:65-112`).

Both kernels keep the entire time loop in VMEM (one kernel launch per
batch instead of T dependent HLO while-loop iterations bouncing carries
through HBM) and are numerically validated against the `lax.scan`
reference implementations in interpret mode on CPU.

Backend selection: `resolve_backend("auto")` picks pallas on TPU and the
lax.scan reference elsewhere; `DRL_TPU_PALLAS=0` force-disables.
"""

from __future__ import annotations

import os

import jax


# Per-kernel scoped VMEM is 16MB on current TPUs; leave slack for the
# compiler's own scratch and the replicated (non-tiled) operands.
_VMEM_BUDGET = 11 << 20


def pick_block(
    b: int, block: int, per_row_bytes: int = 0, fixed_bytes: int = 0
) -> int:
    """Batch-tile size for a 1-D grid over B.

    Tile by `block` when it divides B, otherwise one program owns the
    whole (padded) batch. When `per_row_bytes` (total bytes of all tiled
    refs per batch row) is given, the tile is instead the largest DIVISOR
    of B, at most `block`, whose VMEM footprint — double-buffered tiles +
    `fixed_bytes` of replicated operands — fits the scoped budget, so big
    [T, B, 4H] workloads don't hit the 16MB scoped-vmem stack limit (seen
    at B=256, T=20, H=256) even when B is not a power of two (a
    whole-batch fallback here would reintroduce exactly that failure).
    """
    if per_row_bytes:
        n = min(block, b)
        while n > 1 and (
            b % n != 0 or fixed_bytes + 2 * n * per_row_bytes > _VMEM_BUDGET
        ):
            n -= 1
        return n
    return b if b < block or b % block != 0 else block


def resolve_backend(backend: str = "auto", opt_in_env: str | None = None) -> str:
    """-> 'pallas' | 'pallas_interpret' | 'reference'.

    `opt_in_env`: name of an env var that must be "1" for `auto` to pick
    the kernel — used by ops whose measured advantage is not (or not
    yet) established, e.g. the fused LSTM (DRL_LSTM_PALLAS). Ops with a
    stable margin (V-trace) pass None and auto-enable on TPU.
    """
    if backend == "auto":
        if os.environ.get("DRL_TPU_PALLAS", "1") == "0":
            return "reference"
        if opt_in_env is not None and os.environ.get(opt_in_env, "0") != "1":
            return "reference"
        return "pallas" if jax.default_backend() == "tpu" else "reference"
    if backend not in ("pallas", "pallas_interpret", "reference"):
        raise ValueError(f"unknown backend {backend!r}")
    return backend
