"""Fused flash-attention TPU kernels: causal, segment-masked MHA.

The transformer family's hot op (models/transformer_net.py). The XLA
paths in ops/attention.py materialize [Tq, Tkv] probability blocks in
HBM between the softmax and the PV matmul; these kernels keep the whole
online-softmax recurrence in VMEM per query block — one launch per
(batch*head, q-block) instead of a scan of fused-but-HBM-roundtripping
block steps.

Layout: inputs are flattened to `[BH, T, D]` (batch*heads leading); the
grid is (BH, q-blocks, kv-blocks) with ONLY one block of each operand
VMEM-resident per step (online-softmax / gradient accumulators live in
scratch across the innermost kv/q walk), so T is bounded by HBM, not by
the 16MB scoped VMEM — a whole-K/V-resident design capped out at T~8k.
Per-row vectors (segment ids, logsumexp, delta) travel as `[BH, T, 1]`
so their blocks satisfy the TPU (8, 128)-tiling rule on the last two
dims. Segment ids confine attention within episodes exactly like the
XLA paths; "no segments" is the all-zeros id vector (same segment
everywhere), so one kernel serves both cases.

Backward follows the standard flash decomposition: the forward saves
only (out, logsumexp); dq and (dk, dv) are two kernels that recompute
the probabilities from q/k/lse, using the precomputed per-row
`delta = rowsum(dout * out)` (a cheap XLA reduction outside).

Numerics are validated against `ops/attention.dense_attention` (values
and grads) in interpret mode on CPU and on TPU by tests/bench.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_reinforcement_learning_tpu.ops.attention import _MASK_VALUE as _NEG

_BLOCK_Q = 128
_BLOCK_KV = 128


def _pos(start, rows, cols, axis):
    """2-D position grid [rows, cols] counting along `axis` from `start`."""
    return start + jax.lax.broadcasted_iota(jnp.int32, (rows, cols), axis)


def _block_mask(iq_start, jk_start, bq, bkv, qs, ks_row):
    """[bq, bkv] causal & same-segment mask.

    qs: [bq, 1] query segment ids; ks_row: [1, bkv] key segment ids.
    """
    causal = _pos(iq_start, bq, bkv, 0) >= _pos(jk_start, bq, bkv, 1)
    return causal & (qs == ks_row)


def _fwd_kernel(q_ref, k_ref, v_ref, qs_ref, ks_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr):
    """Grid (BH, nq, nkv): kv is a GRID axis (one k/v block VMEM-resident
    at a time — a full [T, D] K/V residency caps T at ~8k), with the
    online-softmax state in scratch across the inner kv walk; o/lse
    blocks revisit and flush on the last contributing step."""
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    bq = q_ref.shape[1]
    bkv = k_ref.shape[1]
    scale = q_ref.shape[2] ** -0.5

    @pl.when(jk == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    last = ((iq + 1) * bq - 1) // bkv  # last kv block this q block attends

    @pl.when(jk <= last)
    def _():
        q = q_ref[0]
        qs = qs_ref[0]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        ks_row = ks_ref[0].reshape(1, bkv)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        msk = _block_mask(iq * bq, jk * bkv, bq, bkv, qs, ks_row)
        s = jnp.where(msk, s, _NEG)
        m = m_scr[:]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(msk, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v_blk.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(jk == pl.num_programs(2) - 1)
    def _():
        l_safe = jnp.maximum(l_scr[:], jnp.finfo(jnp.float32).tiny)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l_safe)


def _dq_kernel(q_ref, k_ref, v_ref, qs_ref, ks_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_scr):
    """Grid (BH, nq, nkv), kv walked by the grid; dq accumulates in
    scratch and flushes on the last step (same shape as _fwd_kernel)."""
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    bq = q_ref.shape[1]
    bkv = k_ref.shape[1]
    scale = q_ref.shape[2] ** -0.5

    @pl.when(jk == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    last = ((iq + 1) * bq - 1) // bkv

    @pl.when(jk <= last)
    def _():
        q = q_ref[0]
        qs = qs_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        ks_row = ks_ref[0].reshape(1, bkv)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        msk = _block_mask(iq * bq, jk * bkv, bq, bkv, qs, ks_row)
        p = jnp.where(msk, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds, k_blk.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(jk == pl.num_programs(2) - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, qs_ref, ks_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, block_q: int):
    """Grid (BH, nk, nq): the q axis is a GRID dimension, not an
    in-kernel loop, so only one q/do block is VMEM-resident at a time
    (a full [T, D] q + do residency overflowed scoped VMEM at T=8192).
    dk/dv accumulate in scratch across the inner q walk — the (b, jk)
    output blocks revisit — and flush on the last q step."""
    jk = pl.program_id(1)
    iq = pl.program_id(2)
    n_q = pl.num_programs(2)
    bkv = k_ref.shape[1]
    scale = k_ref.shape[2] ** -0.5

    @pl.when(iq == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # Causal: q blocks strictly before this kv block are fully masked.
    @pl.when(iq * block_q + block_q > jk * bkv)
    def _():
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        ks_row = ks_ref[0].reshape(1, bkv)
        q_i = q_ref[0]
        do_i = do_ref[0].astype(jnp.float32)
        lse_i = lse_ref[0]
        delta_i = delta_ref[0]
        qs_i = qs_ref[0]
        s = jax.lax.dot_general(
            q_i, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        msk = _block_mask(iq * block_q, jk * bkv, block_q, bkv, qs_i, ks_row)
        p = jnp.where(msk, jnp.exp(s - lse_i), 0.0)
        dv_scr[:] += jax.lax.dot_general(
            p, do_i, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_i, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_i) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds, q_i.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == n_q - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _qkv_specs(d: int, bq: int, bkv: int):
    """3-D-grid (b, i_q, j_kv) block specs: q-indexed, kv-indexed rows.

    The kv index is CLAMPED to the last causally-visible block for the
    current q block: past it the index map repeats the same block, which
    Pallas recognizes as a revisit and does not re-DMA — the ~half of
    the rectangular grid that is fully future-masked (compute skipped by
    pl.when in the kernels) costs no HBM traffic either.
    """

    def jcap(i, j):
        return jnp.minimum(j, ((i + 1) * bq - 1) // bkv)

    q3 = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM)
    qrow3 = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM)
    kv3 = pl.BlockSpec(
        (1, bkv, d), lambda b, i, j: (b, jcap(i, j), 0), memory_space=pltpu.VMEM)
    krow3 = pl.BlockSpec(
        (1, bkv, 1), lambda b, i, j: (b, jcap(i, j), 0), memory_space=pltpu.VMEM)
    return q3, qrow3, kv3, krow3


def _fwd_call(q, k, v, qs, ks, bq, bkv, interpret):
    bh, t, d = q.shape
    q3, qrow3, kv3, krow3 = _qkv_specs(d, bq, bkv)
    return pl.pallas_call(
        _fwd_kernel,
        grid=(bh, t // bq, t // bkv),
        in_specs=[q3, kv3, kv3, qrow3, krow3],
        out_specs=[q3, qrow3],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, qs, ks)


def _bwd_call(q, k, v, qs, ks, do, lse, delta, bq, bkv, interpret):
    bh, t, d = q.shape
    q3, qrow3, kv3, krow3 = _qkv_specs(d, bq, bkv)
    dq = pl.pallas_call(
        _dq_kernel,
        grid=(bh, t // bq, t // bkv),
        in_specs=[q3, kv3, kv3, qrow3, krow3, q3, qrow3, qrow3],
        out_specs=[q3],
        out_shape=[jax.ShapeDtypeStruct((bh, t, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, qs, ks, do, lse, delta)[0]
    # 3-D grid: kv blocks indexed by j (middle), q/do blocks by the
    # innermost iq axis; dk/dv blocks revisit across iq. The q index is
    # clamped to the first causally-contributing block for this kv block
    # (skipped early steps revisit it — no re-DMA, compute pl.when'd off).
    def icap(j, i):
        return jnp.maximum(i, (j * bkv) // bq)

    kv3 = pl.BlockSpec((1, bkv, d), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM)
    krow3 = pl.BlockSpec((1, bkv, 1), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM)
    q3 = pl.BlockSpec(
        (1, bq, d), lambda b, j, i: (b, icap(j, i), 0), memory_space=pltpu.VMEM)
    qrow3 = pl.BlockSpec(
        (1, bq, 1), lambda b, j, i: (b, icap(j, i), 0), memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=bq),
        grid=(bh, t // bkv, t // bq),
        in_specs=[q3, kv3, kv3, qrow3, krow3, q3, qrow3, qrow3],
        out_specs=[kv3, kv3],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bkv, d), jnp.float32),
            pltpu.VMEM((bkv, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, qs, ks, do, lse, delta)
    return dq, dk, dv


@functools.cache
def _make_flash(bq: int, bkv: int, interpret: bool):
    @jax.custom_vjp
    def f(q, k, v, qs, ks):
        out, _ = _fwd_call(q, k, v, qs, ks, bq, bkv, interpret)
        return out

    def f_fwd(q, k, v, qs, ks):
        out, lse = _fwd_call(q, k, v, qs, ks, bq, bkv, interpret)
        return out, (q, k, v, qs, ks, out, lse)

    def f_bwd(res, do):
        q, k, v, qs, ks, out, lse = res
        delta = jnp.sum(
            do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True)
        dq, dk, dv = _bwd_call(q, k, v, qs, ks, do, lse, delta, bq, bkv, interpret)
        return dq, dk, dv, None, None

    f.defvjp(f_fwd, f_bwd)
    return f


def flash_attention_bhtd(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_seg: jax.Array,
    k_seg: jax.Array,
    block_q: int = _BLOCK_Q,
    block_kv: int = _BLOCK_KV,
    interpret: bool = False,
) -> jax.Array:
    """Causal flash attention on `[BH, T, D]` with `[BH, T]` segment ids.

    T must divide by both block sizes (choose blocks via
    `flash_blocks`); differentiable via the fused dq/dkv kernels.
    """
    bh, t, d = q.shape
    if t % block_q or t % block_kv:
        raise ValueError(f"T={t} not divisible by blocks ({block_q}, {block_kv})")
    f = _make_flash(block_q, block_kv, interpret)
    return f(q, k, v,
             q_seg.astype(jnp.int32).reshape(bh, t, 1),
             k_seg.astype(jnp.int32).reshape(bh, t, 1))


def flash_blocks(t: int, cap: int = _BLOCK_Q) -> int:
    """Largest power-of-two block <= cap dividing t (>= 8), or 0 if none."""
    b = cap
    while b >= 8:
        if t % b == 0:
            return b
        b //= 2
    return 0
