"""V-trace off-policy corrected returns and IMPALA losses.

TPU-native re-design of the reference's V-trace module
(`/root/reference/optimizer/vtrace.py:3-126`): the reference builds a TF1
graph with a serialized `tf.scan(parallel_iterations=1)`; here the
backward recursion is a `jax.lax.scan(reverse=True)` over time with the
delta computation fused in front of it, all inside one XLA compilation.

Conventions:
- Batch-major public API: tensors are `[B, T, ...]` like the reference
  (`optimizer/vtrace.py:29-44`). The time-major core (`[T, B]`) is also
  exposed for callers that already hold time-major data.
- Loss reductions are **sums** over batch and time, matching the reference
  (`optimizer/vtrace.py:105-126`); IMPALA's gradient-clip/LR settings were
  tuned against sum-reduced losses.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class VTraceReturns(NamedTuple):
    """Outputs of the V-trace recursion (both stop-gradiented)."""

    vs: jax.Array  # V-trace value targets, same shape as `values`.
    clipped_rhos: jax.Array  # min(rho_bar, pi/mu), the pg-advantage weights.


def split_data(x: jax.Array) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Time-shifted first/middle/last views of a `[B, T, ...]` tensor.

    Mirrors `optimizer/vtrace.py:3-14`: given a T-step unroll, returns the
    three `[B, T-2, ...]` slices `x[:, :-2]`, `x[:, 1:-1]`, `x[:, 2:]` used
    to form (s_t, s_{t+1}, s_{t+2}) aligned views for the double V-trace
    pass in the IMPALA loss.
    """
    return x[:, :-2], x[:, 1:-1], x[:, 2:]


def action_log_probs(policy_probs: jax.Array, actions: jax.Array, eps: float = 0.0) -> jax.Array:
    """log pi(a_t | x_t) from softmax probabilities and taken actions.

    Parity with `optimizer/vtrace.py:16-27` (one-hot gather + log). `eps`
    guards the log for callers that need it; the rho computation uses
    eps=0 like the reference, the pg loss uses 1e-8
    (`optimizer/vtrace.py:109`).
    """
    taken = jnp.take_along_axis(policy_probs, actions[..., None].astype(jnp.int32), axis=-1)
    return jnp.log(taken[..., 0] + eps)


def from_importance_weights(
    log_rhos: jax.Array,
    discounts: jax.Array,
    rewards: jax.Array,
    values: jax.Array,
    bootstrap_value: jax.Array,
    clip_rho_threshold: float | None = 1.0,
    clip_c_threshold: float = 1.0,
    backend: str = "auto",
) -> VTraceReturns:
    """Time-major V-trace core: `[T, B]` inputs, `[T, B]` outputs.

    Implements the recursion of `optimizer/vtrace.py:71-103`:
        delta_t = clipped_rho_t * (r_t + gamma_t * V(x_{t+1}) - V(x_t))
        vs_t - V(x_t) = delta_t + gamma_t * c_t * (vs_{t+1} - V(x_{t+1}))
    computed with a reverse `lax.scan` (the reference serializes a TF scan
    with `parallel_iterations=1, back_prop=False`; here XLA compiles the
    whole thing and `stop_gradient` replaces `back_prop=False`).

    `backend="auto"` resolves to the fused Pallas kernel on TPU
    (`ops/pallas/vtrace.py`): measured on v5e at IMPALA shapes (T=20,
    B=256) with an on-device timing loop — the only methodology that
    survives the remote-tunnel dispatch noise, see bench.py
    `bench_kernels` — the kernel runs the whole reverse recursion in one
    VMEM-resident launch at ~2.4us/call vs ~9.2us for this lax.scan
    (whose T=20 while-loop iterations each round-trip their carries
    through HBM). Artifact: BENCH_r02 `kernel_compare`. Round 1's
    opposite conclusion (280us vs 263us, kernel disabled by default) came
    from host-side per-dispatch timing, which the tunnel makes
    meaningless.
    """
    from distributed_reinforcement_learning_tpu.ops.pallas import resolve_backend

    resolved = resolve_backend(backend)
    if resolved != "reference":
        from distributed_reinforcement_learning_tpu.ops.pallas.vtrace import vtrace_pallas

        # The whole V-trace target is stop-gradded (the reference's
        # `back_prop=False`), so cut the tape at the kernel's INPUTS too:
        # pallas_call has no jvp rule, and linearization would otherwise
        # fail inside value_and_grad even though no cotangent ever flows.
        sg = jax.lax.stop_gradient
        vs, clipped = vtrace_pallas(
            sg(log_rhos), sg(discounts), sg(rewards), sg(values), sg(bootstrap_value),
            clip_rho_threshold=clip_rho_threshold,
            clip_c_threshold=clip_c_threshold,
            interpret=(resolved == "pallas_interpret"),
        )
        return VTraceReturns(
            vs=jax.lax.stop_gradient(vs),
            clipped_rhos=jax.lax.stop_gradient(clipped),
        )
    rhos = jnp.exp(log_rhos)
    if clip_rho_threshold is not None:
        clipped_rhos = jnp.minimum(clip_rho_threshold, rhos)
    else:
        clipped_rhos = rhos
    cs = jnp.minimum(clip_c_threshold, rhos)

    values_t_plus_1 = jnp.concatenate([values[1:], bootstrap_value[None]], axis=0)
    deltas = clipped_rhos * (rewards + discounts * values_t_plus_1 - values)

    def body(acc, xs):
        discount_t, c_t, delta_t = xs
        acc = delta_t + discount_t * c_t * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        body,
        jnp.zeros_like(bootstrap_value),
        (discounts, cs, deltas),
        reverse=True,
    )
    vs = vs_minus_v + values
    return VTraceReturns(
        vs=jax.lax.stop_gradient(vs),
        clipped_rhos=jax.lax.stop_gradient(clipped_rhos),
    )


def from_softmax(
    behavior_policy: jax.Array,
    target_policy: jax.Array,
    actions: jax.Array,
    discounts: jax.Array,
    rewards: jax.Array,
    values: jax.Array,
    next_values: jax.Array,
    clip_rho_threshold: float | None = 1.0,
    backend: str = "auto",
) -> VTraceReturns:
    """Batch-major V-trace from behavior/target softmax probabilities.

    Parity with `optimizer/vtrace.py:29-69`: inputs `[B, T, A]` policies and
    `[B, T]` trajectories; `next_values[:, -1]` supplies the bootstrap value.
    Returns `[B, T]` vs and clipped rhos.
    """
    log_rhos = action_log_probs(target_policy, actions) - action_log_probs(behavior_policy, actions)
    # Transpose to time-major for the scan, back to batch-major after.
    tm = lambda x: jnp.swapaxes(x, 0, 1)
    out = from_importance_weights(
        log_rhos=tm(log_rhos),
        discounts=tm(discounts),
        rewards=tm(rewards),
        values=tm(values),
        bootstrap_value=next_values[:, -1],
        clip_rho_threshold=clip_rho_threshold,
        backend=backend,
    )
    return VTraceReturns(vs=tm(out.vs), clipped_rhos=tm(out.clipped_rhos))


def policy_gradient_loss(
    policy_probs: jax.Array, actions: jax.Array, advantages: jax.Array
) -> jax.Array:
    """-sum_t log pi(a_t|x_t) * adv_t, summed over batch and time.

    Parity with `optimizer/vtrace.py:105-112` (log has a 1e-8 guard there).
    """
    log_prob = action_log_probs(policy_probs, actions, eps=1e-8)
    return -jnp.sum(log_prob * jax.lax.stop_gradient(advantages))


def baseline_loss(vs: jax.Array, values: jax.Array) -> jax.Array:
    """0.5 * sum (stop_grad(vs) - V)^2, per `optimizer/vtrace.py:114-118`."""
    return 0.5 * jnp.sum(jnp.square(jax.lax.stop_gradient(vs) - values))


def entropy_loss(policy_probs: jax.Array) -> jax.Array:
    """Negative total entropy: sum_{b,t,a} p log p.

    Parity with `optimizer/vtrace.py:120-126` — the reference returns
    `-sum(-p*log(p))`, i.e. a *negative* quantity added to the loss with a
    positive coefficient, which acts as an entropy bonus. Uses the
    `p > 0 ? p*log(p) : 0` form so exact-zero probabilities contribute 0
    instead of NaN (the reference would NaN there).
    """
    plogp = jnp.where(policy_probs > 0, policy_probs * jnp.log(jnp.where(policy_probs > 0, policy_probs, 1.0)), 0.0)
    return jnp.sum(plogp)
