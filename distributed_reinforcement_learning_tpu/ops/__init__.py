"""Pure-function losses and return computations (reference layer L3).

Everything here is stateless, jit-safe, and static-shaped: the building
blocks the agents compose into loss functions.
"""

from distributed_reinforcement_learning_tpu.ops import dqn, value_rescale, vtrace

__all__ = ["vtrace", "dqn", "value_rescale"]
