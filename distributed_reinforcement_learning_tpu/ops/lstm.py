"""LSTM sequence recursion as a pure op, with a fused Pallas TPU path.

The recurrent models split one LSTM unroll into:

  (a) the input projection `xg = [x] @ Wx + b` for ALL timesteps — one big
      MXU matmul, embarrassingly parallel, left to XLA;
  (b) the sequential recursion over T carrying (h, c) with done-masking —
      this module.

The reference instead replicated the entire network per timestep in
Python graph-building loops (`/root/reference/model/r2d2_lstm.py:65-112`,
`model/impala_actor_critic.py:73-114`). Here (b) is a `lax.scan`
(reference backend, differentiable by autodiff) or a Pallas kernel pair
(`ops/pallas/lstm.py`) that keeps the carries in VMEM across a
time-gridded launch, wired up through `jax.custom_vjp` with a
hand-derived BPTT backward kernel.

The kernel is OPT-IN (`DRL_LSTM_PALLAS=1`, or backend="pallas"), not
auto: round-2's two committed v5e artifacts disagree on it — run 1
measured pallas 128.0us vs scan 166.6us (kernel ahead), run 2 pallas
149.6us vs scan 141.7us (kernel behind) — a spread inside the tunnel's
noise floor, so the "fused pair wins" claim did not survive its own
second measurement (VERDICT r2 "what's weak" #1; artifacts:
benchmarks/r02_v5e_single_chip*.json `kernel_compare`). Round 4's
re-adjudication on a healthy tunnel (VERDICT r3 item 7) CLOSES the
question: 1.09x (r04_v5e_run1: 129.1 vs 140.4us) and 1.00x
(r04_v5e_run2: 126.3 vs 125.9us), both stable-flagged — below the
1.15x auto-enable bar in both artifacts. The kernel stays a documented,
tested reference kernel (`tests/test_pallas.py` keeps it numerically
matched to the scan); `auto` resolves to the XLA scan. The V-trace
kernel keeps its auto-enable — its margin is stable across ALL
committed artifacts (r3: 2.3/1.4x-5.0x; r4: 2.4 vs 4.6, 2.4 vs 4.9us).

Gate math (TF1 `LSTMCell` parity, forget bias 1.0):

    i, f, g, o = split(gates, 4)
    c' = sigmoid(f + 1) * c + sigmoid(i) * tanh(g)
    h' = sigmoid(o) * tanh(c')

Done-masking: the carried (h, c) are zeroed AFTER the step at which
done[t] is set (`model/r2d2_lstm.py:78-80`); the emitted h_t is pre-mask.

Shapes (batch-major public API, matching the models):
    xg   [B, T, 4H]   input projection + bias
    wh   [H, 4H]      recurrent weights
    keep [B, T]       1.0 - done
    h0/c0 [B, H]      sequence-start stored state (`agent/r2d2.py:110-111`)
Returns (h_all [B, T, H], (hT [B, H], cT [B, H])).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_reinforcement_learning_tpu.ops.pallas import resolve_backend


def lstm_step(gates: jax.Array, c: jax.Array) -> tuple[jax.Array, jax.Array]:
    """One cell update from pre-activation gates. Shared by every backend."""
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    new_c = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    new_h = jax.nn.sigmoid(o) * jnp.tanh(new_c)
    return new_h, new_c


def _scan_reference(xg_tm, wh, keep_tm, h0, c0):
    """Time-major lax.scan recursion; autodiff provides its gradient."""

    def body(carry, xs):
        h, c = carry
        xg_t, keep_t = xs
        gates = xg_t + jnp.dot(h, wh)
        new_h, new_c = lstm_step(gates, c)
        k = keep_t[:, None]
        return (new_h * k, new_c * k), new_h

    (hT, cT), h_all = jax.lax.scan(body, (h0, c0), (xg_tm, keep_tm))
    return h_all, (hT, cT)


def lstm_scan(
    xg: jax.Array,
    wh: jax.Array,
    keep: jax.Array,
    h0: jax.Array,
    c0: jax.Array,
    backend: str = "auto",
):
    """Run the recursion; see module docstring for shapes/semantics."""
    backend = resolve_backend(backend, opt_in_env="DRL_LSTM_PALLAS")
    xg_tm = jnp.swapaxes(xg, 0, 1)  # [T, B, 4H]
    keep_tm = jnp.swapaxes(keep, 0, 1).astype(xg.dtype)  # [T, B]
    if backend == "reference":
        h_all_tm, (hT, cT) = _scan_reference(xg_tm, wh, keep_tm, h0, c0)
    else:
        from distributed_reinforcement_learning_tpu.ops.pallas.lstm import lstm_pallas

        h_all_tm, hT, cT = lstm_pallas(
            xg_tm, wh, keep_tm[..., None], h0, c0,
            interpret=(backend == "pallas_interpret"),
        )
    return jnp.swapaxes(h_all_tm, 0, 1), (hT, cT)
