"""Mixture-of-Experts MLP with expert parallelism over an `expert` mesh axis.

The reference has no MoE (its largest model is a 4-layer conv net); this
is part of the parallelism toolkit the TPU-native framework adds
(DP/TP/SP/PP/EP). The formulation is the canonical dense-dispatch one
from GShard/Switch — top-k routing expressed as one-hot dispatch/combine
einsums over a fixed per-expert capacity — because that is the shape XLA
partitions well: static shapes, batched matmuls on the MXU, and when the
expert-stacked tensors are sharded over the mesh's `expert` axis, GSPMD
inserts the token all-to-alls automatically. No scatter/gather, no
ragged buffers.

Routing semantics:
- `top_k` experts per token, gate weights renormalized over the chosen k.
- Fixed capacity `ceil(top_k * N * capacity_factor / E)` slots per
  expert; slots fill in (choice-rank, token-order) priority and
  overflowing tokens are dropped from that expert (their combine weight
  is zero — the token's output falls back to the residual stream).
- Aux load-balancing loss (Switch Transformer eq. 4): `E * sum_e f_e * p_e`
  with `f_e` the fraction of tokens whose FIRST choice is `e` and `p_e`
  the mean router probability; 1.0 at perfect balance.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_reinforcement_learning_tpu.parallel.mesh import EXPERT_AXIS


def init_moe_params(
    rng: jax.Array, d_model: int, d_hidden: int, num_experts: int
) -> dict[str, jax.Array]:
    """Router + expert-stacked MLP weights; glorot over the matmul dims
    (leading expert dim is a batch axis for init scaling)."""
    glorot = jax.nn.initializers.glorot_uniform(in_axis=-2, out_axis=-1, batch_axis=0)
    kg, k1, k2 = jax.random.split(rng, 3)
    e = num_experts
    return {
        "moe_gate": jax.nn.initializers.glorot_uniform()(kg, (d_model, e)),
        "moe_w1": glorot(k1, (e, d_model, d_hidden)),
        "moe_b1": jnp.zeros((e, d_hidden)),
        "moe_w2": glorot(k2, (e, d_hidden, d_model)),
        "moe_b2": jnp.zeros((e, d_model)),
    }


def expert_capacity(
    num_tokens: int, num_experts: int, top_k: int, capacity_factor: float
) -> int:
    return max(1, math.ceil(top_k * num_tokens * capacity_factor / num_experts))


def _dispatch_combine(probs: jax.Array, top_k: int, capacity: int):
    """[N, E] router probs -> ([N, E, C] 0/1 dispatch, [N, E, C] combine, aux).

    Slot priority is (choice rank, token order): all first choices claim
    capacity before any second choice — the GShard ordering, which keeps
    a token's strongest expert the last to overflow.
    """
    n, e = probs.shape
    vals, idx = jax.lax.top_k(probs, top_k)  # [N, k]
    gate = vals / (jnp.sum(vals, axis=-1, keepdims=True) + 1e-9)
    onehot = jax.nn.one_hot(idx, e, dtype=probs.dtype)  # [N, k, E]

    # Rank each (choice, token) within its expert, choice-major ordering.
    # Integer cumsum: float32 ranks go inexact past ~2^24 routed slots
    # per expert, silently double-booking capacity on huge B*T batches.
    flat = onehot.transpose(1, 0, 2).reshape(top_k * n, e).astype(jnp.int32)
    pos_flat = jnp.sum((jnp.cumsum(flat, axis=0) - 1) * flat, axis=-1)
    pos = pos_flat.reshape(top_k, n).T  # [N, k]
    # Positions >= capacity one-hot to all-zeros: the overflow drop.
    slot = jax.nn.one_hot(pos, capacity, dtype=probs.dtype)  # [N, k, C]

    dispatch = jnp.einsum("nke,nkc->nec", onehot, slot)
    combine = jnp.einsum("nk,nke,nkc->nec", gate, onehot, slot)

    # Switch aux: fraction routed (first choice) x mean router prob.
    frac = jnp.mean(onehot[:, 0, :], axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


def moe_mlp(
    x: jax.Array,
    params: dict[str, jax.Array],
    *,
    top_k: int = 2,
    capacity_factor: float = 2.0,
    mesh: Mesh | None = None,
) -> tuple[jax.Array, jax.Array]:
    """MoE feed-forward over `[..., d_model]` tokens -> (y, aux_loss).

    With `mesh` carrying an `expert` axis > 1, the expert-stacked
    dispatch buffer and activations are sharding-constrained over it so
    each device runs only its experts (the weights' sharding comes from
    the train-state placement, `parallel/learner.py`).
    """
    e = params["moe_gate"].shape[-1]
    lead = x.shape[:-1]
    d = x.shape[-1]
    xf = x.reshape(-1, d)
    n = xf.shape[0]

    logits = xf.astype(jnp.float32) @ params["moe_gate"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    cap = expert_capacity(n, e, top_k, capacity_factor)
    dispatch, combine, aux = _dispatch_combine(probs, top_k, cap)

    constrain = lambda a: a
    if mesh is not None and mesh.shape.get(EXPERT_AXIS, 1) > 1:
        constrain = lambda a: jax.lax.with_sharding_constraint(
            a, NamedSharding(mesh, P(EXPERT_AXIS))
        )

    expert_in = constrain(jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), xf))
    h = jax.nn.relu(
        jnp.einsum("ecd,edh->ech", expert_in, params["moe_w1"].astype(x.dtype))
        + params["moe_b1"][:, None].astype(x.dtype)
    )
    expert_out = constrain(
        jnp.einsum("ech,ehd->ecd", h, params["moe_w2"].astype(x.dtype))
        + params["moe_b2"][:, None].astype(x.dtype)
    )
    y = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), expert_out)
    return y.reshape(*lead, d), aux
