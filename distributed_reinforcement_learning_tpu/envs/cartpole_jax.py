"""CartPole-v0 as a pure-JAX function: the on-device (Anakin) env.

Same physics, termination, and auto-reset semantics as the numpy
`envs.cartpole.VectorCartPole` (itself the in-tree stand-in for the
reference's `gym.make("CartPole-v0")`, `train_r2d2.py:171`), expressed
as jittable pure functions so whole collect+learn loops can live inside
one compiled program on the TPU — the "Anakin" pattern of the Podracer
architectures (arXiv:2104.06272). No host, no queue, no transport: the
env IS device compute.

Numerics note: the numpy env integrates in float64; this one uses
float32 (TPU-native). Trajectories diverge per-step at the 1e-7 level —
immaterial for control, not bit-identical.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from distributed_reinforcement_learning_tpu.envs.cartpole import (
    _FORCE_MAG,
    _GRAVITY,
    _LENGTH,
    _MASSPOLE,
    _POLEMASS_LENGTH,
    _TAU,
    _THETA_LIMIT,
    _TOTAL_MASS,
    _X_LIMIT,
)

NUM_ACTIONS = 2
OBS_SHAPE = (4,)


class CartPoleState(NamedTuple):
    physics: jax.Array  # [N, 4] f32 (x, x_dot, theta, theta_dot)
    steps: jax.Array  # [N] i32 since episode start
    returns: jax.Array  # [N] f32 accumulated episode return


def _fresh(rng: jax.Array, n: int) -> jax.Array:
    return jax.random.uniform(rng, (n, 4), jnp.float32, -0.05, 0.05)


def reset(rng: jax.Array, num_envs: int) -> tuple[CartPoleState, jax.Array]:
    physics = _fresh(rng, num_envs)
    state = CartPoleState(
        physics=physics,
        steps=jnp.zeros(num_envs, jnp.int32),
        returns=jnp.zeros(num_envs, jnp.float32),
    )
    return state, physics


def step(
    state: CartPoleState, actions: jax.Array, rng: jax.Array, max_steps: int = 200
) -> tuple[CartPoleState, jax.Array, jax.Array, jax.Array, jax.Array]:
    """-> (state', obs', reward, done, episode_return).

    Matches VectorCartPole.step: `obs'` holds the RESET observation for
    done slots, `episode_return` is the completed return where done else 0.
    """
    x, x_dot, theta, theta_dot = jnp.moveaxis(state.physics, -1, 0)
    force = jnp.where(actions == 1, _FORCE_MAG, -_FORCE_MAG).astype(jnp.float32)
    costheta, sintheta = jnp.cos(theta), jnp.sin(theta)
    temp = (force + _POLEMASS_LENGTH * theta_dot**2 * sintheta) / _TOTAL_MASS
    thetaacc = (_GRAVITY * sintheta - costheta * temp) / (
        _LENGTH * (4.0 / 3.0 - _MASSPOLE * costheta**2 / _TOTAL_MASS)
    )
    xacc = temp - _POLEMASS_LENGTH * thetaacc * costheta / _TOTAL_MASS
    physics = jnp.stack(
        [x + _TAU * x_dot, x_dot + _TAU * xacc,
         theta + _TAU * theta_dot, theta_dot + _TAU * thetaacc], axis=-1)

    steps = state.steps + 1
    returns = state.returns + 1.0
    done = (
        (jnp.abs(physics[:, 0]) > _X_LIMIT)
        | (jnp.abs(physics[:, 2]) > _THETA_LIMIT)
        | (steps >= max_steps)
    )
    episode_return = jnp.where(done, returns, 0.0)
    fresh = _fresh(rng, physics.shape[0])
    new_state = CartPoleState(
        physics=jnp.where(done[:, None], fresh, physics),
        steps=jnp.where(done, 0, steps),
        returns=jnp.where(done, 0.0, returns),
    )
    reward = jnp.ones(physics.shape[0], jnp.float32)
    return new_state, new_state.physics, reward, done, episode_return


def completed_episode_mask(done: jax.Array, new_state: CartPoleState) -> jax.Array:
    """Every CartPole `done` is a completed episode (no life-loss
    boundaries); part of the jittable-env contract (`breakout_jax`)."""
    del new_state
    return done
