"""Gymnasium adapter: real third-party envs behind the framework's seams.

The reference resolves every env through `gym.make`
(`/root/reference/train_impala.py:117`, `/root/reference/wrappers.py:114-138`).
This module is the equivalent seam for gymnasium (the maintained gym
fork, present in this image): `GymnasiumEnv` adapts any gymnasium env to
the framework's `Env` protocol, and `GymnasiumRawFrames` adapts an
ALE-style RGB env to the `RawFrameEnv` protocol so the in-tree Atari
preprocessing pipeline (`envs/atari.py`, parity with the reference's
`wrappers.py`) runs over a real emulator when `ale-py` is installed.

Differences from the in-tree envs the adapter papers over:
- gymnasium's 5-tuple step (`terminated`/`truncated`) is collapsed to the
  reference's single `done` flag (either ends the episode);
- `reset()` returns `(obs, info)` in gymnasium — the info is dropped;
- ALE life counters surface through `info["lives"]` / `.lives()` for the
  reference's life-loss shaping (`train_impala.py:149-154`).
"""

from __future__ import annotations

from typing import Any

import numpy as np


def gymnasium_available() -> bool:
    try:
        import gymnasium  # noqa: F401

        return True
    except ImportError:
        return False


def ale_available() -> bool:
    """True when gymnasium can actually construct Atari envs."""
    try:
        import ale_py  # noqa: F401

        return True
    except ImportError:
        return False


class GymnasiumEnv:
    """`Env`-protocol adapter over `gymnasium.make(name)`."""

    def __init__(self, name: str, seed: int | None = None, **make_kwargs: Any):
        import gymnasium

        self._env = gymnasium.make(name, **make_kwargs)
        self._seed = seed
        self._first_reset = True
        self.num_actions = int(self._env.action_space.n)
        space_shape = getattr(self._env.observation_space, "shape", None)
        self.obs_shape = tuple(space_shape) if space_shape else None

    def reset(self) -> np.ndarray:
        # Seed once on the first reset (gymnasium's seeding surface), then
        # let the env's own RNG evolve like the reference's gym usage.
        if self._first_reset:
            obs, _ = self._env.reset(seed=self._seed)
            self._first_reset = False
        else:
            obs, _ = self._env.reset()
        return np.asarray(obs, dtype=np.float32 if np.asarray(obs).dtype != np.uint8 else np.uint8)

    def step(self, action: int) -> tuple[np.ndarray, float, bool, dict[str, Any]]:
        obs, reward, terminated, truncated, info = self._env.step(int(action))
        obs = np.asarray(obs)
        if obs.dtype != np.uint8:
            obs = obs.astype(np.float32)
        done = bool(terminated or truncated)
        out_info: dict[str, Any] = {"truncated": bool(truncated and not terminated)}
        if "lives" in info:
            out_info["lives"] = int(info["lives"])
        return obs, float(reward), done, out_info

    def close(self) -> None:
        self._env.close()


class GymnasiumRawFrames:
    """`RawFrameEnv`-protocol adapter: raw RGB frames + ALE life counter.

    Wraps `gymnasium.make(name)` for an Atari name (needs `ale-py`). The
    in-tree `AtariPreprocessor` then applies the reference's pipeline
    (2-frame max, luma, area resize, crop, 4-stack — `wrappers.py:26-111`)
    on top, exactly as it does over `SyntheticAtari`.
    """

    def __init__(self, name: str, seed: int | None = None):
        import gymnasium

        # The name encodes the emulator frameskip the reference trained
        # with (`*Deterministic-v4` = built-in skip 4, `*NoFrameskip-v4` =
        # skip 1); the reference's MaxAndSkipEnv(skip=1) adds only a
        # 2-frame max over the post-skip frames (`wrappers.py:26-51`),
        # which the in-tree AtariPreprocessor reproduces — so take the
        # registration's native frameskip unmodified.
        self._env = gymnasium.make(name)
        self._seed = seed
        self._first_reset = True
        self.num_actions = int(self._env.action_space.n)
        self._lives = 0

    def reset(self) -> np.ndarray:
        if self._first_reset:
            obs, info = self._env.reset(seed=self._seed)
            self._first_reset = False
        else:
            obs, info = self._env.reset()
        self._lives = int(info.get("lives", 0))
        return np.asarray(obs, np.uint8)

    def step(self, action: int) -> tuple[np.ndarray, float, bool, dict[str, Any]]:
        obs, reward, terminated, truncated, info = self._env.step(int(action))
        self._lives = int(info.get("lives", self._lives))
        return (
            np.asarray(obs, np.uint8),
            float(reward),
            bool(terminated or truncated),
            {"lives": self._lives},
        )

    def lives(self) -> int:
        return self._lives

    def close(self) -> None:
        self._env.close()
