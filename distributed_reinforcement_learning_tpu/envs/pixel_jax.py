"""Shared on-device Atari observation pipeline for the JAX pixel envs.

One implementation of the `envs.atari.AtariPreprocessor` stages —
2-frame max over consecutive post-frameskip raw frames, luma, INTER_AREA
resize as two matmuls (the separable overlap weights of
`atari.area_resize`, rows pre-cropped), `[84, 84]` uint8, 4-frame
newest-last stacking — used by both `breakout_jax` and `pong_jax` so the
subtle parts (crop window, stack shift, reset-stack semantics,
auto-reset merge) cannot diverge between games.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from distributed_reinforcement_learning_tpu.envs.atari import _area_weights

H, W = 210, 160

# Resize rows 210 -> 110 then crop [18:102] == one 84x210 matrix
# (`atari.preprocess_frame` parity); cols 160 -> 84.
_WH_CROP = np.asarray(_area_weights(H, 110))[18:102, :]  # [84, 210]
_WW_T = np.asarray(_area_weights(W, 84)).T  # [160, 84]
_LUMA = np.array([0.299, 0.587, 0.114], np.float32)


def preprocess(rgb: jax.Array) -> jax.Array:
    """`[210, 160, 3]` u8 -> `[84, 84]` u8 (luma, area-resize, crop)."""
    luma = rgb.astype(jnp.float32) @ jnp.asarray(_LUMA)  # [210, 160]
    resized = jnp.asarray(_WH_CROP) @ luma @ jnp.asarray(_WW_T)  # [84, 84]
    return resized.astype(jnp.uint8)


def observe(raw: jax.Array, prev_raw: jax.Array, stack: jax.Array) -> jax.Array:
    """Next observation stack: 2-frame max with the previous adapter-step
    raw frame, preprocess, shift the newest-last 4-stack."""
    maxed = jnp.maximum(raw, prev_raw)
    frame = jax.vmap(preprocess)(maxed)
    return jnp.concatenate([stack[..., 1:], frame[..., None]], axis=-1)


def reset_stack(raw0: jax.Array) -> jax.Array:
    """Observation stack right after a reset: zeros with the reset frame
    in the newest slot (the host pipeline clears its buffer on reset)."""
    frame0 = jax.vmap(preprocess)(raw0)
    stack = jnp.zeros(frame0.shape[:1] + (84, 84, 4), jnp.uint8)
    return stack.at[..., -1].set(frame0)


def make_pick(game_over: jax.Array):
    """-> pick(reset_val, cont_val): per-env select of the auto-reset
    value for game-over slots, broadcasting the mask over trailing dims."""
    n = game_over.shape[0]

    def pick(reset_val: jax.Array, cont_val: jax.Array) -> jax.Array:
        mask = game_over.reshape((n,) + (1,) * (cont_val.ndim - 1))
        return jnp.where(mask, reset_val, cont_val)

    return pick
