"""Space-Invaders simulator: the third faithfully-simulated game.

Like `breakout_sim.py` / `pong_sim.py` (ale-py is not installable in
this image), this is an honest ALE proxy at real Atari specs — but with
a STRUCTURALLY different objective, stressing the env abstraction past
the paddle-game pair (VERDICT r4 missing #1): a marching 6x6 alien
grid that speeds up as it thins, enemy bombs the player must dodge,
destructible shields, combined move+fire actions, and lives that matter
mid-episode (a bomb hit costs a life and respawns the cannon with the
wave still descending).

Fidelity targets (vs ALE SpaceInvaders):
- 210x160x3 uint8 frames; black background, row-tinted alien sprites,
  green cannon/shields, white projectiles; score strip region that the
  reference crop removes (`wrappers.py:63-74`).
- Minimal action set NOOP/FIRE/RIGHT/LEFT/RIGHTFIRE/LEFTFIRE (ALE
  `SpaceInvaders-v*` = 6 actions — the combined move+fire actions are
  the structural novelty vs Breakout/Pong's pure-move sets).
- Row scores 30/25/20/15/10/5 top->bottom (the 2600's values), one
  player missile in flight at a time (the 2600's signature constraint),
  up to 2 alien bombs, 3 lives with `info["lives"]`.
- Wave clear respawns the grid one step lower and faster (the 2600
  continues waves indefinitely); game over when lives run out or the
  grid reaches the cannon row.

Deliberate simplifications (documented, pixels-honest): aliens are
solid 8x6 blocks (no per-frame sprite animation), shields are solid
blocks that shrink as they erode (hit points, not per-pixel damage),
and there is no mystery ship / UFO bonus row.

Registers `SpaceInvadersSim-v0` (+`Deterministic`) with gymnasium so the
`GymnasiumRawFrames` adapter is the code path under test, exactly like
the other two games.
"""

from __future__ import annotations

from typing import Any

import numpy as np

H, W = 210, 160

# Alien grid geometry: 6 rows x 6 cols of 8x6 sprites on a 16x12 pitch.
ROWS, COLS = 6, 6
ALIEN_W, ALIEN_H = 8, 6
PITCH_X, PITCH_Y = 16, 12
GRID_SPAN = (COLS - 1) * PITCH_X + ALIEN_W  # 88 px
GRID_X0 = 20.0          # spawn offset (left edge of the grid)
GRID_Y0 = 40.0
GRID_X_MIN, GRID_X_MAX = 8.0, float(W - 8 - GRID_SPAN)
ROW_POINTS = (30, 25, 20, 15, 10, 5)  # top row is worth most (2600 values)

CANNON_Y = 185          # cannon top scanline
CANNON_W, CANNON_H = 8, 8
CANNON_SPEED = 2
MISSILE_SPEED = 4.0     # player shot, px/frame upward
BOMB_SPEED = 2.0        # alien bomb, px/frame downward
MAX_BOMBS = 2
SHIELD_Y = 157          # shield top scanline
SHIELD_W, SHIELD_H = 16, 10
SHIELD_HP = 8
SHIELD_XS = (28, 76, 124)
PROJ_W, PROJ_H = 2, 6   # missile/bomb sprite

ALIEN_ROW_COLORS = (
    (180, 122, 48),   # top rows tan
    (180, 122, 48),
    (162, 162, 42),   # middle yellow
    (162, 162, 42),
    (72, 160, 72),    # bottom green
    (72, 160, 72),
)
CANNON_RGB = (50, 132, 50)
SHIELD_RGB = (72, 160, 72)
PROJ_RGB = (228, 228, 228)
WALL = (142, 142, 142)

NOOP, FIRE, RIGHT, LEFT, RIGHTFIRE, LEFTFIRE = 0, 1, 2, 3, 4, 5
WALL_TOP_Y = 20  # missiles vanish above this scanline


def march_period(alive: int) -> int:
    """Frames between grid steps — the thinning grid speeds up (36
    aliens: every 8 frames; last alien: every frame)."""
    return 1 + (7 * alive) // (ROWS * COLS)


class InvadersCore:
    """Game state + renderer (`BreakoutCore` conventions: frameskip holds
    the action, rewards sum, last frame returned)."""

    num_actions = 6

    def __init__(self, seed: int = 0, max_frames: int = 10_000, frameskip: int = 1):
        self._rng = np.random.RandomState(seed)
        self._max_frames = max_frames
        self.frameskip = max(1, frameskip)
        self.reset()

    def reset(self) -> np.ndarray:
        self.aliens = np.ones((ROWS, COLS), bool)
        self.grid_x = GRID_X0
        self.grid_y = GRID_Y0
        self.direction = 1
        self.march_count = 0
        self.wave = 0
        self.cannon_x = float((W - CANNON_W) // 2)
        self.missile_live = False
        self.missile_x = 0.0
        self.missile_y = 0.0
        self.bomb_live = np.zeros(MAX_BOMBS, bool)
        self.bomb_x = np.zeros(MAX_BOMBS)
        self.bomb_y = np.zeros(MAX_BOMBS)
        self.shield_hp = np.full(len(SHIELD_XS), SHIELD_HP)
        self.lives = 3
        self.score = 0
        self.frames = 0
        return self.render()

    def step(self, action: int) -> tuple[np.ndarray, float, bool, dict[str, Any]]:
        if not 0 <= action < self.num_actions:
            raise ValueError(
                f"action {action} outside SpaceInvaders' {self.num_actions}-action "
                "set (alias the policy head with `action % available_action` first)")
        reward = 0.0
        done = False
        for _ in range(self.frameskip):
            r, done = self._emulate_frame(action)
            reward += r
            if done:
                break
        return self.render(), reward, done, {"lives": self.lives}

    # -- one emulated frame ---------------------------------------------
    def _emulate_frame(self, action: int) -> tuple[float, bool]:
        self.frames += 1
        reward = 0.0

        # Cannon move + fire (combined actions do both).
        if action in (RIGHT, RIGHTFIRE):
            self.cannon_x = min(float(W - 8 - CANNON_W), self.cannon_x + CANNON_SPEED)
        elif action in (LEFT, LEFTFIRE):
            self.cannon_x = max(8.0, self.cannon_x - CANNON_SPEED)
        if action in (FIRE, RIGHTFIRE, LEFTFIRE) and not self.missile_live:
            self.missile_live = True
            self.missile_x = self.cannon_x + CANNON_W / 2 - PROJ_W / 2
            self.missile_y = float(CANNON_Y - PROJ_H)

        # Grid march: step every march_period(alive) frames; drop a row
        # and flip direction at the walls.
        alive = int(self.aliens.sum())
        self.march_count += 1
        if alive > 0 and self.march_count >= march_period(alive):
            self.march_count = 0
            nx = self.grid_x + self.direction * 2.0
            if nx < GRID_X_MIN or nx > GRID_X_MAX:
                self.direction = -self.direction
                self.grid_y += PITCH_Y // 2
            else:
                self.grid_x = nx

        # Alien bombs: lowest alive alien of a random column drops one.
        if alive > 0 and self._rng.random() < 0.04:
            slot = int(np.argmin(self.bomb_live))  # first free slot, if any
            if not self.bomb_live[slot]:
                cols = np.flatnonzero(self.aliens.any(axis=0))
                col = int(self._rng.choice(cols))
                row = int(np.max(np.flatnonzero(self.aliens[:, col])))
                self.bomb_live[slot] = True
                self.bomb_x[slot] = (self.grid_x + col * PITCH_X
                                     + ALIEN_W / 2 - PROJ_W / 2)
                self.bomb_y[slot] = self.grid_y + row * PITCH_Y + ALIEN_H

        # Player missile flight + hits.
        if self.missile_live:
            self.missile_y -= MISSILE_SPEED
            reward += self._missile_collide()
            if self.missile_y < WALL_TOP_Y:
                self.missile_live = False

        # Bombs fall; erode shields; hit the cannon.
        for b in range(MAX_BOMBS):
            if not self.bomb_live[b]:
                continue
            self.bomb_y[b] += BOMB_SPEED
            if self._shield_absorb(self.bomb_x[b], self.bomb_y[b] + PROJ_H):
                self.bomb_live[b] = False
            elif (self.bomb_y[b] + PROJ_H >= CANNON_Y
                  and self.cannon_x - PROJ_W <= self.bomb_x[b]
                  <= self.cannon_x + CANNON_W):
                self.bomb_live[b] = False
                self.lives -= 1
                # Cannon respawns centered; in-flight bombs clear (the
                # 2600's brief respawn invulnerability, simplified).
                self.bomb_live[:] = False
                self.cannon_x = float((W - CANNON_W) // 2)
                break
            elif self.bomb_y[b] >= H:
                self.bomb_live[b] = False

        # Wave cleared: next wave spawns lower and the march starts
        # faster (the 2600's escalation).
        if not self.aliens.any():
            self.wave += 1
            self.aliens[:] = True
            self.grid_x = GRID_X0
            self.grid_y = GRID_Y0 + min(3, self.wave) * (PITCH_Y // 2)
            self.direction = 1
            self.march_count = 0

        landed = (self.grid_y + (ROWS - 1) * PITCH_Y + ALIEN_H >= SHIELD_Y
                  and self.aliens.any())
        done = self.lives <= 0 or landed or self.frames >= self._max_frames
        return reward, done

    def _missile_collide(self) -> float:
        """Missile vs shields, then the alien grid (one kill per frame)."""
        if self._shield_absorb(self.missile_x, self.missile_y):
            self.missile_live = False
            return 0.0
        # Bombs: a missile can shoot a bomb down (both vanish, no score).
        for b in range(MAX_BOMBS):
            if (self.bomb_live[b]
                    and abs(self.bomb_x[b] - self.missile_x) < PROJ_W + 1
                    and abs(self.bomb_y[b] - self.missile_y) < PROJ_H):
                self.bomb_live[b] = False
                self.missile_live = False
                return 0.0
        col = int((self.missile_x + PROJ_W / 2 - self.grid_x) // PITCH_X)
        row = int((self.missile_y - self.grid_y) // PITCH_Y)
        if 0 <= row < ROWS and 0 <= col < COLS and self.aliens[row, col]:
            # Inside the 8-px sprite (the pitch leaves 8-px gaps)?
            within = (self.missile_x + PROJ_W / 2
                      - (self.grid_x + col * PITCH_X)) < ALIEN_W
            tall = (self.missile_y - (self.grid_y + row * PITCH_Y)) < ALIEN_H
            if within and tall:
                self.aliens[row, col] = False
                self.missile_live = False
                self.score += ROW_POINTS[row]
                return float(ROW_POINTS[row])
        return 0.0

    def _shield_absorb(self, x: float, y: float) -> bool:
        """Projectile tip at (x, y) vs the shrinking shield blocks."""
        for s, sx in enumerate(SHIELD_XS):
            if self.shield_hp[s] <= 0:
                continue
            height = SHIELD_H * self.shield_hp[s] // SHIELD_HP
            if (sx <= x + PROJ_W / 2 <= sx + SHIELD_W
                    and SHIELD_Y <= y <= SHIELD_Y + height):
                self.shield_hp[s] -= 1
                return True
        return False

    # -- rendering -------------------------------------------------------
    def render(self) -> np.ndarray:
        f = np.zeros((H, W, 3), np.uint8)
        # Score strip blocks (cropped by preprocessing, like breakout_sim).
        score_blocks = min(12, self.score // 40)
        for b in range(score_blocks):
            f[6:18, 36 + 8 * b:42 + 8 * b] = WALL
        f[6:18, 16:22] = WALL  # lives indicator block
        # Ground line.
        f[H - 4:H - 2, :] = CANNON_RGB
        # Aliens.
        gy = int(self.grid_y)
        gx = int(self.grid_x)
        for r in range(ROWS):
            y = gy + r * PITCH_Y
            for c in np.flatnonzero(self.aliens[r]):
                x = gx + int(c) * PITCH_X
                f[y:y + ALIEN_H, x:x + ALIEN_W] = ALIEN_ROW_COLORS[r]
        # Shields (height erodes with hp).
        for s, sx in enumerate(SHIELD_XS):
            if self.shield_hp[s] > 0:
                height = SHIELD_H * self.shield_hp[s] // SHIELD_HP
                f[SHIELD_Y:SHIELD_Y + height, sx:sx + SHIELD_W] = SHIELD_RGB
        # Cannon.
        cx = int(self.cannon_x)
        f[CANNON_Y:CANNON_Y + CANNON_H, cx:cx + CANNON_W] = CANNON_RGB
        # Projectiles.
        if self.missile_live:
            y, x = int(self.missile_y), int(self.missile_x)
            f[max(y, 0):max(y, 0) + PROJ_H, x:x + PROJ_W] = PROJ_RGB
        for b in range(MAX_BOMBS):
            if self.bomb_live[b]:
                y, x = int(self.bomb_y[b]), int(self.bomb_x[b])
                f[y:min(y + PROJ_H, H), x:x + PROJ_W] = PROJ_RGB
        return f


class InvadersSimRaw:
    """`RawFrameEnv`-protocol surface over `InvadersCore` (no gymnasium)."""

    def __init__(self, seed: int = 0, max_frames: int = 10_000, frameskip: int = 1):
        self._core = InvadersCore(seed=seed, max_frames=max_frames,
                                  frameskip=frameskip)
        self.num_actions = InvadersCore.num_actions

    def reset(self) -> np.ndarray:
        return self._core.reset()

    def step(self, action: int):
        return self._core.step(int(action))

    def lives(self) -> int:
        return self._core.lives


_GYM_REGISTERED = False


def register_gymnasium() -> bool:
    """Register `SpaceInvadersSim-v0` with gymnasium (idempotent), like
    `breakout_sim.register_gymnasium`."""
    global _GYM_REGISTERED
    try:
        import gymnasium
        from gymnasium import spaces
    except ImportError:
        return False
    if _GYM_REGISTERED:
        return True

    class _GymInvadersSim(gymnasium.Env):
        metadata = {"render_modes": []}

        def __init__(self, max_frames: int = 10_000, frameskip: int = 1):
            self._max_frames = max_frames
            self._frameskip = frameskip
            self._core: InvadersCore | None = None
            self.action_space = spaces.Discrete(InvadersCore.num_actions)
            self.observation_space = spaces.Box(0, 255, (H, W, 3), np.uint8)

        def reset(self, *, seed=None, options=None):
            super().reset(seed=seed)
            if self._core is None or seed is not None:
                self._core = InvadersCore(seed=seed or 0,
                                          max_frames=self._max_frames,
                                          frameskip=self._frameskip)
            obs = self._core.reset()
            return obs, {"lives": self._core.lives}

        def step(self, action):
            obs, reward, done, info = self._core.step(int(action))
            return obs, reward, done, False, info

    gymnasium.register(id="SpaceInvadersSim-v0",
                       entry_point=lambda **kw: _GymInvadersSim(**kw))
    gymnasium.register(
        id="SpaceInvadersSimDeterministic-v0",
        entry_point=lambda **kw: _GymInvadersSim(**{"frameskip": 4, **kw}))
    _GYM_REGISTERED = True
    return True
