"""Env registry: name -> constructor, the `gym.make` seam.

The reference resolves env names via `gym.make` (`train_impala.py:117`,
`wrappers.py:115-138`). This image has no gym/ALE, so:

- `CartPole-v0` maps to the in-tree physics implementation.
- Atari names (`*Deterministic-v4`, `*NoFrameskip-v4`) map to the full
  preprocessing pipeline over `SyntheticAtari` — the real ALE emulator
  plugs into the same `RawFrameEnv` seam when available (install
  `ale-py` and register a factory via `register_env`).
"""

from __future__ import annotations

import re
from typing import Callable

from distributed_reinforcement_learning_tpu.envs.atari import AtariPreprocessor, SyntheticAtari
from distributed_reinforcement_learning_tpu.envs.base import Env
from distributed_reinforcement_learning_tpu.envs.cartpole import CartPoleEnv

_REGISTRY: dict[str, Callable[..., Env]] = {}

_ATARI_PATTERN = re.compile(r".*(Deterministic|NoFrameskip)-v\d+$")


def register_env(name: str, factory: Callable[..., Env]) -> None:
    _REGISTRY[name] = factory


def make_env(name: str, seed: int = 0, num_actions: int = 18) -> Env:
    if name in _REGISTRY:
        return _REGISTRY[name](seed=seed)
    if name == "CartPole-v0":
        return CartPoleEnv(seed=seed)
    if name == "CartPole-v1":
        return CartPoleEnv(seed=seed, max_steps=500)
    if _ATARI_PATTERN.match(name):
        # No emulator in this environment: synthetic frames through the
        # real preprocessing pipeline (same shapes/dtypes/life semantics).
        return AtariPreprocessor(SyntheticAtari(num_actions=num_actions, seed=seed))
    raise ValueError(f"unknown env {name!r}; register a factory with register_env")
