"""Env registry: name -> constructor, the `gym.make` seam.

The reference resolves env names via `gym.make` (`train_impala.py:117`,
`wrappers.py:115-138`). Resolution order here:

- an explicitly registered factory (`register_env`) always wins;
- `CartPole-v*` goes through **gymnasium** (installed in this image) so
  training is validated against an environment the framework didn't
  write; set `DRL_NO_GYMNASIUM=1` to force the in-tree numpy physics
  (tests use it for determinism, and it is the automatic fallback);
- Atari names (`*Deterministic-v4`, `*NoFrameskip-v4`) use gymnasium +
  `ale-py` when the emulator is importable; otherwise `Breakout*`,
  `Pong*` and `SpaceInvaders*` fall back to the in-tree simulators (real
  game dynamics at ALE specs, through the same GymnasiumRawFrames
  adapter — envs/{breakout,pong,invaders}_sim; Pong/SpaceInvaders adapt
  without fire-reset, the reference's `make_uint8_env_no_fire` path)
  and other titles fall back to the full preprocessing pipeline over
  `SyntheticAtari`. All
  fallbacks say so on stderr, once per name, because training
  "Breakout" on a stand-in silently is how a benchmark lies
  (`DRL_SYNTHETIC_ATARI=1` opts into silence).
"""

from __future__ import annotations

import os
import re
import sys
from typing import Callable

from distributed_reinforcement_learning_tpu.envs.atari import AtariPreprocessor, SyntheticAtari
from distributed_reinforcement_learning_tpu.envs.base import Env
from distributed_reinforcement_learning_tpu.envs.cartpole import CartPoleEnv

_REGISTRY: dict[str, Callable[..., Env]] = {}

_ATARI_PATTERN = re.compile(r".*(Deterministic|NoFrameskip)-v\d+$")
_warned_synthetic: set[str] = set()


def register_env(name: str, factory: Callable[..., Env]) -> None:
    _REGISTRY[name] = factory


def _use_gymnasium() -> bool:
    if os.environ.get("DRL_NO_GYMNASIUM", "0") == "1":
        return False
    from distributed_reinforcement_learning_tpu.envs.gymnasium_env import gymnasium_available

    return gymnasium_available()


def _sim_fallback(name: str, sim_mod, id_prefix: str, seed: int,
                  fire_reset: bool, raw_cls, game: str) -> Env:
    """Shared no-ALE fallback: warn once, then route through gymnasium's
    registration of the in-tree simulator (the exact `GymnasiumRawFrames`
    adapter an ale-py install would use) or the raw-protocol class.

    The Deterministic name encodes ALE's built-in frameskip 4 (see
    GymnasiumRawFrames docstring) — honored in the sim either way.
    """
    if name not in _warned_synthetic and os.environ.get("DRL_SYNTHETIC_ATARI") != "1":
        _warned_synthetic.add(name)
        print(f"[envs] WARNING: no ALE emulator available; {name!r} resolves "
              f"to the in-tree {game} simulator (real game dynamics, not "
              f"the 2600 ROM). Install ale-py for the real game.",
              file=sys.stderr)
    skip = 4 if "Deterministic" in name else 1
    if _use_gymnasium() and sim_mod.register_gymnasium():
        from distributed_reinforcement_learning_tpu.envs.gymnasium_env import GymnasiumRawFrames

        sim_name = (f"{id_prefix}Deterministic-v0" if skip == 4
                    else f"{id_prefix}-v0")
        return AtariPreprocessor(GymnasiumRawFrames(sim_name, seed=seed),
                                 fire_reset=fire_reset)
    return AtariPreprocessor(raw_cls(seed=seed, frameskip=skip),
                             fire_reset=fire_reset)


def make_env(name: str, seed: int = 0, num_actions: int = 18) -> Env:
    if name in _REGISTRY:
        return _REGISTRY[name](seed=seed)
    if name in ("CartPole-v0", "CartPole-v1"):
        if _use_gymnasium():
            from distributed_reinforcement_learning_tpu.envs.gymnasium_env import GymnasiumEnv

            return GymnasiumEnv(name, seed=seed)
        return CartPoleEnv(seed=seed, max_steps=200 if name.endswith("v0") else 500)
    if _ATARI_PATTERN.match(name):
        if _use_gymnasium():
            from distributed_reinforcement_learning_tpu.envs.gymnasium_env import (
                GymnasiumRawFrames, ale_available)

            if ale_available():
                return AtariPreprocessor(GymnasiumRawFrames(name, seed=seed))
        # No emulator importable. Breakout falls back to the in-tree
        # Breakout simulator — a real game (paddle/ball/brick dynamics,
        # 2600 palette, FIRE launch, 5 lives) rendered at ALE specs —
        # through the SAME GymnasiumRawFrames adapter an ALE install
        # would use. Other titles fall back to SyntheticAtari noise.
        if name.startswith("Breakout"):
            from distributed_reinforcement_learning_tpu.envs import breakout_sim

            return _sim_fallback(name, breakout_sim, "BreakoutSim", seed,
                                 fire_reset=True,
                                 raw_cls=breakout_sim.BreakoutSimRaw,
                                 game="Breakout")
        if name.startswith("Pong"):
            # Second faithful game (envs/pong_sim): 6-action set, signed
            # rewards, no lives. Adapted WITHOUT fire-reset — the
            # reference's `make_uint8_env_no_fire` path
            # (`wrappers.py:132-138`); serves are FIRE or auto.
            from distributed_reinforcement_learning_tpu.envs import pong_sim

            return _sim_fallback(name, pong_sim, "PongSim", seed,
                                 fire_reset=False,
                                 raw_cls=pong_sim.PongSimRaw, game="Pong")
        if name.startswith("SpaceInvaders"):
            # Third faithful game (envs/invaders_sim): 6-action set with
            # combined move+fire, enemy projectiles, destructible
            # shields, mid-episode lives — the structurally-different
            # objective the paddle pair doesn't exercise. No fire-reset:
            # FIRE shoots (not a serve), so the wrapper would just waste
            # the first frame.
            from distributed_reinforcement_learning_tpu.envs import invaders_sim

            return _sim_fallback(name, invaders_sim, "SpaceInvadersSim", seed,
                                 fire_reset=False,
                                 raw_cls=invaders_sim.InvadersSimRaw,
                                 game="Space-Invaders")
        # Synthetic frames through the real preprocessing pipeline (same
        # shapes/dtypes/life semantics).
        if name not in _warned_synthetic and os.environ.get("DRL_SYNTHETIC_ATARI") != "1":
            _warned_synthetic.add(name)
            print(f"[envs] WARNING: no ALE emulator available; {name!r} resolves to "
                  f"SyntheticAtari (random frames through the real preprocessing "
                  f"pipeline). Install ale-py for the real game.", file=sys.stderr)
        return AtariPreprocessor(SyntheticAtari(num_actions=num_actions, seed=seed))
    raise ValueError(f"unknown env {name!r}; register a factory with register_env")
