"""Pong simulator: second faithful in-tree game (VERDICT r3 item 6).

`ale-py` is not installable in this image, so — like `breakout_sim` —
this is a faithful implementation of the game at genuine Atari specs,
NOT the 2600 ROM. It exists to widen real-dynamics env coverage beyond
Breakout and to exercise the parts of the pipeline Breakout cannot:

- a DIFFERENT minimal action set (6: NOOP/FIRE/RIGHT/LEFT/RIGHTFIRE/
  LEFTFIRE, ALE Pong's, where RIGHT=up and LEFT=down) driving the
  per-task `env`/`available_action` lists the reference config carries
  (`/root/reference/config.json:26-28`, `train_impala.py:145` aliasing);
- NEGATIVE rewards (-1 when the agent's side is scored on) so
  `soft_asymmetric` reward clipping (`agents/common.py`) and the
  life-loss path see signed returns — Breakout rewards are all >= 0;
- the no-fire-reset wrapper path (`/root/reference/wrappers.py:132-138`
  `make_uint8_env_no_fire`): the registry adapts Pong with
  `fire_reset=False`; serves happen on FIRE or auto-serve, like the ROM;
- no lives: `info["lives"]` is always 0, so life-loss shaping must
  correctly no-op (it keys on transitions, `runtime/impala_runner.py`).

Fidelity targets (vs ALE Pong):
- 210x160x3 uint8 frames in the ALE Pong palette: brown background
  (144, 72, 17), white bounds/ball (236, 236, 236), orange enemy paddle
  (213, 130, 74) on the left, green agent paddle (92, 186, 92) on the
  right; a blocky score strip that the preprocessing crop removes
  (`wrappers.py:63-74`).
- Playfield rows [34, 194): paddles 4x16 at x=16/x=140, ball 2x4,
  rally speed-up, hit-offset deflection, first to 21 ends the episode.
- `*Deterministic` registration = frameskip 4, like ALE's.

Registers `PongSim-v0`/`PongSimDeterministic-v0` with gymnasium so the
`GymnasiumRawFrames` adapter — the exact code path a real ALE install
would use — is what the registry and tests drive.
"""

from __future__ import annotations

from typing import Any

import numpy as np

# ALE Pong palette (NTSC).
BACKGROUND = (144, 72, 17)
BOUNDS = (236, 236, 236)      # top/bottom bounds, ball, score glyphs
ENEMY = (213, 130, 74)        # left (computer) paddle
PLAYER = (92, 186, 92)        # right (agent) paddle

H, W = 210, 160
FIELD_TOP = 34                # first playfield scanline (score strip above)
FIELD_BOT = 194               # one past the last playfield scanline
BOUND_H = 10                  # white strips: [24, 34) and [194, 204)
PADDLE_H = 16
PADDLE_W = 4
ENEMY_X = 16
PLAYER_X = 140
BALL_W, BALL_H = 2, 4
WIN_SCORE = 21
SERVE_DELAY = 36              # emulated frames before auto-serve

NOOP, FIRE, RIGHT, LEFT, RIGHTFIRE, LEFTFIRE = range(6)
_UP_ACTIONS = (RIGHT, RIGHTFIRE)      # ALE Pong: RIGHT moves the paddle up
_DOWN_ACTIONS = (LEFT, LEFTFIRE)
_FIRE_ACTIONS = (FIRE, RIGHTFIRE, LEFTFIRE)


class PongCore:
    """Game state + renderer.

    `frameskip` follows ALE's built-in action repeat (see
    `breakout_sim.BreakoutCore` for why Deterministic names must bake
    skip=4 into the sim rather than serving skip-1 dynamics).
    """

    num_actions = 6

    def __init__(self, seed: int = 0, max_frames: int = 20_000, frameskip: int = 1):
        self._rng = np.random.RandomState(seed)
        self._max_frames = max_frames
        self.frameskip = max(1, frameskip)
        self.reset()

    def reset(self) -> np.ndarray:
        self.player_score = 0
        self.enemy_score = 0
        self.frames = 0
        self.player_y = (FIELD_TOP + FIELD_BOT - PADDLE_H) // 2
        self.enemy_y = self.player_y
        self._ball_dead = True
        self._serve_timer = SERVE_DELAY
        self._serve_dir = 1.0  # toward the agent first, like the ROM
        self._rally = 0
        self.ball_x = 0.0
        self.ball_y = 0.0
        self.vx = 0.0
        self.vy = 0.0
        return self.render()

    def _serve(self) -> None:
        self.ball_x = float(W // 2)
        self.ball_y = float(self._rng.randint(FIELD_TOP + 20, FIELD_BOT - 20))
        self.vx = 2.0 * self._serve_dir
        self.vy = float(self._rng.choice([-1.0, -0.5, 0.5, 1.0]))
        self._rally = 0
        self._ball_dead = False

    def step(self, action: int) -> tuple[np.ndarray, float, bool, dict[str, Any]]:
        if not 0 <= action < self.num_actions:
            # ALE raises on out-of-range actions (see breakout_sim.step).
            raise ValueError(
                f"action {action} outside Pong's {self.num_actions}-action set "
                f"(alias the policy head with `action % available_action` first)")
        reward = 0.0
        done = False
        for _ in range(self.frameskip):  # action held for every skipped frame
            r, done = self._emulate_frame(action)
            reward += r
            if done:
                break
        # ALE reports lives=0 for Pong throughout: scoring is the reward
        # channel, not a life counter — shaping must no-op on this.
        return self.render(), reward, done, {"lives": 0}

    def _emulate_frame(self, action: int) -> tuple[float, bool]:
        self.frames += 1
        reward = 0.0

        # Agent paddle (right). 2600 paddle speed ~2px/frame.
        if action in _UP_ACTIONS:
            self.player_y = max(FIELD_TOP, self.player_y - 2)
        elif action in _DOWN_ACTIONS:
            self.player_y = min(FIELD_BOT - PADDLE_H, self.player_y + 2)

        # Serve: FIRE serves immediately; otherwise auto-serve when the
        # timer runs out (the ROM serves on its own after a beat).
        if self._ball_dead:
            self._serve_timer -= 1
            if action in _FIRE_ACTIONS or self._serve_timer <= 0:
                self._serve()

        # Computer paddle (left): tracks the ball with capped speed and a
        # dead zone — beatable by steering the ball off the paddle edge,
        # like the ROM's AI, not a perfect wall.
        if not self._ball_dead and self.vx < 0:
            target = self.ball_y + BALL_H / 2 - PADDLE_H / 2
            diff = target - self.enemy_y
            if abs(diff) > 3:
                self.enemy_y += int(np.clip(diff, -2, 2))
        self.enemy_y = int(np.clip(self.enemy_y, FIELD_TOP, FIELD_BOT - PADDLE_H))

        if not self._ball_dead:
            # Sub-step so the ball cannot tunnel a 4px paddle at speed 3+.
            for _ in range(2):
                self.ball_x += self.vx / 2.0
                self.ball_y += self.vy / 2.0
                r = self._collide()
                reward += r
                if self._ball_dead:
                    break

        done = (self.player_score >= WIN_SCORE or self.enemy_score >= WIN_SCORE
                or self.frames >= self._max_frames)
        return reward, done

    def _deflect(self, paddle_y: int) -> None:
        """Hit position steers vy; rallies speed the ball up, like the ROM."""
        off = (self.ball_y + BALL_H / 2 - paddle_y - PADDLE_H / 2) / (PADDLE_H / 2)
        self.vy = float(np.clip(self.vy + 1.5 * off, -3.0, 3.0))
        self._rally += 1
        speed = min(2.0 + 0.25 * self._rally, 3.5)
        self.vx = speed if self.vx < 0 else -speed  # reverse + speed-up

    def _collide(self) -> float:
        # Top/bottom bounds.
        if self.ball_y <= FIELD_TOP:
            self.ball_y = float(FIELD_TOP)
            self.vy = abs(self.vy)
        elif self.ball_y >= FIELD_BOT - BALL_H:
            self.ball_y = float(FIELD_BOT - BALL_H)
            self.vy = -abs(self.vy)
        # Agent paddle (right): only when moving toward it.
        if (self.vx > 0 and PLAYER_X - BALL_W <= self.ball_x <= PLAYER_X + PADDLE_W
                and self.player_y - BALL_H <= self.ball_y <= self.player_y + PADDLE_H):
            self.ball_x = float(PLAYER_X - BALL_W)
            self._deflect(self.player_y)
        # Enemy paddle (left).
        if (self.vx < 0 and ENEMY_X - BALL_W <= self.ball_x <= ENEMY_X + PADDLE_W
                and self.enemy_y - BALL_H <= self.ball_y <= self.enemy_y + PADDLE_H):
            self.ball_x = float(ENEMY_X + PADDLE_W)
            self._deflect(self.enemy_y)
        # Scoring: ball crosses either edge. The agent owns the RIGHT
        # side, so right-edge = scored on (-1), left-edge = scored (+1);
        # the signed reward is the point of this env (soft_asymmetric).
        if self.ball_x >= W - BALL_W:
            self.enemy_score += 1
            self._point_over(serve_dir=1.0)  # loser receives the serve
            return -1.0
        if self.ball_x <= 0:
            self.player_score += 1
            self._point_over(serve_dir=-1.0)
            return 1.0
        return 0.0

    def _point_over(self, serve_dir: float) -> None:
        self._ball_dead = True
        self._serve_timer = SERVE_DELAY
        self._serve_dir = serve_dir

    def render(self) -> np.ndarray:
        f = np.empty((H, W, 3), np.uint8)
        f[:] = BACKGROUND
        # Bounds strips.
        f[FIELD_TOP - BOUND_H:FIELD_TOP, :] = BOUNDS
        f[FIELD_BOT:FIELD_BOT + BOUND_H, :] = BOUNDS
        # Score strip: blocky glyph regions (statistics, not digits — the
        # preprocessing crop removes rows [0, 34), `wrappers.py:63-74`).
        for b in range(min(10, self.enemy_score)):
            f[6:18, 16 + 4 * b:18 + 4 * b] = ENEMY
        for b in range(min(10, self.player_score)):
            f[6:18, 96 + 4 * b:98 + 4 * b] = PLAYER
        # Paddles.
        f[self.enemy_y:self.enemy_y + PADDLE_H, ENEMY_X:ENEMY_X + PADDLE_W] = ENEMY
        f[self.player_y:self.player_y + PADDLE_H,
          PLAYER_X:PLAYER_X + PADDLE_W] = PLAYER
        # Ball.
        if not self._ball_dead:
            y = int(np.clip(self.ball_y, FIELD_TOP, FIELD_BOT - BALL_H))
            x = int(np.clip(self.ball_x, 0, W - BALL_W))
            f[y:y + BALL_H, x:x + BALL_W] = BOUNDS
        return f


class PongSimRaw:
    """`RawFrameEnv`-protocol surface over `PongCore` (no gymnasium)."""

    def __init__(self, seed: int = 0, max_frames: int = 20_000, frameskip: int = 1):
        self._core = PongCore(seed=seed, max_frames=max_frames,
                              frameskip=frameskip)
        self.num_actions = PongCore.num_actions

    def reset(self) -> np.ndarray:
        return self._core.reset()

    def step(self, action: int):
        return self._core.step(int(action))

    def lives(self) -> int:
        return 0


_GYM_REGISTERED = False


def register_gymnasium() -> bool:
    """Register `PongSim-v0` with gymnasium (idempotent); mirrors
    `breakout_sim.register_gymnasium` so the same real-adapter path is
    under test."""
    global _GYM_REGISTERED
    try:
        import gymnasium
        from gymnasium import spaces
    except ImportError:
        return False
    if _GYM_REGISTERED:
        return True

    class _GymPongSim(gymnasium.Env):
        metadata = {"render_modes": []}

        def __init__(self, max_frames: int = 20_000, frameskip: int = 1):
            self._max_frames = max_frames
            self._frameskip = frameskip
            self._core: PongCore | None = None
            self.action_space = spaces.Discrete(PongCore.num_actions)
            self.observation_space = spaces.Box(0, 255, (H, W, 3), np.uint8)

        def reset(self, *, seed=None, options=None):
            super().reset(seed=seed)
            if self._core is None or seed is not None:
                self._core = PongCore(seed=seed or 0, max_frames=self._max_frames,
                                      frameskip=self._frameskip)
            obs = self._core.reset()
            return obs, {"lives": 0}

        def step(self, action):
            obs, reward, done, info = self._core.step(int(action))
            return obs, reward, done, False, info

    gymnasium.register(id="PongSim-v0", entry_point=lambda **kw: _GymPongSim(**kw))
    gymnasium.register(
        id="PongSimDeterministic-v0",
        entry_point=lambda **kw: _GymPongSim(**{"frameskip": 4, **kw}))
    _GYM_REGISTERED = True
    return True
