"""Environments and preprocessing (reference layer L2 env adapters)."""

from distributed_reinforcement_learning_tpu.envs.atari import (
    AtariPreprocessor,
    SyntheticAtari,
    area_resize,
    preprocess_frame,
)
from distributed_reinforcement_learning_tpu.envs.cartpole import (
    CartPoleEnv,
    VectorCartPole,
    pomdp_project,
)

__all__ = [
    "AtariPreprocessor",
    "SyntheticAtari",
    "area_resize",
    "preprocess_frame",
    "CartPoleEnv",
    "VectorCartPole",
    "pomdp_project",
]
