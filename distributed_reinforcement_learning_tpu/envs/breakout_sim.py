"""Breakout simulator: the closest honest ALE proxy this image allows.

`ale-py` is not installable here, so real-emulator frames are
unobtainable (VERDICT r2 gap #2). This module closes the gap the honest
way short of an emulator: a faithful Breakout implementation — real game
dynamics (paddle, ball physics, brick wall, lives, row-scored rewards)
rendered to genuine Atari specs — so the preprocessing pipeline and the
`GymnasiumRawFrames` adapter are validated on frames with REAL pixel
statistics (sparse sprites on a flat background, the 2600 palette, a
score strip that the reference's crop removes, `wrappers.py:63-74`)
instead of `np.roll` noise.

Fidelity targets (vs ALE Breakout):
- 210x160x3 uint8 frames; gray walls, black background, the six brick
  rows in the 2600 row colors; paddle/ball in the red sprite color.
- Minimal action set NOOP/FIRE/RIGHT/LEFT (ALE `Breakout-v*` = 4
  actions) so the reference's 18-way `action % available_action`
  aliasing (`train_impala.py:145`) is exercised for real.
- FIRE launches the ball (so the reference's fire-reset wrapper,
  `wrappers.py:7-24`, has a real effect), 5 lives with `info["lives"]`
  (life-loss shaping, `train_impala.py:149-154`), row scores 1/1/4/4/7/7.

Also registers itself with gymnasium (`BreakoutSim-v0`) so the
`GymnasiumRawFrames` adapter — the exact code path a real ALE install
would use — is what the registry and tests drive.
"""

from __future__ import annotations

from typing import Any

import numpy as np

# ALE Breakout palette (NTSC): row colors top->bottom, walls, sprites.
ROW_COLORS = (
    (200, 72, 72),    # red     (7 points)
    (198, 108, 58),   # orange  (7)
    (180, 122, 48),   # tan     (4)
    (162, 162, 42),   # yellow  (4)
    (72, 160, 72),    # green   (1)
    (66, 72, 200),    # blue    (1)
)
ROW_POINTS = (7, 7, 4, 4, 1, 1)
WALL = (142, 142, 142)
SPRITE = (200, 72, 72)

H, W = 210, 160
WALL_TOP = 32          # rows [WALL_TOP, WALL_TOP+4) are the top wall
WALL_SIDE = 8          # px of wall on each side
BRICK_TOP = 57         # first brick row's top scanline
BRICK_H = 6            # scanlines per brick row
BRICK_W = 8            # px per brick; (160 - 2*8)/8 = 18 bricks per row
PADDLE_Y = 189         # paddle top scanline
PADDLE_H = 4
PADDLE_W = 16
BALL_SIZE = 2

NOOP, FIRE, RIGHT, LEFT = 0, 1, 2, 3


class BreakoutCore:
    """Game state + renderer.

    `frameskip`: ALE's built-in action repeat — the action is applied for
    `frameskip` emulated frames, rewards sum, and the LAST frame is
    returned (exactly what a `*Deterministic-v4` registration does;
    `*NoFrameskip-v4` = 1). Serving a skip-1 game under a Deterministic
    name would make dynamics 4x slower per action than the real env this
    proxies, silently breaking configs the moment ale-py appears.
    """

    num_actions = 4

    def __init__(self, seed: int = 0, max_frames: int = 10_000, frameskip: int = 1):
        self._rng = np.random.RandomState(seed)
        self._max_frames = max_frames
        self.frameskip = max(1, frameskip)
        self._consume_reward = 0.0
        self.reset()

    def reset(self) -> np.ndarray:
        self.bricks = np.ones((6, 18), bool)
        self.lives = 5
        self.score = 0
        self.frames = 0
        self.paddle_x = (W - PADDLE_W) // 2
        self._ball_dead = True  # awaiting FIRE
        self.ball_x = 0.0
        self.ball_y = 0.0
        self.vx = 0.0
        self.vy = 0.0
        return self.render()

    def _launch(self) -> None:
        self.ball_x = float(self.paddle_x + PADDLE_W // 2)
        self.ball_y = float(PADDLE_Y - 8)
        self.vx = self._rng.choice([-2.0, -1.0, 1.0, 2.0])
        self.vy = -3.0
        self._ball_dead = False

    def step(self, action: int) -> tuple[np.ndarray, float, bool, dict[str, Any]]:
        if not 0 <= action < self.num_actions:
            # ALE raises on out-of-range actions; silently NOOP-ing here
            # would mask an action-space/config mismatch (e.g. an 18-way
            # head with available_action left at 18) that a real emulator
            # surfaces immediately.
            raise ValueError(
                f"action {action} outside Breakout's {self.num_actions}-action set "
                f"(alias the policy head with `action % available_action` first)")
        reward = 0.0
        done = False
        for _ in range(self.frameskip):  # action held for every skipped frame
            r, done = self._emulate_frame(action)
            reward += r
            if done:
                break
        return self.render(), reward, done, {"lives": self.lives}

    def _emulate_frame(self, action: int) -> tuple[float, bool]:
        """One emulated frame under a held action -> (reward, done)."""
        self.frames += 1
        reward = 0.0
        if action == RIGHT:
            self.paddle_x = min(W - WALL_SIDE - PADDLE_W, self.paddle_x + 4)
        elif action == LEFT:
            self.paddle_x = max(WALL_SIDE, self.paddle_x - 4)
        elif action == FIRE and self._ball_dead and self.lives > 0:
            self._launch()

        if not self._ball_dead:
            # Sub-stepping keeps the ball from tunnelling through a
            # 6-scanline brick row at 3+ px/frame.
            for _ in range(2):
                self.ball_x += self.vx / 2.0
                self.ball_y += self.vy / 2.0
                self._collide()
                reward += self._consume_reward
                self._consume_reward = 0.0
                if self._ball_dead:
                    break

        done = self.lives <= 0 or not self.bricks.any() or self.frames >= self._max_frames
        return reward, done

    def _collide(self) -> None:
        # Side walls.
        if self.ball_x <= WALL_SIDE:
            self.ball_x = float(WALL_SIDE)
            self.vx = abs(self.vx)
        elif self.ball_x >= W - WALL_SIDE - BALL_SIZE:
            self.ball_x = float(W - WALL_SIDE - BALL_SIZE)
            self.vx = -abs(self.vx)
        # Top wall.
        if self.ball_y <= WALL_TOP + 4:
            self.ball_y = float(WALL_TOP + 4)
            self.vy = abs(self.vy)
        # Bricks.
        row = int((self.ball_y - BRICK_TOP) // BRICK_H)
        if 0 <= row < 6:
            col = int((self.ball_x - WALL_SIDE) // BRICK_W)
            if 0 <= col < 18 and self.bricks[row, col]:
                self.bricks[row, col] = False
                self._consume_reward += float(ROW_POINTS[row])
                self.score += ROW_POINTS[row]
                self.vy = -self.vy
        # Paddle.
        if (self.vy > 0 and PADDLE_Y - BALL_SIZE <= self.ball_y <= PADDLE_Y + PADDLE_H
                and self.paddle_x - BALL_SIZE <= self.ball_x <= self.paddle_x + PADDLE_W):
            self.vy = -abs(self.vy)
            # Hit position steers the ball, like the real paddle.
            off = (self.ball_x + BALL_SIZE / 2 - self.paddle_x - PADDLE_W / 2) / (PADDLE_W / 2)
            self.vx = float(np.clip(self.vx + 2.0 * off, -3.0, 3.0))
            if abs(self.vx) < 0.5:
                self.vx = 0.5 if off >= 0 else -0.5
        # Bottom: life lost.
        if self.ball_y >= H - BALL_SIZE:
            self.lives -= 1
            self._ball_dead = True

    def render(self) -> np.ndarray:
        f = np.zeros((H, W, 3), np.uint8)
        # Walls.
        f[WALL_TOP:WALL_TOP + 4, :] = WALL
        f[WALL_TOP:, :WALL_SIDE] = WALL
        f[WALL_TOP:, W - WALL_SIDE:] = WALL
        # Score strip: blocky gray digits region (statistics, not glyphs —
        # the preprocessing crop removes it anyway, `wrappers.py:74`).
        score_blocks = min(12, self.score // 8)
        for b in range(score_blocks):
            f[6:18, 36 + 8 * b:42 + 8 * b] = WALL
        f[6:18, 16:22] = WALL  # lives indicator block
        # Bricks.
        for r in range(6):
            y = BRICK_TOP + r * BRICK_H
            cols = np.flatnonzero(self.bricks[r])
            for c in cols:
                x = WALL_SIDE + c * BRICK_W
                f[y:y + BRICK_H, x:x + BRICK_W] = ROW_COLORS[r]
        # Paddle.
        f[PADDLE_Y:PADDLE_Y + PADDLE_H, self.paddle_x:self.paddle_x + PADDLE_W] = SPRITE
        # Ball.
        if not self._ball_dead:
            y, x = int(self.ball_y), int(self.ball_x)
            f[y:y + BALL_SIZE, x:x + BALL_SIZE] = SPRITE
        return f


class BreakoutSimRaw:
    """`RawFrameEnv`-protocol surface over `BreakoutCore` (no gymnasium)."""

    def __init__(self, seed: int = 0, max_frames: int = 10_000, frameskip: int = 1):
        self._core = BreakoutCore(seed=seed, max_frames=max_frames,
                                  frameskip=frameskip)
        self.num_actions = BreakoutCore.num_actions

    def reset(self) -> np.ndarray:
        return self._core.reset()

    def step(self, action: int):
        return self._core.step(int(action))

    def lives(self) -> int:
        return self._core.lives


_GYM_REGISTERED = False


def register_gymnasium() -> bool:
    """Register `BreakoutSim-v0` with gymnasium (idempotent); returns
    whether the registration is usable. Routing the simulator through a
    real `gymnasium.make` means `GymnasiumRawFrames` — the exact adapter
    a real ALE install would use — is the code under test."""
    global _GYM_REGISTERED
    try:
        import gymnasium
        from gymnasium import spaces
    except ImportError:
        return False
    if _GYM_REGISTERED:
        return True

    class _GymBreakoutSim(gymnasium.Env):
        metadata = {"render_modes": []}

        def __init__(self, max_frames: int = 10_000, frameskip: int = 1):
            self._max_frames = max_frames
            self._frameskip = frameskip
            self._core: BreakoutCore | None = None
            self.action_space = spaces.Discrete(BreakoutCore.num_actions)
            self.observation_space = spaces.Box(0, 255, (H, W, 3), np.uint8)

        def reset(self, *, seed=None, options=None):
            super().reset(seed=seed)
            if self._core is None or seed is not None:
                self._core = BreakoutCore(seed=seed or 0, max_frames=self._max_frames,
                                          frameskip=self._frameskip)
            obs = self._core.reset()
            return obs, {"lives": self._core.lives}

        def step(self, action):
            obs, reward, done, info = self._core.step(int(action))
            return obs, reward, done, False, info

    # Mirror ALE's registrations: the Deterministic id bakes in the
    # emulator frameskip of 4, NoFrameskip/plain = 1.
    gymnasium.register(id="BreakoutSim-v0", entry_point=lambda **kw: _GymBreakoutSim(**kw))
    gymnasium.register(
        id="BreakoutSimDeterministic-v0",
        entry_point=lambda **kw: _GymBreakoutSim(**{"frameskip": 4, **kw}))
    _GYM_REGISTERED = True
    return True
