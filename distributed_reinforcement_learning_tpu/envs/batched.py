"""Batch N single envs behind the VectorEnv interface.

The reference runs one env per actor process and one `sess.run` per env
step (SURVEY §3.5). The TPU-first actor instead steps N envs and issues
ONE jitted act call per timestep; this wrapper provides that batching for
any single-env implementation (AtariPreprocessor, CartPoleEnv, custom).
Auto-resets on done and surfaces per-env episode returns and ALE-style
life counters for the life-loss shaping done in the actor loop
(`train_impala.py:149-154`).
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

from distributed_reinforcement_learning_tpu.envs.base import Env


def completed_returns(infos: dict, done: np.ndarray) -> np.ndarray:
    """Returns of the episodes that just finished, `[sum(done)]`.

    Shared by every actor runner: tolerates envs whose infos carry no
    `episode_return` (a bare list default would raise TypeError when
    indexed with the boolean done mask).
    """
    rets = infos.get("episode_return")
    if rets is None:
        return np.zeros(0)  # no known returns — do not fabricate 0.0 entries
    return np.asarray(rets)[done]


class BatchedEnv:
    def __init__(self, env_fns: Sequence[Callable[[], Env]]):
        self.envs = [fn() for fn in env_fns]
        self.num_envs = len(self.envs)
        self.num_actions = self.envs[0].num_actions
        self._returns = np.zeros(self.num_envs, np.float64)
        self._lengths = np.zeros(self.num_envs, np.int64)

    def reset(self) -> np.ndarray:
        self._returns[:] = 0
        self._lengths[:] = 0
        return np.stack([env.reset() for env in self.envs])

    def step(self, actions: np.ndarray):
        obs_list, rewards, dones, lives, truncs = [], [], [], [], []
        episode_returns = np.zeros(self.num_envs, np.float64)
        episode_lengths = np.zeros(self.num_envs, np.int64)
        for i, env in enumerate(self.envs):
            obs, r, done, info = env.step(int(actions[i]))
            self._returns[i] += r
            self._lengths[i] += 1
            if done:
                episode_returns[i] = self._returns[i]
                episode_lengths[i] = self._lengths[i]
                self._returns[i] = 0
                self._lengths[i] = 0
                obs = env.reset()
            obs_list.append(obs)
            rewards.append(r)
            dones.append(done)
            truncs.append(bool(info.get("truncated", False)))
            lives.append(info.get("lives", -1))
        infos = {
            "episode_return": episode_returns,
            "episode_length": episode_lengths,
            "lives": np.asarray(lives),
            "truncated": np.asarray(truncs, bool),
        }
        return (
            np.stack(obs_list),
            np.asarray(rewards, np.float32),
            np.asarray(dones, bool),
            infos,
        )
