"""Pong as pure-JAX functions: the second on-device (Anakin) pixel env.

Same game as `envs.pong_sim.PongCore` (the faithful ALE-spec proxy),
re-expressed as jittable batched pure functions following the
`cartpole_jax`/`breakout_jax` env contract, so Anakin IMPALA can train
both in-tree pixel games at chip rate. What Pong exercises that
Breakout cannot (see pong_sim's module docstring): the 6-action set,
SIGNED rewards, serve timers, an opponent AI, and no lives — `done`
here is always a true game end, so `completed_episode_mask` is the
identity.

Dynamics parity: constants and update order mirror `pong_sim.py` line
for line (2px/frame paddle, serve-timer auto-serve, capped-speed
tracking AI with dead zone, 2 collision substeps, hit-offset
deflection + rally speed-up, first to 21). Divergences match
`breakout_jax`'s documented set: float32 physics, `jax.random` streams
for the serve draws, and the score strip unrendered (the crop removes
scanlines < ~34; the bound strips ARE rendered — row 194 reaches the
last output row of the resize).

The observation pipeline is shared with `breakout_jax._preprocess`
(2-frame max -> luma -> INTER_AREA resize matmuls -> crop -> uint8 ->
4-stack), i.e. `envs.atari.AtariPreprocessor` stage for stage.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_reinforcement_learning_tpu.envs import pixel_jax
from distributed_reinforcement_learning_tpu.envs import pong_sim as sim
from distributed_reinforcement_learning_tpu.envs.pixel_jax import preprocess as _preprocess

NUM_ACTIONS = sim.PongCore.num_actions  # NOOP/FIRE/RIGHT/LEFT/RIGHTFIRE/LEFTFIRE
OBS_SHAPE = (84, 84, 4)

H, W = sim.H, sim.W

# Static base frame: background + the two bound strips (the score strip
# region stays background — it is cropped out of every observation).
_BASE = np.empty((H, W, 3), np.uint8)
_BASE[:] = sim.BACKGROUND
_BASE[sim.FIELD_TOP - sim.BOUND_H:sim.FIELD_TOP, :] = sim.BOUNDS
_BASE[sim.FIELD_BOT:sim.FIELD_BOT + sim.BOUND_H, :] = sim.BOUNDS

_YS = np.arange(H)[:, None]  # [210, 1]
_XS = np.arange(W)[None, :]  # [1, 160]


class PongState(NamedTuple):
    """Batched game + observation-pipeline state (`[N, ...]` leaves)."""

    player_score: jax.Array  # [N] i32
    enemy_score: jax.Array   # [N] i32
    frames: jax.Array        # [N] i32 emulated frames this episode
    player_y: jax.Array      # [N] i32 (agent paddle, right side)
    enemy_y: jax.Array       # [N] i32 (AI paddle, left side)
    ball_dead: jax.Array     # [N] bool — between points
    serve_timer: jax.Array   # [N] i32 frames until auto-serve
    serve_dir: jax.Array     # [N] f32 (+1 toward the agent)
    rally: jax.Array         # [N] i32 hits this rally (speed-up)
    ball_x: jax.Array        # [N] f32
    ball_y: jax.Array        # [N] f32
    vx: jax.Array            # [N] f32
    vy: jax.Array            # [N] f32
    prev_raw: jax.Array      # [N, 210, 160, 3] u8
    stack: jax.Array         # [N, 84, 84, 4] u8
    returns: jax.Array       # [N] f32 signed episode return


# -- rendering (single env; vmapped) ----------------------------------------


def _render(player_y, enemy_y, ball_dead, ball_x, ball_y) -> jax.Array:
    """`[210, 160, 3]` uint8 frame, `pong_sim.render` draw order."""
    f = jnp.asarray(_BASE)
    ys, xs = jnp.asarray(_YS), jnp.asarray(_XS)
    enemy = (
        (ys >= enemy_y) & (ys < enemy_y + sim.PADDLE_H)
        & (xs >= sim.ENEMY_X) & (xs < sim.ENEMY_X + sim.PADDLE_W)
    )
    f = jnp.where(enemy[:, :, None], jnp.asarray(np.asarray(sim.ENEMY, np.uint8)), f)
    player = (
        (ys >= player_y) & (ys < player_y + sim.PADDLE_H)
        & (xs >= sim.PLAYER_X) & (xs < sim.PLAYER_X + sim.PADDLE_W)
    )
    f = jnp.where(player[:, :, None], jnp.asarray(np.asarray(sim.PLAYER, np.uint8)), f)
    by = jnp.clip(ball_y, sim.FIELD_TOP, sim.FIELD_BOT - sim.BALL_H).astype(jnp.int32)
    bx = jnp.clip(ball_x, 0, W - sim.BALL_W).astype(jnp.int32)
    ball = (
        (~ball_dead)
        & (ys >= by) & (ys < by + sim.BALL_H)
        & (xs >= bx) & (xs < bx + sim.BALL_W)
    )
    return jnp.where(ball[:, :, None], jnp.asarray(np.asarray(sim.BOUNDS, np.uint8)), f)


# -- physics (single env; vmapped) ------------------------------------------


def _deflect(vy, vx, rally, ball_y, paddle_y):
    """Hit-offset steering + rally speed-up (`pong_sim._deflect`)."""
    off = (ball_y + sim.BALL_H / 2 - paddle_y - sim.PADDLE_H / 2) / (sim.PADDLE_H / 2)
    vy = jnp.clip(vy + 1.5 * off, -3.0, 3.0)
    rally = rally + 1
    speed = jnp.minimum(2.0 + 0.25 * rally.astype(jnp.float32), 3.5)
    vx = jnp.where(vx < 0, speed, -speed)  # reverse + speed-up
    return vy, vx, rally


def _collide(player_y, enemy_y, x, y, vx, vy, rally, dead,
             player_score, enemy_score, serve_timer, serve_dir, reward):
    """One `pong_sim._collide` pass; returns updated running values."""
    # Top/bottom bounds.
    vy = jnp.where(y <= sim.FIELD_TOP, jnp.abs(vy), vy)
    vy = jnp.where(y >= sim.FIELD_BOT - sim.BALL_H, -jnp.abs(vy), vy)
    y = jnp.clip(y, sim.FIELD_TOP, sim.FIELD_BOT - sim.BALL_H)
    # Agent paddle (right): only when moving toward it.
    pyf = player_y.astype(jnp.float32)
    hit_p = (
        (vx > 0) & ~dead
        & (x >= sim.PLAYER_X - sim.BALL_W) & (x <= sim.PLAYER_X + sim.PADDLE_W)
        & (y >= pyf - sim.BALL_H) & (y <= pyf + sim.PADDLE_H)
    )
    x = jnp.where(hit_p, jnp.float32(sim.PLAYER_X - sim.BALL_W), x)
    dvy, dvx, drally = _deflect(vy, vx, rally, y, pyf)
    vy = jnp.where(hit_p, dvy, vy)
    vx = jnp.where(hit_p, dvx, vx)
    rally = jnp.where(hit_p, drally, rally)
    # Enemy paddle (left).
    eyf = enemy_y.astype(jnp.float32)
    hit_e = (
        (vx < 0) & ~dead
        & (x >= sim.ENEMY_X - sim.BALL_W) & (x <= sim.ENEMY_X + sim.PADDLE_W)
        & (y >= eyf - sim.BALL_H) & (y <= eyf + sim.PADDLE_H)
    )
    x = jnp.where(hit_e, jnp.float32(sim.ENEMY_X + sim.PADDLE_W), x)
    dvy, dvx, drally = _deflect(vy, vx, rally, y, eyf)
    vy = jnp.where(hit_e, dvy, vy)
    vx = jnp.where(hit_e, dvx, vx)
    rally = jnp.where(hit_e, drally, rally)
    # Scoring: the agent owns the right side.
    scored_on = (x >= W - sim.BALL_W) & ~dead
    scored = (x <= 0) & ~dead & ~scored_on
    enemy_score = enemy_score + scored_on.astype(jnp.int32)
    player_score = player_score + scored.astype(jnp.int32)
    point = scored_on | scored
    dead = dead | point
    serve_timer = jnp.where(point, sim.SERVE_DELAY, serve_timer)
    serve_dir = jnp.where(scored_on, 1.0, jnp.where(scored, -1.0, serve_dir))
    reward = reward - scored_on.astype(jnp.float32) + scored.astype(jnp.float32)
    return (player_y, enemy_y, x, y, vx, vy, rally, dead,
            player_score, enemy_score, serve_timer, serve_dir, reward)


def _emulate_frame(carry, action, serve_y, serve_vy, max_frames):
    """One emulated frame under a held action (`_emulate_frame` parity)."""
    (player_score, enemy_score, frames, player_y, enemy_y, dead, serve_timer,
     serve_dir, rally, x, y, vx, vy, reward, halted) = carry
    live = ~halted
    frames = frames + live.astype(jnp.int32)

    up = (action == sim.RIGHT) | (action == sim.RIGHTFIRE)
    down = (action == sim.LEFT) | (action == sim.LEFTFIRE)
    fire = (action == sim.FIRE) | (action == sim.RIGHTFIRE) | (action == sim.LEFTFIRE)
    player_y = jnp.where(live & up,
                         jnp.maximum(sim.FIELD_TOP, player_y - 2), player_y)
    player_y = jnp.where(live & down,
                         jnp.minimum(sim.FIELD_BOT - sim.PADDLE_H, player_y + 2),
                         player_y)

    # Serve: FIRE serves immediately; the timer auto-serves otherwise.
    serve_timer = serve_timer - (live & dead).astype(jnp.int32)
    serving = live & dead & (fire | (serve_timer <= 0))
    x = jnp.where(serving, jnp.float32(W // 2), x)
    y = jnp.where(serving, serve_y, y)
    vx = jnp.where(serving, 2.0 * serve_dir, vx)
    vy = jnp.where(serving, serve_vy, vy)
    rally = jnp.where(serving, 0, rally)
    dead = dead & ~serving

    # Computer paddle: capped-speed ball tracking with a dead zone.
    track = live & ~dead & (vx < 0)
    target = y + sim.BALL_H / 2 - sim.PADDLE_H / 2
    diff = target - enemy_y.astype(jnp.float32)
    step_px = jnp.clip(diff, -2.0, 2.0).astype(jnp.int32)
    enemy_y = jnp.where(track & (jnp.abs(diff) > 3), enemy_y + step_px, enemy_y)
    enemy_y = jnp.clip(enemy_y, sim.FIELD_TOP, sim.FIELD_BOT - sim.PADDLE_H)

    # Two collision substeps (anti-tunnelling, `pong_sim.py:150-158`).
    for _ in range(2):
        moving = live & ~dead
        x = x + jnp.where(moving, vx / 2.0, 0.0)
        y = y + jnp.where(moving, vy / 2.0, 0.0)
        new = _collide(player_y, enemy_y, x, y, vx, vy, rally, dead,
                       player_score, enemy_score, serve_timer, serve_dir,
                       reward)
        (_, _, x2, y2, vx2, vy2, rally2, dead2,
         ps2, es2, st2, sd2, reward2) = new
        x = jnp.where(moving, x2, x)
        y = jnp.where(moving, y2, y)
        vx = jnp.where(moving, vx2, vx)
        vy = jnp.where(moving, vy2, vy)
        rally = jnp.where(moving, rally2, rally)
        dead = jnp.where(moving, dead2, dead)
        player_score = jnp.where(moving, ps2, player_score)
        enemy_score = jnp.where(moving, es2, enemy_score)
        serve_timer = jnp.where(moving, st2, serve_timer)
        serve_dir = jnp.where(moving, sd2, serve_dir)
        reward = jnp.where(moving, reward2, reward)

    game_over = ((player_score >= sim.WIN_SCORE)
                 | (enemy_score >= sim.WIN_SCORE)
                 | (frames >= max_frames))
    halted = halted | (live & game_over)
    return (player_score, enemy_score, frames, player_y, enemy_y, dead,
            serve_timer, serve_dir, rally, x, y, vx, vy, reward, halted)


# -- public API (cartpole_jax contract) -------------------------------------


def _reset_fields(n: int):
    mid = (sim.FIELD_TOP + sim.FIELD_BOT - sim.PADDLE_H) // 2
    return dict(
        player_score=jnp.zeros((n,), jnp.int32),
        enemy_score=jnp.zeros((n,), jnp.int32),
        frames=jnp.zeros((n,), jnp.int32),
        player_y=jnp.full((n,), mid, jnp.int32),
        enemy_y=jnp.full((n,), mid, jnp.int32),
        ball_dead=jnp.ones((n,), bool),
        serve_timer=jnp.full((n,), sim.SERVE_DELAY, jnp.int32),
        serve_dir=jnp.ones((n,), jnp.float32),  # toward the agent first
        rally=jnp.zeros((n,), jnp.int32),
        ball_x=jnp.zeros((n,), jnp.float32),
        ball_y=jnp.zeros((n,), jnp.float32),
        vx=jnp.zeros((n,), jnp.float32),
        vy=jnp.zeros((n,), jnp.float32),
        returns=jnp.zeros((n,), jnp.float32),
    )


def reset(rng: jax.Array, num_envs: int) -> tuple[PongState, jax.Array]:
    """-> (state, obs `[N, 84, 84, 4]` u8). Deterministic (paddles
    centered, serve pending); `rng` kept for the env contract."""
    del rng
    f = _reset_fields(num_envs)
    raw = jax.vmap(_render)(
        f["player_y"], f["enemy_y"], f["ball_dead"], f["ball_x"], f["ball_y"])
    state = PongState(prev_raw=raw, stack=pixel_jax.reset_stack(raw), **f)
    return state, state.stack


@functools.partial(jax.jit, static_argnames=("frameskip", "max_frames"))
def step(
    state: PongState,
    actions: jax.Array,
    rng: jax.Array,
    frameskip: int = 4,
    max_frames: int = 20_000,
) -> tuple[PongState, jax.Array, jax.Array, jax.Array, jax.Array]:
    """-> (state', obs', reward, done, episode_return).

    Contract matches `cartpole_jax.step`; every `done` is a true game
    end (first to 21 or the frame cap), with the fresh-game observation
    in the done slots' `obs'`.
    """
    n = state.frames.shape[0]
    k_y, k_vy = jax.random.split(rng)
    serve_y = jax.random.randint(
        k_y, (frameskip, n), sim.FIELD_TOP + 20, sim.FIELD_BOT - 20
    ).astype(jnp.float32)
    serve_vy = jnp.asarray([-1.0, -0.5, 0.5, 1.0], jnp.float32)[
        jax.random.randint(k_vy, (frameskip, n), 0, 4)]

    carry = (state.player_score, state.enemy_score, state.frames,
             state.player_y, state.enemy_y, state.ball_dead,
             state.serve_timer, state.serve_dir, state.rally,
             state.ball_x, state.ball_y, state.vx, state.vy,
             jnp.zeros((n,), jnp.float32), jnp.zeros((n,), bool))
    actions = actions.astype(jnp.int32)
    emulate = jax.vmap(_emulate_frame, in_axes=(0, 0, 0, 0, None))
    for i in range(frameskip):  # static unroll: action held, break-on-done
        carry = emulate(carry, actions, serve_y[i], serve_vy[i], max_frames)
    (player_score, enemy_score, frames, player_y, enemy_y, ball_dead,
     serve_timer, serve_dir, rally, ball_x, ball_y, vx, vy, reward,
     game_over) = carry

    raw = jax.vmap(_render)(player_y, enemy_y, ball_dead, ball_x, ball_y)
    stack = pixel_jax.observe(raw, state.prev_raw, state.stack)

    returns = state.returns + reward
    episode_return = jnp.where(game_over, returns, 0.0)

    fresh = _reset_fields(n)
    raw0 = jax.vmap(_render)(
        fresh["player_y"], fresh["enemy_y"], fresh["ball_dead"],
        fresh["ball_x"], fresh["ball_y"])
    stack0 = pixel_jax.reset_stack(raw0)

    pick = pixel_jax.make_pick(game_over)
    new_state = PongState(
        player_score=pick(fresh["player_score"], player_score),
        enemy_score=pick(fresh["enemy_score"], enemy_score),
        frames=pick(fresh["frames"], frames),
        player_y=pick(fresh["player_y"], player_y),
        enemy_y=pick(fresh["enemy_y"], enemy_y),
        ball_dead=pick(fresh["ball_dead"], ball_dead),
        serve_timer=pick(fresh["serve_timer"], serve_timer),
        serve_dir=pick(fresh["serve_dir"], serve_dir),
        rally=pick(fresh["rally"], rally),
        ball_x=pick(fresh["ball_x"], ball_x),
        ball_y=pick(fresh["ball_y"], ball_y),
        vx=pick(fresh["vx"], vx),
        vy=pick(fresh["vy"], vy),
        prev_raw=pick(raw0, raw),
        stack=pick(stack0, stack),
        returns=pick(fresh["returns"], returns),
    )
    return new_state, new_state.stack, reward, game_over, episode_return


def completed_episode_mask(done: jax.Array, new_state: PongState) -> jax.Array:
    """Pong has no lives: every `done` is a finished game."""
    del new_state
    return done
