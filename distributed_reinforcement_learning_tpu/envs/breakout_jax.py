"""Breakout as pure-JAX functions: the on-device (Anakin) pixel env.

Same game as `envs.breakout_sim.BreakoutCore` (the faithful ALE-spec
proxy — see its fidelity notes), re-expressed as jittable pure functions
over a batch of N games so whole collect+learn loops run inside one
compiled TPU program (the Podracer "Anakin" pattern, arXiv:2104.06272).
This is the configuration that makes a DECISIVE Breakout score reachable
in this image: the host loop tops out at a few hundred frames/s on the
single CPU core (`benchmarks/longrun/ANALYSIS.md`), while this path
collects and learns at chip rate.

Dynamics parity: constants and update order are imported from / mirror
`breakout_sim.py` line for line (paddle ±4/frame, 2 collision substeps,
hit-position steering, row-scored bricks, 5 lives, frameskip held
action). Divergences, all deliberate and documented:

- float32 instead of Python float64 physics (TPU-native; positions are
  halves so most arithmetic is exact anyway);
- the launch velocity draw uses `jax.random` instead of
  `np.random.RandomState` — same support {-2,-1,1,2}, different stream;
- the score strip and lives indicator are NOT rendered: the reference
  crop (`wrappers.py:74`, rows 18:102 of the 110-row resize = source
  scanlines ~34..195) removes scanlines 0..34 entirely, so those pixels
  can never reach an observation;
- no fire-reset wrapper: the 4-action set includes FIRE and the policy
  learns to serve (standard for vectorized ALE training loops); a lost
  life is surfaced as `done` to the learner (the reference's life-loss
  shaping, `train_impala.py:149-154`) while the game only restarts on
  a true game-over, exactly the EpisodicLife semantics the reference's
  shaping approximates.

The observation pipeline runs on-device and matches
`envs.atari.AtariPreprocessor` stage for stage: 2-frame max over
consecutive post-frameskip raw frames -> luma -> INTER_AREA resize to
110x84 (the separable overlap weights of `atari.area_resize`, folded to
an 84x210 matrix by pre-cropping the row weights) -> [84, 84] uint8 ->
4-frame newest-last stack. The resize is two small matmuls per frame —
MXU work, which is the point of doing it on-device.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_reinforcement_learning_tpu.envs import breakout_sim as sim
from distributed_reinforcement_learning_tpu.envs import pixel_jax
from distributed_reinforcement_learning_tpu.envs.pixel_jax import preprocess as _preprocess

NUM_ACTIONS = sim.BreakoutCore.num_actions  # NOOP / FIRE / RIGHT / LEFT
OBS_SHAPE = (84, 84, 4)

H, W = sim.H, sim.W
_BALL = sim.BALL_SIZE

# -- static render tables ---------------------------------------------------

_YS = np.arange(H)[:, None]  # [210, 1]
_XS = np.arange(W)[None, :]  # [1, 160]

# Walls (drawn below everything else, exactly breakout_sim.render's order).
_BASE = np.zeros((H, W, 3), np.uint8)
_BASE[sim.WALL_TOP:sim.WALL_TOP + 4, :] = sim.WALL
_BASE[sim.WALL_TOP:, :sim.WALL_SIDE] = sim.WALL
_BASE[sim.WALL_TOP:, W - sim.WALL_SIDE:] = sim.WALL

# Per-pixel brick coordinates: which (row, col) a pixel belongs to, and
# whether it is inside the brick field at all.
_ROW_IDX = np.clip((_YS - sim.BRICK_TOP) // sim.BRICK_H, 0, 5)  # [210, 1]
_COL_IDX = np.clip((_XS - sim.WALL_SIDE) // sim.BRICK_W, 0, 17)  # [1, 160]
_IN_FIELD = (
    (_YS >= sim.BRICK_TOP) & (_YS < sim.BRICK_TOP + 6 * sim.BRICK_H)
    & (_XS >= sim.WALL_SIDE) & (_XS < sim.WALL_SIDE + 18 * sim.BRICK_W)
)  # [210, 160]
_ROW_RGB = np.asarray(sim.ROW_COLORS, np.uint8)  # [6, 3]
_SPRITE = np.asarray(sim.SPRITE, np.uint8)
_ROW_POINTS = np.asarray(sim.ROW_POINTS, np.float32)

class BreakoutState(NamedTuple):
    """Batched game + observation-pipeline state (`[N, ...]` leaves)."""

    bricks: jax.Array      # [N, 6, 18] bool
    lives: jax.Array       # [N] i32
    frames: jax.Array      # [N] i32 emulated frames this episode
    paddle_x: jax.Array    # [N] f32 (integer-valued)
    ball_dead: jax.Array   # [N] bool — awaiting FIRE
    ball_x: jax.Array      # [N] f32
    ball_y: jax.Array      # [N] f32
    vx: jax.Array          # [N] f32
    vy: jax.Array          # [N] f32
    prev_raw: jax.Array    # [N, 210, 160, 3] u8 — last adapter-step frame
    stack: jax.Array       # [N, 84, 84, 4] u8 — current observation
    returns: jax.Array     # [N] f32 raw (unclipped) episode return


# -- rendering + preprocessing (single env; vmapped) ------------------------


def _render(bricks, paddle_x, ball_dead, ball_x, ball_y) -> jax.Array:
    """`[210, 160, 3]` uint8 frame, `breakout_sim.render` draw order."""
    f = jnp.asarray(_BASE)
    alive = bricks[jnp.asarray(_ROW_IDX[:, 0])][:, jnp.asarray(_COL_IDX[0, :])]
    brick_mask = alive & jnp.asarray(_IN_FIELD)
    row_colors = jnp.asarray(_ROW_RGB)[jnp.asarray(_ROW_IDX[:, 0])]  # [210, 3]
    f = jnp.where(brick_mask[:, :, None], row_colors[:, None, :], f)

    px = paddle_x.astype(jnp.int32)
    ys, xs = jnp.asarray(_YS), jnp.asarray(_XS)
    paddle = (
        (ys >= sim.PADDLE_Y) & (ys < sim.PADDLE_Y + sim.PADDLE_H)
        & (xs >= px) & (xs < px + sim.PADDLE_W)
    )
    f = jnp.where(paddle[:, :, None], jnp.asarray(_SPRITE), f)

    by = ball_y.astype(jnp.int32)
    bx = ball_x.astype(jnp.int32)
    ball = (
        (~ball_dead)
        & (ys >= by) & (ys < by + _BALL)
        & (xs >= bx) & (xs < bx + _BALL)
    )
    return jnp.where(ball[:, :, None], jnp.asarray(_SPRITE), f)


# -- physics (single env; vmapped) ------------------------------------------


def _collide(bricks, paddle_x, lives, x, y, vx, vy, dead, reward):
    """One `breakout_sim._collide` pass; returns updated running values."""
    # Side walls.
    x = jnp.clip(x, sim.WALL_SIDE, W - sim.WALL_SIDE - _BALL)
    vx = jnp.where(x <= sim.WALL_SIDE, jnp.abs(vx), vx)
    vx = jnp.where(x >= W - sim.WALL_SIDE - _BALL, -jnp.abs(vx), vx)
    # Top wall.
    vy = jnp.where(y <= sim.WALL_TOP + 4, jnp.abs(vy), vy)
    y = jnp.maximum(y, jnp.float32(sim.WALL_TOP + 4))
    # Bricks (the moving ball can hit at most one per substep).
    row = jnp.floor((y - sim.BRICK_TOP) / sim.BRICK_H).astype(jnp.int32)
    col = jnp.floor((x - sim.WALL_SIDE) / sim.BRICK_W).astype(jnp.int32)
    rc = jnp.clip(row, 0, 5)
    cc = jnp.clip(col, 0, 17)
    hit = (
        (row >= 0) & (row < 6) & (col >= 0) & (col < 18)
        & bricks[rc, cc] & ~dead
    )
    knock = hit & (jnp.arange(6)[:, None] == rc) & (jnp.arange(18)[None, :] == cc)
    bricks = bricks & ~knock
    reward = reward + jnp.where(hit, jnp.asarray(_ROW_POINTS)[rc], 0.0)
    vy = jnp.where(hit, -vy, vy)
    # Paddle (hit position steers, exactly the sim's formula).
    on_paddle = (
        (vy > 0)
        & (y >= sim.PADDLE_Y - _BALL) & (y <= sim.PADDLE_Y + sim.PADDLE_H)
        & (x >= paddle_x - _BALL) & (x <= paddle_x + sim.PADDLE_W)
        & ~dead
    )
    off = (x + _BALL / 2 - paddle_x - sim.PADDLE_W / 2) / (sim.PADDLE_W / 2)
    steered = jnp.clip(vx + 2.0 * off, -3.0, 3.0)
    steered = jnp.where(
        jnp.abs(steered) < 0.5, jnp.where(off >= 0, 0.5, -0.5), steered)
    vx = jnp.where(on_paddle, steered, vx)
    vy = jnp.where(on_paddle, -jnp.abs(vy), vy)
    # Bottom: life lost.
    lost = (y >= H - _BALL) & ~dead
    lives = lives - lost.astype(jnp.int32)
    dead = dead | lost
    return bricks, lives, x, y, vx, vy, dead, reward


def _emulate_frame(carry, action, launch_vx, max_frames):
    """One emulated frame under a held action (`_emulate_frame` parity).

    `carry` holds the running per-env scalars plus `halted` — set once
    the episode ended mid-frameskip, freezing the remaining frames the
    way the numpy loop's `break` does.
    """
    (bricks, lives, frames, paddle_x, dead, x, y, vx, vy, reward,
     halted) = carry
    live = ~halted
    frames = frames + live.astype(jnp.int32)

    paddle_x = jnp.where(
        live & (action == sim.RIGHT),
        jnp.minimum(jnp.float32(W - sim.WALL_SIDE - sim.PADDLE_W), paddle_x + 4),
        paddle_x)
    paddle_x = jnp.where(
        live & (action == sim.LEFT),
        jnp.maximum(jnp.float32(sim.WALL_SIDE), paddle_x - 4),
        paddle_x)
    fire = live & (action == sim.FIRE) & dead & (lives > 0)
    x = jnp.where(fire, paddle_x + sim.PADDLE_W // 2, x)
    y = jnp.where(fire, jnp.float32(sim.PADDLE_Y - 8), y)
    vx = jnp.where(fire, launch_vx, vx)
    vy = jnp.where(fire, jnp.float32(-3.0), vy)
    dead = dead & ~fire

    # Two collision substeps (anti-tunnelling, `breakout_sim.py:130-140`).
    for _ in range(2):
        moving = live & ~dead
        x = x + jnp.where(moving, vx / 2.0, 0.0)
        y = y + jnp.where(moving, vy / 2.0, 0.0)
        bricks2, lives2, x2, y2, vx2, vy2, dead2, reward2 = _collide(
            bricks, paddle_x, lives, x, y, vx, vy, dead, reward)
        keep = moving  # scalar under vmap: broadcasts over every shape
        bricks = jnp.where(keep, bricks2, bricks)
        lives = jnp.where(keep, lives2, lives)
        x = jnp.where(keep, x2, x)
        y = jnp.where(keep, y2, y)
        vx = jnp.where(keep, vx2, vx)
        vy = jnp.where(keep, vy2, vy)
        dead = jnp.where(keep, dead2, dead)
        reward = jnp.where(keep, reward2, reward)

    game_over = (lives <= 0) | ~bricks.any() | (frames >= max_frames)
    halted = halted | (live & game_over)
    return (bricks, lives, frames, paddle_x, dead, x, y, vx, vy, reward,
            halted)


# -- public API (cartpole_jax contract) -------------------------------------


def _reset_fields(n: int):
    return dict(
        bricks=jnp.ones((n, 6, 18), bool),
        lives=jnp.full((n,), 5, jnp.int32),
        frames=jnp.zeros((n,), jnp.int32),
        paddle_x=jnp.full((n,), float((W - sim.PADDLE_W) // 2), jnp.float32),
        ball_dead=jnp.ones((n,), bool),
        ball_x=jnp.zeros((n,), jnp.float32),
        ball_y=jnp.zeros((n,), jnp.float32),
        vx=jnp.zeros((n,), jnp.float32),
        vy=jnp.zeros((n,), jnp.float32),
        returns=jnp.zeros((n,), jnp.float32),
    )


def reset(rng: jax.Array, num_envs: int) -> tuple[BreakoutState, jax.Array]:
    """-> (state, obs `[N, 84, 84, 4]` u8). `rng` unused (reset is
    deterministic: centered paddle, dead ball awaiting FIRE), kept for
    the cartpole_jax signature."""
    del rng
    f = _reset_fields(num_envs)
    raw = jax.vmap(_render)(
        f["bricks"], f["paddle_x"], f["ball_dead"], f["ball_x"], f["ball_y"])
    state = BreakoutState(prev_raw=raw, stack=pixel_jax.reset_stack(raw), **f)
    return state, state.stack


@functools.partial(jax.jit, static_argnames=("frameskip", "max_frames",
                                             "life_loss"))
def step(
    state: BreakoutState,
    actions: jax.Array,
    rng: jax.Array,
    frameskip: int = 4,
    max_frames: int = 10_000,
    life_loss: bool = True,
) -> tuple[BreakoutState, jax.Array, jax.Array, jax.Array, jax.Array]:
    """-> (state', obs', reward, done, episode_return).

    Contract matches `cartpole_jax.step`: `obs'` holds the RESET
    observation for game-over slots, `episode_return` is the completed
    raw return where the game ended else 0. `done` is the TRAINING
    signal: game-over or (with `life_loss`) a lost life — the
    reference's shaping (`train_impala.py:149-154`).
    """
    n = state.lives.shape[0]
    lives_before = state.lives
    # One launch-velocity draw per emulated frame, like the sim's
    # per-launch `choice` — only consumed by a FIRE on a dead ball.
    draws = jax.random.randint(rng, (frameskip, n), 0, 4)
    launch_vx = jnp.asarray([-2.0, -1.0, 1.0, 2.0], jnp.float32)[draws]

    carry = (state.bricks, state.lives, state.frames, state.paddle_x,
             state.ball_dead, state.ball_x, state.ball_y, state.vx, state.vy,
             jnp.zeros((n,), jnp.float32), jnp.zeros((n,), bool))
    actions = actions.astype(jnp.int32)
    emulate = jax.vmap(_emulate_frame, in_axes=(0, 0, 0, None))
    for i in range(frameskip):  # static unroll: action held, break-on-done
        carry = emulate(carry, actions, launch_vx[i], max_frames)
    (bricks, lives, frames, paddle_x, ball_dead, ball_x, ball_y, vx, vy,
     reward, game_over) = carry

    raw = jax.vmap(_render)(bricks, paddle_x, ball_dead, ball_x, ball_y)
    stack = pixel_jax.observe(raw, state.prev_raw, state.stack)

    returns = state.returns + reward
    episode_return = jnp.where(game_over, returns, 0.0)
    lost_life = lives < lives_before
    done = (game_over | lost_life) if life_loss else game_over
    if life_loss:
        # The reference's life-loss shaping REPLACES the step reward with
        # -1 on a lost life (`train_impala.py:149-154`). On the TERMINAL
        # life the reference still records -1 (it keys on any lives
        # change); here true game-overs keep the raw reward instead —
        # a deliberate deviation matching this repo's host path
        # (`runtime/impala_runner.py` `lost = ... & ~done`), so host and
        # on-device runners see identical shaping rather than exact
        # reference semantics on the final step. Omitting the -1 entirely
        # (pre-r4s3 versions of this env) makes ball loss nearly costless
        # to the learner — the core keep-the-rally-alive incentive
        # disappears. `returns` above is accumulated from the RAW reward,
        # so episode_return stays the true game score.
        reward = jnp.where(lost_life & ~game_over, -1.0, reward)

    # Auto-reset game-over slots (fresh board; obs = reset observation).
    fresh = _reset_fields(n)
    raw0 = jax.vmap(_render)(
        fresh["bricks"], fresh["paddle_x"], fresh["ball_dead"],
        fresh["ball_x"], fresh["ball_y"])
    stack0 = pixel_jax.reset_stack(raw0)

    pick = pixel_jax.make_pick(game_over)
    new_state = BreakoutState(
        bricks=pick(fresh["bricks"], bricks),
        lives=pick(fresh["lives"], lives),
        frames=pick(fresh["frames"], frames),
        paddle_x=pick(fresh["paddle_x"], paddle_x),
        ball_dead=pick(fresh["ball_dead"], ball_dead),
        ball_x=pick(fresh["ball_x"], ball_x),
        ball_y=pick(fresh["ball_y"], ball_y),
        vx=pick(fresh["vx"], vx),
        vy=pick(fresh["vy"], vy),
        prev_raw=pick(raw0, raw),
        stack=pick(stack0, stack),
        returns=pick(fresh["returns"], returns),
    )
    return new_state, new_state.stack, reward, done, episode_return


def completed_episode_mask(done: jax.Array, new_state: BreakoutState) -> jax.Array:
    """Which `done` slots ended a GAME (vs a life-loss boundary).

    The auto-reset restores 5 lives; a life-loss done leaves <=4. Lets
    callers count true episodes (including zero-return ones, which
    `episode_return != 0` would miss) without a second done channel.
    """
    return done & (new_state.lives == 5)
