"""Space Invaders as pure-JAX functions: the third on-device pixel env.

Same game as `envs.invaders_sim.InvadersCore` (see its fidelity notes),
re-expressed as jittable pure functions over a batch of N games, exactly
like `breakout_jax.py` / `pong_jax.py`. Structurally this env stresses
what the paddle pair doesn't: 36 independent entities (the alien grid),
enemy projectiles, destructible shields, combined move+fire actions,
and mid-episode lives.

Dynamics parity: constants and the per-frame update ORDER mirror
`invaders_sim._emulate_frame` statement for statement (cannon/fire ->
march -> bomb spawn -> missile flight/hits -> bombs fall -> wave
respawn -> landed/done). Divergences, deliberate and documented:

- float32 physics (all speeds are integral, so arithmetic is exact);
- the bomb-spawn draws use `jax.random` instead of
  `np.random.RandomState` — same per-frame (spawn?, column) decisions,
  different stream. `bomb_prob` is a static arg so parity tests can set
  it to 0 on both sides and compare deterministic dynamics exactly;
- the score strip / lives indicator are not rendered (the reference
  crop removes scanlines < 34, `wrappers.py:74`), same as breakout_jax.

Observation pipeline: shared `pixel_jax.observe` (2-frame max -> luma ->
resize matmuls -> crop -> 4-stack), identical to the other two games.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_reinforcement_learning_tpu.envs import invaders_sim as sim
from distributed_reinforcement_learning_tpu.envs import pixel_jax
from distributed_reinforcement_learning_tpu.envs.pixel_jax import preprocess as _preprocess

NUM_ACTIONS = sim.InvadersCore.num_actions  # NOOP/FIRE/R/L/RFIRE/LFIRE
OBS_SHAPE = (84, 84, 4)

H, W = sim.H, sim.W
_ROWS, _COLS = sim.ROWS, sim.COLS

_YS = np.arange(H)[:, None]  # [210, 1]
_XS = np.arange(W)[None, :]  # [1, 160]

# Static base frame: ground line only (score strip never reaches an
# observation; walls don't exist in this game).
_BASE = np.zeros((H, W, 3), np.uint8)
_BASE[H - 4:H - 2, :] = sim.CANNON_RGB

_ALIEN_RGB = np.asarray(sim.ALIEN_ROW_COLORS, np.uint8)  # [6, 3]
_CANNON = np.asarray(sim.CANNON_RGB, np.uint8)
_SHIELD = np.asarray(sim.SHIELD_RGB, np.uint8)
_PROJ = np.asarray(sim.PROJ_RGB, np.uint8)
_ROW_POINTS = np.asarray(sim.ROW_POINTS, np.float32)


class InvadersState(NamedTuple):
    """Batched game + observation-pipeline state (`[N, ...]` leaves)."""

    aliens: jax.Array       # [N, 6, 6] bool
    grid_x: jax.Array       # [N] f32 grid origin
    grid_y: jax.Array       # [N] f32
    direction: jax.Array    # [N] i32 (+-1)
    march_count: jax.Array  # [N] i32
    wave: jax.Array         # [N] i32
    cannon_x: jax.Array     # [N] f32
    missile_live: jax.Array  # [N] bool
    missile_x: jax.Array    # [N] f32
    missile_y: jax.Array    # [N] f32
    bomb_live: jax.Array    # [N, 2] bool
    bomb_x: jax.Array       # [N, 2] f32
    bomb_y: jax.Array       # [N, 2] f32
    shield_hp: jax.Array    # [N, 3] i32
    lives: jax.Array        # [N] i32
    frames: jax.Array       # [N] i32
    prev_raw: jax.Array     # [N, 210, 160, 3] u8
    stack: jax.Array        # [N, 84, 84, 4] u8
    returns: jax.Array      # [N] f32 raw episode return


# -- rendering (single env; vmapped) ----------------------------------------


def _render(aliens, grid_x, grid_y, cannon_x, missile_live, missile_x,
            missile_y, bomb_live, bomb_x, bomb_y, shield_hp) -> jax.Array:
    """`[210, 160, 3]` uint8 frame, `invaders_sim.render` draw order."""
    f = jnp.asarray(_BASE)
    ys, xs = jnp.asarray(_YS), jnp.asarray(_XS)

    # Aliens: pixel -> (row, col) in the marching grid.
    ry = ys - grid_y                  # [210, 1] f32
    rx = xs - grid_x                  # [1, 160] f32
    r = jnp.floor(ry / sim.PITCH_Y).astype(jnp.int32)
    c = jnp.floor(rx / sim.PITCH_X).astype(jnp.int32)
    in_r = (r >= 0) & (r < _ROWS) & (ry - r * sim.PITCH_Y < sim.ALIEN_H)
    in_c = (c >= 0) & (c < _COLS) & (rx - c * sim.PITCH_X < sim.ALIEN_W)
    rc = jnp.clip(r, 0, _ROWS - 1)
    cc = jnp.clip(c, 0, _COLS - 1)
    alive = aliens[rc[:, 0]][:, cc[0, :]]         # [210, 160]
    mask = alive & in_r & in_c
    colors = jnp.asarray(_ALIEN_RGB)[rc[:, 0]]    # [210, 3]
    f = jnp.where(mask[:, :, None], colors[:, None, :], f)

    # Shields (height erodes with hp; integer division like the sim).
    for s, sx in enumerate(sim.SHIELD_XS):
        height = sim.SHIELD_H * shield_hp[s] // sim.SHIELD_HP
        m = ((ys >= sim.SHIELD_Y) & (ys < sim.SHIELD_Y + height)
             & (xs >= sx) & (xs < sx + sim.SHIELD_W))
        f = jnp.where(m[:, :, None], jnp.asarray(_SHIELD), f)

    # Cannon.
    cx = cannon_x.astype(jnp.int32)
    m = ((ys >= sim.CANNON_Y) & (ys < sim.CANNON_Y + sim.CANNON_H)
         & (xs >= cx) & (xs < cx + sim.CANNON_W))
    f = jnp.where(m[:, :, None], jnp.asarray(_CANNON), f)

    # Player missile (clamped to the screen like the numpy slice).
    my = jnp.maximum(missile_y.astype(jnp.int32), 0)
    mx = missile_x.astype(jnp.int32)
    m = (missile_live & (ys >= my) & (ys < my + sim.PROJ_H)
         & (xs >= mx) & (xs < mx + sim.PROJ_W))
    f = jnp.where(m[:, :, None], jnp.asarray(_PROJ), f)

    # Bombs.
    for b in range(sim.MAX_BOMBS):
        by = bomb_y[b].astype(jnp.int32)
        bx = bomb_x[b].astype(jnp.int32)
        m = (bomb_live[b] & (ys >= by) & (ys < jnp.minimum(by + sim.PROJ_H, H))
             & (xs >= bx) & (xs < bx + sim.PROJ_W))
        f = jnp.where(m[:, :, None], jnp.asarray(_PROJ), f)
    return f


# -- physics helpers (single env) -------------------------------------------


def _shield_absorb(active, shield_hp, x, y):
    """Projectile tip at (x, y) vs the shield blocks -> (absorbed, hp').

    The three blocks are horizontally disjoint, so at most one can hit —
    the sim's sequential first-hit return is equivalent."""
    tip = x + sim.PROJ_W / 2
    hits = []
    for s, sx in enumerate(sim.SHIELD_XS):
        height = sim.SHIELD_H * shield_hp[s] // sim.SHIELD_HP
        hits.append(active & (shield_hp[s] > 0)
                    & (sx <= tip) & (tip <= sx + sim.SHIELD_W)
                    & (sim.SHIELD_Y <= y) & (y <= sim.SHIELD_Y + height))
    hit_vec = jnp.stack(hits)                     # [3]
    return hit_vec.any(), shield_hp - hit_vec.astype(jnp.int32)


def _missile_collide(aliens, grid_x, grid_y, shield_hp, bomb_live, bomb_x,
                     bomb_y, missile_live, x, y, reward):
    """`invaders_sim._missile_collide` order: shields -> bombs -> grid."""
    absorbed, shield_hp = _shield_absorb(missile_live, shield_hp, x, y)
    missile_live = missile_live & ~absorbed

    # Bombs: first matching bomb only (the sim's loop-and-return).
    prior = jnp.zeros((), bool)
    new_bomb_live = bomb_live
    for b in range(sim.MAX_BOMBS):
        hit_b = (missile_live & bomb_live[b] & ~prior
                 & (jnp.abs(bomb_x[b] - x) < sim.PROJ_W + 1)
                 & (jnp.abs(bomb_y[b] - y) < sim.PROJ_H))
        new_bomb_live = new_bomb_live.at[b].set(new_bomb_live[b] & ~hit_b)
        prior = prior | hit_b
    missile_live = missile_live & ~prior

    # Alien grid (one kill per frame).
    col = jnp.floor((x + sim.PROJ_W / 2 - grid_x) / sim.PITCH_X).astype(jnp.int32)
    row = jnp.floor((y - grid_y) / sim.PITCH_Y).astype(jnp.int32)
    rc = jnp.clip(row, 0, _ROWS - 1)
    cc = jnp.clip(col, 0, _COLS - 1)
    within = (x + sim.PROJ_W / 2 - (grid_x + cc * sim.PITCH_X)) < sim.ALIEN_W
    tall = (y - (grid_y + rc * sim.PITCH_Y)) < sim.ALIEN_H
    kill = (missile_live & (row >= 0) & (row < _ROWS) & (col >= 0)
            & (col < _COLS) & aliens[rc, cc] & within & tall)
    knock = (kill & (jnp.arange(_ROWS)[:, None] == rc)
             & (jnp.arange(_COLS)[None, :] == cc))
    aliens = aliens & ~knock
    reward = reward + jnp.where(kill, jnp.asarray(_ROW_POINTS)[rc], 0.0)
    missile_live = missile_live & ~kill
    return aliens, shield_hp, new_bomb_live, missile_live, reward


def _emulate_frame(carry, action, u_spawn, u_col, bomb_prob, max_frames):
    """One emulated frame under a held action (`_emulate_frame` parity)."""
    (aliens, grid_x, grid_y, direction, march_count, wave, cannon_x,
     missile_live, missile_x, missile_y, bomb_live, bomb_x, bomb_y,
     shield_hp, lives, frames, reward, halted) = carry
    live = ~halted
    frames = frames + live.astype(jnp.int32)

    # Cannon move + fire (combined actions do both).
    move_r = live & ((action == sim.RIGHT) | (action == sim.RIGHTFIRE))
    move_l = live & ((action == sim.LEFT) | (action == sim.LEFTFIRE))
    cannon_x = jnp.where(
        move_r, jnp.minimum(jnp.float32(W - 8 - sim.CANNON_W),
                            cannon_x + sim.CANNON_SPEED), cannon_x)
    cannon_x = jnp.where(
        move_l, jnp.maximum(jnp.float32(8.0), cannon_x - sim.CANNON_SPEED),
        cannon_x)
    fire = (live & ~missile_live
            & ((action == sim.FIRE) | (action == sim.RIGHTFIRE)
               | (action == sim.LEFTFIRE)))
    missile_x = jnp.where(fire, cannon_x + sim.CANNON_W / 2 - sim.PROJ_W / 2,
                          missile_x)
    missile_y = jnp.where(fire, jnp.float32(sim.CANNON_Y - sim.PROJ_H),
                          missile_y)
    missile_live = missile_live | fire

    # Grid march (uses the alien count from the frame's start, like the
    # sim's `alive` read before the missile section).
    alive_n = aliens.sum().astype(jnp.int32)
    period = 1 + (7 * alive_n) // (_ROWS * _COLS)
    march_count = march_count + live.astype(jnp.int32)
    stepping = live & (alive_n > 0) & (march_count >= period)
    nx = grid_x + direction.astype(jnp.float32) * 2.0
    bounce = (nx < sim.GRID_X_MIN) | (nx > sim.GRID_X_MAX)
    direction = jnp.where(stepping & bounce, -direction, direction)
    grid_y = jnp.where(stepping & bounce, grid_y + sim.PITCH_Y // 2, grid_y)
    grid_x = jnp.where(stepping & ~bounce, nx, grid_x)
    march_count = jnp.where(stepping, 0, march_count)

    # Alien bombs: lowest alive alien of a random column drops one into
    # the first free slot (`invaders_sim` order: spawn check, slot check).
    alive_cols = aliens.any(axis=0)               # [6]
    slot_free = ~bomb_live                        # [2]
    slot = jnp.argmax(slot_free)                  # first free (sim argmin)
    spawn = (live & (alive_n > 0) & (u_spawn < bomb_prob)
             & slot_free.any())
    count = alive_cols.sum()
    k = jnp.clip((u_col * count).astype(jnp.int32), 0, count - 1)
    col = jnp.argmax(jnp.cumsum(alive_cols.astype(jnp.int32)) > k)
    row = jnp.max(jnp.where(aliens[:, col], jnp.arange(_ROWS), -1))
    sel = (jnp.arange(sim.MAX_BOMBS) == slot) & spawn
    bomb_x = jnp.where(sel, grid_x + col * sim.PITCH_X
                       + sim.ALIEN_W / 2 - sim.PROJ_W / 2, bomb_x)
    bomb_y = jnp.where(sel, grid_y + row * sim.PITCH_Y + sim.ALIEN_H, bomb_y)
    bomb_live = bomb_live | sel

    # Player missile flight + hits.
    missile_y = jnp.where(live & missile_live, missile_y - sim.MISSILE_SPEED,
                          missile_y)
    (aliens, shield_hp, bomb_live, missile_live, reward) = _missile_collide(
        aliens, grid_x, grid_y, shield_hp, bomb_live, bomb_x, bomb_y,
        live & missile_live, missile_x, missile_y, reward)
    missile_live = missile_live & (missile_y >= sim.WALL_TOP_Y)

    # Bombs fall; erode shields; hit the cannon. Sequential like the
    # sim's loop: a cannon hit clears ALL bombs and freezes the rest of
    # the pass (its `break`).
    cannon_hit_any = jnp.zeros((), bool)
    new_live, new_y = [], []
    for b in range(sim.MAX_BOMBS):
        active = live & bomb_live[b] & ~cannon_hit_any
        y2 = jnp.where(active, bomb_y[b] + sim.BOMB_SPEED, bomb_y[b])
        absorbed, shield_hp = _shield_absorb(active, shield_hp, bomb_x[b],
                                             y2 + sim.PROJ_H)
        cannon_hit = (active & ~absorbed
                      & (y2 + sim.PROJ_H >= sim.CANNON_Y)
                      & (cannon_x - sim.PROJ_W <= bomb_x[b])
                      & (bomb_x[b] <= cannon_x + sim.CANNON_W))
        off = active & (y2 >= H)
        new_live.append(bomb_live[b] & ~(absorbed | cannon_hit | off))
        new_y.append(y2)
        cannon_hit_any = cannon_hit_any | cannon_hit
    bomb_live = jnp.stack(new_live)
    bomb_y = jnp.stack(new_y)
    lives = lives - cannon_hit_any.astype(jnp.int32)
    bomb_live = jnp.where(cannon_hit_any, jnp.zeros_like(bomb_live), bomb_live)
    cannon_x = jnp.where(cannon_hit_any,
                         jnp.float32((W - sim.CANNON_W) // 2), cannon_x)

    # Wave cleared: respawn lower and faster (sim order: before `landed`).
    cleared = live & ~aliens.any()
    wave = wave + cleared.astype(jnp.int32)
    aliens = aliens | cleared
    grid_x = jnp.where(cleared, jnp.float32(sim.GRID_X0), grid_x)
    grid_y = jnp.where(
        cleared,
        sim.GRID_Y0 + jnp.minimum(3, wave).astype(jnp.float32)
        * (sim.PITCH_Y // 2), grid_y)
    direction = jnp.where(cleared, 1, direction)
    march_count = jnp.where(cleared, 0, march_count)

    landed = (grid_y + (_ROWS - 1) * sim.PITCH_Y + sim.ALIEN_H
              >= sim.SHIELD_Y) & aliens.any()
    game_over = (lives <= 0) | landed | (frames >= max_frames)
    halted = halted | (live & game_over)
    return (aliens, grid_x, grid_y, direction, march_count, wave, cannon_x,
            missile_live, missile_x, missile_y, bomb_live, bomb_x, bomb_y,
            shield_hp, lives, frames, reward, halted)


# -- public API (cartpole_jax contract) -------------------------------------


def _reset_fields(n: int):
    return dict(
        aliens=jnp.ones((n, _ROWS, _COLS), bool),
        grid_x=jnp.full((n,), sim.GRID_X0, jnp.float32),
        grid_y=jnp.full((n,), sim.GRID_Y0, jnp.float32),
        direction=jnp.ones((n,), jnp.int32),
        march_count=jnp.zeros((n,), jnp.int32),
        wave=jnp.zeros((n,), jnp.int32),
        cannon_x=jnp.full((n,), float((W - sim.CANNON_W) // 2), jnp.float32),
        missile_live=jnp.zeros((n,), bool),
        missile_x=jnp.zeros((n,), jnp.float32),
        missile_y=jnp.zeros((n,), jnp.float32),
        bomb_live=jnp.zeros((n, sim.MAX_BOMBS), bool),
        bomb_x=jnp.zeros((n, sim.MAX_BOMBS), jnp.float32),
        bomb_y=jnp.zeros((n, sim.MAX_BOMBS), jnp.float32),
        shield_hp=jnp.full((n, len(sim.SHIELD_XS)), sim.SHIELD_HP, jnp.int32),
        lives=jnp.full((n,), 3, jnp.int32),
        frames=jnp.zeros((n,), jnp.int32),
        returns=jnp.zeros((n,), jnp.float32),
    )


def _render_state(f: dict) -> jax.Array:
    return jax.vmap(_render)(
        f["aliens"], f["grid_x"], f["grid_y"], f["cannon_x"],
        f["missile_live"], f["missile_x"], f["missile_y"],
        f["bomb_live"], f["bomb_x"], f["bomb_y"], f["shield_hp"])


def reset(rng: jax.Array, num_envs: int) -> tuple[InvadersState, jax.Array]:
    """-> (state, obs `[N, 84, 84, 4]` u8). Deterministic reset (`rng`
    kept for the cartpole_jax signature)."""
    del rng
    f = _reset_fields(num_envs)
    raw = _render_state(f)
    state = InvadersState(prev_raw=raw, stack=pixel_jax.reset_stack(raw), **f)
    return state, state.stack


@functools.partial(jax.jit, static_argnames=("frameskip", "max_frames",
                                             "life_loss", "bomb_prob"))
def step(
    state: InvadersState,
    actions: jax.Array,
    rng: jax.Array,
    frameskip: int = 4,
    max_frames: int = 10_000,
    life_loss: bool = True,
    bomb_prob: float = 0.04,
) -> tuple[InvadersState, jax.Array, jax.Array, jax.Array, jax.Array]:
    """-> (state', obs', reward, done, episode_return).

    Contract matches `breakout_jax.step`: auto-reset on game over with
    the reset observation in `obs'`, `done` = game over or (with
    `life_loss`) a lost life, the shaping reward -1 on non-terminal life
    loss, raw returns accumulated separately.
    """
    n = state.lives.shape[0]
    lives_before = state.lives
    k_spawn, k_col = jax.random.split(rng)
    u_spawn = jax.random.uniform(k_spawn, (frameskip, n))
    u_col = jax.random.uniform(k_col, (frameskip, n))

    carry = (state.aliens, state.grid_x, state.grid_y, state.direction,
             state.march_count, state.wave, state.cannon_x,
             state.missile_live, state.missile_x, state.missile_y,
             state.bomb_live, state.bomb_x, state.bomb_y, state.shield_hp,
             state.lives, state.frames,
             jnp.zeros((n,), jnp.float32), jnp.zeros((n,), bool))
    actions = actions.astype(jnp.int32)
    emulate = jax.vmap(_emulate_frame, in_axes=(0, 0, 0, 0, None, None))
    for i in range(frameskip):  # static unroll: action held, break-on-done
        carry = emulate(carry, actions, u_spawn[i], u_col[i], bomb_prob,
                        max_frames)
    (aliens, grid_x, grid_y, direction, march_count, wave, cannon_x,
     missile_live, missile_x, missile_y, bomb_live, bomb_x, bomb_y,
     shield_hp, lives, frames, reward, game_over) = carry

    fields = dict(
        aliens=aliens, grid_x=grid_x, grid_y=grid_y, direction=direction,
        march_count=march_count, wave=wave, cannon_x=cannon_x,
        missile_live=missile_live, missile_x=missile_x, missile_y=missile_y,
        bomb_live=bomb_live, bomb_x=bomb_x, bomb_y=bomb_y,
        shield_hp=shield_hp, lives=lives, frames=frames,
        returns=state.returns + reward)
    raw = _render_state(fields)
    stack = pixel_jax.observe(raw, state.prev_raw, state.stack)

    episode_return = jnp.where(game_over, fields["returns"], 0.0)
    lost_life = lives < lives_before
    done = (game_over | lost_life) if life_loss else game_over
    if life_loss:
        # Same convention as breakout_jax (host-path parity): -1 replaces
        # the reward on a NON-terminal life loss; true game-overs keep
        # the raw reward.
        reward = jnp.where(lost_life & ~game_over, -1.0, reward)

    fresh = _reset_fields(n)
    raw0 = _render_state(fresh)
    stack0 = pixel_jax.reset_stack(raw0)
    pick = pixel_jax.make_pick(game_over)
    new_fields = {k: pick(fresh[k], fields[k]) for k in fresh}
    new_state = InvadersState(
        prev_raw=pick(raw0, raw), stack=pick(stack0, stack), **new_fields)
    return new_state, new_state.stack, reward, done, episode_return


def completed_episode_mask(done: jax.Array, new_state: InvadersState) -> jax.Array:
    """Which `done` slots ended a GAME (vs a life-loss boundary): the
    auto-reset restores 3 lives, a life-loss done leaves <= 2."""
    return done & (new_state.lives == 3)
