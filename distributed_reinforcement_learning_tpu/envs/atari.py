"""Atari preprocessing pipeline in pure numpy + synthetic frame source.

Behavioral parity with `/root/reference/wrappers.py` without gym/cv2:

- `area_resize`: separable pixel-area-overlap downscale, the algorithm
  behind `cv2.resize(..., interpolation=cv2.INTER_AREA)` (`wrappers.py:71`).
- `preprocess_frame`: luma 0.299/0.587/0.114, resize to 110x84, crop rows
  18:102 -> `[84, 84]` uint8 (`wrappers.py:63-74`).
- `AtariPreprocessor`: stateful per-env pipeline = 2-frame max
  (`wrappers.py:26-51`, skip=1 as the reference configures it), fire-reset
  (`wrappers.py:7-24`), 4-frame stacking to `[84, 84, 4]` uint8
  (`wrappers.py:96-111`), life-loss shaping hooks
  (`train_impala.py:149-154`).
- `SyntheticAtari`: a `RawFrameEnv` producing deterministic pseudo-frames
  with an ALE-style life counter — exercises the full pipeline and feeds
  throughput benchmarks without an emulator. A real ALE backend plugs in
  via the same protocol.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Any

import numpy as np

from distributed_reinforcement_learning_tpu.envs.base import RawFrameEnv


@lru_cache(maxsize=16)
def _area_weights(src: int, dst: int) -> np.ndarray:
    """`[dst, src]` row-overlap weight matrix for 1-D area interpolation."""
    w = np.zeros((dst, src), np.float32)
    scale = src / dst
    for i in range(dst):
        start = i * scale
        end = (i + 1) * scale
        j0 = int(np.floor(start))
        j1 = int(np.ceil(end))
        for j in range(j0, min(j1, src)):
            overlap = min(end, j + 1) - max(start, j)
            w[i, j] = overlap / scale
    return w


def area_resize(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Separable area-interpolation resize of a `[H, W]` float image."""
    wh = _area_weights(img.shape[0], out_h)
    ww = _area_weights(img.shape[1], out_w)
    return wh @ img @ ww.T


def preprocess_frame(frame: np.ndarray) -> np.ndarray:
    """RGB `[H, W, 3]` -> `[84, 84]` uint8: luma, area-resize 110x84, crop.

    Parity with `wrappers.py:63-74` (including the 250-row variant)."""
    if frame.shape[:2] not in ((210, 160), (250, 160)):
        raise ValueError(f"unexpected Atari frame shape {frame.shape}")
    img = frame.astype(np.float32)
    luma = img[:, :, 0] * 0.299 + img[:, :, 1] * 0.587 + img[:, :, 2] * 0.114
    resized = area_resize(luma, 110, 84)
    return resized[18:102, :].astype(np.uint8)


class AtariPreprocessor:
    """Stateful frame pipeline over any `RawFrameEnv`: the reference's
    `make_uint8_env` composition (`wrappers.py:123-131`).

    Emits `[84, 84, 4]` uint8 observations (4 newest-last stacked frames).
    """

    def __init__(self, env: RawFrameEnv, fire_reset: bool = True, frame_max: int = 2):
        self.env = env
        self.num_actions = env.num_actions
        self.obs_shape = (84, 84, 4)
        self._fire_reset = fire_reset
        self._frame_max = frame_max
        self._raw_buffer: list[np.ndarray] = []
        self._stack = np.zeros((84, 84, 4), np.uint8)

    def _observe(self, raw: np.ndarray) -> np.ndarray:
        self._raw_buffer.append(raw)
        if len(self._raw_buffer) > self._frame_max:
            self._raw_buffer.pop(0)
        maxed = np.max(np.stack(self._raw_buffer), axis=0)
        frame = preprocess_frame(maxed)
        self._stack[:, :, :-1] = self._stack[:, :, 1:]
        self._stack[:, :, -1] = frame
        return self._stack.copy()

    def reset(self) -> np.ndarray:
        self._raw_buffer.clear()
        self._stack[:] = 0
        raw = self.env.reset()
        if self._fire_reset and self.env.num_actions >= 3:
            # FIRE then a second action to unstick, like `wrappers.py:16-23`.
            raw, _, done, _ = self.env.step(1)
            if done:
                raw = self.env.reset()
            raw, _, done, _ = self.env.step(2)
            if done:
                raw = self.env.reset()
        return self._observe(raw)

    def step(self, action: int) -> tuple[np.ndarray, float, bool, dict[str, Any]]:
        raw, reward, done, info = self.env.step(action)
        info = dict(info)
        info.setdefault("lives", self.env.lives())
        return self._observe(raw), reward, done, info

    def lives(self) -> int:
        return self.env.lives()


class SyntheticAtari:
    """Deterministic pseudo-Atari `RawFrameEnv` for tests and benchmarks.

    Produces 210x160x3 uint8 frames from a cheap per-step pattern, a
    5-life counter that decrements on a fixed cadence, and +1 reward on a
    fixed cadence. No emulator, no I/O: designed so the preprocessing +
    data-plane + learner path can be driven at full speed.
    """

    def __init__(self, num_actions: int = 18, seed: int = 0, episode_len: int = 512,
                 life_every: int = 128, reward_every: int = 16):
        self.num_actions = num_actions
        self._seed = seed
        self._episode_len = episode_len
        self._life_every = life_every
        self._reward_every = reward_every
        self._t = 0
        self._lives = 5
        self._base = np.random.RandomState(seed).randint(0, 255, (210, 160, 3)).astype(np.uint8)

    def _frame(self) -> np.ndarray:
        # Cheap deterministic variation: roll the base pattern by step count.
        return np.roll(self._base, self._t * 3, axis=0)

    def reset(self) -> np.ndarray:
        self._t = 0
        self._lives = 5
        return self._frame()

    def step(self, action: int):
        self._t += 1
        if self._t % self._life_every == 0 and self._lives > 0:
            self._lives -= 1
        reward = 1.0 if self._t % self._reward_every == 0 else 0.0
        done = self._t >= self._episode_len or self._lives == 0
        return self._frame(), reward, done, {"lives": self._lives}

    def lives(self) -> int:
        return self._lives
