"""CartPole-v0 physics in numpy: single and vectorized variants.

In-tree replacement for `gym.make("CartPole-v0")` (used by the reference's
R2D2 path, `train_r2d2.py:171` and config `config.json:6-8`): classic
Barto-Sutton-Anderson cart-pole with Euler integration, +1 reward per
step, termination at |x| > 2.4 or |theta| > 12deg, 200-step limit (v0).

The vectorized variant steps N independent carts with one numpy call so a
single jitted act handles the whole actor batch.
"""

from __future__ import annotations

from typing import Any

import numpy as np

_GRAVITY = 9.8
_MASSCART = 1.0
_MASSPOLE = 0.1
_TOTAL_MASS = _MASSCART + _MASSPOLE
_LENGTH = 0.5  # half pole length
_POLEMASS_LENGTH = _MASSPOLE * _LENGTH
_FORCE_MAG = 10.0
_TAU = 0.02
_THETA_LIMIT = 12 * 2 * np.pi / 360
_X_LIMIT = 2.4


def _physics_step(state: np.ndarray, actions: np.ndarray) -> np.ndarray:
    """Euler-integrated cart-pole dynamics on `[N, 4]` states."""
    x, x_dot, theta, theta_dot = state.T
    force = np.where(actions == 1, _FORCE_MAG, -_FORCE_MAG)
    costheta = np.cos(theta)
    sintheta = np.sin(theta)
    temp = (force + _POLEMASS_LENGTH * theta_dot**2 * sintheta) / _TOTAL_MASS
    thetaacc = (_GRAVITY * sintheta - costheta * temp) / (
        _LENGTH * (4.0 / 3.0 - _MASSPOLE * costheta**2 / _TOTAL_MASS)
    )
    xacc = temp - _POLEMASS_LENGTH * thetaacc * costheta / _TOTAL_MASS
    return np.stack(
        [x + _TAU * x_dot, x_dot + _TAU * xacc, theta + _TAU * theta_dot, theta_dot + _TAU * thetaacc],
        axis=1,
    )


class CartPoleEnv:
    """Single CartPole-v0 with the gym step/reset contract."""

    num_actions = 2
    obs_shape = (4,)

    def __init__(self, seed: int | None = None, max_steps: int = 200):
        self._rng = np.random.RandomState(seed)
        self._max_steps = max_steps
        self._state = np.zeros(4, np.float64)
        self._steps = 0

    def reset(self) -> np.ndarray:
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._steps = 0
        return self._state.astype(np.float32)

    def step(self, action: int) -> tuple[np.ndarray, float, bool, dict[str, Any]]:
        self._state = _physics_step(self._state[None], np.asarray([action]))[0]
        self._steps += 1
        x, _, theta, _ = self._state
        failed = abs(x) > _X_LIMIT or abs(theta) > _THETA_LIMIT
        done = bool(failed or self._steps >= self._max_steps)
        # Time-limit truncation vs real termination (gymnasium semantics):
        # the cap ending an otherwise-alive episode is `truncated`.
        return (self._state.astype(np.float32), 1.0, done,
                {"truncated": bool(done and not failed)})


class VectorCartPole:
    """N independent CartPoles stepped in one numpy call, with auto-reset.

    step returns (obs `[N, 4]`, reward `[N]`, done `[N]`, infos). When an env
    terminates, `obs` already contains its *reset* observation and `done`
    is True for that slot — the batched-actor convention.
    """

    num_actions = 2
    obs_shape = (4,)

    def __init__(self, num_envs: int, seed: int = 0, max_steps: int = 200):
        self.num_envs = num_envs
        self._rng = np.random.RandomState(seed)
        self._max_steps = max_steps
        self._state = np.zeros((num_envs, 4), np.float64)
        self._steps = np.zeros(num_envs, np.int64)
        # Per-env episode returns, surfaced on done for score logging.
        self._returns = np.zeros(num_envs, np.float64)

    def reset(self) -> np.ndarray:
        self._state = self._rng.uniform(-0.05, 0.05, size=(self.num_envs, 4))
        self._steps[:] = 0
        self._returns[:] = 0
        return self._state.astype(np.float32)

    def step(self, actions: np.ndarray):
        self._state = _physics_step(self._state, np.asarray(actions))
        self._steps += 1
        self._returns += 1.0
        x = self._state[:, 0]
        theta = self._state[:, 2]
        failed = (np.abs(x) > _X_LIMIT) | (np.abs(theta) > _THETA_LIMIT)
        done = failed | (self._steps >= self._max_steps)
        truncated = done & ~failed  # time-limit cap, not a real terminal
        reward = np.ones(self.num_envs, np.float32)
        episode_returns = np.where(done, self._returns, 0.0)
        if done.any():
            idx = np.nonzero(done)[0]
            self._state[idx] = self._rng.uniform(-0.05, 0.05, size=(len(idx), 4))
            self._steps[idx] = 0
            self._returns[idx] = 0
        infos = {"episode_return": episode_returns, "done_mask": done.copy(),
                 "truncated": truncated}
        return self._state.astype(np.float32), reward, done, infos


def pomdp_project(obs: np.ndarray) -> np.ndarray:
    """CartPole POMDP view: keep position and pole angle only.

    Parity with `train_r2d2.py:176-178`: `[s[0], s[2]]`, scaled x255 and
    int-cast (the reference quantizes so all queue payloads share the uint8
    transport convention; `/255` is undone at the model input).
    """
    proj = obs[..., [0, 2]] * 255.0
    return proj.astype(np.int32)
