"""Environment API.

The reference drives OpenAI Gym envs (`wrappers.py`, `train_*.py` loops).
The framework defines its own minimal env protocol with the same
step/reset contract, an in-tree CartPole physics implementation, a
gymnasium adapter (`envs/gymnasium_env.py` — gymnasium ships in this
image; ale-py does not), and wrappers mirroring the reference's Atari
pipeline. Anything needing a real Atari emulator goes through the
`RawFrameEnv` protocol, served by ALE when importable and by
`SyntheticAtari` otherwise.
"""

from __future__ import annotations

from typing import Any, Protocol

import numpy as np


class Env(Protocol):
    """Single environment: the reference's gym surface (`train_impala.py:145`)."""

    num_actions: int

    def reset(self) -> np.ndarray: ...

    def step(self, action: int) -> tuple[np.ndarray, float, bool, dict[str, Any]]: ...


class VectorEnv(Protocol):
    """N synchronized environments stepped with an `[N]` action vector.

    The TPU-first actor batches envs so one jitted act call serves all of
    them (replacing the reference's one `sess.run` per env step per actor,
    SURVEY §3.5).
    """

    num_envs: int
    num_actions: int

    def reset(self) -> np.ndarray: ...

    def step(self, actions: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray, list[dict]]: ...


class RawFrameEnv(Protocol):
    """Raw RGB frame source (what gym.make('...Deterministic-v4') provides).

    step/reset return `[H, W, 3]` uint8 frames; `lives()` exposes the ALE
    life counter used by the reference's life-loss shaping
    (`train_impala.py:149-154`).
    """

    num_actions: int

    def reset(self) -> np.ndarray: ...

    def step(self, action: int) -> tuple[np.ndarray, float, bool, dict[str, Any]]: ...

    def lives(self) -> int: ...
