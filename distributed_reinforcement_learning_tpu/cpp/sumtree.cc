// Array-backed binary sum tree with batch add / sample / update.
//
// Native replacement for the reference's pure-Python SumTree
// (distributed_queue/buffer_queue.py:256-301), the learner-host hotspot
// called per transition at train_apex.py:114-122 (SURVEY §2.2 E7). The
// priority math (propagate-to-root on set, subtractive descent on get)
// is identical; the wins are batch entry points (one FFI call per batch,
// O(n log C) in C++) and an internal mutex so the Ape-X learner's
// ingest and train phases can run from different threads.
//
// Payloads stay in Python — the tree stores only priorities; `add`
// returns the leaf slot (= write cursor) so the Python side keeps its
// data list aligned.

#include <cstdint>
#include <mutex>
#include <vector>

namespace {

struct SumTree {
  explicit SumTree(size_t cap)
      : capacity(cap), tree(2 * cap - 1, 0.0), write(0), count(0) {}
  size_t capacity;
  std::vector<double> tree;  // tree[0] = root total; leaves at [cap-1, 2cap-1)
  size_t write;
  size_t count;
  std::mutex mu;

  void set_priority(size_t idx, double priority) {
    double delta = priority - tree[idx];
    while (true) {
      tree[idx] += delta;
      if (idx == 0) break;
      idx = (idx - 1) / 2;
    }
  }

  // Leaf index whose cumulative-priority interval contains `value`.
  size_t retrieve(double value) const {
    size_t idx = 0;
    while (true) {
      size_t left = 2 * idx + 1;
      if (left >= tree.size()) break;
      if (value <= tree[left]) {
        idx = left;
      } else {
        value -= tree[left];
        idx = left + 1;
      }
    }
    return idx;
  }
};

}  // namespace

extern "C" {

void* st_create(int64_t capacity) {
  if (capacity <= 0) return nullptr;
  return new SumTree(static_cast<size_t>(capacity));
}

void st_destroy(void* h) { delete static_cast<SumTree*>(h); }

double st_total(void* h) {
  auto* t = static_cast<SumTree*>(h);
  std::lock_guard<std::mutex> lk(t->mu);
  return t->tree[0];
}

int64_t st_size(void* h) {
  auto* t = static_cast<SumTree*>(h);
  std::lock_guard<std::mutex> lk(t->mu);
  return static_cast<int64_t>(t->count);
}

double st_leaf_priority(void* h, int64_t tree_idx) {
  auto* t = static_cast<SumTree*>(h);
  std::lock_guard<std::mutex> lk(t->mu);
  return t->tree[static_cast<size_t>(tree_idx)];
}

// Copy the leaf priorities of slots [start, start+n) in one call — the
// checkpoint-snapshot read path (one FFI call for the whole ring instead
// of count individual st_leaf_priority calls under the Python lock).
void st_leaf_priorities(void* h, int64_t start, int64_t n, double* out) {
  auto* t = static_cast<SumTree*>(h);
  std::lock_guard<std::mutex> lk(t->mu);
  for (int64_t i = 0; i < n; ++i)
    out[i] = t->tree[static_cast<size_t>(start + i) + t->capacity - 1];
}

// Append n priorities at the ring-write cursor; out_data_idx[i] receives
// the leaf slot each landed in (tree idx = slot + capacity - 1).
void st_add_batch(void* h, const double* priorities, int64_t n,
                  int64_t* out_data_idx) {
  auto* t = static_cast<SumTree*>(h);
  std::lock_guard<std::mutex> lk(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    size_t slot = t->write;
    t->set_priority(slot + t->capacity - 1, priorities[i]);
    out_data_idx[i] = static_cast<int64_t>(slot);
    t->write = (t->write + 1) % t->capacity;
    if (t->count < t->capacity) ++t->count;
  }
}

void st_update_batch(void* h, const int64_t* tree_idxs,
                     const double* priorities, int64_t n) {
  auto* t = static_cast<SumTree*>(h);
  std::lock_guard<std::mutex> lk(t->mu);
  for (int64_t i = 0; i < n; ++i)
    t->set_priority(static_cast<size_t>(tree_idxs[i]), priorities[i]);
}

// Subtractive descent for each query value (caller supplies the values so
// RNG stays in Python for reproducibility). Returns tree idx + priority.
void st_get_batch(void* h, const double* values, int64_t n,
                  int64_t* out_tree_idx, double* out_priority) {
  auto* t = static_cast<SumTree*>(h);
  std::lock_guard<std::mutex> lk(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    size_t idx = t->retrieve(values[i]);
    out_tree_idx[i] = static_cast<int64_t>(idx);
    out_priority[i] = t->tree[idx];
  }
}

}  // extern "C"
