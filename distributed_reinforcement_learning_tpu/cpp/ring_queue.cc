// Bounded MPMC byte-blob queue with condition-variable backpressure.
//
// TPU-native replacement for the C++ tf.FIFOQueue kernel the reference
// leans on (reference distributed_queue/buffer_queue.py:28-36,153-160,
// 368-378 places a shared_name FIFOQueue on the learner; its blocking
// enqueue is the actors' backpressure). Items are opaque byte blobs —
// the Python side owns serialization (data/codec.py) so one memcpy moves
// a whole trajectory. Blocking put when full, blocking get when empty,
// batch get into a caller-provided strided buffer so a 32-item batch is
// one FFI call instead of the reference's 32 sequential RPC round-trips
// (buffer_queue.py:416-435).
//
// Exposed as a C ABI for ctypes; no Python.h dependency. All calls
// release the GIL naturally (ctypes releases it around foreign calls),
// so producers and the learner thread overlap.

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>

namespace {

struct RingQueue {
  explicit RingQueue(size_t cap) : capacity(cap), closed(false) {}
  size_t capacity;
  bool closed;
  std::deque<std::string> items;
  std::mutex mu;
  std::condition_variable not_full;
  std::condition_variable not_empty;
};

bool wait_until(std::condition_variable& cv, std::unique_lock<std::mutex>& lk,
                double timeout_s, bool (*pred)(RingQueue*), RingQueue* q) {
  if (timeout_s < 0) {
    cv.wait(lk, [&] { return pred(q); });
    return true;
  }
  return cv.wait_for(lk, std::chrono::duration<double>(timeout_s),
                     [&] { return pred(q); });
}

}  // namespace

extern "C" {

// Status codes shared with the Python wrapper (data/native.py).
enum { RQ_OK = 0, RQ_TIMEOUT = -1, RQ_CLOSED = -2, RQ_TOO_SMALL = -3 };

void* rq_create(int64_t capacity) {
  if (capacity <= 0) return nullptr;
  return new RingQueue(static_cast<size_t>(capacity));
}

void rq_destroy(void* h) { delete static_cast<RingQueue*>(h); }

int64_t rq_size(void* h) {
  auto* q = static_cast<RingQueue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  return static_cast<int64_t>(q->items.size());
}

void rq_close(void* h) {
  auto* q = static_cast<RingQueue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  q->closed = true;
  q->not_full.notify_all();
  q->not_empty.notify_all();
}

// Blocks while full (backpressure). timeout_s < 0 means wait forever.
int64_t rq_put(void* h, const uint8_t* data, int64_t len, double timeout_s) {
  auto* q = static_cast<RingQueue*>(h);
  std::unique_lock<std::mutex> lk(q->mu);
  bool ready = wait_until(
      q->not_full, lk, timeout_s,
      [](RingQueue* qq) { return qq->items.size() < qq->capacity || qq->closed; },
      q);
  if (!ready) return RQ_TIMEOUT;
  if (q->closed) return RQ_CLOSED;
  q->items.emplace_back(reinterpret_cast<const char*>(data),
                        static_cast<size_t>(len));
  q->not_empty.notify_one();
  return RQ_OK;
}

// Next item's size without consuming it; RQ_TIMEOUT / RQ_CLOSED on failure.
int64_t rq_peek_size(void* h, double timeout_s) {
  auto* q = static_cast<RingQueue*>(h);
  std::unique_lock<std::mutex> lk(q->mu);
  bool ready = wait_until(
      q->not_empty, lk, timeout_s,
      [](RingQueue* qq) { return !qq->items.empty() || qq->closed; }, q);
  if (!ready) return RQ_TIMEOUT;
  if (q->items.empty()) return RQ_CLOSED;  // closed and drained
  return static_cast<int64_t>(q->items.front().size());
}

// Pop one item into `out` (capacity `out_cap`); returns bytes written.
int64_t rq_get(void* h, uint8_t* out, int64_t out_cap, double timeout_s) {
  auto* q = static_cast<RingQueue*>(h);
  std::unique_lock<std::mutex> lk(q->mu);
  bool ready = wait_until(
      q->not_empty, lk, timeout_s,
      [](RingQueue* qq) { return !qq->items.empty() || qq->closed; }, q);
  if (!ready) return RQ_TIMEOUT;
  if (q->items.empty()) return RQ_CLOSED;
  std::string& item = q->items.front();
  if (static_cast<int64_t>(item.size()) > out_cap) return RQ_TOO_SMALL;
  std::memcpy(out, item.data(), item.size());
  int64_t n = static_cast<int64_t>(item.size());
  q->items.pop_front();
  q->not_full.notify_one();
  return n;
}

// Pop exactly `n` items, item i written at out + i*stride, its length in
// lens[i]. All-or-nothing: on timeout nothing is consumed (items already
// popped under the lock are pushed back in order). One FFI call per batch.
int64_t rq_get_batch(void* h, int64_t n, uint8_t* out, int64_t stride,
                     int64_t* lens, double timeout_s) {
  auto* q = static_cast<RingQueue*>(h);
  std::unique_lock<std::mutex> lk(q->mu);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                      std::chrono::duration<double>(timeout_s < 0 ? 3e8 : timeout_s));
  for (int64_t i = 0; i < n; ++i) {
    bool ready = q->not_empty.wait_until(lk, deadline, [&] {
      return !q->items.empty() || q->closed;
    });
    if (!ready || q->items.empty()) {
      // Roll back: restore consumed items to the front, oldest first.
      for (int64_t j = i - 1; j >= 0; --j)
        q->items.emplace_front(reinterpret_cast<char*>(out + j * stride),
                               static_cast<size_t>(lens[j]));
      if (i > 0) q->not_empty.notify_all();
      return !ready ? RQ_TIMEOUT : RQ_CLOSED;
    }
    std::string& item = q->items.front();
    if (static_cast<int64_t>(item.size()) > stride) {
      for (int64_t j = i - 1; j >= 0; --j)
        q->items.emplace_front(reinterpret_cast<char*>(out + j * stride),
                               static_cast<size_t>(lens[j]));
      if (i > 0) q->not_empty.notify_all();
      return RQ_TOO_SMALL;
    }
    std::memcpy(out + i * stride, item.data(), item.size());
    lens[i] = static_cast<int64_t>(item.size());
    q->items.pop_front();
    q->not_full.notify_one();
  }
  return RQ_OK;
}

}  // extern "C"
