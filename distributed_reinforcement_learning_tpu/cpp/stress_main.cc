// Race-detection stress harness for the native data plane.
//
// The reference has NO race detection of any kind (SURVEY §5.2 — its
// concurrency safety is delegated to the TF queue kernel's internal
// locking). This binary hammers the MPMC ring queue and the SumTree
// from many threads and is built with -fsanitize=thread by the `tsan`
// Makefile target; tests/test_native.py builds and runs it and fails on
// any ThreadSanitizer report. Exit 0 + silent stderr = clean.
//
// Workload:
// - ring queue: P producers x C consumers over a small (backpressuring)
//   queue, mixing single gets, batch gets, and a mid-run close; every
//   consumed payload is integrity-checked (first/last byte tag).
// - sum tree: writer threads add/update priorities while reader threads
//   sample — mirrors the learner's ingest-vs-train contention.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
void* rq_create(int64_t capacity);
void rq_destroy(void* h);
int64_t rq_size(void* h);
void rq_close(void* h);
int64_t rq_put(void* h, const uint8_t* data, int64_t len, double timeout_s);
int64_t rq_get(void* h, uint8_t* out, int64_t out_cap, double timeout_s);
int64_t rq_get_batch(void* h, int64_t n, uint8_t* out, int64_t stride,
                     int64_t* lens, double timeout_s);

void* st_create(int64_t capacity);
void st_destroy(void* h);
double st_total(void* h);
void st_add_batch(void* h, const double* priorities, int64_t n, int64_t* slots);
void st_update_batch(void* h, const int64_t* tree_idxs, const double* priorities,
                     int64_t n);
void st_get_batch(void* h, const double* values, int64_t n, int64_t* idxs,
                  double* prios);
}

namespace {

std::atomic<int64_t> consumed{0};
std::atomic<int64_t> corrupt{0};

void check(const uint8_t* buf, int64_t len) {
  // Payload invariant: byte 0 == byte len-1 == tag, middle constant.
  if (len < 3 || buf[0] != buf[len - 1] || buf[1] != 0x5A) corrupt++;
  consumed++;
}

void producer(void* q, int id, int items) {
  uint8_t buf[257];
  for (int i = 0; i < items; ++i) {
    int64_t len = 3 + ((id * 131 + i * 17) % 250);
    uint8_t tag = static_cast<uint8_t>((id * 7 + i) & 0xFF);
    std::memset(buf, 0x5A, sizeof(buf));
    buf[0] = buf[len - 1] = tag;
    while (rq_put(q, buf, len, 0.05) != 0) {
      // timeout under backpressure: retry (close never races puts here;
      // producers all finish before close)
    }
  }
}

void consumer(void* q) {
  uint8_t one[4096];
  uint8_t batch[4 * 4096];
  int64_t lens[4];
  for (;;) {
    // Alternate single and batch pops so both paths race each other.
    // Only the SINGLE get decides termination: it returns RQ_CLOSED
    // strictly after the queue drains, whereas a batch of 4 reports
    // RQ_CLOSED while up to 3 leftovers remain (all-or-nothing).
    int64_t n = rq_get(q, one, sizeof(one), 0.02);
    if (n >= 0) check(one, n);
    if (n == -2) return;  // RQ_CLOSED and drained
    int64_t rc = rq_get_batch(q, 4, batch, 4096, lens, 0.02);
    if (rc == 0) {
      for (int i = 0; i < 4; ++i) check(batch + i * 4096, lens[i]);
    }
  }
}

void tree_writer(void* t, int id, int rounds) {
  double prios[16];
  int64_t slots[16];
  int64_t idxs[16];
  for (int r = 0; r < rounds; ++r) {
    for (int i = 0; i < 16; ++i) prios[i] = 0.1 + ((id + r + i) % 13);
    st_add_batch(t, prios, 16, slots);
    for (int i = 0; i < 16; ++i) idxs[i] = slots[i] + 1024 - 1;
    st_update_batch(t, idxs, prios, 16);
  }
}

void tree_reader(void* t, int rounds) {
  double values[32];
  int64_t idxs[32];
  double prios[32];
  for (int r = 0; r < rounds; ++r) {
    double total = st_total(t);
    if (total <= 0) continue;
    for (int i = 0; i < 32; ++i) values[i] = total * ((i + 0.5) / 32.0);
    st_get_batch(t, values, 32, idxs, prios);
  }
}

}  // namespace

int main() {
  // Ring queue stress.
  void* q = rq_create(8);  // small: constant backpressure
  const int P = 4, C = 3, ITEMS = 2000;
  std::vector<std::thread> threads;
  for (int p = 0; p < P; ++p) threads.emplace_back(producer, q, p, ITEMS);
  std::vector<std::thread> consumers;
  for (int c = 0; c < C; ++c) consumers.emplace_back(consumer, q);
  for (auto& t : threads) t.join();
  rq_close(q);
  for (auto& t : consumers) t.join();
  int64_t got = consumed.load();
  // close() lets consumers drain; every produced item must be consumed.
  if (got != P * ITEMS || corrupt.load() != 0) {
    std::fprintf(stderr, "FAIL ring: consumed=%lld/%d corrupt=%lld\n",
                 static_cast<long long>(got), P * ITEMS,
                 static_cast<long long>(corrupt.load()));
    rq_destroy(q);
    return 1;
  }
  rq_destroy(q);

  // SumTree stress.
  void* t = st_create(1024);
  std::vector<std::thread> tw;
  for (int w = 0; w < 3; ++w) tw.emplace_back(tree_writer, t, w, 500);
  for (int r = 0; r < 2; ++r) tw.emplace_back(tree_reader, t, 800);
  for (auto& th : tw) th.join();
  st_destroy(t);

  std::printf("stress ok: consumed=%lld\n", static_cast<long long>(got));
  return 0;
}
