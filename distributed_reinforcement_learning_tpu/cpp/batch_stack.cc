// Batched trajectory assembly for the learner's ingest hot path.
//
// The native queue's batch pop (`rq_get_batch`, ring_queue.cc) lands N
// codec blobs in ONE contiguous buffer at a fixed stride. All blobs in a
// queue share one schema (fixed unroll shapes — the same invariant the
// reference's fixed-shape queue placeholders encode at
// `distributed_queue/buffer_queue.py:40-50`), so batch assembly is a
// pure gather: for each field, copy its bytes out of every blob into a
// [N, ...] batch-major array. Doing the N*L copies here instead of
// Python (N frombuffer views + L np.stack calls per batch, plus N JSON
// header parses) keeps the single learner host core off the critical
// path — SURVEY §7 hard part (a).
//
// Plain C ABI for ctypes (pybind11 is not in the image).

#include <cstdint>
#include <cstring>

extern "C" {

// 1 iff every blob's first `prefix_len` bytes equal blob 0's. The codec
// header (magic + length + JSON) fully determines the layout, so equal
// prefixes mean the Python caller may parse ONE header for the batch.
int64_t bs_all_equal_prefix(const uint8_t* base, int64_t stride, int64_t n,
                            int64_t prefix_len) {
  for (int64_t i = 1; i < n; ++i) {
    if (std::memcmp(base, base + i * stride, prefix_len) != 0) return 0;
  }
  return 1;
}

// Gather one field: dst[i] = blob_i[src_offset : src_offset + nbytes].
void bs_gather(const uint8_t* base, int64_t stride, int64_t n,
               int64_t src_offset, int64_t nbytes, uint8_t* dst) {
  for (int64_t i = 0; i < n; ++i) {
    std::memcpy(dst + i * nbytes, base + i * stride + src_offset, nbytes);
  }
}

}  // extern "C"
