"""Publish-cadence gating shared by the three learners.

One place for the every-K-steps weight-publication semantics (the
`publish_interval` throughput knob) and its close()-time flush, so the
three runner classes cannot drift apart on them. Mixin contract: the
host class provides `weights`, `state`, `train_steps`,
`publish_interval`, and `timer`.
"""

from __future__ import annotations

import os
import queue as _queue
import threading
import time

from distributed_reinforcement_learning_tpu.observability import TELEMETRY as _OBS


def _async_publish(sync_default: bool) -> bool:
    """Async by default: hand the params D2H + store to the weight
    store's background worker (an on-device copy is the only cost on
    the learn thread) — measured 2316ms -> 3.6ms/step at publish
    interval 1. DRL_ASYNC_PUBLISH=0 restores the synchronous path,
    whose host snapshot doubles as a per-step device sync (useful when
    timing individual steps). An explicit env setting always wins;
    `sync_default` only flips the unset-env default (run_sync loops)."""
    env = os.environ.get("DRL_ASYNC_PUBLISH")
    if env is not None:
        return env.strip().lower() not in ("0", "false", "no", "off", "")
    return not sync_default


class MetricsPump:
    """Background metrics materialization for free-running learners.

    With async publication, the publish-step `float(metric)` becomes the
    learn thread's only device sync — on a thin-pipe host that is a
    hundreds-of-ms stall per publish for numbers only a logger reads.
    The pump takes the DEVICE arrays off the learn thread and floats +
    logs them on a worker. Bounded: at most `depth` batches pending —
    past that submit() blocks, which also caps how far ahead the host
    loop can dispatch device steps.
    """

    # Concurrency map (tools/drlint lock-discipline): empty on purpose,
    # and kept as documentation — the pump owns no lock because all of
    # its mutable attributes (`_thread`, `_logger`, `_prefix`) are
    # touched only by the learn thread (submit/close callers); the
    # internally-synchronized `_q` is the single cross-thread channel,
    # and the worker reads nothing else.
    _GUARDED_BY: dict = {}

    def __init__(self, logger, prefix: str = "learner/", depth: int = 4):
        self._logger = logger
        self._prefix = prefix
        self._q: _queue.Queue = _queue.Queue(maxsize=depth)
        self._thread: threading.Thread | None = None

    def submit(self, metrics: dict, step: int) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="metrics-pump")
            self._thread.start()
        self._q.put((metrics, step))

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            metrics, step = item
            try:
                floats = {k: float(v) for k, v in metrics.items()}
                self._logger.add_scalars(
                    {f"{self._prefix}{k}": v for k, v in floats.items()}, step)
            except Exception as e:  # noqa: BLE001 — logging must not kill training
                import sys

                print(f"[metrics] WARNING: drop step {step}: {e!r}", file=sys.stderr)

    def close(self) -> None:
        if self._thread is not None:
            try:
                # Bounded: a worker wedged inside float(v) (stuck device
                # sync) with a full queue must not hang shutdown forever.
                self._q.put(None, timeout=10.0)
            except _queue.Full:
                pass
            self._thread.join(timeout=10.0)
            self._thread = None


def _async_metrics(sync_default: bool) -> bool:
    """Follows the async-publish gate unless DRL_ASYNC_METRICS overrides.

    Additionally defaults OFF on the CPU backend: there the "device"
    compute shares the host cores, so a metrics worker thread contends
    with the very compute it is trying not to block (measured slower on
    a 1-core host); on TPU/GPU the compute is elsewhere and the float()
    it absorbs is a pure stall."""
    env = os.environ.get("DRL_ASYNC_METRICS")
    if env is not None:
        return env.strip().lower() not in ("0", "false", "no", "off", "")
    import jax

    return jax.default_backend() not in ("cpu",) and _async_publish(sync_default)


class PublishCadenceMixin:
    # Single-threaded run_sync loops set this True: there the learner and
    # actors interleave on one thread, so async publication buys nothing
    # and only makes the weight-staleness sequence nondeterministic.
    sync_publish = False
    # Lazily-created MetricsPump (free-running async-metrics path); the
    # class default keeps __init__-less adoption safe across learners.
    _metrics_pump = None
    # Step count at the last publish. Cadence is "at least every
    # `publish_interval` steps since the last publish", NOT a modulo on
    # train_steps: learners advancing in strides (updates_per_call K, or
    # a partial drain of K' < K) would alias a modulo to lcm(K, interval)
    # — or miss it forever once the counter goes off-grid.
    _last_publish_step = 0

    def maybe_publish(self) -> bool:
        """Publish once `publish_interval` steps accumulate since the last.

        The publish's host snapshot (np.asarray) is the step's device
        sync, so with K>1 the intervening learn steps pipeline on-device
        with no host sync between them. Returns True when it published.
        """
        if self.train_steps - self._last_publish_step < self.publish_interval:
            return False
        self._last_publish_step = self.train_steps
        t0 = time.perf_counter()  # unconditional: telemetry enablement can
        with self.timer.stage("publish"):  # race the post-publish check
            if _async_publish(self.sync_publish):
                # Sub-stages so a fat `publish` mean is attributable: the
                # handoff (device-side copy dispatch) vs the bounded-
                # staleness stall (r4's shm-mode 2278 ms publish row was
                # unexplained for lack of exactly this split).
                with self.timer.stage("publish_handoff"):
                    self.weights.publish_async(self.state.params, self.train_steps)
                # Bounded staleness: latest-wins async publication may
                # drop intermediate versions, but actors must never act
                # on weights more than ~3 publish intervals old (the
                # off-policyness V-trace's truncated-IS correction
                # targets). If the background worker lags past that,
                # wait for it here — the common case never blocks.
                if self.train_steps - self.weights.version > 3 * self.publish_interval:
                    with self.timer.stage("publish_stall"):
                        ok = self.weights.flush_async(timeout=10.0)
                    if not ok:
                        import sys

                        print(f"[publish] WARNING: async weight publication "
                              f"stalled; actors hold version "
                              f"{self.weights.version} at step {self.train_steps}",
                              file=sys.stderr)
            else:
                self.weights.publish(self.state.params, self.train_steps)
        if _OBS.enabled:
            # Learn-thread cost of publication (async: handoff + any
            # bounded-staleness stall; sync: the full D2H). The landed
            # version's timeline is the weights/version gauge.
            _OBS.gauge("publish/latency_ms", (time.perf_counter() - t0) * 1e3)
            _OBS.count("publish/count")
        return True

    def log_step_metrics(self, metrics: dict) -> dict:
        """Per-train-step metrics to the logger WITHOUT stalling the learn
        thread (the replay learners' old unconditional `float()` per step
        was a per-step device sync — the two grandfathered drlint
        baseline entries this method retired). Async mode hands the
        DEVICE arrays to the bounded MetricsPump, which floats + logs
        them on its worker (the returned dict stays un-materialized);
        sync mode floats inline — that deliberate device sync doubles as
        the sync loop's pipelining bound, exactly like ImpalaLearner's —
        and logs host floats."""
        if _async_metrics(self.sync_publish):
            if self._metrics_pump is None:
                self._metrics_pump = MetricsPump(self.logger)
            with self.timer.stage("metrics_sync"):
                self._metrics_pump.submit(dict(metrics), self.train_steps)
            return metrics
        with self.timer.stage("metrics_sync"):
            metrics = {k: float(v) for k, v in metrics.items()}
        self.logger.add_scalars(
            {f"learner/{k}": v for k, v in metrics.items()}, self.train_steps)
        return metrics

    def close_metrics(self) -> None:
        """Drain any pending pump lines at close() (safe when unused)."""
        if self._metrics_pump is not None:
            self._metrics_pump.close()

    def flush_publish(self) -> None:
        """close()-time flush: any updates since the last publish would
        otherwise never reach the store."""
        if self.train_steps > self._last_publish_step:
            self.weights.publish(self.state.params, self.train_steps)
            self._last_publish_step = self.train_steps
        if _async_publish(self.sync_publish):
            # Retire the worker, not just drain it: the learner is the
            # store's only publisher, so past this point the worker
            # would idle on its condvar forever (the sanitizer's leak
            # census flags exactly that). Store close() drains pending
            # then joins; any later publish falls back to the sync path.
            self.weights.close()
