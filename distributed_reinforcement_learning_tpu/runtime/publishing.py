"""Publish-cadence gating shared by the three learners.

One place for the every-K-steps weight-publication semantics (the
`publish_interval` throughput knob) and its close()-time flush, so the
three runner classes cannot drift apart on them. Mixin contract: the
host class provides `weights`, `state`, `train_steps`,
`publish_interval`, and `timer`.
"""

from __future__ import annotations

import os


def _async_publish() -> bool:
    """DRL_ASYNC_PUBLISH=1: hand the params D2H + store to the weight
    store's background worker (an on-device copy is the only cost on the
    learn thread). Off by default — the synchronous publish doubles as
    the step's device sync, which the deterministic tests rely on."""
    return os.environ.get("DRL_ASYNC_PUBLISH", "0") == "1"


class PublishCadenceMixin:
    def maybe_publish(self) -> bool:
        """Publish every `publish_interval`-th train step.

        The publish's host snapshot (np.asarray) is the step's device
        sync, so with K>1 the intervening learn steps pipeline on-device
        with no host sync between them. Returns True when it published.
        """
        if self.train_steps % self.publish_interval != 0:
            return False
        with self.timer.stage("publish"):
            if _async_publish():
                self.weights.publish_async(self.state.params, self.train_steps)
            else:
                self.weights.publish(self.state.params, self.train_steps)
        return True

    def flush_publish(self) -> None:
        """close()-time flush: with interval K and total steps % K != 0
        the last <K updates would otherwise never reach the store."""
        if self.train_steps > 0 and self.train_steps % self.publish_interval != 0:
            self.weights.publish(self.state.params, self.train_steps)
        if _async_publish():
            self.weights.flush_async()
