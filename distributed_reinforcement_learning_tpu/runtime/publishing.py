"""Publish-cadence gating shared by the three learners.

One place for the every-K-steps weight-publication semantics (the
`publish_interval` throughput knob) and its close()-time flush, so the
three runner classes cannot drift apart on them. Mixin contract: the
host class provides `weights`, `state`, `train_steps`,
`publish_interval`, and `timer`.
"""

from __future__ import annotations


class PublishCadenceMixin:
    def maybe_publish(self) -> bool:
        """Publish every `publish_interval`-th train step.

        The publish's host snapshot (np.asarray) is the step's device
        sync, so with K>1 the intervening learn steps pipeline on-device
        with no host sync between them. Returns True when it published.
        """
        if self.train_steps % self.publish_interval != 0:
            return False
        with self.timer.stage("publish"):
            self.weights.publish(self.state.params, self.train_steps)
        return True

    def flush_publish(self) -> None:
        """close()-time flush: with interval K and total steps % K != 0
        the last <K updates would otherwise never reach the store."""
        if self.train_steps > 0 and self.train_steps % self.publish_interval != 0:
            self.weights.publish(self.state.params, self.train_steps)
