"""Zero-copy shared-memory ring transport for co-hosted actors.

The actor -> learner PUT path is the framework's data plane, and PR 1's
telemetry made its cost measurable: every trajectory crosses a loopback
TCP socket (`runtime/transport.py` OP_PUT_TRAJ_N), paying the wire frame,
two kernel copies, and a request/reply RTT even when actor and learner
share a host. ROADMAP asked to "compare against a shared-memory ring for
co-hosted actors" before investing — TorchBeast (arXiv:1910.03552)
showed shared-memory actor<->learner batching is the decisive throughput
lever on one host, and Podracer (arXiv:2104.06272) frames the same
principle for TPU pods: keep the feed path off the kernel network stack
whenever topology allows.

This module is that ring: a lock-free SPSC byte ring over
`multiprocessing.shared_memory` — ONE ring per co-hosted actor (the
actor's process is the single producer, a learner-side drainer thread
the single consumer), carrying framed codec blobs. An actor PUT becomes
a single memcpy into shared memory: no wire frame, no syscalls, no
per-unroll RTT. Control traffic (weight pulls, remote inference, stats,
queue-size polls) stays on the TCP transport.

Memory layout (offsets in the shared segment):

    0    magic u32 | version u32 | capacity u64
    64   head u64   — producer cursor (monotonic byte count, incl. pads)
    128  tail u64   — consumer cursor (monotonic)
    192  producer_closed u32 | consumer_closed u32
    256  data[capacity]

head and tail live on their own cache lines (seqlock-style: each side
OWNS one index and only READS the other); each side additionally caches
the remote index and re-reads it only when the cached value is
insufficient, so the steady-state put/get touches one shared word.
Records are [u32 len][payload] padded to 8 bytes; a record that would
straddle the end of the buffer is preceded by a 0xFFFFFFFF wrap marker
(or, when fewer than 4 bytes remain, an implicit skip both sides
compute) so every blob is one contiguous memcpy on both ends.

Why this is safe without atomics — and WHERE: each index has exactly
one writer; aligned 8-byte stores/loads through a memoryview are single
memcpy calls (not torn by CPython), and the payload bytes are written
before the head store in program order. On x86-64 (every TPU host and
this container) TSO guarantees other cores observe those stores in that
order, so the head store is a valid publish. On weakly-ordered CPUs
(aarch64) that guarantee does NOT hold — pure Python has no portable
store fence — so `ring_enabled()` refuses to auto-enable off x86-64
(DRL_SHM_RING=1 still forces, for single-machine testing), and the
consumer validates every record length against the readable span,
failing LOUDLY (RingClosed -> the actor's TCP fallback) instead of
decoding garbage if a torn publish ever surfaces. Full or empty rings
wait with a bounded spin on the shared index, then escalate to short
sleeps (50us doubling to 1ms) — a cross-process condvar is not
available to independently spawned (non-forked) processes in the
stdlib, and the 1ms worst-case wake latency is far under the TCP RTT
this path replaces.

Lifecycle: the LEARNER creates rings (`serve_rings`, names from
`DRL_SHM_RING_CREATE`), registers an atexit unlink, and drains them
into its `TrajectoryQueue`; the actor attaches by name
(`DRL_SHM_RING_NAME`) with a bounded retry and FALLS BACK to the TCP
queue when the ring never appears or dies mid-run; the local-cluster
launcher additionally reaps the segments after the topology exits, so a
SIGKILLed learner cannot leak /dev/shm. `DRL_SHM_RING` gates the whole
feature: 1 forces on, 0 forces off, unset defers to the committed
`benchmarks/transport_verdict.json` adjudication written from bench.py's
`transport_compare` section (the repo's Pallas-LSTM rule: no
un-adjudicated fast path ships enabled).
"""

from __future__ import annotations

import atexit
import json
import os
import struct
import threading
import time
from typing import Any

from distributed_reinforcement_learning_tpu.observability import TELEMETRY as _OBS
from distributed_reinforcement_learning_tpu.runtime.fleet import ShmReattachMixin
from distributed_reinforcement_learning_tpu.runtime.transport import _LockedStatsMixin

_MAGIC = 0x52494E47  # "RING"
_VERSION = 1
_PID_OFF = 24  # creator pid u64 — shared with the weight-board layouts
_PRESSURE_OFF = 32  # learner admission pressure, u32 permille (consumer
#   writes, producer reads): ring PUTs have no reply payload, so the
#   live backpressure signal TCP actors get on every PUT reply
#   (runtime/transport.py) rides the shared header instead.
_HEAD_OFF = 64
_TAIL_OFF = 128
_PCLOSED_OFF = 192
_CCLOSED_OFF = 196
_DATA_OFF = 256
_WRAP = 0xFFFFFFFF
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_SPIN = 200          # bounded spin before the first sleep
_SLEEP_MIN = 50e-6   # first sleep once the spin budget is burned
_SLEEP_MAX = 1e-3    # backoff cap: worst-case wake latency
# Confirm-before-corrupt budget for the consumer: a record-length
# validation failure is re-checked this many times (fresh head + length
# re-reads; the first _SPIN confirms are back-to-back, the remainder
# sleep with the same 50us->1ms escalation as the empty-ring wait, so
# the full budget spans ~200ms of wall clock) before the ring is
# declared corrupt. Rationale: on some sandboxed kernels (this
# container reports 4.4.0) a cross-process mmap read can TRANSIENTLY
# return stale bytes — observed as a zero head word while the producer
# was thousands of records ahead — and the old fail-fast check turned
# that one stale read into a permanently dropped ring. A real torn
# publish stays torn across every re-read (the ~200ms confirm cost is
# paid once, on the way to a permanent verdict); a stale snapshot
# heals within the window.
_CORRUPT_CONFIRM = 400


def _align8(n: int) -> int:
    return (n + 7) & ~7


class RingClosed(ConnectionError):
    """The other side of the ring is gone (subclasses ConnectionError so
    the actor's elastic-grace loop treats it like a transport outage)."""


def _attach_shm(name: str):
    """Attach an existing segment WITHOUT handing it to this process's
    resource tracker: the creator owns unlink, and (pre-3.13, where
    there is no track=False) an attached process exiting would otherwise
    unlink the segment under the creator or spam tracker warnings."""
    from multiprocessing import shared_memory

    shm = shared_memory.SharedMemory(name=name, create=False)
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")  # noqa: SLF001
    except Exception:  # noqa: BLE001  # drlint: disable=silent-except(tracker internals are stdlib-version-dependent; worst case is a spurious resource_tracker warning at exit, never corruption)
        pass
    return shm


def pid_alive(pid: int) -> bool:
    """Best-effort liveness for the creator-pid word (0 = unknown
    creator, treated as not-alive: only ever consulted for a segment
    bearing OUR name, so reclaiming an unowned homonym is correct)."""
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, just not ours to signal


def segment_owner_pid(name: str) -> int:
    """Creator pid recorded in a ring/board segment header (offset 24 in
    every layout); 0 when unreadable/absent. The launcher's reaper keys
    its sweep on this so it never unlinks a RESPAWNED learner's live
    segment while reaping the dead incarnation's leftovers."""
    try:
        shm = _attach_shm(name)
    except (FileNotFoundError, OSError, ValueError):
        return 0
    try:
        if shm.size < _PID_OFF + 8:
            return 0
        return int(_U64.unpack_from(shm.buf, _PID_OFF)[0])
    finally:
        shm.close()


def create_or_reclaim_shm(name: str, size: int):
    """`SharedMemory(create=True)` that RECLAIMS a stale same-name
    segment whose creator process is dead (the header's pid word,
    offset 24). A SIGKILLed learner leaves its segments in /dev/shm;
    without this, the respawned learner's create fails and the whole
    fast plane silently stays demoted to TCP. A live creator still
    fails the create — two learners must never share a segment name."""
    from multiprocessing import shared_memory

    try:
        return shared_memory.SharedMemory(name=name, create=True, size=size)
    except FileExistsError:
        if pid_alive(segment_owner_pid(name)):
            raise
        import sys

        try:
            stale = _attach_shm(name)
            stale.unlink()
            stale.close()
        except (FileNotFoundError, OSError):
            pass  # raced another reaper: the name may be free now
        print(f"[shm] reclaimed stale segment {name!r} (creator dead)",
              file=sys.stderr)
        return shared_memory.SharedMemory(name=name, create=True, size=size)


class ShmRing:
    """One SPSC ring. Exactly one process calls `put_blob` (the
    producer) and exactly one calls `get_blob` (the consumer); the
    creator additionally owns `unlink`.

    Concurrency map (tools/drlint lock-discipline): deliberately EMPTY
    and kept as documentation — the ring is lock-free by construction.
    Each shared index has a single writer (`_head`: producer,
    `_tail`: consumer), the flags are monotonic one-way latches, and
    every local attribute is touched only by its own side's single
    thread. Cross-thread/-process visibility goes through the shared
    segment, never through Python attributes.
    """

    _GUARDED_BY: dict = {}

    def __init__(self, shm, capacity: int, owner: bool):
        self._shm = shm
        self._buf = shm.buf
        self.capacity = capacity
        self.name = shm.name.lstrip("/")
        self._owner = owner
        self._closed = False
        # Each side's authoritative copy of ITS index plus a cache of the
        # remote one (refreshed only when insufficient).
        self._head = self._read_u64(_HEAD_OFF)
        self._tail = self._read_u64(_TAIL_OFF)
        self._cached_tail = self._tail
        self._cached_head = self._head
        # Confirm-before-corrupt state (consumer-thread-only): persists
        # ACROSS get_blob calls so a short-timeout caller (the drainer's
        # 0.2s polls) still accumulates toward the corrupt verdict on a
        # genuinely torn record instead of restarting the budget every
        # call and spinning on it forever.
        self._suspect = 0  # consecutive failed validations at one tail
        self._confirm_sleep = _SLEEP_MIN

    # -- construction -----------------------------------------------------

    @classmethod
    def create(cls, name: str, capacity: int) -> "ShmRing":
        capacity = _align8(max(capacity, 4096))
        # create_or_reclaim: a respawned learner re-creates its rings
        # under the SAME names; the dead incarnation's stale segment
        # (SIGKILL skipped the unlink) is reclaimed by creator-pid.
        shm = create_or_reclaim_shm(name, _DATA_OFF + capacity)
        ring = cls(shm, capacity, owner=True)
        # Magic is written LAST: it is the header's commit word, so an
        # attacher racing this constructor either sees no magic (and
        # retries) or a fully-initialized header — never a zero capacity.
        ring._write_u64(8, capacity)
        ring._write_u64(_PID_OFF, os.getpid())
        ring._write_u64(_HEAD_OFF, 0)
        ring._write_u64(_TAIL_OFF, 0)
        ring._write_u32(_PCLOSED_OFF, 0)
        ring._write_u32(_CCLOSED_OFF, 0)
        ring._write_u32(_PRESSURE_OFF, 0)
        ring._write_u32(4, _VERSION)
        ring._write_u32(0, _MAGIC)
        return ring

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        shm = _attach_shm(name)
        view = shm.buf
        magic = _U32.unpack_from(view, 0)[0]
        version = _U32.unpack_from(view, 4)[0]
        capacity = int(_U64.unpack_from(view, 8)[0])
        # Capacity/segment-size validation doubles as the race guard for
        # the commit-word scheme above: a half-written header can never
        # hand back a usable-looking ring.
        if (magic != _MAGIC or version != _VERSION or capacity <= 0
                or shm.size < _DATA_OFF + capacity):
            shm.close()
            raise ValueError(f"{name}: not an initialized v{_VERSION} shm ring")
        return cls(shm, capacity, owner=False)

    # -- raw header access -------------------------------------------------

    def _read_u32(self, off: int) -> int:
        return _U32.unpack_from(self._buf, off)[0]

    def _write_u32(self, off: int, value: int) -> None:
        _U32.pack_into(self._buf, off, value)

    def _read_u64(self, off: int) -> int:
        return _U64.unpack_from(self._buf, off)[0]

    def _write_u64(self, off: int, value: int) -> None:
        _U64.pack_into(self._buf, off, value)

    @property
    def creator_pid(self) -> int:
        """The creating process's pid (header word): reattach probes
        validate a reappeared segment belongs to the CURRENT learner
        incarnation, not the dead one's un-reaped corpse."""
        return int(self._read_u64(_PID_OFF))

    @property
    def producer_closed(self) -> bool:
        return self._read_u32(_PCLOSED_OFF) != 0

    @property
    def consumer_closed(self) -> bool:
        return self._read_u32(_CCLOSED_OFF) != 0

    def set_pressure(self, permille: int) -> None:
        """Consumer-side: publish the learner's live ingest pressure
        (0..1000 permille) into the shared header — the ring's
        equivalent of the u16 the TCP server appends to PUT replies.
        Single writer (the drain thread), word-sized: tearing-free."""
        self._write_u32(_PRESSURE_OFF, max(0, min(1000, int(permille))))

    def pressure(self) -> int:
        """Producer-side: the last pressure permille the consumer
        published (0 until it ever does)."""
        return int(self._read_u32(_PRESSURE_OFF))

    def used_bytes(self) -> int:
        """Bytes in flight (includes framing/padding) — the `ring/depth`
        telemetry signal; safe to poll from any thread."""
        return max(self._read_u64(_HEAD_OFF) - self._read_u64(_TAIL_OFF), 0)

    # -- producer side -----------------------------------------------------

    def put_blob(self, blob, timeout: float | None = None) -> bool:
        """One framed memcpy into the ring. Blocks (bounded spin, then
        sleeps) while full; False on timeout; RingClosed once the
        consumer is gone. The caller's buffer is consumed by value — it
        may be reused the moment this returns."""
        if self.consumer_closed:  # fail fast, not only once full
            raise RingClosed(f"ring {self.name}: consumer closed")
        n = len(blob)
        rec = _align8(4 + n)
        if 2 * rec > self.capacity:
            raise ValueError(
                f"blob of {n} bytes cannot fit a {self.capacity}-byte ring "
                f"(need 2*{rec} <= capacity for guaranteed progress)")
        pos = self._head % self.capacity
        to_end = self.capacity - pos
        if to_end < 4:
            skip = to_end          # no room for a wrap marker: implicit
            start, marker = 0, False  # skip both sides compute from pos
        elif to_end < rec:
            skip = to_end
            start, marker = 0, True
        else:
            skip = 0
            start, marker = pos, False
        total = skip + rec
        deadline = None if timeout is None else time.monotonic() + timeout
        waited_since: float | None = None
        spins = 0
        sleep_s = _SLEEP_MIN
        while self.capacity - (self._head - self._cached_tail) < total:
            if self.consumer_closed:
                raise RingClosed(f"ring {self.name}: consumer closed")
            self._cached_tail = self._read_u64(_TAIL_OFF)
            if self.capacity - (self._head - self._cached_tail) >= total:
                break
            if waited_since is None:
                waited_since = time.perf_counter()
            spins += 1
            if spins <= _SPIN:
                continue
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(sleep_s)
            sleep_s = min(2 * sleep_s, _SLEEP_MAX)
        if marker:
            self._write_u32(_DATA_OFF + pos, _WRAP)
        self._write_u32(_DATA_OFF + start, n)
        if n:
            self._buf[_DATA_OFF + start + 4:_DATA_OFF + start + 4 + n] = blob
        # Publish AFTER the payload bytes: the head store is the commit.
        self._head += total
        self._write_u64(_HEAD_OFF, self._head)
        if _OBS.enabled:
            _OBS.count("ring/bytes_total", n)
            if waited_since is not None:
                _OBS.gauge("ring/full_wait_ms",
                           (time.perf_counter() - waited_since) * 1e3)
        return True

    def close_producer(self) -> None:
        """Latch 'no more blobs' so the consumer can drain-and-stop."""
        self._write_u32(_PCLOSED_OFF, 1)

    # -- consumer side -----------------------------------------------------

    def get_blob(self, timeout: float | None = None) -> bytes | None:
        """Pop one blob (copied out of the segment, so the slot frees
        immediately); None on timeout. `drained()` distinguishes a
        producer that is gone from one that is merely quiet."""
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        sleep_s = _SLEEP_MIN
        while True:
            if self._cached_head == self._tail:
                self._cached_head = self._read_u64(_HEAD_OFF)
                if self._cached_head == self._tail:
                    spins += 1
                    if spins <= _SPIN:
                        continue
                    if deadline is not None and time.monotonic() >= deadline:
                        return None
                    time.sleep(sleep_s)
                    sleep_s = min(2 * sleep_s, _SLEEP_MAX)
                    continue
            pos = self._tail % self.capacity
            to_end = self.capacity - pos
            if to_end < 4:
                self._tail += to_end  # implicit skip (mirrors the producer)
                self._write_u64(_TAIL_OFF, self._tail)
                continue
            n = self._read_u32(_DATA_OFF + pos)
            if n == _WRAP:
                self._tail += to_end
                self._write_u64(_TAIL_OFF, self._tail)
                self._suspect = 0  # tail advanced: suspicion resolved
                self._confirm_sleep = _SLEEP_MIN
                continue
            if n == 0 and self._suspect <= _CORRUPT_CONFIRM:
                # A zero length here is almost certainly the same stale
                # read as above (no plane ships empty blobs), and unlike
                # an oversize length it would pass validation and DESYNC
                # the framing. Confirm through the same budget; a zero
                # that persists is a genuine empty record and falls
                # through to normal consumption.
                self._suspect += 1
                self._cached_head = self._read_u64(_HEAD_OFF)
                if deadline is not None and time.monotonic() >= deadline:
                    return None  # confirm state persists to the next call
                if self._suspect > _SPIN:
                    time.sleep(self._confirm_sleep)
                    self._confirm_sleep = min(2 * self._confirm_sleep,
                                              _SLEEP_MAX)
                continue
            if _align8(4 + n) > to_end or \
                    self._tail + _align8(4 + n) > self._cached_head:
                # A length that overruns the readable span is EITHER a
                # real torn publish (weakly-ordered CPU without
                # DRL_SHM_RING forced — module docstring) or a stale
                # cross-process read (this container's kernel: observed
                # zero head words; _CORRUPT_CONFIRM comment). CONFIRM
                # before the nuclear verdict: refresh the head snapshot
                # and re-read the length; only a validation failure that
                # SURVIVES the whole confirm budget drops the ring.
                self._suspect += 1
                if self._suspect <= _CORRUPT_CONFIRM:
                    self._cached_head = self._read_u64(_HEAD_OFF)
                    if deadline is not None and time.monotonic() >= deadline:
                        return None  # confirm state persists to next call
                    if self._suspect > _SPIN:
                        time.sleep(self._confirm_sleep)
                        self._confirm_sleep = min(2 * self._confirm_sleep,
                                                  _SLEEP_MAX)
                    continue
                self.close_consumer()
                raise RingClosed(
                    f"ring {self.name}: corrupt record length {n} at "
                    f"tail {self._tail} (torn publish? confirmed "
                    f"{_CORRUPT_CONFIRM}x)")
            self._suspect = 0
            self._confirm_sleep = _SLEEP_MIN
            start = _DATA_OFF + pos + 4
            blob = bytes(self._buf[start:start + n])
            self._tail += _align8(4 + n)
            self._write_u64(_TAIL_OFF, self._tail)
            return blob

    def drained(self) -> bool:
        """True only when the producer latched closed AND everything it
        published has been consumed (flag read BEFORE the final head
        re-read, so a put racing the close is never missed)."""
        if not self.producer_closed:
            return False
        return self._read_u64(_HEAD_OFF) == self._tail

    def close_consumer(self) -> None:
        """Latch 'stop producing' so a blocked producer fails fast."""
        self._write_u32(_CCLOSED_OFF, 1)

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Release this process's mapping (idempotent; both sides)."""
        if self._closed:
            return
        self._closed = True
        self._buf = None
        self._shm.close()

    def unlink(self) -> None:
        """Remove the segment from /dev/shm (creator only; idempotent)."""
        if not self._owner:
            return
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass


# -- adjudication gate -------------------------------------------------------

_VERDICT_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "benchmarks", "transport_verdict.json")


def ring_auto_enabled(verdict_path: str = _VERDICT_PATH) -> bool:
    """The committed `transport_compare` verdict (bench.py): rings ship
    enabled-by-default only if the A/B showed >= 1.2x TCP PUT
    throughput, mirroring the repo's Pallas-LSTM adjudication bar."""
    try:
        with open(verdict_path) as f:
            return bool(json.load(f).get("auto_enable", False))
    except (OSError, ValueError):
        return False


def ring_enabled() -> bool:
    """DRL_SHM_RING=1 forces rings on, =0 off; unset/auto defers to the
    committed adjudication — but never auto-enables off x86-64, where
    the ring's store-ordering argument does not hold (module docstring);
    the corrupt-record check + TCP fallback make a forced =1 survivable
    for single-machine experimentation there."""
    env = os.environ.get("DRL_SHM_RING", "").strip().lower()
    if env in ("1", "true", "yes", "on"):
        return True
    if env in ("0", "false", "no", "off"):
        return False
    import platform

    if platform.machine().lower() not in ("x86_64", "amd64"):
        return False
    return ring_auto_enabled()


def ring_capacity_bytes() -> int:
    return int(float(os.environ.get("DRL_SHM_RING_MB", "64")) * 1e6)


# -- learner side: create + drain into the TrajectoryQueue -------------------


class RingDrainer(_LockedStatsMixin):
    """One thread per ring popping blobs into the learner's bounded
    queue — the learner-side half of the zero-copy PUT path. Ingest
    semantics are shared with the TCP server via `fifo.blob_ingest`
    (raw bytes for blob-native queues, a decoded copy otherwise), so the
    two transports cannot drift on what lands in the queue. Under
    DRL_REPLAY_SHARDS the "queue" is the replay-shard facade
    (runtime/replay_shard.py): the same seam then makes each drain
    thread the owner of a replay shard — decode + initial priority +
    insert happen right here instead of on the learner thread."""

    # Concurrency map (tools/drlint lock-discipline): the per-ring drain
    # threads bump `stats` while telemetry providers and stop() read it
    # from other threads (accessors from transport._LockedStatsMixin,
    # the same locked-stats contract the TCP server/client use), and
    # `_dropped` is written by a drain thread on corruption while the
    # telemetry flush thread reads it in depth_bytes. Rings themselves
    # are SPSC (each drain thread is the sole consumer of its ring) and
    # `_threads` is written once in start() before the threads exist,
    # then only read.
    _GUARDED_BY = {"stats": "_stats_lock", "_dropped": "_stats_lock"}
    _NOT_GUARDED = {
        "_threads": "written once in start() before the drain threads "
                    "exist, then only read (see map comment above)",
    }

    def __init__(self, rings: list[ShmRing], queue):
        self.rings = rings
        self.queue = queue
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self.stats = {"unrolls_drained": 0, "bytes_drained": 0}
        self._dropped: set[int] = set()  # ids of corrupt, abandoned rings
        self._stats_lock = threading.Lock()

    def depth_bytes(self) -> int:
        """Summed in-flight bytes across LIVE rings (the `ring/depth`
        provider): a corruption-dropped ring's never-to-drain backlog
        must not render as a frozen stall in obs_report."""
        with self._stats_lock:
            dropped = set(self._dropped)
        return sum(r.used_bytes() for r in self.rings
                   if id(r) not in dropped)

    def start(self) -> "RingDrainer":
        self._threads = [
            threading.Thread(target=self._drain_loop, args=(ring,),
                             daemon=True, name=f"ring-drain-{i}")
            for i, ring in enumerate(self.rings)
        ]
        for t in self._threads:
            t.start()
        return self

    def _drain_loop(self, ring: ShmRing) -> None:
        import time as _time

        from distributed_reinforcement_learning_tpu.data.fifo import blob_ingest

        prepare, put = blob_ingest(self.queue)
        # Backpressure parity with TCP actors: the sharded-ingest facade
        # exposes the learner's live pressure permille (the value the
        # TCP server appends to PUT replies); publish it through the
        # ring header so co-hosted producers run the SAME admission
        # ladder. Throttled — a header word per ~100ms, not per blob.
        pressure = getattr(self.queue, "ingest_pressure", None)
        last_pub = 0.0
        while not self._stop.is_set():
            if pressure is not None:
                now = _time.monotonic()
                if now - last_pub >= 0.1:
                    last_pub = now
                    ring.set_pressure(pressure())
            try:
                blob = ring.get_blob(timeout=0.2)
            except RingClosed as e:  # corrupt record: drop the ring, the
                import sys           # producer demotes itself to TCP

                print(f"[shm_ring] WARNING: {e}; ring dropped",
                      file=sys.stderr)
                with self._stats_lock:  # hide its backlog from ring/depth
                    self._dropped.add(id(ring))
                return
            if blob is None:
                if ring.drained():
                    return
                continue
            item = prepare(blob)
            try:
                # _stop-aware slices, like the TCP server's _enqueue: the
                # bounded queue's backpressure propagates to the ring
                # (which fills, blocking the actor) instead of dropping.
                while not self._stop.is_set():
                    if put(item, timeout=0.5):
                        self._bump("unrolls_drained")
                        self._bump("bytes_drained", len(blob))
                        break
            except RuntimeError:  # queue closed: learner shutting down
                return

    def stop(self) -> None:
        self._stop.set()
        for ring in self.rings:
            ring.close_consumer()
        for t in self._threads:
            t.join(timeout=2.0)
        for ring in self.rings:
            ring.close()
            ring.unlink()


def serve_rings(names: list[str], queue) -> RingDrainer | None:
    """Learner-side wiring: create one ring per co-hosted actor and start
    the drainer. Returns None (TCP-only operation continues) if any
    segment cannot be created — the ring is an optimization, never a
    prerequisite. Created segments are unlinked at stop() and again via
    atexit (crash backstop)."""
    import sys

    rings: list[ShmRing] = []
    capacity = ring_capacity_bytes()
    try:
        for name in names:
            rings.append(ShmRing.create(name, capacity))
    except (OSError, ValueError) as e:
        print(f"[shm_ring] WARNING: cannot create ring segments ({e}); "
              f"staying on TCP", file=sys.stderr)
        for ring in rings:
            ring.close()
            ring.unlink()
        return None
    drainer = RingDrainer(rings, queue).start()
    atexit.register(lambda: [r.unlink() for r in rings])
    return drainer


# -- actor side: put surface with graceful TCP fallback ----------------------


class RingQueue(_LockedStatsMixin, ShmReattachMixin):
    """The actor-runner queue surface (`put`/`put_many`/`size`) with the
    DATA plane on a shm ring and the CONTROL plane (queue-size polls) on
    the TCP client. Mirrors `RemoteQueue` semantics: puts block under
    backpressure, a wedged learner surfaces as ConnectionError after
    `full_timeout`, and a dead ring (consumer closed — learner gone or
    restarted) demotes this queue to the TCP path rather than killing
    the actor. Demotion is no longer permanent: `reattach()` (driven
    from the fleet heartbeat cadence, runtime/fleet.py) re-attaches the
    SAME ring name on a bounded RetryLadder once a respawned learner
    re-creates the segment — validated fresh (neither side latched
    closed) and belonging to the CURRENT learner incarnation (the
    header's creator-pid word against the heartbeat-reported pid), so
    the probe can never re-adopt the dead incarnation's corpse.

    Concurrency map (tools/drlint lock-discipline): `stats` is bumped on
    the actor loop thread and polled by the telemetry flush thread's
    providers (accessors from transport._LockedStatsMixin). `_ring` is
    swapped by the actor loop thread (demote/close) AND the heartbeat
    thread (reattach install), so the reference lives under `_lock`;
    the ring OBJECT stays actor-thread-only — the heartbeat thread only
    installs a fresh attach it has not used, never touches an installed
    one.
    """

    _GUARDED_BY = {"stats": "_stats_lock", "_ring": "_lock",
                   "_closed": "_lock", "_stale": "_lock"}
    _NOT_GUARDED = {
        "_admission": "set once by the owning actor runner "
                      "(set_admission) before the publish thread starts; "
                      "read-only on the put paths thereafter",
    }

    surface_name = "ring"  # fleet heartbeat registration label

    def __init__(self, ring: ShmRing | None, client,
                 full_timeout: float = 90.0, name: str | None = None):
        from distributed_reinforcement_learning_tpu.runtime.fleet import RetryLadder

        self._closed = False
        self._stale = False  # heartbeat-flagged: demote on next put
        self._ring: ShmRing | None = ring
        self._name = name or (ring.name if ring is not None else None)
        self._client = client
        self.full_timeout = full_timeout
        self._lock = threading.Lock()
        self._ladder = RetryLadder(f"ring-{self._name}")
        self.stats = {"unrolls_sent": 0, "bytes_sent": 0, "tcp_fallbacks": 0,
                      "reattaches": 0, "unrolls_admission_dropped": 0}
        self._stats_lock = threading.Lock()
        self._admission = None  # data/admission.AdmissionController —
        #   set once by the owning runner before the publish thread
        #   starts (see set_admission), read-only on put paths after

    def set_admission(self, controller) -> None:
        """Attach an actor-side admission controller
        (data/admission.AdmissionController): ring PUTs score + stamp
        each unroll, and each PUT feeds the controller the learner's
        live pressure permille from the ring header's pressure word
        (published by the drain thread) — the same admission ladder TCP
        actors drive from PUT-reply pressure. `DRL_ADMISSION_PRESSURE`
        still overrides both; the demote-to-TCP path falls back to
        plain (learner-scored) PUTs."""
        self._admission = controller

    @property
    def attached(self) -> bool:
        """True when PUTs currently ride shared memory (False while
        demoted to TCP — including a demoted-at-birth queue that has
        not yet won a reattach probe)."""
        with self._lock:
            return self._ring is not None

    def _ring_ref(self) -> ShmRing | None:
        """The attached ring, or None — handling a heartbeat-flagged
        STALE attachment by demoting here, on the actor thread (the
        ring object is actor-thread-owned; the heartbeat thread never
        closes it, only flags it)."""
        with self._lock:
            ring, stale = self._ring, self._stale
        if ring is not None and stale:
            self._demote(reason=f"ring {self._name!r} belongs to a dead "
                                f"learner incarnation")
            return None
        return ring

    def _demote(self, reason: str = "ring closed under the actor") -> None:
        import sys

        with self._lock:
            ring, self._ring = self._ring, None
            self._stale = False
        if ring is not None:
            ring.close()
        self._bump("tcp_fallbacks")
        print(f"[shm_ring] WARNING: {reason}; "
              f"falling back to TCP PUTs", file=sys.stderr)

    # -- reattach (fleet.ShmReattachMixin template) -----------------------
    # The stale-attach consequence here: a SIGKILLed learner latches
    # nothing, so the actor would otherwise keep memcpying unrolls into
    # the dead incarnation's orphan segment forever — a trajectory
    # black hole no put-side error ever surfaces. The actor thread
    # demotes on its next put via _ring_ref.

    _ref_attr = "_ring"

    def _probe_attach(self):
        return ShmRing.attach(self._name)

    def _probe_fresh(self, ring, expect) -> bool:
        return (not ring.consumer_closed
                and not ring.producer_closed
                and (expect is None or ring.creator_pid == expect))

    def _on_reattached(self) -> None:
        import sys

        print(f"[shm_ring] ring {self._name!r} re-attached; PUTs back on "
              f"shared memory", file=sys.stderr)

    def reset_reattach(self) -> None:
        """Fresh probe budget (learner epoch change)."""
        self._ladder.reset()

    def _put_blob(self, ring: ShmRing, blob) -> None:
        if not ring.put_blob(blob, timeout=self.full_timeout):
            # Learner alive but the ring stayed full through the whole
            # window: the ring analogue of the TCP client's busy_timeout.
            raise ConnectionError(
                f"ring full for >{self.full_timeout:.0f}s (wedged learner?)")
        self._bump("unrolls_sent")
        self._bump("bytes_sent", len(blob))

    def put(self, item: Any, timeout: float | None = None) -> bool:
        from distributed_reinforcement_learning_tpu.data import codec

        ring = self._ring_ref()
        if ring is None:
            return self._client.put_trajectory(item)
        if self._admission is not None:
            # Header pressure word -> admission ladder (the ring-path
            # mirror of the TCP client's PUT-reply observe_pressure).
            self._admission.observe_pressure(ring.pressure())
        try:
            # Same dedup gating as the TCP client's trajectory PUTs: the
            # drainer's blob_ingest reconstructs before the queue.
            blob = self._admitted_blob(item, codec)
            if blob is None:  # dropped at source (mass folded)
                return True
            self._put_blob(ring, blob)
            return True
        except (RingClosed, ValueError):
            # ValueError = blob too large for this ring's capacity: TCP
            # has no such limit, so demote instead of killing the actor.
            self._demote()
            return self._client.put_trajectory(item)

    def put_many(self, items: list[Any], timeout: float | None = None) -> int:
        from distributed_reinforcement_learning_tpu.data import codec

        ring = self._ring_ref()
        if ring is None:
            return self._client.put_trajectories(items)
        if self._admission is not None:
            self._admission.observe_pressure(ring.pressure())
        sent = 0
        for item in items:
            try:
                blob = self._admitted_blob(item, codec)
                if blob is None:  # dropped at source (mass folded)
                    sent += 1
                    continue
                self._put_blob(ring, blob)
                sent += 1
            except (RingClosed, ValueError):  # dead ring / oversize blob
                self._demote()
                return sent + self._client.put_trajectories(items[sent:])
        return sent

    def _admitted_blob(self, item: Any, codec):
        """Encode one unroll for the ring, applying admission + the
        priority stamp when a controller is attached. None = the
        controller dropped the unroll whole."""
        ctrl = self._admission
        dedup = codec.obs_dedup_enabled()
        if ctrl is None:
            return codec.encode(item, dedup=dedup)
        decision = ctrl.admit(item)
        if not decision.send:
            self._bump("unrolls_admission_dropped")
            return None
        tree = item if decision.tree is None else decision.tree
        blob = codec.stamp_blob(codec.encode(tree, dedup=dedup),
                                decision.stamp)
        ctrl.note_wire(len(blob), decision)
        return blob

    def size(self) -> int:
        return self._client.queue_size()

    def close(self) -> None:
        with self._lock:
            ring, self._ring = self._ring, None
            self._closed = True  # a late reattach must not resurrect us
        if ring is not None:
            ring.close()


def attach_ring_queue(name: str, client,
                      deadline_s: float | None = None) -> RingQueue | None:
    """Actor-side wiring: attach the named ring with a bounded retry and
    wrap it in a RingQueue. None = fall back to the plain TCP queue.

    The window is deliberately SHORT: this runs after the TransportClient
    connected, and the learner creates its rings milliseconds after its
    server starts accepting — so a missing segment a few seconds past
    connect almost certainly means the learner declined (creation
    failed, e.g. an undersized /dev/shm) and a long wait would only
    delay every actor's start in an already-degraded run.

    With the fleet plane on, attach failure returns a DEMOTED-AT-BIRTH
    RingQueue (ring=None, name kept): PUTs ride TCP immediately, but
    the queue still exposes `reattach()` so the heartbeat-driven ladder
    can promote it once the segment appears — an actor respawned
    DURING a learner outage must not be stranded on TCP forever."""
    import sys

    from distributed_reinforcement_learning_tpu.runtime import fleet

    if deadline_s is None:
        deadline_s = float(os.environ.get("DRL_SHM_RING_ATTACH_S", "5"))
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            return RingQueue(ShmRing.attach(name), client)
        except (FileNotFoundError, ValueError) as e:
            if time.monotonic() >= deadline:
                if fleet.fleet_enabled():
                    print(f"[shm_ring] WARNING: cannot attach ring "
                          f"{name!r} ({e}); starting demoted to TCP "
                          f"(reattach ladder armed)", file=sys.stderr)
                    return RingQueue(None, client, name=name)
                print(f"[shm_ring] WARNING: cannot attach ring {name!r} "
                      f"({e}); falling back to TCP", file=sys.stderr)
                return None
            time.sleep(0.2)
