"""Anakin Ape-X: prioritized DQN training entirely on-device.

The third on-device family (after `runtime/anakin.py` IMPALA and
`runtime/anakin_r2d2.py` recurrent replay): the reference's
`train_apex.py` stack — epsilon-ladder actors pushing TD-scored
transitions into prioritized replay, a double-DQN learner with IS
weights and target syncs — expressed as one compiled program over a
jittable env. With the pixel envs (`envs/{breakout,pong}_jax.py`) this
trains the dueling conv network on real game dynamics at chip rate,
replay included: the transition ring (uint8 frame stacks) lives in
device memory via `data/device_replay.py`.

Semantics:
- actors: per-episode epsilon decay `1/(0.05*episodes+1)` (the
  reference's schedule, `train_apex.py:69`) with an optional floor;
  life-loss boundaries arrive as `done` from the pixel envs exactly as
  the host path's life-loss shaping records them;
- transitions: (s, prev_a, a, r, s', done) — `prev_a` embeds for s and
  `a` for s' (`agents/apex.py` ApexBatch contract); the auto-reset
  observation standing in for a terminal s' is harmless (its Q is
  masked by the zero discount);
- ingest scored by `agent.td_error` under current params; sampled
  priorities refreshed every step; IS-weighted double-DQN updates;
  target syncs on a steps-since-last cadence.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from distributed_reinforcement_learning_tpu.agents.apex import ApexAgent, ApexBatch
from distributed_reinforcement_learning_tpu.data import device_replay
from distributed_reinforcement_learning_tpu.data.device_replay import DeviceReplay
from distributed_reinforcement_learning_tpu.envs import cartpole_jax
from distributed_reinforcement_learning_tpu.runtime.anakin_mesh import (
    DataMeshReplayMixin,
    batched_specs,
    replay_specs,
)
from distributed_reinforcement_learning_tpu.parallel.mesh import DATA_AXIS as _DATA_AXIS, P


class AnakinApexState(NamedTuple):
    train: Any  # common.TargetTrainState
    replay: DeviceReplay
    env: Any
    obs: jax.Array
    prev_action: jax.Array
    episodes: jax.Array  # [B] i32 (epsilon schedule)
    last_sync: jax.Array  # i32 train step of the last target sync
    rng: jax.Array


class AnakinApex(DataMeshReplayMixin):
    """Ape-X over a pure-JAX env with on-device prioritized replay.

    Each update collects `steps_per_collect` transitions from all
    `num_envs` envs (write width W = num_envs * steps_per_collect;
    `capacity` must be a multiple of W), then runs
    `updates_per_collect` prioritized batches.
    """

    def __init__(self, agent: ApexAgent, num_envs: int, batch_size: int = 32,
                 capacity: int = 8192, steps_per_collect: int = 16,
                 target_sync_interval: int = 100, updates_per_collect: int = 1,
                 epsilon_decay: float = 0.05, epsilon_floor: float = 0.0,
                 env=None, obs_transform=None, mesh=None):
        self.env = env if env is not None else cartpole_jax
        self.agent = agent
        self.num_envs = num_envs
        self.batch_size = batch_size
        self.steps_per_collect = steps_per_collect
        self.write_width = num_envs * steps_per_collect
        if capacity % self.write_width != 0:
            raise ValueError(
                f"capacity ({capacity}) must be a multiple of num_envs * "
                f"steps_per_collect ({self.write_width})")
        self.capacity = capacity
        self.target_sync_interval = target_sync_interval
        if updates_per_collect > target_sync_interval:
            raise ValueError(
                f"updates_per_collect ({updates_per_collect}) must not "
                f"exceed target_sync_interval ({target_sync_interval})")
        self.updates_per_collect = updates_per_collect
        self.epsilon_decay = epsilon_decay
        self.epsilon_floor = epsilon_floor
        self.obs_transform = obs_transform or (lambda x: x)
        if agent.cfg.num_actions < self.env.NUM_ACTIONS:
            raise ValueError(
                f"Q head ({agent.cfg.num_actions}) narrower than the env's "
                f"action set ({self.env.NUM_ACTIONS})")
        # Multi-chip: shard over the `data` axis ONLY, with PER-DEVICE
        # replay shards (see _state_specs). The replay families scale by
        # replicating the (small) dueling net and splitting envs + ring;
        # a global prioritized sampler over a capacity-sharded ring would
        # serialize every learn batch behind cross-chip gathers of frame
        # stacks, so each device samples its own shard locally and only
        # the GRADIENTS cross ICI (pmean in agents/apex.py _learn).
        # Tensor/pipeline axes stay with the IMPALA/transformer families.
        self._setup_mesh(mesh, num_envs=num_envs, batch_size=batch_size,
                         capacity=capacity)
        self.write_width_local = self.write_width // self.dshard
        self._greedy_eval_jit = jax.jit(self._greedy_eval,
                                        static_argnums=(1, 2))

    # -- sharding --------------------------------------------------------
    def _state_specs(self) -> AnakinApexState:
        """PartitionSpecs: per-env leaves and the replay rings shard over
        `data`; the TrainState and ring bookkeeping replicate (see
        runtime/anakin_mesh.py for the design argument)."""
        train_abs = jax.eval_shape(self.agent.init_state, jax.random.PRNGKey(0))
        env_abs, _ = jax.eval_shape(
            lambda k: self.env.reset(k, self.num_envs), jax.random.PRNGKey(0))
        return AnakinApexState(
            train=jax.tree.map(lambda _: P(), train_abs),
            replay=replay_specs(ApexBatch(0, 0, 0, 0, 0, 0)),
            env=batched_specs(env_abs),
            obs=P(_DATA_AXIS), prev_action=P(_DATA_AXIS),
            episodes=P(_DATA_AXIS), last_sync=P(),
            rng=P(_DATA_AXIS),
        )

    # -- init ------------------------------------------------------------
    def init(self, rng: jax.Array) -> AnakinApexState:
        k_train, k_env, k_run = jax.random.split(rng, 3)
        train = self.agent.init_state(k_train)
        env, obs = self.env.reset(k_env, self.num_envs)
        obs = self.obs_transform(obs)
        replay = device_replay.make(self._zero_transitions(obs), self.capacity)
        state = AnakinApexState(
            train=train, replay=replay, env=env, obs=obs,
            prev_action=jnp.zeros(self.num_envs, jnp.int32),
            episodes=jnp.zeros(self.num_envs, jnp.int32),
            last_sync=jnp.int32(0),
            rng=k_run,
        )
        return self._place_init(state, k_run)

    def _zero_transitions(self, obs: jax.Array) -> ApexBatch:
        C = self.capacity
        return ApexBatch(
            state=jnp.zeros((C, *obs.shape[1:]), obs.dtype),
            next_state=jnp.zeros((C, *obs.shape[1:]), obs.dtype),
            previous_action=jnp.zeros((C,), jnp.int32),
            action=jnp.zeros((C,), jnp.int32),
            reward=jnp.zeros((C,), jnp.float32),
            done=jnp.zeros((C,), bool),
        )

    # -- collection ------------------------------------------------------
    def _epsilon(self, episodes: jax.Array) -> jax.Array:
        return jnp.maximum(1.0 / (self.epsilon_decay * episodes + 1.0),
                           self.epsilon_floor)

    def _env_step(self, params, carry, _):
        env, obs, prev_action, episodes, rng = carry
        rng, k_act, k_env = jax.random.split(rng, 3)
        action, _q = self.agent._act(
            params, obs, prev_action, self._epsilon(episodes), k_act)
        env_action = (action % self.env.NUM_ACTIONS
                      if self.agent.cfg.num_actions != self.env.NUM_ACTIONS
                      else action)
        env, next_obs, reward, done, ep_ret = self.env.step(env, env_action, k_env)
        next_obs = self.obs_transform(next_obs)
        mask_fn = getattr(self.env, "completed_episode_mask",
                          lambda done, _state: done)
        record = dict(
            state=obs, next_state=next_obs, previous_action=prev_action,
            action=action, reward=reward, done=done,
            episode_return=ep_ret, episode_completed=mask_fn(done, env),
        )
        carry = (env, next_obs, jnp.where(done, 0, action).astype(jnp.int32),
                 episodes + done.astype(jnp.int32), rng)
        return carry, record

    def _collect(self, state: AnakinApexState):
        """steps_per_collect env steps -> (state', flat ApexBatch [W],
        episode stats). Under a mesh this body runs per-device on the
        local env shard, so the flat width is the LOCAL one."""
        carry = (state.env, state.obs, state.prev_action, state.episodes,
                 state.rng)
        carry, rec = jax.lax.scan(
            functools.partial(self._env_step, state.train.params), carry,
            None, length=self.steps_per_collect)
        env, obs, prev_action, episodes, rng = carry
        flat = lambda name: rec[name].reshape((self.write_width_local,)
                                              + rec[name].shape[2:])
        batch = ApexBatch(
            state=flat("state"), next_state=flat("next_state"),
            previous_action=flat("previous_action"), action=flat("action"),
            reward=flat("reward"), done=flat("done"),
        )
        stats = {
            "episode_return_sum": rec["episode_return"].sum(),
            "episodes_done": rec["episode_completed"].sum().astype(jnp.float32),
            "boundaries_done": rec["done"].sum().astype(jnp.float32),
        }
        new_state = state._replace(env=env, obs=obs, prev_action=prev_action,
                                   episodes=episodes, rng=rng)
        return new_state, batch, stats

    def _ingest(self, train, replay: DeviceReplay, batch: ApexBatch
                ) -> DeviceReplay:
        errs = self.agent._td_error(train, batch)  # [W]
        return device_replay.ingest(replay, batch, errs)

    # -- one update: collect, ingest, K prioritized steps ----------------
    def _update(self, state: AnakinApexState, _):
        state, trans, stats = self._collect(state)
        replay = self._ingest(state.train, state.replay, trans)
        train = state.train

        def one_learn(carry, _):
            train, replay, rng = carry
            rng, k = jax.random.split(rng)
            replay, batch, idx, weights = device_replay.sample(
                replay, k, self.batch_local, axis_name=self._axis)
            train, td, metrics = self.agent._learn(train, batch, weights,
                                                   axis_name=self._axis)
            replay = device_replay.update_priorities(replay, idx, td)
            return (train, replay, rng), metrics

        rng, k_learn = jax.random.split(state.rng)
        (train, replay, _), metrics = jax.lax.scan(
            one_learn, (train, replay, k_learn), None,
            length=self.updates_per_collect)
        metrics = jax.tree.map(lambda m: m[-1], metrics)

        do_sync = (train.step - state.last_sync) >= self.target_sync_interval
        train = jax.lax.cond(do_sync, lambda t: t.sync_target(), lambda t: t,
                             train)
        last_sync = jnp.where(do_sync, train.step, state.last_sync)
        metrics.update(self._psum(stats))
        metrics["replay_size"] = self._psum(replay.size.astype(jnp.float32))
        metrics["epsilon_mean"] = self._pmean(
            self._epsilon(state.episodes).mean())
        return state._replace(train=train, replay=replay, rng=rng,
                              last_sync=last_sync), metrics

    def _train_chunk(self, state: AnakinApexState, num_updates: int):
        """U x (collect + K prioritized learns) in one compiled program."""
        return jax.lax.scan(self._update, state, None, length=num_updates)

    def _collect_only(self, state: AnakinApexState, _):
        state, trans, stats = self._collect(state)
        replay = self._ingest(state.train, state.replay, trans)
        return state._replace(replay=replay), self._psum(stats)

    def _collect_chunk(self, state: AnakinApexState, num_collects: int):
        """Warm-up: fill the ring without training."""
        return jax.lax.scan(self._collect_only, state, None, length=num_collects)

    # -- greedy evaluation (argmax-Q, fresh envs, all on-device) ---------
    def _greedy_eval(self, params, num_envs: int, num_steps: int, rng):
        k_reset, k_run = jax.random.split(rng)
        env, obs = self.env.reset(k_reset, num_envs)
        obs = self.obs_transform(obs)
        pa = jnp.zeros(num_envs, jnp.int32)
        mask_fn = getattr(self.env, "completed_episode_mask",
                          lambda done, _state: done)

        def step_fn(carry, k):
            env, obs, pa = carry
            # epsilon = 0 through the shared act path: pure argmax-Q.
            action, _q = self.agent._act(params, obs, pa, 0.0, k)
            env_action = (action % self.env.NUM_ACTIONS
                          if self.agent.cfg.num_actions != self.env.NUM_ACTIONS
                          else action)
            env, next_obs, _r, done, ep = self.env.step(env, env_action, k)
            carry = (env, self.obs_transform(next_obs),
                     jnp.where(done, 0, action).astype(jnp.int32))
            return carry, (ep, mask_fn(done, env))

        keys = jax.random.split(k_run, num_steps)
        _, (eps, completed) = jax.lax.scan(step_fn, (env, obs, pa), keys)
        return {
            "return_sum": (eps * completed.astype(jnp.float32)).sum(),
            "episodes": completed.sum().astype(jnp.int32),
        }

    def greedy_eval(self, params, num_envs: int, num_steps: int, rng) -> dict:
        """Deterministic (argmax-Q) score on fresh envs — the ground-truth
        metric behind the behavior curves, which keep the epsilon ladder's
        exploration mixed in (same contract as AnakinImpala.greedy_eval)."""
        out = self._greedy_eval_jit(params, num_envs, num_steps, rng)
        episodes = int(out["episodes"])
        return {
            "mean_return": float(out["return_sum"]) / max(episodes, 1),
            "episodes": episodes,
        }
